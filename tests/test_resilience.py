"""Fault-tolerance layer units (train/resilience.py, chaos.py, and the
checkpoint/loader/step hooks it rides on).

End-to-end injected-fault runs live in test_chaos_e2e.py; this file covers
the pieces in isolation: chaos-spec parsing, the anomaly guard's robust
spike statistics and rewind streak, the watchdog's fire/beat behavior, the
device-side non-finite skip, torn-checkpoint errors + the resume-candidate
ladder, and bit-exact loader fast-forward for both host backends.
"""

import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deepfake_detection_tpu.chaos import ChaosInjector, chaos_from_env
from deepfake_detection_tpu.train.resilience import (
    EXIT_WATCHDOG, AnomalyGuard, PreemptionHandler, RewindRequested,
    StallWatchdog)

pytestmark = pytest.mark.smoke


# ---------------------------------------------------------------------------
# chaos spec
# ---------------------------------------------------------------------------

class TestChaosInjector:
    def test_parse_forms(self):
        c = ChaosInjector("sigterm@8,nanbatch@5x3,stall_loader@3:30.5")
        assert c.points["sigterm"] == (8, 1, None)
        assert c.points["nanbatch"] == (5, 3, None)
        assert c.points["stall_loader"] == (3, 1, 30.5)
        assert c.arg("stall_loader") == 30.5
        assert c.arg("sigterm", 7.0) == 7.0

    def test_fire_once_per_step_in_window(self):
        c = ChaosInjector("nanbatch@5x3")
        assert not c.fires("nanbatch", 4)
        assert c.fires("nanbatch", 5) and c.fires("nanbatch", 6) \
            and c.fires("nanbatch", 7)
        # re-executed steps after a rewind see clean data
        assert not any(c.fires("nanbatch", s) for s in (5, 6, 7, 8))
        assert not c.fires("other", 5)

    def test_empty_inactive_and_env(self, monkeypatch):
        assert not ChaosInjector("").active
        monkeypatch.delenv("DFD_CHAOS", raising=False)
        assert not chaos_from_env().active
        monkeypatch.setenv("DFD_CHAOS", "sigterm@2")
        assert chaos_from_env().fires("sigterm", 2)

    def test_bad_spec_raises(self):
        with pytest.raises(ValueError):
            ChaosInjector("sigterm")
        with pytest.raises(ValueError):
            ChaosInjector("@5")


# ---------------------------------------------------------------------------
# anomaly guard
# ---------------------------------------------------------------------------

class TestAnomalyGuard:
    def test_spike_detection_robust(self):
        g = AnomalyGuard(spike_window=8, spike_zmax=6.0, rewind_after=99)
        for i in range(8):
            assert not g.observe(i, 1.0 + 0.01 * (i % 3), False)
        assert g.observe(8, 40.0, False)        # spike
        assert g.spike_total == 1
        # the spike did NOT enter the rolling stats: baseline unchanged
        assert not g.observe(9, 1.01, False)
        assert g.bad_streak == 0

    def test_window_not_full_never_spikes(self):
        g = AnomalyGuard(spike_window=16, spike_zmax=6.0)
        assert not g.observe(0, 1.0, False)
        assert not g.observe(1, 1e9, False)      # only 1 sample of history

    def test_rewind_after_consecutive_bad(self):
        g = AnomalyGuard(rewind_after=3)
        assert g.observe(0, float("nan"), False)
        assert g.observe(1, 1.0, True)           # device flag counts too
        with pytest.raises(RewindRequested):
            g.observe(2, float("inf"), False)
        assert g.nonfinite_total == 3
        g.reset_streak()
        assert not g.observe(3, 1.0, False)

    def test_isolated_bad_steps_only_count(self):
        g = AnomalyGuard(rewind_after=2)
        for i in range(6):
            g.observe(2 * i, float("nan"), False)
            assert not g.observe(2 * i + 1, 1.0, False)
        assert g.nonfinite_total == 6


# ---------------------------------------------------------------------------
# watchdog + preemption handler
# ---------------------------------------------------------------------------

class TestStallWatchdog:
    def test_fires_with_position_and_code(self, capfd):
        # capfd, not capsys: faulthandler dumps to the stderr FD
        fired = []
        w = StallWatchdog(0.2, position_fn=lambda: "epoch 3 batch 7",
                          exit_fn=fired.append)
        w.start()
        w.beat()                # past the first-compile grace window
        time.sleep(1.0)
        w.stop()
        assert fired == [EXIT_WATCHDOG]
        err = capfd.readouterr().err
        assert "epoch 3 batch 7" in err
        assert "Thread" in err                  # faulthandler stack dump

    def test_first_window_has_compile_grace(self):
        # before the first beat the window is first_grace x timeout, so a
        # watchdog sized to step time survives first-step compilation
        fired = []
        w = StallWatchdog(0.15, exit_fn=fired.append, first_grace=10.0)
        w.start()
        time.sleep(0.8)         # > timeout, < first_grace * timeout
        assert fired == []
        w.beat()
        time.sleep(0.8)         # > timeout after a beat: fires
        w.stop()
        assert fired == [EXIT_WATCHDOG]

    def test_beats_prevent_fire(self):
        fired = []
        w = StallWatchdog(0.4, exit_fn=fired.append)
        w.start()
        for _ in range(6):
            time.sleep(0.1)
            w.beat()
        w.stop()
        assert fired == []

    def test_disabled_never_starts(self):
        w = StallWatchdog(0.0, exit_fn=lambda c: (_ for _ in ()).throw(
            AssertionError("must not fire")))
        w.start()
        assert w._thread is None
        w.stop()

    def test_resilience_note_updates_position_without_beating(self):
        # the runner's epoch-start marker must NOT count as a beat, or
        # it would end the first-compile grace window before the first
        # train step's compile — exactly what the grace exists to cover
        from deepfake_detection_tpu.train.resilience import Resilience
        w = StallWatchdog(60.0, exit_fn=lambda c: None)
        r = Resilience(watchdog=w)
        r.note("epoch 0 start (batch 0)")
        assert r.position == "epoch 0 start (batch 0)"
        assert not w._seen_beat
        r.heartbeat("epoch 0 batch 1/10")
        assert w._seen_beat


def test_preemption_handler_flag_and_restore():
    h = PreemptionHandler(signals=(signal.SIGUSR1,))
    before = signal.getsignal(signal.SIGUSR1)
    assert h.install()
    try:
        assert not h.stop_requested
        signal.raise_signal(signal.SIGUSR1)
        assert h.stop_requested and h.signum == signal.SIGUSR1
    finally:
        h.uninstall()
    assert signal.getsignal(signal.SIGUSR1) is before


# ---------------------------------------------------------------------------
# device-side non-finite skip (train/steps.py nonfinite_guard)
# ---------------------------------------------------------------------------

def _tiny_setup():
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, training=False):
            x = nn.Conv(4, (3, 3))(x)
            x = nn.BatchNorm(use_running_average=not training,
                             momentum=0.9)(x)
            x = x.mean(axis=(1, 2))
            return nn.Dense(2)(x)

    from deepfake_detection_tpu.train import create_train_state
    m = Tiny()
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(4, 8, 8, 3)), jnp.float32)
    y = jnp.asarray([0, 1, 0, 1])
    v = m.init({"params": jax.random.PRNGKey(0)}, x, training=True)
    v = {"params": v["params"], "batch_stats": v["batch_stats"]}
    tx = optax.adam(1e-2)
    state = create_train_state(v, tx, donate=False)
    return m, tx, state, x, y


class TestNonfiniteGuard:
    def test_finite_step_updates_and_flags_zero(self):
        from deepfake_detection_tpu.train import make_train_step
        m, tx, state, x, y = _tiny_setup()
        step = make_train_step(m, tx, mesh=None, bn_mode="global",
                               donate=False, nonfinite_guard=True)
        new_state, metrics = step(state, x, y, jax.random.PRNGKey(1))
        assert float(metrics["nonfinite"]) == 0.0
        assert np.isfinite(float(metrics["gnorm"]))
        assert int(new_state.step) == int(state.step) + 1
        k = new_state.params["Dense_0"]["kernel"]
        assert not np.array_equal(np.asarray(k),
                                  np.asarray(state.params["Dense_0"]["kernel"]))

    def test_poisoned_step_is_skipped_entirely(self):
        from deepfake_detection_tpu.train import make_train_step
        m, tx, state, x, y = _tiny_setup()
        step = make_train_step(m, tx, mesh=None, bn_mode="global",
                               donate=False, nonfinite_guard=True)
        bad = jnp.full_like(x, np.nan)
        new_state, metrics = step(state, bad, y, jax.random.PRNGKey(1))
        assert float(metrics["nonfinite"]) == 1.0
        # the ENTIRE state rolled back: params, BN stats, moments, step
        for a, b in zip(jax.tree.leaves(new_state), jax.tree.leaves(state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_guard_off_reproduces_reference_poisoning(self):
        from deepfake_detection_tpu.train import make_train_step
        m, tx, state, x, y = _tiny_setup()
        step = make_train_step(m, tx, mesh=None, bn_mode="global",
                               donate=False, nonfinite_guard=False)
        bad = jnp.full_like(x, np.nan)
        new_state, metrics = step(state, bad, y, jax.random.PRNGKey(1))
        assert "nonfinite" not in metrics
        k = np.asarray(new_state.params["Dense_0"]["kernel"])
        assert not np.isfinite(k).all()

    def test_guarded_clean_run_matches_unguarded(self):
        # the guard must be numerically invisible on healthy steps
        from deepfake_detection_tpu.train import make_train_step
        m, tx, state, x, y = _tiny_setup()
        g = make_train_step(m, tx, mesh=None, bn_mode="global",
                            donate=False, nonfinite_guard=True)
        u = make_train_step(m, tx, mesh=None, bn_mode="global",
                            donate=False, nonfinite_guard=False)
        sg, _ = g(state, x, y, jax.random.PRNGKey(1))
        su, _ = u(state, x, y, jax.random.PRNGKey(1))
        for a, b in zip(jax.tree.leaves(sg), jax.tree.leaves(su)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# torn checkpoints + the resume-candidate ladder
# ---------------------------------------------------------------------------

class TestCheckpointCorrupt:
    def _save_one(self, path):
        from deepfake_detection_tpu.train import save_checkpoint_file
        state = {"w": np.arange(64, dtype=np.float32)}
        save_checkpoint_file(str(path), state, {"epoch": 3})
        return state

    def test_truncated_raises_named_error(self, tmp_path):
        from deepfake_detection_tpu.train import (CheckpointCorrupt,
                                                  load_checkpoint_file)
        p = tmp_path / "recovery-3-5.ckpt"
        self._save_one(p)
        size = os.path.getsize(p)
        with open(p, "r+b") as f:
            f.truncate(size // 2)
        with pytest.raises(CheckpointCorrupt) as ei:
            load_checkpoint_file(str(p))
        assert str(p) in str(ei.value)

    def test_empty_and_garbage_raise(self, tmp_path):
        from deepfake_detection_tpu.train import (CheckpointCorrupt,
                                                  load_checkpoint_file)
        p = tmp_path / "empty.ckpt"
        p.write_bytes(b"")
        with pytest.raises(CheckpointCorrupt):
            load_checkpoint_file(str(p))
        p.write_bytes(b"\x00garbage-not-msgpack" * 7)
        with pytest.raises(CheckpointCorrupt):
            load_checkpoint_file(str(p))

    def test_intact_roundtrip_unaffected(self, tmp_path):
        from deepfake_detection_tpu.train import load_checkpoint_file
        p = tmp_path / "ok.ckpt"
        state = self._save_one(p)
        sd, meta = load_checkpoint_file(str(p))
        np.testing.assert_array_equal(sd["w"], state["w"])
        assert meta["epoch"] == 3

    def test_chaos_cli_truncate(self, tmp_path):
        import subprocess
        import sys
        from deepfake_detection_tpu.train import (CheckpointCorrupt,
                                                  load_checkpoint_file)
        p = tmp_path / "t.ckpt"
        self._save_one(p)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "chaos.py"),
             "truncate", str(p)], capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        with pytest.raises(CheckpointCorrupt):
            load_checkpoint_file(str(p))


def test_async_snapshot_owns_its_bytes():
    # np.asarray(jax.Array) is ZERO-COPY on the CPU backend: a background
    # checkpoint writer serializing such a view races the donating train
    # step and tears the snapshot (step counter from N steps later, params
    # overwritten by reused buffers — observed in the e2e chaos runs).
    # The async path must therefore own its bytes.
    from deepfake_detection_tpu.train.checkpoint import _to_host
    x = jnp.arange(1024, dtype=jnp.float32)
    assert _to_host(x, copy=True).flags["OWNDATA"]
    plain = np.arange(8)
    np.testing.assert_array_equal(_to_host(plain, copy=True), plain)


def test_find_resume_candidates_order(tmp_path):
    from deepfake_detection_tpu.train import find_resume_candidates
    d = tmp_path / "run"
    bak = d / "_bak"
    bak.mkdir(parents=True)
    for name in ("recovery-0-5.ckpt", "recovery-1-2.ckpt",
                 "recovery-0-999.ckpt", "model_best.ckpt"):
        (d / name).write_bytes(b"x")
    (bak / "model_best.ckpt").write_bytes(b"x")
    got = find_resume_candidates(str(d), bak_dir=str(bak))
    names = [os.path.relpath(p, tmp_path) for p in got]
    # newest recovery first (NUMERIC ordering: 1-2 beats 0-999), then the
    # _bak mirror, then model_best itself
    assert names == ["run/recovery-1-2.ckpt", "run/recovery-0-999.ckpt",
                     "run/recovery-0-5.ckpt", "run/_bak/model_best.ckpt",
                     "run/model_best.ckpt"]


def test_save_recovery_sync_lands_immediately(tmp_path):
    from deepfake_detection_tpu.train import (CheckpointSaver,
                                              load_checkpoint_file)
    saver = CheckpointSaver(checkpoint_dir=str(tmp_path))
    state = {"w": np.zeros(8, np.float32)}
    saver.save_recovery(state, {"num_updates": 37}, epoch=2, batch_idx=4,
                        sync=True)
    p = os.path.join(str(tmp_path), "recovery-2-4.ckpt")
    assert os.path.exists(p)        # no wait_pending_saves needed: sync
    _, meta = load_checkpoint_file(p)
    assert meta == {"num_updates": 37, "epoch": 2, "batch_idx": 4}


# ---------------------------------------------------------------------------
# loader fast-forward: bit-exact mid-epoch resume streams
# ---------------------------------------------------------------------------

def _collect(loader):
    return [tuple(np.asarray(p) for p in item) for item in loader]


class TestLoaderFastForward:
    def _make(self, backend="thread", **kw):
        from deepfake_detection_tpu.data import (SyntheticDataset,
                                                 create_deepfake_loader_v3)
        ds = SyntheticDataset(16, (32, 32, 3), 2, seed=0)
        return create_deepfake_loader_v3(
            ds, (3, 32, 32), 2, is_training=True, num_workers=1, seed=11,
            dtype=jnp.float32, loader_backend=backend, re_prob=0.5, **kw)

    def test_thread_backend_tail_is_bit_identical(self):
        full = self._make()
        full.set_epoch(1)
        want = _collect(full)
        ff = self._make()
        ff.set_epoch(1)
        ff.fast_forward(3)
        got = _collect(ff)
        assert len(want) == 8 and len(got) == 5
        for a, b in zip(want[3:], got):
            for xa, xb in zip(a, b):
                np.testing.assert_array_equal(xa, xb)
        full.close()
        ff.close()

    def test_prologue_key_stream_aligns_across_constructions(self):
        # a FRESH loader fast-forwarded into epoch 1 must reproduce the
        # RandomErasing draws of a loader that iterated epochs 0 and 1 —
        # i.e. _step is a function of absolute position, not history
        warm = self._make()
        warm.set_epoch(0)
        _ = _collect(warm)
        warm.set_epoch(1)
        want = _collect(warm)
        cold = self._make()
        cold.set_epoch(1)
        cold.fast_forward(5)
        got = _collect(cold)
        for a, b in zip(want[5:], got):
            np.testing.assert_array_equal(a[0], b[0])
        warm.close()
        cold.close()

    def test_shm_backend_tail_is_bit_identical(self):
        full = self._make(backend="shm")
        try:
            full.set_epoch(1)
            want = _collect(full)
        finally:
            full.close()
        ff = self._make(backend="shm")
        try:
            ff.set_epoch(1)
            ff.fast_forward(3)
            got = _collect(ff)
        finally:
            ff.close()
        for a, b in zip(want[3:], got):
            for xa, xb in zip(a, b):
                np.testing.assert_array_equal(xa, xb)

    @pytest.mark.slow   # tier-1 budget: spawned-worker kill/respawn (~18s)
    def test_shm_chaos_worker_kill_recovers_identically(self, monkeypatch):
        want = None
        full = self._make(backend="shm")
        try:
            full.set_epoch(0)
            want = _collect(full)
        finally:
            full.close()
        monkeypatch.setenv("DFD_CHAOS", "kill_shm_worker@2")
        hurt = self._make(backend="shm")
        try:
            hurt.set_epoch(0)
            got = _collect(hurt)
            assert hurt.loader.respawn_count >= 1
        finally:
            hurt.close()
        for a, b in zip(want, got):
            for xa, xb in zip(a, b):
                np.testing.assert_array_equal(xa, xb)
