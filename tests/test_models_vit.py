"""ViT: param parity, forward, ring-attention sequence parallelism, e2e."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepfake_detection_tpu.models import create_model, init_model


def test_vit_base_param_count():
    # canonical timm vit_base_patch16_224 @1000 classes
    m = create_model("vit_base_patch16_224", num_classes=1000)
    shapes = jax.eval_shape(
        lambda r: m.init(r, jnp.zeros((1, 224, 224, 3)), training=False),
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)})
    n = sum(int(jnp.prod(jnp.asarray(x.shape)))
            for x in jax.tree.leaves(shapes["params"]))
    assert n == 86_567_656


def test_vit_forward_and_pools():
    m = create_model("vit_tiny_patch16_224", num_classes=4)
    v = init_model(m, jax.random.PRNGKey(0), (2, 64, 64, 3))
    out = m.apply(v, jnp.zeros((2, 64, 64, 3)), training=False)
    assert out.shape == (2, 4)
    m2 = create_model("vit_tiny_patch16_224", num_classes=4,
                      class_token=False, global_pool="avg")
    v2 = init_model(m2, jax.random.PRNGKey(0), (2, 64, 64, 3))
    assert "cls_token" not in v2["params"]
    assert m2.apply(v2, jnp.zeros((2, 64, 64, 3)),
                    training=False).shape == (2, 4)


def test_vit_12chan_flagship_input():
    """The deepfake 12-channel frame stack works through the patch embed."""
    m = create_model("vit_tiny_patch16_224", num_classes=2, in_chans=12)
    v = init_model(m, jax.random.PRNGKey(0), (1, 64, 64, 12))
    out = m.apply(v, jnp.zeros((1, 64, 64, 12)), training=False)
    assert out.shape == (1, 2)


class TestSequenceParallel:
    def _models(self, devices, impl):
        from jax.sharding import Mesh
        mesh = Mesh(np.asarray(devices), ("data",))
        common = dict(num_classes=2, class_token=False, global_pool="avg")
        m_full = create_model("vit_tiny_patch16_224", **common)
        m_sp = create_model("vit_tiny_patch16_224", **common,
                            attn_impl=impl, sp_mesh=mesh)
        return m_full, m_sp

    @pytest.mark.parametrize("impl", [
        "ring",
        # tier-1 budget: ring_flash is env-broken on this jaxlib
        # (PartitionId, pre-existing) and burns ~5 s failing; it stays
        # in the slow tier with the other ring_flash pins
        pytest.param("ring_flash", marks=pytest.mark.slow),
        "ulysses"])
    def test_sp_attention_matches_full(self, devices, impl):
        """128 tokens sharded 8-ways through the SP kernels must match the
        dense forward (BASELINE.json: 'ViT … stress XLA attention path')."""
        m_full, m_sp = self._models(devices, impl)
        # 128×128/16 → 64 tokens per side isn't enough for 8-way ulysses
        # heads split (3 heads) — ring shards the SEQUENCE so 64 works; for
        # ulysses heads must divide axis, so skip when they don't
        if impl == "ulysses" and 3 % len(devices) != 0:
            pytest.skip("ulysses needs heads % axis == 0 (3 heads, 8 dev)")
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 128, 128, 3))
        v = init_model(m_full, jax.random.PRNGKey(0), (2, 128, 128, 3))
        out_full = m_full.apply(v, x, training=False)
        out_sp = jax.jit(lambda v, x: m_sp.apply(v, x, training=False))(v, x)
        np.testing.assert_allclose(np.asarray(out_full),
                                   np.asarray(out_sp), atol=2e-5)

    @pytest.mark.slow   # tier-1 budget: full SP train-step grads vs the
    # dense path (~11 s); SP forward parity (ring/ulysses above) stays
    # fast and train-step grads ride test_train's unified-step coverage
    def test_sp_train_step_grads(self, devices):
        """One jitted train step with the token axis ring-sharded: grads
        flow and match the dense path."""
        m_full, m_ring = self._models(devices, "ring")
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 128, 128, 3))
        y = jnp.array([0, 1])
        v = init_model(m_full, jax.random.PRNGKey(0), (2, 128, 128, 3))

        def loss_fn(model):
            def inner(params):
                logits = model.apply({"params": params}, x, training=False)
                lp = jax.nn.log_softmax(logits)
                return -jnp.mean(jnp.take_along_axis(lp, y[:, None], 1))
            return inner

        g_full = jax.grad(loss_fn(m_full))(v["params"])
        g_ring = jax.jit(jax.grad(loss_fn(m_ring)))(v["params"])
        flat_f = jax.tree.leaves(g_full)
        flat_r = jax.tree.leaves(g_ring)
        assert all(np.allclose(a, b, atol=5e-5)
                   for a, b in zip(flat_f, flat_r))


@pytest.mark.slow
def test_vit_synthetic_e2e_train(tmp_path, devices):
    from deepfake_detection_tpu.runners.train import launch_main
    out = launch_main([
        "--dataset", "synthetic", "--model", "vit_tiny_patch16_224",
        "--model-version", "", "--input-size-v2", "3,32,32",
        "--batch-size", "1", "--epochs", "1", "--opt", "adamw",
        "--lr", "1e-3", "--sched", "step", "--log-interval", "4",
        "--workers", "1", "--compute-dtype", "float32",
        "--output", str(tmp_path / "out")])
    assert out["best_metric"] is not None


@pytest.mark.parametrize("policy", [
    pytest.param("full", marks=pytest.mark.slow),   # tier-1 budget
    "dots"])
def test_vit_remat_matches_baseline(policy):
    """remat changes the backward schedule, not the math."""
    base = create_model("vit_tiny_patch16_224", num_classes=2)
    rem = create_model("vit_tiny_patch16_224", num_classes=2,
                       remat_policy=policy)
    v = init_model(base, jax.random.PRNGKey(0), (1, 64, 64, 3))
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 64, 64, 3))

    def loss(model):
        return lambda p: model.apply({"params": p}, x).sum()

    np.testing.assert_allclose(
        np.asarray(base.apply(v, x)), np.asarray(rem.apply(v, x)), atol=5e-6)
    g0 = jax.grad(loss(base))(v["params"])
    g1 = jax.jit(jax.grad(loss(rem)))(v["params"])
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
