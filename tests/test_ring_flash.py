"""Fused ring attention (Pallas blocks over a ppermute ring) vs dense.

Runs on the 8-virtual-device CPU mesh; the Pallas kernels execute under the
interpreter, the ring schedule (ppermute of K/V forward, of dK/dV backward)
is the real compiled collective program.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deepfake_detection_tpu.parallel.ring_attention import (
    full_attention, ring_self_attention)


def _qkv(b, l, h, d, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, l, h, d)) for k in ks)


@pytest.fixture()
def sp_mesh(devices):
    return Mesh(np.asarray(devices[:4]), ("sp",))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_dense(sp_mesh, causal):
    # L_local = 96: exercises both seq padding (96→128) and D padding
    q, k, v = _qkv(2, 4 * 96, 2, 32)
    out = jax.jit(lambda q, k, v: ring_self_attention(
        q, k, v, sp_mesh, seq_axis="sp", causal=causal,
        impl="ring_flash"))(q, k, v)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_grads_match_dense(sp_mesh, causal):
    q, k, v = _qkv(1, 4 * 64, 2, 32, seed=1)

    def loss_ring(q, k, v):
        return jnp.sum(ring_self_attention(
            q, k, v, sp_mesh, seq_axis="sp", causal=causal,
            impl="ring_flash") ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=causal) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd, name in zip(g_ring, g_dense, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{name} mismatch")


def test_ring_flash_agrees_with_xla_ring(sp_mesh):
    # the two ring implementations are independent programs; agreement is a
    # strong cross-check of both
    q, k, v = _qkv(2, 4 * 128, 2, 64, seed=2)
    o1 = jax.jit(lambda q, k, v: ring_self_attention(
        q, k, v, sp_mesh, seq_axis="sp", impl="ring"))(q, k, v)
    o2 = jax.jit(lambda q, k, v: ring_self_attention(
        q, k, v, sp_mesh, seq_axis="sp", impl="ring_flash"))(q, k, v)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                               atol=2e-5, rtol=2e-5)
