"""Unit tests for bench.py's chip-verified row artifact (BENCH_TPU_ROWS.json).

The artifact is the CPU fallback's only source of real TPU numbers during a
relay outage, so its merge semantics are load-bearing: a budget-truncated
or partial matrix run must never clobber previously verified rows.
"""

import importlib.util
import json
import os

import pytest

pytestmark = pytest.mark.smoke


@pytest.fixture()
def bench(tmp_path, monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod._TPU_ROWS_PATH = str(tmp_path / "rows.json")
    return mod


def _row(metric, value, device="TPU v5 lite", steps=10, **kw):
    return dict(metric=metric, value=value, device=device, steps=steps, **kw)


class TestVerifiedRowStore:
    def test_merge_keeps_unmeasured_rows(self, bench):
        bench._store_verified_tpu_rows([_row("a", 1.0), _row("b", 2.0)])
        bench._store_verified_tpu_rows([_row("b", 3.0)])
        rows = {r["metric"]: r for r in bench._load_verified_tpu_rows()}
        assert rows["a"]["value"] == 1.0          # survived the partial run
        assert rows["b"]["value"] == 3.0          # updated in place
        assert rows["b"]["source"].startswith("chip_verified_")

    def test_non_tpu_and_errored_rows_never_stored(self, bench):
        bench._store_verified_tpu_rows([
            _row("cpu_row", 1.0, device="cpu"),
            {"metric": "failed", "error": "boom", "device": "TPU v5 lite"},
        ])
        assert not os.path.exists(bench._TPU_ROWS_PATH)

    def test_low_step_rows_gated_per_row(self, bench):
        """A 5-step flagship debug rung must not overwrite a verified row,
        even when other rows in the same run pass the gate (ADVICE r4)."""
        bench._store_verified_tpu_rows([_row("flagship", 100.0, steps=20)])
        bench._store_verified_tpu_rows([
            _row("flagship", 1.0, steps=5),       # OOM-ladder debug rung
            _row("b4", 2.0, steps=20),
        ])
        rows = {r["metric"]: r for r in bench._load_verified_tpu_rows()}
        assert rows["flagship"]["value"] == 100.0
        assert rows["b4"]["value"] == 2.0

    def test_write_is_atomic(self, bench):
        """No .tmp residue after a store (crash-safe replace pattern)."""
        bench._store_verified_tpu_rows([_row("a", 1.0)])
        assert os.path.exists(bench._TPU_ROWS_PATH)
        assert not os.path.exists(bench._TPU_ROWS_PATH + ".tmp")

    @staticmethod
    def _unstamped(rows):
        return [{k: v for k, v in r.items() if k != "round"} for r in rows]

    def test_load_falls_back_to_builtin_rows(self, bench):
        rows = bench._load_verified_tpu_rows()   # no file at the tmp path
        assert self._unstamped(rows) == bench._LAST_VERIFIED_TPU_ROWS
        assert all("value" in r for r in rows)

    @pytest.mark.parametrize("content", [
        "{not json",                       # invalid JSON
        "[1, 2, 3]",                       # valid JSON, wrong shape
        '{"rows": [1, 2]}',                # rows not dicts
    ])
    def test_corrupt_file_falls_back(self, bench, content):
        with open(bench._TPU_ROWS_PATH, "w") as f:
            f.write(content)
        assert self._unstamped(bench._load_verified_tpu_rows()) == \
            bench._LAST_VERIFIED_TPU_ROWS

    def test_store_then_load_round_trip(self, bench):
        stored = [_row("m1", 10.5, mfu=0.7), _row("m2", 2.0)]
        bench._store_verified_tpu_rows(stored)
        loaded = {r["metric"] for r in bench._load_verified_tpu_rows()}
        # the first store seeds from the builtin fallback rows (by design:
        # the last-known-good set survives), then adds the new metrics
        builtin = {r["metric"] for r in bench._LAST_VERIFIED_TPU_ROWS}
        assert loaded == builtin | {"m1", "m2"}
        payload = json.load(open(bench._TPU_ROWS_PATH))
        assert "note" in payload and len(payload["rows"]) == len(loaded)


class TestFallbackRowHygiene:
    """ISSUE 2 satellite (VERDICT weak #4): CPU-fallback rows must not
    carry pseudo-MFU numbers computed against the TPU baseline, and the
    embedded verified rows must say which round captured them."""

    def test_cpu_rows_null_vs_baseline_and_mfu(self, bench):
        row = {"metric": "m", "value": 10.0, "vs_baseline": 0.12,
               "mfu": 0.08, "step_ms": 5.0}
        out = bench._null_nonchip_noise(row, "cpu")
        assert out["vs_baseline"] is None and out["mfu"] is None
        assert out["value"] == 10.0 and out["step_ms"] == 5.0
        assert row["vs_baseline"] == 0.12     # input not mutated

    def test_tpu_rows_keep_mfu(self, bench):
        row = {"metric": "m", "value": 10.0, "vs_baseline": 0.5,
               "mfu": 0.35}
        assert bench._null_nonchip_noise(row, "tpu") == row

    def test_round_stamped_from_env_on_store(self, bench, monkeypatch):
        monkeypatch.setenv("BENCH_ROUND", "6")
        bench._store_verified_tpu_rows([_row("a", 1.0)])
        rows = {r["metric"]: r for r in bench._load_verified_tpu_rows()}
        assert rows["a"]["round"] == 6
        assert rows["a"]["source"].startswith("chip_verified_")

    def test_round_backfilled_from_legacy_source_tag(self, bench):
        # the builtin fallback rows carry round3_chip_verified sources
        rows = bench._load_verified_tpu_rows()
        assert rows and all(r.get("round") == 3 for r in rows)

    def test_round_survives_reload_from_file(self, bench, monkeypatch):
        monkeypatch.setenv("BENCH_ROUND", "7")
        bench._store_verified_tpu_rows([_row("b", 2.0)])
        monkeypatch.delenv("BENCH_ROUND")
        bench._store_verified_tpu_rows([_row("c", 3.0)])
        rows = {r["metric"]: r for r in bench._load_verified_tpu_rows()}
        assert rows["b"]["round"] == 7        # merge kept the stamp
        assert "round" not in rows["c"] or rows["c"]["round"] != 7


def test_retry_budget_left(bench):
    """Watchdog retry gating (ISSUE 1 satellite): a transient-fault retry
    is skipped once less than the floor remains of the GLOBAL
    BENCH_RUN_TIMEOUT budget — no fixed 60 s grant past exhaustion."""
    assert bench._retry_budget_left(2400.0, 100.0)
    assert bench._retry_budget_left(2400.0, 2340.0)       # exactly the floor
    assert not bench._retry_budget_left(2400.0, 2341.0)
    assert not bench._retry_budget_left(120.0, 119.0)
    assert bench._retry_budget_left(120.0, 100.0, floor=10.0)
