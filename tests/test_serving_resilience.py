"""Serving resilience units (ISSUE 10): circuit breaker, stuck-batch
watchdog, non-finite batch handling, reload canary + rollback, jittered
Retry-After, request books.

Fast tier (``serving`` marker): every chaos fault here is injected
in-process through the engine's ``chaos`` argument (no env vars, no
subprocesses) against the small conv model at a 32² canvas, so the
bucket compiles hit the persistent compilation cache.  The live-server
versions of these scenarios (real HTTP load, SIGTERM, /metrics
scrapes) are the slow-tier ``tools/chaos_serve.py`` e2e
(tests/test_chaos_serve_e2e.py).
"""

import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepfake_detection_tpu.chaos import ChaosInjector
from deepfake_detection_tpu.models import create_model, init_model
from deepfake_detection_tpu.models.helpers import save_model_checkpoint
from deepfake_detection_tpu.params import normalize_replicate, prepare_canvas
from deepfake_detection_tpu.serving.batcher import MicroBatcher, QueueFull
from deepfake_detection_tpu.serving.engine import InferenceEngine
from deepfake_detection_tpu.serving.http import (make_server,
                                                 serve_forever_in_thread)
from deepfake_detection_tpu.serving.metrics import (ServingMetrics,
                                                    backend_compile_count)
from deepfake_detection_tpu.serving.resilience import (BreakerOpen,
                                                       CircuitBreaker,
                                                       EngineStalled,
                                                       NonFiniteScores,
                                                       jittered_retry_after)

pytestmark = pytest.mark.serving

_MODEL = "mobilenetv3_small_100"
_SIZE = 32


def _perturbed_variables(model, size, chans, seed=0):
    variables = init_model(model, jax.random.PRNGKey(0),
                           (1, size, size, chans))
    rng = np.random.default_rng(seed)
    return jax.tree.map(
        lambda a: a + jnp.asarray(
            0.02 * rng.standard_normal(np.shape(a)).astype(np.float32)
        ).astype(a.dtype),
        variables)


def _payload(seed=0):
    rng = np.random.default_rng(seed)
    return normalize_replicate(prepare_canvas(
        rng.integers(0, 255, (48, 40, 3), dtype=np.uint8), _SIZE), 1)


@pytest.fixture(scope="module")
def mv():
    model = create_model(_MODEL, num_classes=2, in_chans=3)
    return model, _perturbed_variables(model, _SIZE, 3)


def _engine(mv, *, chaos="", buckets=(1,), watchdog_timeout_s=0.0, **kw):
    model, variables = mv
    metrics = ServingMetrics()
    return InferenceEngine(
        model, variables, image_size=_SIZE, img_num=1, buckets=buckets,
        metrics=metrics, chaos=ChaosInjector(chaos),
        watchdog_timeout_s=watchdog_timeout_s, **kw)


def _books(m: ServingMetrics):
    return (m.accepted_total.value,
            m.scored_total.value + m.shed_total.value +
            m.deadline_total.value + m.failed_total.value)


# ---------------------------------------------------------------------------
# circuit breaker state machine (injected clock, no jax)
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_breaker_opens_on_consecutive_failures_only():
    clk = _Clock()
    b = CircuitBreaker(failure_threshold=3, open_s=5.0, clock=clk)
    # sporadic failures interleaved with successes never open it
    for _ in range(10):
        b.record_failure()
        b.record_failure()
        b.record_success()
        b.allow()
    assert b.state == "closed"
    for _ in range(3):
        b.record_failure()
    assert b.state == "open"
    with pytest.raises(BreakerOpen) as ei:
        b.allow()
    # remaining cooldown plus the bounded anti-herd jitter
    assert 0 < ei.value.retry_after_s <= 5.0 + b.retry_jitter_s


def test_breaker_half_open_single_probe_then_close_or_reopen():
    clk = _Clock()
    b = CircuitBreaker(failure_threshold=1, open_s=5.0, clock=clk)
    b.record_failure()
    assert b.state == "open"
    clk.t += 5.1
    b.allow()                      # the probe is admitted
    assert b.state == "half_open"
    with pytest.raises(BreakerOpen):
        b.allow()                  # ...but only ONE probe
    b.record_success()             # probe succeeded
    assert b.state == "closed"
    b.allow()
    # reopen path: probe failure restarts the full cooldown
    b.record_failure()
    clk.t += 5.1
    b.allow()
    b.record_failure()             # probe failed
    assert b.state == "open"
    with pytest.raises(BreakerOpen):
        b.allow()


def test_breaker_unreported_probe_cannot_wedge_it_shut():
    clk = _Clock()
    b = CircuitBreaker(failure_threshold=1, open_s=2.0, clock=clk)
    b.record_failure()
    clk.t += 2.1
    b.allow()                      # probe admitted, outcome never reported
    clk.t += 2.1                   # a cooldown's silence later...
    b.allow()                      # ...the next arrival re-probes


def test_breaker_threshold_zero_disables():
    b = CircuitBreaker(failure_threshold=0, open_s=1.0)
    for _ in range(100):
        b.record_failure()
    assert b.state == "closed"
    b.allow()


# ---------------------------------------------------------------------------
# jittered Retry-After (thundering-herd satellite)
# ---------------------------------------------------------------------------

def test_jittered_retry_after_bounded_spread():
    import random
    rng = random.Random(3)
    vals = [jittered_retry_after(1.0, 2.0, rng) for _ in range(200)]
    assert all(1.0 <= v < 3.0 for v in vals)
    assert len({round(v, 3) for v in vals}) > 100    # spread, not constant


def test_queue_full_retry_after_is_jittered():
    m = ServingMetrics()
    b = MicroBatcher(max_batch=4, deadline_ms=1.0, max_queue=1,
                     metrics=m, retry_jitter_s=2.0)
    b.submit(np.zeros((4, 4, 3), np.uint8))
    retries = []
    for _ in range(24):
        with pytest.raises(QueueFull) as ei:
            b.submit(np.zeros((4, 4, 3), np.uint8))
        retries.append(ei.value.retry_after_s)
    assert all(1.0 <= r < 3.0 for r in retries)      # base 1 + [0, 2)
    assert len({round(r, 3) for r in retries}) >= 2  # jittered, not fixed
    # books: the shed submits are accepted + shed, the queued one pending
    assert m.accepted_total.value == 25
    assert m.shed_total.value == 24


# ---------------------------------------------------------------------------
# non-finite batch: 503 + counter, never a silent score
# ---------------------------------------------------------------------------

def test_nonfinite_batch_fails_requests_and_next_batch_serves(mv):
    eng = _engine(mv, chaos="serve_nan@0")
    b = MicroBatcher(max_batch=1, deadline_ms=1.0, max_queue=8,
                     metrics=eng.metrics)
    eng.start(b)
    try:
        with pytest.raises(NonFiniteScores):
            b.submit(_payload(), timeout_s=10).result(timeout=10)
        assert eng.metrics.nonfinite_batches_total.value == 1
        # the engine self-heals: the next batch serves normally
        scores = b.submit(_payload(1), timeout_s=10).result(timeout=10)
        assert scores.shape == (2,) and np.isfinite(scores).all()
        acc, resolved = _books(eng.metrics)
        assert acc == resolved == 2
    finally:
        eng.stop()
        b.close()


def test_injected_score_fn_exception_recovers(mv):
    eng = _engine(mv, chaos="serve_exc@0")
    b = MicroBatcher(max_batch=1, deadline_ms=1.0, max_queue=8,
                     metrics=eng.metrics)
    eng.start(b)
    try:
        with pytest.raises(RuntimeError, match="chaos"):
            b.submit(_payload(), timeout_s=10).result(timeout=10)
        # the request fails BEFORE the exception finishes unwinding into
        # the serve loop's crash counter: poll for it
        deadline = time.monotonic() + 5
        while eng.metrics.worker_restarts_total.value == 0 and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.metrics.worker_restarts_total.value == 1
        scores = b.submit(_payload(1), timeout_s=10).result(timeout=10)
        assert scores.shape == (2,)
        assert _books(eng.metrics)[0] == _books(eng.metrics)[1] == 2
    finally:
        eng.stop()
        b.close()


# ---------------------------------------------------------------------------
# stuck-batch watchdog: fail in-flight, restart worker, re-warm, readyz
# ---------------------------------------------------------------------------

def test_hang_watchdog_fails_inflight_rewarm_drops_readiness(mv):
    eng = _engine(mv, chaos="serve_hang@0:8", watchdog_timeout_s=0.5)
    eng.watchdog.poll_s = 0.02
    ready_during_rewarm = []
    orig_rewarm = eng._rewarm

    def spying_rewarm():
        ready_during_rewarm.append(eng.metrics.ready)
        orig_rewarm()

    eng._rewarm = spying_rewarm
    b = MicroBatcher(max_batch=1, deadline_ms=1.0, max_queue=8,
                     metrics=eng.metrics)
    backend0 = backend_compile_count()
    eng.start(b)
    try:
        with pytest.raises(EngineStalled):
            b.submit(_payload(), timeout_s=30).result(timeout=20)
        assert eng.metrics.watchdog_recoveries_total.value == 1
        # the requests fail BEFORE the (bounded, helper-thread) re-warm
        # runs: wait for recovery to finish, then check the flag history
        deadline = time.monotonic() + 10
        while not eng.metrics.ready and time.monotonic() < deadline:
            time.sleep(0.02)
        assert eng.metrics.ready            # serving again...
        assert ready_during_rewarm == [False]   # ...and readiness was
        assert eng.metrics.rewarms_total.value == 1   # DOWN mid-re-warm
        # the restarted worker serves, on the SAME executables
        scores = b.submit(_payload(1), timeout_s=10).result(timeout=10)
        assert scores.shape == (2,)
        assert backend_compile_count() == backend0   # zero recompiles
        assert _books(eng.metrics)[0] == _books(eng.metrics)[1] == 2
    finally:
        eng.stop()
        b.close()


def test_worker_kill_respawned_by_watchdog(mv):
    eng = _engine(mv, chaos="serve_kill@0", watchdog_timeout_s=5.0)
    eng.watchdog.poll_s = 0.02
    b = MicroBatcher(max_batch=1, deadline_ms=1.0, max_queue=8,
                     metrics=eng.metrics)
    eng.start(b)
    try:
        deadline = time.monotonic() + 10
        while eng.metrics.watchdog_recoveries_total.value == 0 and \
                time.monotonic() < deadline:
            time.sleep(0.02)
        assert eng.metrics.watchdog_recoveries_total.value == 1
        scores = b.submit(_payload(), timeout_s=10).result(timeout=10)
        assert scores.shape == (2,)
    finally:
        eng.stop()
        b.close()


def test_breaker_opens_after_consecutive_batch_failures(mv):
    eng = _engine(mv, chaos="serve_exc@0x2", breaker_threshold=2,
                  breaker_open_s=0.3)
    b = MicroBatcher(max_batch=1, deadline_ms=1.0, max_queue=8,
                     metrics=eng.metrics)
    eng.start(b)
    try:
        for seed in (0, 1):        # two consecutive injected batch faults
            with pytest.raises(RuntimeError):
                b.submit(_payload(seed), timeout_s=10).result(timeout=10)
        assert eng.breaker.state == "open"
        assert eng.metrics.breaker_opens_total.value == 1
        with pytest.raises(BreakerOpen):
            eng.breaker.allow()
        assert eng.metrics.breaker_rejected_total.value == 1
        time.sleep(0.35)           # cooldown -> half-open probe
        eng.breaker.allow()
        assert eng.metrics.breaker_probes_total.value == 1
        scores = b.submit(_payload(2), timeout_s=10).result(timeout=10)
        assert scores.shape == (2,)
        assert eng.breaker.state == "closed"     # probe batch closed it
    finally:
        eng.stop()
        b.close()


# ---------------------------------------------------------------------------
# hot-reload canary gate + rollback (satellite: torn / mismatched / NaN
# checkpoints each leave the old weights serving bit-identically)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def reload_stack(mv):
    """Engine + HTTP server with NO worker thread: the canary tests
    drive ``_maybe_apply_reload`` synchronously (deterministic
    assertions, no race with a serve loop); /healthz, /readyz and
    /metrics still serve."""
    model, variables = mv
    metrics = ServingMetrics()
    engine = InferenceEngine(model, variables, image_size=_SIZE, img_num=1,
                             buckets=(1,), metrics=metrics,
                             watchdog_timeout_s=0.0)
    batcher = MicroBatcher(max_batch=1, deadline_ms=1.0, max_queue=16,
                           metrics=metrics)
    server = make_server("127.0.0.1", 0, engine, batcher, metrics,
                         request_timeout_s=10.0)
    serve_forever_in_thread(server)
    yield type("S", (), dict(model=model, variables=variables,
                             engine=engine, batcher=batcher,
                             metrics=metrics, server=server,
                             port=server.server_address[1]))
    server.shutdown()
    engine.stop()
    batcher.close()
    server.server_close()


@pytest.fixture(scope="module")
def live_stack(mv):
    """Engine + RUNNING worker + HTTP server, for tests that score over
    the wire (breaker shedding, request books)."""
    model, variables = mv
    metrics = ServingMetrics()
    engine = InferenceEngine(model, variables, image_size=_SIZE, img_num=1,
                             buckets=(1,), metrics=metrics,
                             watchdog_timeout_s=0.0)
    batcher = MicroBatcher(max_batch=1, deadline_ms=1.0, max_queue=16,
                           metrics=metrics)
    engine.start(batcher)
    server = make_server("127.0.0.1", 0, engine, batcher, metrics,
                         request_timeout_s=10.0)
    serve_forever_in_thread(server)
    yield type("S", (), dict(model=model, variables=variables,
                             engine=engine, batcher=batcher,
                             metrics=metrics, server=server,
                             port=server.server_address[1]))
    server.shutdown()
    engine.stop()
    batcher.close()
    server.server_close()


def _host_tree(variables):
    return jax.tree.map(np.asarray, variables)


def test_canary_rejects_nan_params_bit_identical_rollback(reload_stack):
    s = reload_stack
    payload = _payload(5)
    before = s.engine.score_batch([payload])
    errors0 = s.metrics.reload_errors_total.value
    canary0 = s.metrics.reload_canary_failures_total.value
    nan_tree = jax.tree.map(
        lambda a: np.full_like(np.asarray(a), np.nan)
        if np.issubdtype(np.asarray(a).dtype, np.floating)
        else np.asarray(a), s.variables)
    s.engine.submit_reload(nan_tree, source="<nan-test>")
    s.engine._maybe_apply_reload()
    assert s.metrics.reload_errors_total.value == errors0 + 1
    assert s.metrics.reload_canary_failures_total.value == canary0 + 1
    assert s.engine.reload_count == 0
    np.testing.assert_array_equal(s.engine.score_batch([payload]), before)


def test_canary_rejects_shape_mismatch_bit_identical_rollback(reload_stack):
    s = reload_stack
    payload = _payload(6)
    before = s.engine.score_batch([payload])
    errors0 = s.metrics.reload_errors_total.value
    s.engine.submit_reload(
        {"params": {"nope": np.zeros((3, 3), np.float32)}},
        source="<shape-test>")
    s.engine._maybe_apply_reload()
    assert s.metrics.reload_errors_total.value == errors0 + 1
    np.testing.assert_array_equal(s.engine.score_batch([payload]), before)


def test_watcher_rejects_torn_msgpack_bit_identical_rollback(
        reload_stack, tmp_path):
    s = reload_stack
    payload = _payload(7)
    before = s.engine.score_batch([payload])
    errors0 = s.metrics.reload_errors_total.value
    good = _host_tree(_perturbed_variables(s.model, _SIZE, 3, seed=9))
    watch_dir = tmp_path / "watch"
    watch_dir.mkdir()
    # the watcher only reacts to files appearing AFTER it starts, so
    # tear the checkpoint in a staging dir and move it in atomically
    staging = tmp_path / "next.msgpack"
    save_model_checkpoint(str(staging), good)
    data = staging.read_bytes()
    staging.write_bytes(data[:len(data) // 2])       # tear it in half
    s.engine.start_reload_watcher(str(watch_dir), interval_s=0.05)
    import os
    os.replace(staging, watch_dir / "next.msgpack")
    try:
        deadline = time.monotonic() + 10
        while s.metrics.reload_errors_total.value == errors0 and \
                time.monotonic() < deadline:
            time.sleep(0.02)
        assert s.metrics.reload_errors_total.value == errors0 + 1
        assert s.engine.reload_count == 0
        np.testing.assert_array_equal(s.engine.score_batch([payload]),
                                      before)
    finally:
        s.engine._stop.set()       # stop only the watcher thread
        s.engine._watcher.join(timeout=5)
        s.engine._watcher = None
        s.engine._stop.clear()


def test_canary_drift_tolerance_gates_and_admits(reload_stack):
    s = reload_stack
    payload = _payload(8)
    before = s.engine.score_batch([payload])
    nudged = _host_tree(_perturbed_variables(s.model, _SIZE, 3, seed=4))
    canary0 = s.metrics.reload_canary_failures_total.value
    try:
        s.engine.reload_drift_tol = 0.0      # zero tolerance: any change
        s.engine.submit_reload(nudged, source="<drift-test>")
        s.engine._maybe_apply_reload()
        assert s.metrics.reload_canary_failures_total.value == canary0 + 1
        assert s.engine.reload_count == 0
        np.testing.assert_array_equal(s.engine.score_batch([payload]),
                                      before)
        s.engine.reload_drift_tol = 1.0      # softmax drift is <= 1
        s.engine.submit_reload(nudged, source="<drift-test-2>")
        s.engine._maybe_apply_reload()
        assert s.engine.reload_count == 1
        after = s.engine.score_batch([payload])
        assert not np.array_equal(after, before)
    finally:
        s.engine.reload_drift_tol = -1.0
        # restore the original serving weights for later tests
        s.engine.submit_reload(_host_tree(s.variables), source="<restore>")
        s.engine._maybe_apply_reload()


def test_readyz_drops_during_canary_healthz_stays(reload_stack):
    """The satellite fix pinned: while the reload canary runs, /readyz
    must say 503 (readiness would otherwise lie about the paused worker)
    and /healthz must stay 200."""
    s = reload_stack
    seen = {}

    def hook():
        seen["ready_flag"] = s.engine.ready
        for path in ("/healthz", "/readyz"):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{s.port}{path}", timeout=5) as r:
                    seen[path] = r.status
            except urllib.error.HTTPError as e:
                seen[path] = e.code

    s.engine._canary_hook = hook
    try:
        s.engine.submit_reload(_host_tree(s.variables), source="<ready>")
        s.engine._maybe_apply_reload()
    finally:
        s.engine._canary_hook = None
    assert seen == {"ready_flag": False, "/healthz": 200, "/readyz": 503}
    assert s.engine.ready                    # restored after the canary


# ---------------------------------------------------------------------------
# HTTP mapping: non-finite -> 503 + Retry-After, breaker -> 503
# ---------------------------------------------------------------------------

def _post_image(port, body, timeout=30):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/score", data=body,
        headers={"Content-Type": "image/jpeg"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers)


def _jpeg(seed=0):
    import io

    from PIL import Image
    rng = np.random.default_rng(seed)
    buf = io.BytesIO()
    Image.fromarray(rng.integers(0, 255, (40, 40, 3), dtype=np.uint8)
                    ).save(buf, "JPEG", quality=90)
    return buf.getvalue()


def test_http_nonfinite_maps_503_with_retry_after(mv):
    eng = _engine(mv, chaos="serve_nan@0")
    b = MicroBatcher(max_batch=1, deadline_ms=1.0, max_queue=8,
                     metrics=eng.metrics)
    eng.start(b)
    server = make_server("127.0.0.1", 0, eng, b, eng.metrics,
                         request_timeout_s=10.0)
    serve_forever_in_thread(server)
    port = server.server_address[1]
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_image(port, _jpeg())
        assert ei.value.code == 503
        assert int(ei.value.headers["Retry-After"]) >= 1
        status, _ = _post_image(port, _jpeg(1))   # self-healed
        assert status == 200
    finally:
        server.shutdown()
        eng.stop()
        b.close()
        server.server_close()


def test_http_breaker_open_sheds_503(live_stack):
    s = live_stack
    # force the breaker open without faulting the shared engine
    for _ in range(s.engine.breaker.failure_threshold):
        s.engine.breaker.record_failure()
    try:
        assert s.engine.breaker.state == "open"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_image(s.port, _jpeg(2))
        assert ei.value.code == 503
        assert int(ei.value.headers["Retry-After"]) >= 1
        assert s.metrics.breaker_rejected_total.value >= 1
    finally:
        s.engine.breaker.record_success()        # close it again
    assert s.engine.breaker.state == "closed"
    assert _post_image(s.port, _jpeg(3))[0] == 200


# ---------------------------------------------------------------------------
# request books under mixed outcomes
# ---------------------------------------------------------------------------

def test_books_balance_under_mixed_load(live_stack):
    """accepted == scored + shed + deadline + failed, exactly, across a
    mix of successes, a poisoned request, queue-expired deadlines and
    shutdown — the invariant tools/chaos_serve.py asserts from /metrics
    after every live fault scenario."""
    s = live_stack
    m = s.metrics
    # successes
    reqs = [s.batcher.submit(_payload(i), timeout_s=10) for i in range(3)]
    for r in reqs:
        assert r.result(timeout=10).shape == (2,)
    # a poisoned request (bad shape) fails
    bad = s.batcher.submit(np.zeros((7, 9, 3), np.float32), timeout_s=10)
    with pytest.raises(Exception):
        bad.result(timeout=10)
    # one more success so the worker is provably healthy again
    assert s.batcher.submit(_payload(9),
                            timeout_s=10).result(timeout=10).shape == (2,)
    deadline = time.monotonic() + 10
    while _books(m)[0] != _books(m)[1] and time.monotonic() < deadline:
        time.sleep(0.02)
    acc, resolved = _books(m)
    assert acc == resolved
