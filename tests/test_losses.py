"""Loss tests, cross-checked against independent torch-CPU implementations."""

import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from deepfake_detection_tpu.losses import (create_loss_fn, cross_entropy,
                                           jsd_cross_entropy,
                                           label_smoothing_cross_entropy,
                                           one_hot,
                                           soft_target_cross_entropy)

pytestmark = pytest.mark.smoke  # fast tier: see pyproject [tool.pytest]

rng = np.random.default_rng(7)
LOGITS = rng.normal(size=(12, 2)).astype(np.float32)
LABELS = rng.integers(0, 2, size=12).astype(np.int32)


def test_cross_entropy_matches_torch():
    ours = float(cross_entropy(jnp.asarray(LOGITS), jnp.asarray(LABELS)))
    theirs = float(F.cross_entropy(torch.tensor(LOGITS),
                                   torch.tensor(LABELS, dtype=torch.long)))
    assert ours == pytest.approx(theirs, rel=1e-5)


def test_label_smoothing_matches_formula():
    s = 0.1
    ours = float(label_smoothing_cross_entropy(
        jnp.asarray(LOGITS), jnp.asarray(LABELS), smoothing=s))
    logp = F.log_softmax(torch.tensor(LOGITS), dim=-1)
    nll = -logp.gather(1, torch.tensor(LABELS, dtype=torch.long)[:, None])[:, 0]
    smooth = -logp.mean(dim=-1)
    theirs = float(((1 - s) * nll + s * smooth).mean())
    assert ours == pytest.approx(theirs, rel=1e-5)


def test_soft_target_matches_torch():
    target = rng.dirichlet((1.0, 1.0), size=12).astype(np.float32)
    ours = float(soft_target_cross_entropy(jnp.asarray(LOGITS),
                                           jnp.asarray(target)))
    logp = F.log_softmax(torch.tensor(LOGITS), dim=-1)
    theirs = float((-torch.tensor(target) * logp).sum(-1).mean())
    assert ours == pytest.approx(theirs, rel=1e-5)


def test_jsd_matches_torch():
    ours = float(jsd_cross_entropy(jnp.asarray(LOGITS), jnp.asarray(LABELS),
                                   num_splits=3, alpha=12.0, smoothing=0.1))
    x = torch.tensor(LOGITS)
    split = 4
    splits = torch.split(x, split)
    logp = F.log_softmax(splits[0], dim=-1)
    nll = -logp.gather(1, torch.tensor(LABELS[:split], dtype=torch.long)[:, None])[:, 0]
    ce = (0.9 * nll + 0.1 * -logp.mean(-1)).mean()
    probs = [F.softmax(s, dim=1) for s in splits]
    logp_mix = torch.clamp(torch.stack(probs).mean(0), 1e-7, 1).log()
    kl = sum(F.kl_div(logp_mix, p, reduction="batchmean") for p in probs) / 3
    theirs = float(ce + 12.0 * kl)
    assert ours == pytest.approx(theirs, rel=1e-4)


def test_masked_eval_padding():
    # padded rows must not change the loss (TPU static-shape eval pattern)
    w = jnp.asarray([1.0] * 8 + [0.0] * 4)
    full = float(cross_entropy(jnp.asarray(LOGITS[:8]), jnp.asarray(LABELS[:8])))
    masked = float(cross_entropy(jnp.asarray(LOGITS), jnp.asarray(LABELS),
                                 weight=w))
    assert masked == pytest.approx(full, rel=1e-6)


def test_one_hot_smoothing():
    oh = one_hot(jnp.asarray([0, 1]), 2, on_value=0.9, off_value=0.1)
    np.testing.assert_allclose(np.asarray(oh), [[0.9, 0.1], [0.1, 0.9]],
                               rtol=1e-6)


def test_selection_precedence():
    class Cfg:
        jsd = False
        mixup = 0.0
        smoothing = 0.0
        aug_splits = 0
    cfg = Cfg()
    assert create_loss_fn(cfg) is cross_entropy
    cfg.smoothing = 0.1
    assert create_loss_fn(cfg) is not cross_entropy
    cfg.mixup = 0.2
    assert create_loss_fn(cfg) is soft_target_cross_entropy
    cfg.jsd = True
    with pytest.raises(AssertionError):
        create_loss_fn(cfg)       # --jsd without --aug-splits is an error
    cfg.aug_splits = 3
    fn = create_loss_fn(cfg)
    assert fn is not soft_target_cross_entropy
