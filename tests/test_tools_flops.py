"""tools/flops_breakdown.py: the MXU/VPU classification must stay honest."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.smoke

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


def test_breakdown_classifies_depthwise_and_dots():
    env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "flops_breakdown.py"),
         "mnasnet_small", "--size", "64"],
        capture_output=True, text=True, env=env, timeout=300, check=True)
    r = json.loads(out.stdout)
    # mnasnet has both dense and depthwise convs; totals must be positive
    # and percentages sum to ~100
    assert r["total_gflops_fwd"] > 0
    assert r["conv_depthwise_vpu"]["pct"] > 0
    assert r["conv_dense_mxu"]["pct"] > 0
    pct = sum(v["pct"] for k, v in r.items() if isinstance(v, dict))
    assert abs(pct - 100.0) < 0.1
