"""tools/flops_breakdown.py: the MXU/VPU classification must stay honest."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.smoke

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


def _run(*args):
    env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "flops_breakdown.py"),
         *args],
        capture_output=True, text=True, env=env, timeout=300, check=True)
    return json.loads(out.stdout)


def test_breakdown_classifies_depthwise_and_dots():
    r = _run("mnasnet_small", "--size", "64")
    # mnasnet has both dense and depthwise convs; totals must be positive
    # and percentages sum to ~100
    assert r["total_gflops_fwd"] > 0
    assert r["conv_depthwise_vpu"]["pct"] > 0
    assert r["conv_dense_mxu"]["pct"] > 0
    pct = sum(v["pct"] for k, v in r.items()
              if isinstance(v, dict) and "pct" in v)
    assert abs(pct - 100.0) < 0.1
    # the stem is split out: a 3-channel 3x3 conv feeding 27 of 128 lanes
    (stem,) = r["stem"]
    assert stem["kernel"] == "3x3x3"
    assert stem["contraction_depth"] == 27
    assert 0.2 < stem["mxu_lane_occupancy"] < 0.22


def test_ceilings_band_and_s2d_reclassification():
    base = _run("mnasnet_small", "--size", "64", "--ceilings")
    c = base["ceilings"]
    # the unfused worst case can only be WORSE than the fused bound, and
    # both are proper fractions
    assert 0 < c["mfu_ceiling_unfused_worst"] \
        < c["mfu_ceiling_post_fusion"] <= 1.0
    assert c["dw_epilogue_extra_mb_per_sample"] > 0

    s2d = _run("mnasnet_small", "--size", "64", "--ceilings", "--stem-s2d")
    (stem,) = s2d["stem"]
    # the s2d stem is reclassified from the flag-built model's own jaxpr:
    # 2x2 kernel over 4C channels, 16/9 the taps of the embedded 3x3
    assert stem["kernel"] == "2x2x12"
    assert stem["contraction_depth"] == 48
    assert s2d["total_gflops_fwd"] >= base["total_gflops_fwd"]
    # MFU stays normalized to the STOCK model's useful FLOPs, so the s2d
    # compute ceiling prices the zero-tap overhead (layout wins are
    # measured, not modeled — PERF.md post-fusion roofline)
    assert s2d["ceilings"]["mfu_ceiling_post_fusion"] \
        <= c["mfu_ceiling_post_fusion"]
