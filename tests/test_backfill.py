"""Backfill subsystem tests (ISSUE 13).

Fast tier: the jax-free contracts — manifest build/validate/staleness,
lease contention and stale-lease expiry (the atomic link/rename CAS),
torn verdict-tail repair + mid-shard resume, done-marker idempotence,
exact books — plus the in-process runner e2e (balanced books, zero
steady-state recompiles, deterministic verdicts, lease-race chaos).

Slow tier (fresh-interpreter subprocess drives, chaos-e2e idiom):
SIGTERM mid-corpus → exit 75 → relaunch resumes at shard granularity
with books exactly balanced and verdicts identical (order-normalized)
to an unkilled run; same for the hard-death + torn-shard point through
the stale-lease path; and the bench --smoke gate.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.backfill

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from deepfake_detection_tpu.backfill import (                  # noqa: E402
    BackfillManifestStale, LeaseDir, ShardVerdictWriter,
    build_manifest_from_lists, build_manifest_from_pack, collect_books,
    load_manifest, manifest_entries, read_verdicts,
    verify_manifest_source)
from deepfake_detection_tpu.backfill.manifest import save_manifest  # noqa: E402
from deepfake_detection_tpu.backfill.writer import verdict_path  # noqa: E402

EXIT_PREEMPTED = 75


# ---------------------------------------------------------------------------
# corpus builders
# ---------------------------------------------------------------------------

def _write_lists(root, fake=5, real=4, frames=2):
    os.makedirs(root, exist_ok=True)
    for kind, n in (("fake", fake), ("real", real)):
        with open(os.path.join(root, f"{kind}_list.txt"), "w") as f:
            f.write("".join(f"c{c}:{frames}\n" for c in range(n)))


def _write_tree(root, fake=5, real=4, frames=2, size=32, seed=0):
    from PIL import Image
    rng = np.random.default_rng(seed)
    for kind, n in (("fake", fake), ("real", real)):
        for c in range(n):
            d = os.path.join(root, kind, f"c{c}")
            os.makedirs(d, exist_ok=True)
            for i in range(frames):
                Image.fromarray(rng.integers(
                    0, 255, (size, size, 3), dtype=np.uint8)).save(
                    os.path.join(d, f"{i}.jpg"), quality=92)
    _write_lists(root, fake=fake, real=real, frames=frames)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """Tiny JPEG tree + packed cache + manifest (module-shared)."""
    from deepfake_detection_tpu.data.packed import write_pack
    td = tmp_path_factory.mktemp("bf_corpus")
    root = str(td / "root")
    _write_tree(root, fake=7, real=6, frames=2, size=32)
    pack = str(td / "pack")
    write_pack(root, pack, image_size=0, frames_per_clip=2,
               shard_size=8, workers=2)
    manifest = build_manifest_from_pack(pack, shard_clips=4)
    mpath = str(td / "manifest.json")
    save_manifest(mpath, manifest)
    return {"root": root, "pack": pack, "manifest_path": mpath,
            "manifest": manifest}


def _cfg(corpus, out, **kw):
    from deepfake_detection_tpu.config import BackfillConfig
    kw.setdefault("model", "vit_tiny_patch16_224")
    kw.setdefault("batch_size", 8)      # conftest mesh = 8 devices
    kw.setdefault("workers", 2)
    return BackfillConfig(manifest=corpus["manifest_path"], out=str(out),
                          data_packed=corpus["pack"], **kw)


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------

class TestManifest:
    def test_from_lists_matches_pack_order(self, tmp_path, corpus):
        m_lists = build_manifest_from_lists(corpus["root"], shard_clips=4)
        m_pack = corpus["manifest"]
        assert [s["clips"] for s in m_lists["shards"]] == \
            [s["clips"] for s in m_pack["shards"]]
        assert m_lists["num_clips"] == 13 and len(m_lists["shards"]) == 4
        # different sources → different fingerprints (lists vs pack)
        assert m_lists["fingerprint"] != m_pack["fingerprint"]

    def test_roundtrip_and_validation(self, tmp_path):
        root = str(tmp_path / "r")
        _write_lists(root, fake=3, real=2)
        m = build_manifest_from_lists(root, shard_clips=2)
        path = str(tmp_path / "m.json")
        save_manifest(path, m)
        assert load_manifest(path) == m
        verify_manifest_source(m, roots=root)
        # structural damage is loud
        bad = dict(m, num_clips=99)
        save_manifest(path, bad)
        with pytest.raises(BackfillManifestStale, match="damaged"):
            load_manifest(path)
        dup = json.loads(json.dumps(m))
        dup["shards"][0]["clips"][0] = dup["shards"][-1]["clips"][-1]
        save_manifest(path, dup)
        with pytest.raises(BackfillManifestStale, match="twice"):
            load_manifest(path)

    def test_source_drift_is_loud(self, tmp_path, corpus):
        root = str(tmp_path / "r")
        _write_lists(root, fake=3, real=2)
        m = build_manifest_from_lists(root, shard_clips=2)
        with open(os.path.join(root, "fake_list.txt"), "a") as f:
            f.write("c99:2\n")
        with pytest.raises(BackfillManifestStale, match="changed"):
            verify_manifest_source(m, roots=root)
        # pack-sourced manifest against a different pack fingerprint
        with pytest.raises(BackfillManifestStale, match="fingerprint"):
            verify_manifest_source(m, pack_dir=corpus["pack"])

    def test_make_lists_cli_emits_manifest(self, tmp_path, corpus):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import make_lists
        out = str(tmp_path / "m.json")
        rc = make_lists.main([corpus["root"], "--manifest", out,
                              "--shard-clips", "5"])
        assert rc == 0
        m = load_manifest(out)
        assert m["num_clips"] == 13 and len(m["shards"]) == 3
        verify_manifest_source(m, roots=corpus["root"])
        # --packed routes the fingerprint to the pack index
        out2 = str(tmp_path / "m2.json")
        rc = make_lists.main([corpus["root"], "--manifest", out2,
                              "--shard-clips", "5", "--packed",
                              corpus["pack"]])
        assert rc == 0
        verify_manifest_source(load_manifest(out2),
                               pack_dir=corpus["pack"])


# ---------------------------------------------------------------------------
# leases
# ---------------------------------------------------------------------------

class TestLease:
    def test_contention_exactly_one_winner(self, tmp_path):
        a = LeaseDir(str(tmp_path), "a", ttl_s=30)
        b = LeaseDir(str(tmp_path), "b", ttl_s=30)
        wins = [a.acquire("s0"), b.acquire("s0")]
        assert sorted(wins) == [False, True]
        # the loser re-leases the NEXT shard instead
        loser = b if wins[0] else a
        assert loser.acquire("s1")

    def test_concurrent_contention(self, tmp_path):
        """Many threads race one shard: exactly one claim succeeds."""
        results = []
        owners = [LeaseDir(str(tmp_path), f"w{i}", ttl_s=30)
                  for i in range(8)]
        barrier = threading.Barrier(8)

        def race(ld):
            barrier.wait()
            results.append(ld.acquire("s0"))

        ts = [threading.Thread(target=race, args=(o,)) for o in owners]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert sum(results) == 1

    def test_stale_lease_expiry_and_steal(self, tmp_path):
        a = LeaseDir(str(tmp_path), "dead-host", ttl_s=5)
        b = LeaseDir(str(tmp_path), "b", ttl_s=5)
        assert a.acquire("s0")
        # a FRESH lease is respected
        assert not b.acquire("s0")
        # ...until its mtime ages past the TTL (a dead host stops
        # heartbeating); then exactly one contender re-leases it
        os.utime(a._lease_path("s0"), (1, 1))
        assert b.acquire("s0")
        assert b.last_steal["owner"] == "dead-host"
        assert b.still_owner("s0") and not a.still_owner("s0")

    def test_heartbeat_keeps_lease_live(self, tmp_path):
        a = LeaseDir(str(tmp_path), "a", ttl_s=1.0)
        b = LeaseDir(str(tmp_path), "b", ttl_s=1.0)
        assert a.acquire("s0")
        time.sleep(0.6)
        a.heartbeat("s0")
        time.sleep(0.6)
        assert not b.acquire("s0")    # beaten 0.6s ago < 1s TTL

    def test_done_marker_idempotent_and_final(self, tmp_path):
        a = LeaseDir(str(tmp_path), "a", ttl_s=30)
        b = LeaseDir(str(tmp_path), "b", ttl_s=30)
        assert a.acquire("s0")
        assert a.mark_done("s0", {"clips": 3})
        assert a.is_done("s0") and b.is_done("s0")
        assert a.done_record("s0")["clips"] == 3
        # done shards are never re-leased, by anyone, ever
        assert not a.acquire("s0") and not b.acquire("s0")
        # marking again is a no-op success
        assert a.mark_done("s0", {"clips": 3})

    def test_lost_lease_refuses_commit(self, tmp_path):
        a = LeaseDir(str(tmp_path), "a", ttl_s=5)
        b = LeaseDir(str(tmp_path), "b", ttl_s=5)
        assert a.acquire("s0")
        os.utime(a._lease_path("s0"), (1, 1))
        assert b.acquire("s0")        # stole it
        # the TTL-starved original must NOT commit over the stealer
        assert not a.mark_done("s0", {"clips": 3})
        assert not a.still_owner("s0")
        assert b.mark_done("s0", {"clips": 3})

    def test_pending_shards(self, tmp_path):
        m = {"shards": [{"id": "s0"}, {"id": "s1"}]}
        a = LeaseDir(str(tmp_path), "a", ttl_s=30)
        assert a.pending_shards(m) == ["s0", "s1"]
        assert a.acquire("s0") and a.mark_done("s0", {})
        assert a.pending_shards(m) == ["s1"]


# ---------------------------------------------------------------------------
# verdict writer + books
# ---------------------------------------------------------------------------

class TestWriter:
    def test_torn_tail_repaired_and_resumed(self, tmp_path):
        run = str(tmp_path)
        w = ShardVerdictWriter(run, "s0")
        w.append_many([("fake", 0, "c0", 0, 0.9, ""),
                       ("fake", 0, "c1", 0, None, "IOError: boom")])
        w.tear()                      # exactly a mid-write kill's damage
        w.close()
        w2 = ShardVerdictWriter(run, "s0")
        assert w2.torn_bytes_dropped > 0
        assert w2.scored_keys == {("fake", 0, "c0"), ("fake", 0, "c1")}
        assert w2.records == 2 and w2.failed == 1
        w2.append("real", 0, "c0", 1, 0.1)
        book = w2.finalize()
        w2.close()
        assert book == {"clips": 3, "scored": 2, "failed": 1,
                        "skipped_dup": 0, "sha256": book["sha256"]}
        # the incremental sha IS the file's content hash
        import hashlib
        with open(verdict_path(run, "s0"), "rb") as f:
            assert hashlib.sha256(f.read()).hexdigest() == book["sha256"]
        # every surviving record is schema-stamped and parseable
        recs = read_verdicts(verdict_path(run, "s0"))
        assert len(recs) == 3
        assert all(r["schema"] == "dfd.backfill.verdict.v1"
                   for r in recs)

    def test_books_name_discrepancies(self, tmp_path):
        run = str(tmp_path)
        root = str(tmp_path / "r")
        _write_lists(root, fake=2, real=1)
        m = build_manifest_from_lists(root, shard_clips=3)
        sid = m["shards"][0]["id"]
        lease = LeaseDir(run, "w", ttl_s=30)
        w = ShardVerdictWriter(run, sid)
        w.append("fake", 0, "c0", 0, 0.9)
        w.append("fake", 0, "c0", 0, 0.9)            # duplicate!
        w.append("fake", 0, "alien", 0, 0.9)         # not in manifest
        w.close()
        assert lease.acquire(sid) and lease.mark_done(sid, {})
        books = collect_books(run, m)
        assert not books["balanced"]
        assert books["duplicated"] == ["fake/0/c0"]
        assert books["alien"] == ["fake/0/alien"]
        assert "real/0/c0" in books["missing"]


# ---------------------------------------------------------------------------
# runner (in-process)
# ---------------------------------------------------------------------------

class TestRunner:
    def test_full_corpus_books_balance_zero_recompiles(self, tmp_path,
                                                       corpus):
        from deepfake_detection_tpu.runners.backfill import run_backfill
        s = run_backfill(_cfg(corpus, tmp_path / "run"))
        assert s["books"]["balanced"], s["books"]
        assert s["steady_recompiles"] == 0
        assert s["clips_this_proc"] == 13
        # relaunch over a finished corpus is a cheap no-op
        s2 = run_backfill(_cfg(corpus, tmp_path / "run"))
        assert s2["shards_this_proc"] == 0
        assert s2["books"]["balanced"]
        # telemetry carries per-shard records + lifecycle events (one
        # stream per worker, named by the lease owner)
        import glob
        tele = glob.glob(str(tmp_path / "run" / "telemetry-*.jsonl"))
        assert len(tele) == 1, tele
        recs = [json.loads(l) for l in open(tele[0])]
        kinds = [r.get("event") or r["type"] for r in recs]
        assert kinds[0] == "run_start" and "run_end" in kinds
        shard_recs = [r for r in recs if r["type"] == "metrics"]
        assert {r["shard"] for r in shard_recs} == \
            {sh["id"] for sh in corpus["manifest"]["shards"]}
        assert all(r["backend_compiles"] == 0 for r in shard_recs)

    def test_dedup_books_skipped_dup_against_manifest(self, tmp_path):
        """--dedup (ISSUE 17): byte-identical clips skip the device and
        book skipped_dup rows naming the canonical clip — books balance
        with the third term, no clip silently absent."""
        import shutil
        from deepfake_detection_tpu.data.packed import write_pack
        from deepfake_detection_tpu.runners.backfill import run_backfill
        root = str(tmp_path / "root")
        _write_tree(root, fake=5, real=4, frames=2, size=32, seed=3)
        # byte-copy three clips: identical JPEG bytes decode to
        # identical pixels, so the pack slabs collide on content hash
        for src, dst in (("fake/c0", "fake/c3"), ("fake/c0", "fake/c4"),
                         ("real/c1", "real/c2")):
            shutil.rmtree(os.path.join(root, dst))
            shutil.copytree(os.path.join(root, src),
                            os.path.join(root, dst))
        pack = str(tmp_path / "pack")
        write_pack(root, pack, image_size=0, frames_per_clip=2,
                   shard_size=8, workers=2)
        manifest = build_manifest_from_pack(pack, shard_clips=4)
        mpath = str(tmp_path / "manifest.json")
        save_manifest(mpath, manifest)
        dup_corpus = {"pack": pack, "manifest_path": mpath,
                      "manifest": manifest}
        run = tmp_path / "run"
        s = run_backfill(_cfg(dup_corpus, run, dedup=True))
        b = s["books"]
        assert b["balanced"], b
        assert b["skipped_dup"] == 3
        assert b["scored"] + b["failed"] + b["skipped_dup"] == \
            b["manifest_clips"] == 9
        assert s["skipped_dup_this_proc"] == 3
        assert s["steady_recompiles"] == 0
        recs = []
        for sh in manifest["shards"]:
            recs += read_verdicts(verdict_path(str(run), sh["id"]))
        skips = [r for r in recs if r.get("skipped_dup")]
        assert len(skips) == 3
        # every skip names a canonical clip that was actually SCORED
        # (never a chain of skips, never a failed clip)
        scored = {f"{r['kind']}/{r['root']}/{r['clip']}"
                  for r in recs if r.get("ok")}
        assert all(r["dup_of"] in scored for r in skips)
        assert all(r["score"] is None and not r["ok"] for r in skips)

    @pytest.mark.slow   # tier-1 budget: a second full corpus run (~3 s)
    # re-proving determinism the slow-tier kill/resume identity drive
    # also pins; the books/zero-recompile runner e2e stays fast
    def test_verdicts_deterministic_across_runs(self, tmp_path, corpus):
        from deepfake_detection_tpu.runners.backfill import run_backfill

        def norm(run_dir):
            recs = []
            for sh in corpus["manifest"]["shards"]:
                recs += read_verdicts(verdict_path(str(run_dir),
                                                   sh["id"]))
            return sorted(json.dumps(r, sort_keys=True) for r in recs)

        run_backfill(_cfg(corpus, tmp_path / "a"))
        run_backfill(_cfg(corpus, tmp_path / "b"))
        assert norm(tmp_path / "a") == norm(tmp_path / "b")
        rec = json.loads(norm(tmp_path / "a")[0])
        assert 0.0 <= rec["score"] <= 1.0 and rec["ok"]

    def test_lease_race_chaos_loses_cleanly_then_steals(self, tmp_path,
                                                        corpus,
                                                        monkeypatch):
        from deepfake_detection_tpu.runners.backfill import run_backfill
        # a rival leases the first shard an instant before us: our
        # acquire must lose, the corpus must still complete (the rival's
        # abandoned lease expires by TTL and is re-leased)
        monkeypatch.setenv("DFD_CHAOS", "backfill_lease_race@0")
        s = run_backfill(_cfg(corpus, tmp_path / "run",
                              lease_ttl_s=1.5))
        assert s["books"]["balanced"], s["books"]
        assert s["lease_steals"] >= 1

    def test_stale_source_refuses_to_run(self, tmp_path, corpus):
        from deepfake_detection_tpu.runners.backfill import run_backfill
        m = json.loads(json.dumps(corpus["manifest"]))
        m["source"]["fingerprint"] = "0" * 64
        m["fingerprint"] = "1" * 64
        mpath = str(tmp_path / "stale.json")
        save_manifest(mpath, m)
        from deepfake_detection_tpu.config import BackfillConfig
        cfg = BackfillConfig(manifest=mpath, out=str(tmp_path / "run"),
                             data_packed=corpus["pack"],
                             model="vit_tiny_patch16_224", batch_size=8)
        with pytest.raises(BackfillManifestStale):
            run_backfill(cfg)

    def test_failed_clips_are_booked_not_fatal(self, tmp_path):
        """Raw-tree source with one undecodable clip: ONE failed book
        entry, the corpus still completes balanced."""
        from deepfake_detection_tpu.runners.backfill import run_backfill
        from deepfake_detection_tpu.config import BackfillConfig
        root = str(tmp_path / "root")
        _write_tree(root, fake=3, real=2, frames=2, size=32)
        m = build_manifest_from_lists(root, shard_clips=3)
        mpath = str(tmp_path / "m.json")
        save_manifest(mpath, m)
        os.remove(os.path.join(root, "fake", "c1", "1.jpg"))
        cfg = BackfillConfig(manifest=mpath, out=str(tmp_path / "run"),
                             data=root, frames=2,
                             model="vit_tiny_patch16_224", batch_size=8,
                             workers=2)
        s = run_backfill(cfg)
        assert s["books"]["balanced"], s["books"]
        assert s["books"]["failed"] == 1
        failed = [r for sh in m["shards"]
                  for r in read_verdicts(
                      verdict_path(str(tmp_path / "run"), sh["id"]))
                  if not r["ok"]]
        assert len(failed) == 1 and failed[0]["clip"] == "c1"
        assert "err" in failed[0] and failed[0]["score"] is None

    def test_nonfinite_scores_booked_failed_not_fatal(self, tmp_path,
                                                      corpus,
                                                      monkeypatch):
        """A model emitting NaN probabilities must cost failed book
        entries (the serving engine's never-serve-NaN contract), not a
        strict-JSON writer crash + relaunch loop."""
        import deepfake_detection_tpu.runners.backfill as bf_mod
        from deepfake_detection_tpu.runners.backfill import run_backfill
        monkeypatch.setattr(
            bf_mod._Pipeline, "dispatch",
            lambda self, slab: np.full((self.batch, 2), np.nan,
                                       np.float32))
        s = run_backfill(_cfg(corpus, tmp_path / "run"))
        assert s["books"]["balanced"], s["books"]
        assert s["books"]["failed"] == corpus["manifest"]["num_clips"]
        assert s["failed_this_proc"] == corpus["manifest"]["num_clips"]
        recs = [r for sh in corpus["manifest"]["shards"]
                for r in read_verdicts(
                    verdict_path(str(tmp_path / "run"), sh["id"]))]
        assert all(not r["ok"] and r["score"] is None and
                   "NonFinite" in r["err"] for r in recs)

    def test_obs_report_renders_backfill_table(self, tmp_path, corpus,
                                               capsys):
        from deepfake_detection_tpu.runners.backfill import run_backfill
        run_backfill(_cfg(corpus, tmp_path / "run"))
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import obs_report
        obs_report.main([str(tmp_path / "run")])
        out = capsys.readouterr().out
        assert "backfill" in out and "BALANCED" in out
        assert "shard-00000" in out and "clips/s" in out


# ---------------------------------------------------------------------------
# fresh-interpreter chaos e2e (slow tier)
# ---------------------------------------------------------------------------

def _spawn_backfill(args, chaos="", timeout=600):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("DFD_CHAOS", None)
    if chaos:
        env["DFD_CHAOS"] = chaos
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PYTHONPATH"] = REPO
    import jax
    env["JAX_COMPILATION_CACHE_DIR"] = str(
        jax.config.jax_compilation_cache_dir or "")
    return subprocess.run(
        [sys.executable, "-m", "deepfake_detection_tpu.runners.backfill",
         *args], cwd=REPO, env=env, capture_output=True, text=True,
        timeout=timeout)


@pytest.mark.slow
@pytest.mark.parametrize("fault,expect", [
    ("backfill_kill@1", EXIT_PREEMPTED),      # SIGTERM: graceful stop
    ("backfill_torn_shard@1", 137),           # hard death: torn tail +
])                                            # abandoned lease
def test_kill_midcorpus_resumes_with_exact_books(tmp_path, corpus,
                                                 fault, expect):
    """The acceptance-criterion e2e: a worker dies mid-corpus, the
    relaunch resumes at shard granularity, books balance EXACTLY, and
    the verdict JSONL is identical (order-normalized) to an unkilled
    run's."""
    base = ["--manifest", corpus["manifest_path"],
            "--data-packed", corpus["pack"],
            "--model", "vit_tiny_patch16_224", "--batch-size", "4",
            "--workers", "2", "--lease-ttl-s", "2"]
    out = str(tmp_path / "run")
    r = _spawn_backfill(base + ["--out", out], chaos=fault)
    assert r.returncode == expect, \
        f"rc={r.returncode}\n{r.stdout[-2000:]}\n{r.stderr[-2000:]}"
    if expect != EXIT_PREEMPTED:
        # hard death leaves the lease behind; expiry re-leases it
        time.sleep(2.1)
    r2 = _spawn_backfill(base + ["--out", out])
    assert r2.returncode == 0, \
        f"rc={r2.returncode}\n{r2.stdout[-2000:]}\n{r2.stderr[-2000:]}"
    books = collect_books(out, corpus["manifest"])
    assert books["balanced"], books

    ref = str(tmp_path / "ref")
    r3 = _spawn_backfill(base + ["--out", ref])
    assert r3.returncode == 0

    def norm(run_dir):
        recs = []
        for sh in corpus["manifest"]["shards"]:
            recs += read_verdicts(verdict_path(run_dir, sh["id"]))
        return sorted(json.dumps(r, sort_keys=True) for r in recs)

    killed, clean = norm(out), norm(ref)
    assert len(clean) == corpus["manifest"]["num_clips"]
    assert killed == clean


@pytest.mark.slow
def test_chaos_harness_backfill_scenario(tmp_path, corpus):
    """tools/chaos.py's backfill scenario drives the same contract as a
    CLI (the operator runbook path)."""
    import jax
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PYTHONPATH"] = REPO
    env["JAX_COMPILATION_CACHE_DIR"] = str(
        jax.config.jax_compilation_cache_dir or "")
    out = str(tmp_path / "run")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "backfill", "--fault", "backfill_kill@1", "--",
         sys.executable, "-m", "deepfake_detection_tpu.runners.backfill",
         "--manifest", corpus["manifest_path"],
         "--data-packed", corpus["pack"], "--out", out,
         "--model", "vit_tiny_patch16_224", "--batch-size", "4",
         "--workers", "2", "--lease-ttl-s", "2"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, f"{r.stdout[-3000:]}\n{r.stderr[-2000:]}"
    assert "PASS" in r.stdout


@pytest.mark.slow
def test_bench_backfill_smoke(tmp_path):
    """The verify-recipe row: tiny corpus through both pipelines, books
    balanced, zero steady-state recompiles asserted by the bench."""
    import jax
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    env["JAX_COMPILATION_CACHE_DIR"] = str(
        jax.config.jax_compilation_cache_dir or "")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_backfill.py"),
         "--smoke"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, f"{r.stdout[-3000:]}\n{r.stderr[-2000:]}"
    assert "backfill host-path ceiling" in r.stdout
