"""dfdlint tests: per-rule good/bad fixtures, suppression + baseline
semantics, import-graph cycles, and the whole-package gate.

The gate test is the contract ISSUE 11 asks for: running dfdlint over
``deepfake_detection_tpu`` + ``tools`` with the checked-in baseline must
produce ZERO non-baselined violations AND zero rot — every baseline
entry must still match a live violation and every inline suppression
must still suppress one.  Deleting any single suppression or baseline
entry therefore fails this test: the suppressed/baselined violation
resurfaces as `new` (or the entry itself reports as unused rot).

One subprocess canary validates the DFD001 static import graph against
reality (it replaced the per-module subprocess import tests that used to
live in test_packed_data.py / test_obs.py).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.lint

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
sys.path.insert(0, REPO)

from deepfake_detection_tpu.lint import (  # noqa: E402
    BaselineEntry, LintConfig, ProjectIndex, default_config, load_baseline,
    run_lint, save_baseline)
from deepfake_detection_tpu.lint import rules as R  # noqa: E402


# ---------------------------------------------------------------------------
# fixture helpers
# ---------------------------------------------------------------------------

def make_index(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return ProjectIndex.build([str(tmp_path)], str(tmp_path))


def lint_one(tmp_path, files, rule, config=None, **kw):
    index = make_index(tmp_path, files)
    return run_lint(index, config or LintConfig(), rules=[rule], **kw)


def rule_ids(result):
    return sorted({v.rule for v in result.violations})


# ---------------------------------------------------------------------------
# DFD001 jax purity
# ---------------------------------------------------------------------------

class TestJaxPurity:
    RULE = R.JaxPurity()

    def test_direct_import_fires(self, tmp_path):
        res = lint_one(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/a.py": "import os\nimport jax\n",
        }, self.RULE, LintConfig(jax_free_modules=("pkg.a",)))
        assert [v.rule for v in res.violations] == ["DFD001"]
        assert "pkg.a" in res.violations[0].message

    def test_transitive_and_ancestor_reach(self, tmp_path):
        # a -> b -> flax, and separately an ancestor __init__ that
        # imports jax poisons every submodule declared jax-free
        res = lint_one(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/a.py": "from . import b\n",
            "pkg/b.py": "import flax\n",
            "pkg2/__init__.py": "import jax\n",
            "pkg2/c.py": "import os\n",
        }, self.RULE, LintConfig(jax_free_modules=("pkg.a", "pkg2.c")))
        msgs = " | ".join(v.message for v in res.violations)
        assert len(res.violations) == 2
        assert "pkg.a -> pkg.b" in msgs and "flax" in msgs
        assert "pkg2" in msgs

    def test_lazy_and_type_checking_imports_pass(self, tmp_path):
        res = lint_one(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/a.py": """\
                from typing import TYPE_CHECKING
                if TYPE_CHECKING:
                    import jax
                def f():
                    import jax.numpy as jnp      # lazy: fine
                    return jnp
                def __getattr__(name):
                    import importlib
                    return importlib.import_module('.b', __name__)
            """,
        }, self.RULE, LintConfig(jax_free_modules=("pkg.a",)))
        assert res.violations == []

    def test_import_cycle_terminates_cleanly(self, tmp_path):
        files = {
            "pkg/__init__.py": "",
            "pkg/a.py": "from . import b\n",
            "pkg/b.py": "from . import a\n",
        }
        res = lint_one(tmp_path, files, self.RULE,
                       LintConfig(jax_free_modules=("pkg.a",)))
        assert res.violations == []          # cycle, but no jax: clean
        files["pkg/b.py"] = "from . import a\nimport jax\n"
        res = lint_one(tmp_path, files, self.RULE,
                       LintConfig(jax_free_modules=("pkg.a",)))
        assert [v.rule for v in res.violations] == ["DFD001"]

    def test_manifest_rot_when_module_missing(self, tmp_path):
        res = lint_one(tmp_path, {"pkg/__init__.py": ""}, self.RULE,
                       LintConfig(jax_free_modules=("pkg.gone",)))
        assert len(res.violations) == 1
        assert "not found" in res.violations[0].message


# ---------------------------------------------------------------------------
# DFD002 donation aliasing
# ---------------------------------------------------------------------------

class TestDonationAliasing:
    RULE = R.DonationAliasing()

    def test_read_after_donate_fires(self, tmp_path):
        res = lint_one(tmp_path, {"m.py": """\
            import jax
            def f(state, batch):
                step = jax.jit(run, donate_argnums=(0,))
                new_state, m = step(state, batch)
                return state.params
        """}, self.RULE)
        assert [v.rule for v in res.violations] == ["DFD002"]
        assert "`state` read after being donated" in res.violations[0].message

    def test_rebind_same_statement_passes(self, tmp_path):
        res = lint_one(tmp_path, {"m.py": """\
            import jax
            def f(state, batch):
                step = jax.jit(run, donate_argnums=(0,))
                state, m = step(state, batch)
                return state.params
        """}, self.RULE)
        assert res.violations == []

    def test_donate_argnames_no_crash_and_keyword_match(self, tmp_path):
        """String donate_argnames must not TypeError the run; a keyword-
        passed donated arg is traced, and positional args (whose name
        mapping needs the callee signature) are skipped, not crashed."""
        res = lint_one(tmp_path, {"m.py": """\
            import jax
            def f(state, batch):
                step = jax.jit(run, donate_argnames=("state",))
                out = step(batch, state=state)
                return state.params
            def g(state, batch):
                step = jax.jit(run, donate_argnames=("state",))
                out = step(state, batch)
                return state.params
        """}, self.RULE)
        assert [(v.rule, v.line) for v in res.violations] == [("DFD002", 5)]

    def test_factory_donation_from_manifest(self, tmp_path):
        cfg = LintConfig(donating_factories={"make_train_step": (0,)})
        res = lint_one(tmp_path, {"m.py": """\
            def f(model, state, x):
                step = make_train_step(model)
                out = step(state, x)
                print(state)
        """}, self.RULE, cfg)
        assert [v.rule for v in res.violations] == ["DFD002"]

    def test_view_escape_to_thread_fires_and_copy_passes(self, tmp_path):
        res = lint_one(tmp_path, {"bad.py": """\
            import threading, numpy as np
            def save(buf, pool):
                view = np.frombuffer(buf, np.uint8)
                threading.Thread(target=write, args=(view,)).start()
                pool.submit(write, np.asarray(buf))
        """}, self.RULE)
        assert [v.rule for v in res.violations] == ["DFD002", "DFD002"]
        res = lint_one(tmp_path / "good", {"good.py": """\
            import threading, numpy as np
            def save(buf):
                view = np.frombuffer(buf, np.uint8)
                view = view.copy()
                threading.Thread(target=write, args=(view,)).start()
        """}, self.RULE)
        assert res.violations == []


# ---------------------------------------------------------------------------
# DFD003 RNG discipline
# ---------------------------------------------------------------------------

class TestRngDiscipline:
    RULE = R.RngDiscipline()
    CFG = LintConfig(rng_dirs=("pkg",))

    def test_naked_and_unseeded_fire(self, tmp_path):
        res = lint_one(tmp_path, {"pkg/m.py": """\
            import random, time
            import numpy as np
            def f():
                a = np.random.uniform(0, 1)
                rng = np.random.default_rng()
                b = random.random()
                c = np.random.default_rng(int(time.time()))
                return a, b, c, rng
        """}, self.RULE, self.CFG)
        assert [v.rule for v in res.violations] == ["DFD003"] * 4
        msgs = " | ".join(v.message for v in res.violations)
        assert "naked global-RNG" in msgs and "unseeded" in msgs \
            and "time-seeded" in msgs

    def test_derived_and_injected_pass(self, tmp_path):
        res = lint_one(tmp_path, {"pkg/m.py": """\
            import random
            import numpy as np
            def f(seed, epoch, index, rng):
                g = np.random.default_rng(
                    np.random.SeedSequence([seed, epoch, index]))
                r = random.Random(0x5EED)
                return g.uniform(), rng.normal(), r.random()
        """}, self.RULE, self.CFG)
        assert res.violations == []

    def test_outside_declared_dirs_ignored(self, tmp_path):
        res = lint_one(tmp_path, {"other/m.py": """\
            import numpy as np
            def f():
                return np.random.uniform()
        """}, self.RULE, self.CFG)
        assert res.violations == []


# ---------------------------------------------------------------------------
# DFD004 recompile hygiene
# ---------------------------------------------------------------------------

class TestRecompileHygiene:
    RULE = R.RecompileHygiene()

    def test_jit_in_loop_fires(self, tmp_path):
        res = lint_one(tmp_path, {"m.py": """\
            import jax
            def warm(buckets, score):
                for b in buckets:
                    f = jax.jit(score)
                return f
        """}, self.RULE)
        assert [v.rule for v in res.violations] == ["DFD004"]

    def test_hoisted_jit_passes(self, tmp_path):
        res = lint_one(tmp_path, {"m.py": """\
            import jax
            def warm(buckets, score):
                f = jax.jit(score)
                for b in buckets:
                    f(b)
                return f
        """}, self.RULE)
        assert res.violations == []

    def test_array_closure_fires(self, tmp_path):
        res = lint_one(tmp_path, {"m.py": """\
            import jax
            import jax.numpy as jnp
            def make(x, params):
                w = jnp.asarray(x)
                @jax.jit
                def f(a):
                    return a + w + params["k"]
                return f
        """}, self.RULE)
        assert [v.rule for v in res.violations] == ["DFD004", "DFD004"]
        msgs = " | ".join(v.message for v in res.violations)
        assert "`w`" in msgs and "`params`" in msgs

    def test_arrays_as_arguments_pass(self, tmp_path):
        res = lint_one(tmp_path, {"m.py": """\
            import jax
            import jax.numpy as jnp
            def make(model, use_ema):
                @jax.jit
                def f(params, a):
                    if use_ema:                     # scalar capture: fine
                        return model.apply(params, a)
                    return a
                return f
        """}, self.RULE)
        assert res.violations == []


# ---------------------------------------------------------------------------
# DFD005 metric hygiene
# ---------------------------------------------------------------------------

class TestMetricHygiene:
    RULE = R.MetricHygiene()

    def cfg(self):
        return LintConfig(
            metric_registries={"metrics.py": "dfd_serving"},
            lock_guarded=(("engine.py", "inflight", "_pending_lock"),))

    METRICS = """\
        def render(doc):
            doc.counter("scored_total", "h", 1)
            doc.gauge("inflight", "h", 0)
            doc.histogram("latency_seconds", "h", None)
    """

    def test_duplicate_registration_fires(self, tmp_path):
        res = lint_one(tmp_path, {"metrics.py": """\
            def render(doc):
                doc.counter("scored_total", "h", 1)
                doc.gauge("scored_total", "h", 2)
        """}, self.RULE, self.cfg())
        assert [v.rule for v in res.violations] == ["DFD005"]
        assert "more than once" in res.violations[0].message

    def test_unregistered_reference_fires_registered_passes(self, tmp_path):
        res = lint_one(tmp_path, {
            "metrics.py": self.METRICS,
            "probe.py": """\
                OK = ("dfd_serving_scored_total",
                      "dfd_serving_latency_seconds_bucket",
                      "dfd_other_not_a_registry")
                BAD = "dfd_serving_scoerd_total"
            """,
        }, self.RULE, self.cfg())
        assert [v.rule for v in res.violations] == ["DFD005"]
        assert "dfd_serving_scoerd_total" in res.violations[0].message

    def test_dynamic_prefix_exempt(self, tmp_path):
        cfg = self.cfg()
        cfg.metric_dynamic_prefixes = ("dfd_serving_input_",)
        res = lint_one(tmp_path, {
            "metrics.py": self.METRICS,
            "probe.py": "X = 'dfd_serving_input_anything_total'\n",
        }, self.RULE, cfg)
        assert res.violations == []

    def test_unguarded_gauge_mutation_fires(self, tmp_path):
        res = lint_one(tmp_path, {"engine.py": """\
            class E:
                def bump(self, n):
                    self.metrics.inflight += n
                def ok(self, n):
                    with self._pending_lock:
                        self.metrics.inflight -= n
        """}, self.RULE, self.cfg())
        assert [(v.rule, v.line) for v in res.violations] == [("DFD005", 3)]


# ---------------------------------------------------------------------------
# DFD006 chaos registry
# ---------------------------------------------------------------------------

class TestChaosRegistry:
    RULE = R.ChaosRegistry()
    CFG = LintConfig(chaos_module="chaos.py")

    def test_unknown_point_and_spec_fire(self, tmp_path):
        res = lint_one(tmp_path, {
            "chaos.py": "KNOWN_POINTS = frozenset({'boom', 'stall'})\n",
            "use.py": """\
                def f(inj, step):
                    if inj.fires("bom", step):
                        pass
                SPEC = "stall@3,explode@5x2"
            """,
        }, self.RULE, self.CFG)
        assert [v.rule for v in res.violations] == ["DFD006", "DFD006"]
        msgs = " | ".join(v.message for v in res.violations)
        assert "'bom'" in msgs and "'explode'" in msgs

    def test_known_points_pass(self, tmp_path):
        res = lint_one(tmp_path, {
            "chaos.py": "KNOWN_POINTS = frozenset({'boom', 'stall'})\n",
            "use.py": """\
                def f(inj, step):
                    return inj.fires("boom", step)
                SPEC = "stall@3x2:1.5"
            """,
        }, self.RULE, self.CFG)
        assert res.violations == []

    def test_missing_registry_fires(self, tmp_path):
        res = lint_one(tmp_path, {
            "use.py": "def f(inj):\n    return inj.fires('boom', 1)\n",
        }, self.RULE, self.CFG)
        assert [v.rule for v in res.violations] == ["DFD006"]
        assert "no KNOWN_POINTS registry" in res.violations[0].message


# ---------------------------------------------------------------------------
# DFD007 event-schema discipline
# ---------------------------------------------------------------------------

class TestEventSchema:
    RULE = R.EventSchema()

    def test_missing_flush_and_schema_fire(self, tmp_path):
        res = lint_one(tmp_path, {"w.py": """\
            import json
            class Log:
                def emit(self, rec):
                    line = json.dumps(rec) + "\\n"
                    self._f.write(line)
            def other(f):
                rec = {"a": 1}
                f.write(json.dumps(rec) + "\\n")
                f.flush()
        """}, self.RULE)
        assert [v.rule for v in res.violations] == ["DFD007", "DFD007"]
        msgs = " | ".join(v.message for v in res.violations)
        assert "without a flush()" in msgs and "schema" in msgs

    def test_append_without_newline_fires(self, tmp_path):
        res = lint_one(tmp_path, {"w.py": """\
            import json
            def emit(path, rec):
                with open(path, "a") as f:
                    f.write(json.dumps(rec))
        """}, self.RULE)
        assert [v.rule for v in res.violations] == ["DFD007"]
        assert "not newline-terminated" in res.violations[0].message

    def test_events_py_idiom_passes(self, tmp_path):
        res = lint_one(tmp_path, {"w.py": """\
            import json
            class Log:
                def emit(self, extra):
                    rec = {"v": 1, "x": extra}
                    line = json.dumps(rec) + "\\n"
                    self._f.write(line)
                    self._f.flush()
            def snapshot(path, state):
                with open(path, "w") as f:        # whole-file, not JSONL
                    f.write(json.dumps(state))
            def bench_rows(path, rows):
                with open(path, "a") as f:        # with-managed: close
                    for r in rows:                # flushes
                        f.write(json.dumps(r) + "\\n")
        """}, self.RULE)
        assert res.violations == []


# ---------------------------------------------------------------------------
# DFD008 subprocess discipline
# ---------------------------------------------------------------------------

class TestSubprocessDiscipline:
    RULE = R.SubprocessDiscipline()

    def test_run_without_timeout_and_unowned_popen_fire(self, tmp_path):
        res = lint_one(tmp_path, {"t.py": """\
            import subprocess
            def f(cmd):
                subprocess.run(cmd)
                return subprocess.Popen(cmd)
        """}, self.RULE)
        assert [v.rule for v in res.violations] == ["DFD008", "DFD008"]

    def test_timeout_and_kill_escalation_pass(self, tmp_path):
        res = lint_one(tmp_path, {"t.py": """\
            import subprocess
            def f(cmd):
                subprocess.run(cmd, timeout=60)
                p = subprocess.Popen(cmd)
                try:
                    p.wait(timeout=10)
                finally:
                    p.terminate()
                    p.kill()
        """}, self.RULE)
        assert res.violations == []


# ---------------------------------------------------------------------------
# DFD009 ctypes ABI
# ---------------------------------------------------------------------------

class TestCtypesAbi:
    RULE = R.CtypesAbi()

    def test_unprobed_binding_fires(self, tmp_path):
        res = lint_one(tmp_path, {"b.py": """\
            import ctypes
            lib = ctypes.PyDLL("libdfd_native.so")
            lib.dfd_warp_affine.argtypes = []
        """}, self.RULE)
        assert [v.rule for v in res.violations] == ["DFD009"]

    def test_probed_binding_and_exempt_module_pass(self, tmp_path):
        cfg = LintConfig(ctypes_exempt=("native.py",))
        res = lint_one(tmp_path, {
            "b.py": """\
                import ctypes
                lib = ctypes.PyDLL("libdfd_native.so")
                assert lib.dfd_abi_version() == 3
                lib.dfd_warp_affine.argtypes = []
            """,
            "native.py": """\
                import ctypes
                lib = ctypes.CDLL("libdfd_native.so")
                lib.dfd_decode.argtypes = []
            """,
        }, self.RULE, cfg)
        assert res.violations == []


# ---------------------------------------------------------------------------
# DFD010 sharding hygiene
# ---------------------------------------------------------------------------

class TestShardingHygiene:
    RULE = R.ShardingHygiene()

    def test_bare_shard_map_and_pmap_fire(self, tmp_path):
        res = lint_one(tmp_path, {"t.py": """\
            import jax
            from jax.experimental.shard_map import shard_map
            def f(body, mesh, specs):
                g = shard_map(body, mesh=mesh, in_specs=specs,
                              out_specs=specs)
                h = jax.pmap(body)
                return g, h
        """}, self.RULE)
        assert [v.rule for v in res.violations] == ["DFD010", "DFD010"]

    def test_bare_decorator_form_fires(self, tmp_path):
        """@jax.pmap with no arguments is an Attribute in decorator_list,
        not a Call — the rule must still see it."""
        res = lint_one(tmp_path, {"t.py": """\
            import jax
            @jax.pmap
            def step(x):
                return x + 1
        """}, self.RULE)
        assert [v.rule for v in res.violations] == ["DFD010"]

    def test_partial_argument_form_fires(self, tmp_path):
        """functools.partial(jax.pmap, ...) passes pmap as a Call ARGUMENT
        — reference-level matching must catch it (and a direct call must
        yield exactly one violation, not Name+Call double-counted)."""
        res = lint_one(tmp_path, {"t.py": """\
            import functools
            import jax
            def f(fn):
                return functools.partial(jax.pmap, axis_name="batch")(fn)
        """}, self.RULE)
        assert [v.rule for v in res.violations] == ["DFD010"]

    def test_allowlisted_file_and_jit_path_pass(self, tmp_path):
        cfg = LintConfig(shard_map_allowlist=("ring.py",))
        res = lint_one(tmp_path, {
            "ring.py": """\
                from jax.experimental.shard_map import shard_map
                def ring(body, mesh, specs):
                    return shard_map(body, mesh=mesh, in_specs=specs,
                                     out_specs=specs)
            """,
            "unified.py": """\
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P
                def step(fn, mesh, shardings):
                    return jax.jit(fn, in_shardings=shardings,
                                   out_shardings=shardings,
                                   donate_argnums=(0,))
            """,
        }, self.RULE, cfg)
        assert res.violations == []

    def test_allowlist_rot_fires(self, tmp_path):
        """An allowlist entry whose file no longer shard_maps is rot: the
        frozen debt was paid, so the manifest line must go."""
        cfg = LintConfig(shard_map_allowlist=("clean.py",))
        res = lint_one(tmp_path, {"clean.py": """\
            import jax
            def f(fn):
                return jax.jit(fn)
        """}, self.RULE, cfg)
        assert [v.rule for v in res.violations] == ["DFD010"]
        assert "rot" in res.violations[0].message

    def test_allowlist_rot_skips_unindexed_files(self, tmp_path):
        """A subset run (`dfdlint some/dir`) must not call entries rotten
        for files it never looked at."""
        cfg = LintConfig(shard_map_allowlist=("elsewhere/ring.py",))
        res = lint_one(tmp_path, {"clean.py": """\
            import jax
            def f(fn):
                return jax.jit(fn)
        """}, self.RULE, cfg)
        assert res.violations == []

    def test_unrelated_names_do_not_fire(self, tmp_path):
        """shard_map_check_kwargs / pmean etc. share substrings with the
        banned callees but are not manual-SPMD dispatch."""
        res = lint_one(tmp_path, {"t.py": """\
            from compat import shard_map_check_kwargs
            def f(x, pmean):
                kw = shard_map_check_kwargs(True)
                return pmean(x), kw
        """}, self.RULE)
        assert res.violations == []


# ---------------------------------------------------------------------------
# suppression + baseline semantics
# ---------------------------------------------------------------------------

class TestSuppressionSemantics:
    RULE = R.RngDiscipline()
    CFG = LintConfig(rng_dirs=("pkg",))

    SRC = """\
        import numpy as np
        def f():
            a = np.random.uniform()  # dfdlint: disable=DFD003
            # dfdlint: disable=DFD003
            b = np.random.uniform()
            c = np.random.uniform()
            return a, b, c
    """

    def test_inline_and_comment_above_suppress(self, tmp_path):
        res = lint_one(tmp_path, {"pkg/m.py": self.SRC}, self.RULE,
                       self.CFG)
        assert len(res.violations) == 1 and res.violations[0].line == 6
        assert len(res.suppressed) == 2
        assert res.unused_suppressions == []

    def test_ignoring_suppressions_resurfaces_all(self, tmp_path):
        res = lint_one(tmp_path, {"pkg/m.py": self.SRC}, self.RULE,
                       self.CFG, honor_suppressions=False)
        assert len(res.violations) == 3

    def test_unused_suppression_is_rot(self, tmp_path):
        res = lint_one(tmp_path, {"pkg/m.py": """\
            import numpy as np
            def f(rng):
                return rng.uniform()  # dfdlint: disable=DFD003
        """}, self.RULE, self.CFG)
        assert res.violations == []
        assert res.unused_suppressions == [("pkg/m.py", 3, "DFD003")]
        assert not res.strict_clean

    def test_docstring_mention_is_not_a_suppression(self, tmp_path):
        res = lint_one(tmp_path, {"pkg/m.py": '''\
            """Docs: write  # dfdlint: disable=DFD003  on the line."""
            import numpy as np
            def f():
                return np.random.uniform()
        '''}, self.RULE, self.CFG)
        assert len(res.violations) == 1
        assert res.unused_suppressions == []


class TestBaselineSemantics:
    RULE = R.RngDiscipline()
    CFG = LintConfig(rng_dirs=("pkg",))
    FILES = {"pkg/m.py": """\
        import numpy as np
        def f():
            a = np.random.uniform()
            b = np.random.uniform()
            return a, b
    """}

    def entry(self, count=2):
        return BaselineEntry(rule="DFD003", path="pkg/m.py",
                             line_text="a = np.random.uniform()",
                             count=count, justification="test")

    def test_baseline_absorbs_up_to_count(self, tmp_path):
        index = make_index(tmp_path, self.FILES)
        res = run_lint(index, self.CFG, baseline=[self.entry(1)],
                       rules=[self.RULE])
        # one absorbed, the b-line still new
        assert len(res.baselined) == 1 and len(res.violations) == 1
        assert res.unused_baseline == []

    def test_unused_entry_is_rot(self, tmp_path):
        index = make_index(tmp_path, self.FILES)
        stale = BaselineEntry(rule="DFD003", path="pkg/m.py",
                              line_text="gone = np.random.rand()",
                              count=1, justification="stale")
        res = run_lint(index, self.CFG, baseline=[stale],
                       rules=[self.RULE])
        assert stale in res.unused_baseline
        assert not res.strict_clean

    def test_roundtrip_io(self, tmp_path):
        p = str(tmp_path / "b.json")
        save_baseline(p, [self.entry()])
        loaded = load_baseline(p)
        assert loaded == [self.entry()]
        with open(p) as f:
            assert json.load(f)["version"] == 1

    def test_rule_filter_does_not_rot_other_rules(self, tmp_path):
        """A filtered run (--rules DFD00X) must not report suppressions or
        baseline entries of rules that never executed as rot — otherwise
        `--rules DFD003 --strict` would false-fail on every DFD004 entry."""
        index = make_index(tmp_path, {"pkg/m.py": """\
            import numpy as np
            import subprocess
            def f(cmd):
                subprocess.run(cmd)  # dfdlint: disable=DFD008
                return np.random.uniform()
        """})
        other = BaselineEntry(rule="DFD008", path="pkg/other.py",
                              line_text="subprocess.run(x)", count=1,
                              justification="other rule's debt")
        res = run_lint(index, self.CFG, baseline=[other],
                       rules=[R.RngDiscipline()])
        assert [v.rule for v in res.violations] == ["DFD003"]
        # neither the DFD008 suppression nor the DFD008 entry is rot here
        assert res.unused_suppressions == []
        assert res.unused_baseline == []
        # ...but a full run does judge them
        res = run_lint(index, self.CFG, baseline=[other])
        assert other in res.unused_baseline

    def test_unparseable_file_reports_dfd000(self, tmp_path):
        index = make_index(tmp_path, {"pkg/bad.py": "def f(:\n"})
        res = run_lint(index, self.CFG, rules=[self.RULE])
        assert [v.rule for v in res.violations] == ["DFD000"]


# ---------------------------------------------------------------------------
# the gate: whole package + tools, checked-in baseline, zero rot
# ---------------------------------------------------------------------------

class TestGate:
    def _run(self):
        index = ProjectIndex.build(["deepfake_detection_tpu", "tools"],
                                   REPO)
        baseline = load_baseline(
            os.path.join(REPO, "tools", "dfdlint_baseline.json"))
        return index, baseline, run_lint(index, default_config(),
                                         baseline=baseline)

    def test_tree_is_clean_and_rot_free(self):
        index, baseline, res = self._run()
        assert res.violations == [], "\n".join(
            v.format(fix_hints=True) for v in res.violations)
        # rot-freedom is what makes baseline/suppression deletion fail
        # this test: every baseline entry absorbs >=1 live violation
        # (delete it -> that violation becomes `new`), and every inline
        # suppression suppresses >=1 (delete it -> same)
        assert res.unused_baseline == []
        assert res.unused_suppressions == []
        assert len(baseline) > 0 and len(res.baselined) > 0
        assert len(res.suppressed) > 0

    def test_every_rule_is_alive_on_fixtures(self, tmp_path):
        """No dead rules: each rule produces a violation on a minimal bad
        fixture (the per-rule classes above prove direction and detail;
        this is the aggregate liveness pin)."""
        bad = {
            "pkg/__init__.py": "",
            "pkg/a.py": "import jax\n",
            "pkg/rng.py": "import numpy as np\nX = np.random.uniform()\n",
            "m.py": ("import jax\n"
                     "def f(s, b):\n"
                     "    g = jax.jit(r, donate_argnums=(0,))\n"
                     "    o, _ = g(s, b)\n"
                     "    return s\n"
                     "def w(bs, sc):\n"
                     "    for b in bs:\n"
                     "        jax.jit(sc)\n"),
            "metrics.py": 'def r(doc):\n    doc.counter("a_total", "h", 1)'
                          '\n    doc.counter("a_total", "h", 1)\n',
            "use.py": "def f(i):\n    return i.fires('nope', 1)\n",
            "chaosreg.py": "KNOWN_POINTS = frozenset({'yes'})\n",
            "w.py": ("import json\n"
                     "def e(path, rec):\n"
                     "    with open(path, 'a') as f:\n"
                     "        f.write(json.dumps(rec))\n"),
            "sp.py": "import subprocess\nsubprocess.run(['x'])\n",
            "ct.py": ("import ctypes\nl = ctypes.CDLL('x.so')\n"
                      "l.dfd_y.argtypes = []\n"),
            "sm.py": ("from jax.experimental.shard_map import shard_map\n"
                      "def f(b, m):\n"
                      "    return shard_map(b, mesh=m)\n"),
        }
        cfg = LintConfig(jax_free_modules=("pkg.a",),
                         rng_dirs=("pkg",),
                         metric_registries={"metrics.py": "dfd_serving"},
                         chaos_module="chaosreg.py")
        index = make_index(tmp_path, bad)
        res = run_lint(index, cfg)
        fired = {v.rule for v in res.violations}
        expected = {f"DFD00{i}" for i in range(1, 10)} | {"DFD010"}
        assert expected <= fired, f"dead rules: {expected - fired}"

    def test_filtered_baseline_update_preserves_other_rules(self, tmp_path):
        """`--rules DFD003 --baseline-update` must refresh only DFD003's
        debt — wiping the hand-justified DFD004 entries would be data
        loss through the documented runbook command."""
        import importlib.util
        import shutil
        spec = importlib.util.spec_from_file_location(
            "dfdlint_cli", os.path.join(REPO, "tools", "dfdlint.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        bl = str(tmp_path / "b.json")
        shutil.copy(os.path.join(REPO, "tools", "dfdlint_baseline.json"),
                    bl)
        before = {e.key() for e in load_baseline(bl)
                  if e.rule != "DFD003"}
        assert before, "fixture assumes non-DFD003 entries exist"
        rc = mod.main(["deepfake_detection_tpu", "tools",
                       "--rules", "DFD003", "--baseline-update",
                       "--baseline", bl])
        assert rc == 0
        after = {e.key() for e in load_baseline(bl) if e.rule != "DFD003"}
        assert after == before

    def test_cli_gate_run(self):
        """The CLI itself: strict gate exits 0 on the tree, fast, jax-free
        (this is the command scripts/lint.sh and the verify recipe run)."""
        code = (
            "import sys, runpy\n"
            "sys.argv = ['dfdlint', 'deepfake_detection_tpu', 'tools',"
            " '--strict']\n"
            "try:\n"
            "    runpy.run_path('tools/dfdlint.py', run_name='__main__')\n"
            "except SystemExit as e:\n"
            "    assert e.code == 0, f'dfdlint gate failed: {e.code}'\n"
            "bad = [m for m in sys.modules if m == 'jax' or"
            " m.startswith('jax.')]\n"
            "assert not bad, f'linter dragged jax in: {bad[:3]}'\n"
        )
        r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                           capture_output=True, text=True, timeout=120,
                           env={**os.environ, "PYTHONPATH": ""})
        assert r.returncode == 0, r.stderr[-1500:]


# ---------------------------------------------------------------------------
# the one subprocess canary: static graph vs reality
# ---------------------------------------------------------------------------

def test_jax_free_manifest_canary():
    """DFD001 proves jax-freedom on the *static* import graph; this single
    subprocess imports every declared module for real and asserts jax never
    enters sys.modules — validating the graph against reality.  (Replaces
    the per-module subprocess tests that predated dfdlint: one child, not
    N.)"""
    from deepfake_detection_tpu.lint.manifest import JAX_FREE_MODULES
    imports = "\n".join(f"import {m}" for m in JAX_FREE_MODULES)
    code = (
        "import sys; sys.path.insert(0, '.')\n"
        f"{imports}\n"
        "bad = sorted(m for m in sys.modules if m == 'jax' or "
        "m.startswith('jax.'))\n"
        "assert not bad, f'jax leaked: {bad[:5]}'\n"
    )
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=180,
                       env={**os.environ, "PYTHONPATH": ""})
    assert r.returncode == 0, (r.stderr[-1500:] or r.stdout[-500:])
