"""Expert parallelism: CondConv expert banks sharded over the model axis."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deepfake_detection_tpu.models import create_model, init_model
from deepfake_detection_tpu.ops import CondConv2d
from deepfake_detection_tpu.parallel import (batch_sharding,
                                             condconv_ep_sharding,
                                             condconv_ep_specs)

pytestmark = pytest.mark.smoke  # fast tier: see pyproject [tool.pytest]


@pytest.fixture()
def mesh2d(devices):
    return Mesh(np.asarray(devices).reshape(2, 4), ("data", "model"))


class _CCNet(nn.Module):
    """Tiny routing + CondConv pair (the shape CondConv blocks use)."""

    @nn.compact
    def __call__(self, x):
        routing = nn.sigmoid(nn.Dense(8, name="route")(x.mean(axis=(1, 2))))
        return CondConv2d(16, 3, num_experts=8, padding="",
                          use_bias=True, name="conv")(x, routing)


def test_specs_target_expert_banks():
    m = _CCNet()
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 4)))
    specs = condconv_ep_specs(v["params"], axis="model", axis_size=4)
    assert specs["conv"]["weight"] == P("model")      # (8,3,3,4,16)
    assert specs["conv"]["bias"] == P("model")        # (8,16)
    assert specs["route"]["kernel"] == P()            # not an expert bank


def test_ep_forward_and_grads_match_replicated(mesh2d):
    m = _CCNet()
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((2, 16, 16, 4)))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16, 16, 4))
    ref = m.apply(v, x)
    g_ref = jax.grad(lambda p: (m.apply(p, x) ** 2).mean())(v)

    shardings = condconv_ep_sharding(v["params"], mesh2d, axis="model")
    v_ep = {"params": jax.device_put(v["params"], shardings)}
    x_ep = jax.device_put(np.asarray(x), batch_sharding(mesh2d, "data"))
    out = jax.jit(m.apply)(v_ep, x_ep)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    g_ep = jax.jit(jax.grad(lambda p: (m.apply(p, x_ep) ** 2).mean()))(v_ep)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_ep)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)
    # expert banks remain sharded in the gradient (no re-replication)
    gw = g_ep["params"]["conv"]["weight"]
    assert "model" in str(gw.sharding.spec)


@pytest.mark.slow
def test_ep_full_model_forward(mesh2d):
    m = create_model("efficientnet_cc_b0_4e", num_classes=2)
    v = init_model(m, jax.random.PRNGKey(0), (2, 64, 64, 3))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 64, 3))
    ref = m.apply(v, x, training=False)
    shardings = condconv_ep_sharding(v["params"], mesh2d, axis="model")
    variables = {"params": jax.device_put(v["params"], shardings),
                 "batch_stats": v["batch_stats"]}
    x_ep = jax.device_put(np.asarray(x), batch_sharding(mesh2d, "data"))
    out = jax.jit(lambda vv, x: m.apply(vv, x,
                                        training=False))(variables, x_ep)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
