"""Live-server chaos e2e (ISSUE 10): tools/chaos_serve.py scenarios
against real ``runners/serve.py`` / ``runners/stream.py`` subprocesses.

Slow tier (see tests/README.md): each scenario spawns at least one fresh
interpreter that builds the model and warms buckets (~9 s each on the
reference box even cache-warm), and the stream-resume scenario spawns
THREE.  The fast tier keeps every recovery mechanism covered in-process
(tests/test_serving_resilience.py, tests/test_streaming.py); this file
proves the same contracts over real HTTP + SIGTERM + /metrics scrapes:
books balance exactly, zero post-recovery backend recompiles, recovery
under the SLO, and verdict streams that RESUME across a server bounce
bit-identically to an unkilled replay.

Small conv model at a 32² canvas so every subprocess hits the persistent
compilation cache (the chaos-tier idiom).
"""

import pytest

import tools.chaos_serve as chaos_serve

pytestmark = [pytest.mark.slow, pytest.mark.chaos_serve,
              pytest.mark.serving]

_BASE = ["--model", "mobilenetv3_small_100", "--image-size", "32",
         "--slo-s", "15"]


def test_serve_faults_recover_books_balance_zero_recompiles():
    """exc / nan / hang / kill: each injected fault fires under live
    load, the engine self-heals within the SLO, the request books
    balance exactly, and no backend recompile happens across recovery.
    The verdict cache runs live (ISSUE 17): the posters cycle 8 distinct
    jpegs, so the books identity is asserted with a non-zero cache_hit
    term through every fault window."""
    assert chaos_serve.main(["--scenario", "exc,nan,hang,kill",
                             "--cache-entries", "32"] + _BASE) == 0


def test_torn_reload_rejected_then_clean_reload_lands():
    assert chaos_serve.main(["--scenario", "torn_reload",
                             "--cache-entries", "32"] + _BASE) == 0


def test_two_model_cascade_faults_recover_books_balance():
    """ISSUE 14 acceptance: the PR 10 invariants survive with TWO models
    loaded and cascade routing — recovery re-warms both models' buckets
    with zero recompiles, the global books balance, and the cascade
    books (triaged == cleared + escalated; escalated == flagship_scored
    + escalation_failed) stay exact while faults turn escalations into
    counted student-verdict fallbacks."""
    assert chaos_serve.main(
        ["--scenario", "exc,kill",
         "--models", "student=vit_tiny_patch16_224,size=32,dtype=int8",
         "--cascade", "student"] + _BASE) == 0


def test_stream_server_bounce_resumes_verdicts_bit_identically():
    assert chaos_serve.main(["--scenario", "stream_resume"] + _BASE) == 0


def test_fleet_replica_kill_fails_over_and_rejoins():
    """ISSUE 15 acceptance: SIGKILL one of two serve replicas behind the
    router under load — the router fails traffic over within the SLO,
    router books stay exact (routed == forwarded + migrated + shed +
    failed), and a relaunch on the same port rejoins the rotation."""
    assert chaos_serve.main(["--scenario", "replica_kill"] + _BASE) == 0


def test_fleet_drain_migrates_stream_bit_identically():
    """ISSUE 15 acceptance: draining a stream's replica live-migrates
    the session (PR 10 snapshot/restore) to the peer; the stream
    finishes through the router with final status + event log
    BIT-IDENTICAL to an undrained replay and exact migration books."""
    assert chaos_serve.main(["--scenario", "replica_migrate"] + _BASE) == 0


def test_fleet_elastic_two_tenant_books_exact_through_transitions():
    """ISSUE 18 acceptance: the SLO autoscaler + backfill tenant driven
    through a spike-triggered tenant yield (SIGTERM → exit-75 lease
    release), a SIGKILL of the new warming replica (booked + respawned
    under live load) and a drain-first scale-in, after which the tenant
    reclaims the idle slot and runs the corpus dry.  Books stay exact
    on BOTH tenants (routed == cache_hit + forwarded + migrated + shed
    + failed; manifest clips == scored + failed + skipped_dup), no
    client ever sees a failure, surviving replicas never recompile, and
    the recorded decision trace replays bit-exactly."""
    assert chaos_serve.main(["--scenario", "fleet_elastic"] + _BASE) == 0
