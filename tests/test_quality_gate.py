"""Quality-gate machinery end-to-end (VERDICT r3 item 6).

The north-star gate is "DeeperForensics AUC ≥ the released GPU checkpoint"
(BASELINE.md; reference README.md:35-40).  The released ``model_half.pth.tar``
lives behind BaiduYun and the dataset is unavailable here, so this proves the
*machinery* instead: train the REFERENCE torch stack (vendored at
/root/reference, loaded standalone) on deterministic synthetic 4-frame data
until it actually learns, convert the trained checkpoint with
``tools/convert_torch_checkpoint.py``, and assert the converted flax model
reproduces the torch model's logits and AUC on a held-out split.

This retires the "converter is parity-tested at init but has never carried a
*trained* artifact" risk: a trained checkpoint exercises moved BN running
stats, non-symmetric weights, and a real decision boundary.
"""

import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
from convert_torch_checkpoint import convert_state_dict  # noqa: E402

from test_convert import _load_reference_efficientnet  # noqa: E402

from deepfake_detection_tpu.utils.metrics import auc  # noqa: E402


def _synthetic_clips(n, rng, size=65):
    """4-frame 12-channel clips whose label is a simple luminance rule
    (separable, so 200 steps suffice to learn it)."""
    x = rng.normal(size=(n, 12, size, size)).astype(np.float32) * 0.3
    y = (rng.random(n) > 0.5).astype(np.int64)
    # real clips (y=1) are brighter in every frame
    x += (y * 0.6 - 0.3)[:, None, None, None]
    return x, y


def _train_torch(tm, x_train, y_train, steps, bs, lr=1e-3):
    import torch
    opt = torch.optim.Adam(tm.parameters(), lr=lr)
    loss_fn = torch.nn.CrossEntropyLoss()
    tm.train()
    for s in range(steps):
        i = (s * bs) % len(x_train)
        xb = torch.from_numpy(x_train[i:i + bs])
        yb = torch.from_numpy(y_train[i:i + bs])
        opt.zero_grad()
        loss_fn(tm(xb), yb).backward()
        opt.step()


def _eval_torch(tm, x_eval, bs=32):
    import torch
    tm.eval()
    with torch.no_grad():
        return np.concatenate(
            [tm(torch.from_numpy(x_eval[i:i + bs])).numpy()
             for i in range(0, len(x_eval), bs)])


def _auc_of(logits, y):
    scores = np.exp(logits[:, 1]) / np.exp(logits).sum(-1)
    return float(auc(jnp.asarray(scores), jnp.asarray(y)))


def _assert_converted_parity(tm, model_name, x_eval, y_eval, t_logits,
                             t_auc):
    """Convert the trained torch checkpoint; assert logit + AUC parity."""
    import jax
    variables = convert_state_dict(tm.state_dict())
    from deepfake_detection_tpu.models import create_model
    fm = create_model(model_name, num_classes=2, in_chans=12)
    x_nhwc = jnp.asarray(np.transpose(x_eval, (0, 2, 3, 1)))
    apply = jax.jit(lambda v, x: fm.apply(v, x, training=False))
    f_logits = np.concatenate(
        [np.asarray(apply(variables, x_nhwc[i:i + 32]))
         for i in range(0, len(x_eval), 32)])
    np.testing.assert_allclose(f_logits, t_logits, atol=5e-3, rtol=1e-2)
    f_auc = _auc_of(f_logits, y_eval)
    assert abs(f_auc - t_auc) < 1e-3, (f_auc, t_auc)
    assert f_auc > 0.9


@pytest.mark.slow
def test_trained_reference_checkpoint_converts_with_auc_parity(tmp_path):
    torch = pytest.importorskip("torch")
    ref = _load_reference_efficientnet()
    torch.manual_seed(0)
    tm = ref.mnasnet_small(num_classes=2, in_chans=12)

    rng = np.random.default_rng(0)
    x_train, y_train = _synthetic_clips(256, rng)
    x_eval, y_eval = _synthetic_clips(128, rng)

    _train_torch(tm, x_train, y_train, steps=200, bs=16)
    t_logits = _eval_torch(tm, x_eval)
    t_auc = _auc_of(t_logits, y_eval)
    # the torch reference must actually have learned the rule, or the
    # comparison below proves nothing
    assert t_auc > 0.9, f"reference failed to learn: AUC {t_auc}"
    _assert_converted_parity(tm, "mnasnet_small", x_eval, y_eval,
                             t_logits, t_auc)


@pytest.mark.slow
def test_trained_flagship_v4_converts_with_auc_parity():
    """VERDICT r4 item 4: the FLAGSHIP family (B7-scaled depth-3.1 stages,
    SE at width 2.0, 256-feature head — efficientnet.py:806-848,1187) must
    carry TRAINED weights through the converter, at reduced 64² resolution
    (the arch, not the res, is what's untested).  64 is deliberately EVEN:
    it regression-covers the round-5 padding fix (static symmetric vs XLA
    SAME window-grid shift) at the flagship's own even-size regime."""
    torch = pytest.importorskip("torch")
    ref = _load_reference_efficientnet()
    torch.manual_seed(0)
    tm = ref.efficientnet_deepfake_v4(num_classes=2, in_chans=12)

    rng = np.random.default_rng(0)
    x_train, y_train = _synthetic_clips(192, rng, size=64)
    x_eval, y_eval = _synthetic_clips(64, rng, size=64)

    _train_torch(tm, x_train, y_train, steps=150, bs=8)
    t_logits = _eval_torch(tm, x_eval, bs=16)
    t_auc = _auc_of(t_logits, y_eval)
    assert t_auc > 0.9, f"reference failed to learn: AUC {t_auc}"
    _assert_converted_parity(tm, "efficientnet_deepfake_v4", x_eval, y_eval,
                             t_logits, t_auc)
