"""Streaming frame-once fast path (ISSUE 20): crop rings, zero-copy
window assembly, per-window dedup, and the exact-books contract.

The oracle everywhere is the historical concat path (kept in-tree as
``assembly="concat"``) plus from-scratch recomputation: fast-path
payloads and content keys must be bit-identical to what the old
``prepare_canvas`` + ``np.concatenate`` chain produces on the same
frames, across every overlap regime (hop x stride), and the 6-term
window books must balance exactly through dedup/drop paths.
"""

import io
import itertools
import types

import numpy as np
import pytest
from PIL import Image

from deepfake_detection_tpu.streaming.metrics import StreamingMetrics
from deepfake_detection_tpu.streaming.ring import (CanvasRing, FrameStack,
                                                   RingLease, frame_digest,
                                                   window_key)
from deepfake_detection_tpu.streaming.tracker import GreedyIouTracker, iou
from deepfake_detection_tpu.streaming.windows import build_payload

pytestmark = [pytest.mark.smoke, pytest.mark.streaming]

_SIZE = 16


def _frames(n, h=20, w=24, seed=3):
    """Deterministic non-square frames: prepare_canvas must resize AND
    pad, exercising the full geometry of the ring write."""
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
            for _ in range(n)]


def _jpeg(frame):
    buf = io.BytesIO()
    Image.fromarray(frame).save(buf, format="JPEG", quality=90)
    return buf.getvalue()


def _session(jobs, cache_live=False, **cfg_kw):
    from deepfake_detection_tpu.config import StreamConfig
    from deepfake_detection_tpu.streaming.ingest import StreamSession
    kw = dict(image_size=_SIZE, img_num=4, buckets=(1,), max_queue=1,
              stream_ttl_s=0.0, verdict_vector="0.1*2,0.95*8")
    kw.update(cfg_kw)
    cfg = StreamConfig(**kw)
    disp = types.SimpleNamespace(push=jobs.append)
    if cache_live:
        # a non-None .batcher.cache is all _cache_live() checks: content
        # keys get computed without a real micro-batcher in the loop
        disp.batcher = types.SimpleNamespace(cache=object())
    return StreamSession("fp", cfg, disp, StreamingMetrics(), _SIZE,
                         kw.get("wire", "float32"))


def _score_all(session, jobs):
    """Resolve every pending job the way the dispatcher would: score it
    and release its ring lease."""
    while jobs:
        job = jobs.pop(0)
        session.on_window_result(job, np.asarray([0.5, 0.5]), None)
        if getattr(job, "lease", None) is not None:
            job.lease.release()


# ---------------------------------------------------------------------------
# ring primitives
# ---------------------------------------------------------------------------

def test_canvas_ring_refcount_overflow_and_reuse():
    r = CanvasRing(2, 8)
    a, b = r.acquire(), r.acquire()
    assert a.ring is r and b.ring is r and a.row != b.row
    assert r.free_rows() == 0
    # exhausted pool degrades to a counted standalone row, never blocks
    c = r.acquire()
    assert c.ring is None and c.canvas.shape == (8, 8, 3)
    assert r.overflow_total == 1
    c.incref()
    c.decref()                            # standalone: no-ops, GC-owned
    # release recirculates the row; extra pins hold it
    a.decref()
    assert r.free_rows() == 1
    d = r.acquire()
    assert d.ring is r and d.row == a.row
    b.incref()
    b.decref()
    assert r.free_rows() == 0             # still pinned by the first ref
    b.decref()
    assert r.free_rows() == 1
    # a double-release clamps instead of corrupting the freelist
    b.decref()
    assert r.free_rows() == 1


def test_ring_lease_release_is_idempotent():
    r = CanvasRing(1, 4)
    ref = r.acquire()
    lease = RingLease([ref])
    lease.release()
    assert r.free_rows() == 1
    lease.release()                       # engine gather + dispatcher
    assert r.free_rows() == 1             # terminal path may both fire


def test_framestack_matches_build_payload_both_wires():
    from deepfake_detection_tpu.params import img_mean, img_std
    frames = [np.ascontiguousarray(f[:_SIZE, :_SIZE])
              for f in _frames(4, h=_SIZE, w=_SIZE + 4)]
    for wire, norm in (("float32", (img_mean, img_std)), ("uint8", None)):
        want = build_payload(frames, wire)
        fired = []
        fs = FrameStack(frames, norm=norm, on_consumed=lambda: fired.append(1))
        assert fs.shape == want.shape and fs.dtype == want.dtype
        np.testing.assert_array_equal(fs.materialize(), want)
        assert not fired                  # materialize never consumes
        buf = np.zeros(fs.shape, fs.dtype)
        fs.write_into(buf)
        np.testing.assert_array_equal(buf, want)
        assert fired == [1]
        fs.write_into(buf)
        assert fired == [1]               # consumed exactly once
        np.testing.assert_array_equal(np.asarray(fs), want)


# ---------------------------------------------------------------------------
# overlap parity: ring fast path vs the historical concat path
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("hop,stride", list(itertools.product(
    (1, 2, 4), (1, 2))))
def test_window_payloads_and_keys_bit_identical_across_overlap(hop, stride):
    """For every overlap regime, the zero-copy FrameStack payload must be
    byte-for-byte the old concat payload, and the fast path's content key
    must equal a from-scratch ``prepare_canvas`` -> digest -> compose
    recomputation."""
    from deepfake_detection_tpu.params import prepare_canvas
    frames = _frames(30)
    ring_jobs, concat_jobs = [], []
    s_ring = _session(ring_jobs, cache_live=True, assembly="ring",
                      window_hop=hop, window_stride=stride)
    s_concat = _session(concat_jobs, assembly="concat", window_hop=hop,
                        window_stride=stride)
    for f in frames:
        s_ring.ingest_arrays([f])
        s_concat.ingest_arrays([f])
    assert len(ring_jobs) == len(concat_jobs) > 0
    for rj, cj in zip(ring_jobs, concat_jobs):
        assert (rj.track_id, rj.window_idx, tuple(rj.frame_idxs)) == \
            (cj.track_id, cj.window_idx, tuple(cj.frame_idxs))
        got = rj.payload.materialize()
        assert got.dtype == cj.payload.dtype
        np.testing.assert_array_equal(got, cj.payload)
        # content key == digest-of-digests recomputed from scratch (the
        # full-frame localizer makes crop == frame exactly)
        want_key = window_key(tuple(
            frame_digest(prepare_canvas(frames[i], _SIZE))
            for i in rj.frame_idxs))
        assert rj.content_key == (want_key, None)
        assert cj.content_key is None     # concat path computes no keys
    assert s_ring.canvas_copies_elided == 0


def test_contiguity_elision_is_counted_on_concat_path():
    """The concat path must skip (and count) the historical redundant
    ``ascontiguousarray`` on already-contiguous crops."""
    jobs = []
    s = _session(jobs, assembly="concat", img_num=2, window_hop=2)
    for f in _frames(4, h=_SIZE, w=_SIZE):   # size match: crop IS canvas
        s.ingest_arrays([np.ascontiguousarray(f)])
    assert jobs
    assert s.canvas_copies_elided > 0
    assert s.metrics.canvas_copies_elided_total.value == \
        s.canvas_copies_elided


# ---------------------------------------------------------------------------
# duplicate elision: decode chain, window dedup, exact books
# ---------------------------------------------------------------------------

def test_decode_chunk_duplicate_and_error_chain():
    jobs = []
    s = _session(jobs, assembly="ring", dedup_frames=True)
    a, b = (_jpeg(f) for f in _frames(2))
    arrays, flags, errors = s.decode_chunk([a, a, b, b, b])
    assert errors == 0
    assert flags == [False, True, False, True, True]
    assert s.frames_dup_elided == 3
    assert arrays[0] is arrays[1] and arrays[2] is arrays[3] is arrays[4]
    # the chain crosses chunk boundaries...
    arrays2, flags2, _ = s.decode_chunk([b, a])
    assert flags2 == [True, False]
    # ...a duplicate of an undecodable frame is an error without a decode
    bad = b"\xff\xd8not-a-jpeg"
    arrays3, flags3, errors3 = s.decode_chunk([bad, bad, a])
    assert errors3 == 2 and flags3 == [False] and len(arrays3) == 1
    # ...and never survives a restore (the decoded predecessor is gone)
    s.load_state(s.state_dict())
    _, flags4, _ = s.decode_chunk([a])
    assert flags4 == [False]


def test_dedup_stream_books_exact_and_content_stream_preserved():
    """dedup_frames on a frozen/replayed stream: (a) the submitted
    content-key stream equals the baseline stream with consecutive
    per-track duplicates removed, (b) surviving payloads are
    bit-identical to the baseline window at the same window_idx, and
    (c) emitted == scored + dropped + shed + failed + cache_hit +
    dup_elided exactly."""
    uniq = _frames(2, seed=9)
    chunks = [[_jpeg(uniq[0])] * 6,
              [_jpeg(uniq[0])] * 2 + [_jpeg(uniq[1])] * 4,
              [_jpeg(uniq[1])] * 6,
              [_jpeg(uniq[0])] * 6]
    base_jobs, dd_jobs = [], []
    base = _session(base_jobs, cache_live=True, assembly="ring",
                    img_num=2, window_hop=1)
    dd = _session(dd_jobs, cache_live=True, assembly="ring", img_num=2,
                  window_hop=1, dedup_frames=True)
    base_keys, dd_keys, dd_by_idx, base_by_idx = [], [], {}, {}
    for chunk in chunks:
        for sess, jobs, keys, by_idx in (
                (base, base_jobs, base_keys, base_by_idx),
                (dd, dd_jobs, dd_keys, dd_by_idx)):
            arrays, flags, errors = sess.decode_chunk(chunk)
            assert errors == 0
            sess.ingest_arrays(arrays, flags)
            for j in list(jobs):
                keys.append(j.content_key[0])
                by_idx[j.window_idx] = j.payload.materialize()
            _score_all(sess, jobs)
    assert base.frames_dup_elided == 0 and base.windows_dup_elided == 0
    assert dd.frames_dup_elided > 0 and dd.windows_dup_elided > 0
    assert dd.canvas_copies_elided > 0            # duplicate-crop reuse
    # (a) consecutive-duplicate removal, nothing else
    want = [k for i, k in enumerate(base_keys)
            if i == 0 or k != base_keys[i - 1]]
    assert dd_keys == want
    # (b) surviving windows carry the exact baseline bytes
    for idx, payload in dd_by_idx.items():
        np.testing.assert_array_equal(payload, base_by_idx[idx])
    # (c) exact 6-term books, in both sessions
    for s in (base, dd):
        assert s.windows_emitted == (
            s.windows_scored + s.windows_dropped + s.windows_shed +
            s.windows_failed + s.windows_cache_hit + s.windows_dup_elided)
    assert base.windows_emitted == dd.windows_emitted
    assert dd.windows_scored == base.windows_scored - dd.windows_dup_elided


def test_cache_hit_books_via_dispatcher_collector():
    """A from_cache resolution must book windows_cache_hit (not scored)
    and still keep the 6-term identity."""
    jobs = []
    s = _session(jobs, cache_live=True, assembly="ring", img_num=2,
                 window_hop=2)
    for f in _frames(4, h=_SIZE, w=_SIZE, seed=5):
        s.ingest_arrays([f])
    assert len(jobs) >= 2
    hit, miss = jobs[0], jobs[1]
    hit.cache_hit = True                  # what _collect_loop sets
    s.on_window_result(hit, np.asarray([0.5, 0.5]), None)
    s.on_window_result(miss, np.asarray([0.5, 0.5]), None)
    for job in jobs:
        if job.lease is not None:
            job.lease.release()
    assert s.windows_cache_hit == 1
    assert s.metrics.windows_cache_hit_total.value == 1
    counters = s.status()["counters"]
    assert counters["windows_cache_hit"] == 1
    assert s.windows_emitted >= s.windows_scored + s.windows_cache_hit


# ---------------------------------------------------------------------------
# tracker: vectorized assignment vs the scalar reference
# ---------------------------------------------------------------------------

def _reference_assign(tracks, detections, iou_min):
    """The historical nested-loop greedy assignment, verbatim: candidate
    tuples (-iou, track_id, det_idx), sorted, claimed greedily."""
    pairs = []
    for t in tracks:
        for di, (box, _score) in enumerate(detections):
            v = iou(t.box, box)
            if v >= iou_min:
                pairs.append((-v, t.id, di))
    pairs.sort()
    used_t, used_d, assign = set(), set(), []
    for _nv, tid, di in pairs:
        if tid in used_t or di in used_d:
            continue
        used_t.add(tid)
        used_d.add(di)
        assign.append((tid, di))
    return assign


@pytest.mark.parametrize("seed,iou_min", [(0, 0.3), (7, 0.3), (123, 0.1),
                                          (11, 0.0)])
def test_tracker_vectorized_assignment_matches_scalar_reference(
        seed, iou_min):
    """Property test over jittery multi-box scenes: the numpy IoU-matrix
    assignment must reproduce the scalar loop's matches AND the exact
    EMA arithmetic (bit-identical boxes), including the iou_min=0 edge
    where zero-overlap pairs are eligible."""
    rng = np.random.default_rng(seed)
    tr = GreedyIouTracker(iou_min=iou_min, ema_alpha=0.6, max_coast=2)
    alpha = tr.ema_alpha
    for frame_idx in range(60):
        n = int(rng.integers(0, 4))
        dets = []
        for _ in range(n):
            x1, y1 = rng.uniform(0, 80, 2)
            bw, bh = rng.uniform(5, 30, 2)
            dets.append(((float(x1), float(y1), float(x1 + bw),
                          float(y1 + bh)), float(rng.uniform(0.5, 1.0))))
        live = list(tr.tracks.values())
        pre_boxes = {t.id: t.box for t in live}
        want = _reference_assign(live, dets, iou_min)
        upd = tr.update(frame_idx, dets)
        got_ids = [t.id for t in upd.matched]
        assert got_ids == [tid for tid, _di in want]
        for tid, di in want:
            box = dets[di][0]
            expect = tuple(alpha * float(d) + (1.0 - alpha) * p
                           for d, p in zip(box, pre_boxes[tid]))
            assert tr.tracks[tid].box == expect     # exact, not approx


# ---------------------------------------------------------------------------
# snapshot compatibility across assembly modes
# ---------------------------------------------------------------------------

def test_concat_snapshot_restores_into_ring_session_bit_identically():
    """dfd.streaming.session_state.v1 is assembly-agnostic: a snapshot
    taken by the historical concat path restores into a ring-mode
    session, and the continuation emits bit-identical payloads, keys and
    books vs an uninterrupted ring session."""
    frames = _frames(24, seed=21)
    ref_jobs, old_jobs = [], []
    ref = _session(ref_jobs, cache_live=True, assembly="ring",
                   img_num=2, window_hop=1)
    old = _session(old_jobs, assembly="concat", img_num=2, window_hop=1)
    for f in frames[:10]:
        ref.ingest_arrays([f])
        old.ingest_arrays([f])
        _score_all(ref, ref_jobs)
        _score_all(old, old_jobs)
    snap = old.state_dict()
    # pre-ISSUE-20 producers never wrote the new counter keys: strip
    # them so the snapshot is byte-layout what an old writer serialized
    for k in ("windows_cache_hit", "windows_dup_elided",
              "frames_dup_elided", "canvas_copies_elided"):
        snap["counters"].pop(k)
    res_jobs = []
    res = _session(res_jobs, cache_live=True, assembly="ring",
                   img_num=2, window_hop=1)
    res.load_state(snap)
    assert res.windows_cache_hit == 0
    for f in frames[10:]:
        ref.ingest_arrays([f])
        res.ingest_arrays([f])
        assert len(res_jobs) == len(ref_jobs)
        for rj, fj in zip(res_jobs, ref_jobs):
            assert rj.window_idx == fj.window_idx
            assert rj.content_key == fj.content_key
            np.testing.assert_array_equal(rj.payload.materialize(),
                                          fj.payload.materialize())
        _score_all(ref, ref_jobs)
        _score_all(res, res_jobs)
    a, b = ref.status()["counters"], res.status()["counters"]
    assert a == b
