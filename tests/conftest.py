"""Test configuration: force an 8-device CPU mesh.

Must run before any jax backend is initialized.  The environment's
sitecustomize registers the 'axon' TPU plugin and forces
``jax_platforms="axon,cpu"`` in every interpreter; tests override back to pure
CPU here (backend init is lazy, so this works as long as no fixture touched
jax.devices() earlier).  Eight virtual CPU devices let multi-chip sharding
tests run without TPU hardware (SURVEY.md §4).
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the full suite compiles hundreds of programs;
# a warm cache cuts suite latency from ~25 min to well under 10.  Keyed by
# jax/XLA version internally, so stale entries are never reused.
_CACHE_DIR = os.environ.get(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(__file__), os.pardir, ".jax_cache"))
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_CACHE_DIR))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    import numpy as np
    from jax.sharding import Mesh
    return Mesh(np.asarray(devices).reshape(4, 2), ("data", "model"))
