"""Test configuration: force an 8-device CPU mesh.

Must run before any jax backend is initialized.  The environment's
sitecustomize registers the 'axon' TPU plugin and forces
``jax_platforms="axon,cpu"`` in every interpreter; tests override back to pure
CPU here (backend init is lazy, so this works as long as no fixture touched
jax.devices() earlier).  Eight virtual CPU devices let multi-chip sharding
tests run without TPU hardware (SURVEY.md §4).
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    import numpy as np
    from jax.sharding import Mesh
    return Mesh(np.asarray(devices).reshape(4, 2), ("data", "model"))
