"""Streaming pipeline e2e (ISSUE 8): localhost session round trips
against a real warmed engine, window-score ↔ CLI bit-identity, planted
verdict transitions, and a fresh-interpreter runner drive.

Fast tier (``streaming`` marker): small conv model at a 32² canvas with
``img_num=2`` clips, so the four bucket programs stay cheap and hit the
persistent compilation cache; the subprocess test reuses the same model/
canvas so its warmup is cache-warm too (the chaos-tier idiom).
"""

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepfake_detection_tpu.config import StreamConfig
from deepfake_detection_tpu.models import create_model, init_model
from deepfake_detection_tpu.params import make_score_fn, normalize_concat
from deepfake_detection_tpu.serving.batcher import MicroBatcher
from deepfake_detection_tpu.serving.engine import InferenceEngine
from deepfake_detection_tpu.serving.metrics import ServingMetrics
from deepfake_detection_tpu.streaming.ingest import (StreamManager,
                                                     make_stream_server)
from deepfake_detection_tpu.streaming.metrics import StreamingMetrics
from deepfake_detection_tpu.streaming.windows import WindowDispatcher

pytestmark = pytest.mark.streaming

_MODEL = "mobilenetv3_small_100"
_SIZE = 32
_NUM = 2


def _perturbed_variables(model, size, chans, seed=0):
    """test_serving's helper: nudge every param so scores discriminate
    (zoo heads init classifiers to zeros → softmax pinned at 0.5)."""
    variables = init_model(model, jax.random.PRNGKey(0),
                           (1, size, size, chans))
    rng = np.random.default_rng(seed)
    return jax.tree.map(
        lambda a: a + jnp.asarray(
            0.02 * rng.standard_normal(np.shape(a)).astype(np.float32)
        ).astype(a.dtype),
        variables)


def _cfg(**kw):
    kw.setdefault("image_size", _SIZE)
    kw.setdefault("img_num", _NUM)
    kw.setdefault("buckets", (1, 4))
    kw.setdefault("stream_ttl_s", 0.0)          # no evictor in tests
    kw.setdefault("max_inflight_windows", 16)
    return StreamConfig(**kw)


@pytest.fixture(scope="module")
def stack():
    cfg = _cfg()
    model = create_model(_MODEL, num_classes=2, in_chans=3 * _NUM)
    variables = _perturbed_variables(model, _SIZE, 3 * _NUM)
    serving_metrics = ServingMetrics()
    engine = InferenceEngine(model, variables, image_size=_SIZE,
                             img_num=_NUM, buckets=cfg.buckets,
                             metrics=serving_metrics, wire="float32")
    batcher = MicroBatcher(max_batch=4, deadline_ms=5.0, max_queue=64,
                           metrics=serving_metrics)
    engine.start(batcher)
    metrics = StreamingMetrics()
    manager_box = []
    dispatcher = WindowDispatcher(
        batcher, max_pending=cfg.max_inflight_windows,
        request_timeout_s=10.0,
        on_result=lambda j, s, e: manager_box[0].on_result(j, s, e),
        on_drop=lambda j, r: manager_box[0].on_drop(j, r))
    manager = StreamManager(cfg, dispatcher, metrics,
                            image_size=_SIZE, wire="float32")
    manager_box.append(manager)
    dispatcher.start()
    server = make_stream_server("127.0.0.1", 0, manager, engine,
                                serving_metrics, metrics)
    import threading
    threading.Thread(target=server.serve_forever,
                     kwargs={"poll_interval": 0.1}, daemon=True).start()
    port = server.server_address[1]
    yield type("Stack", (), dict(
        cfg=cfg, model=model, engine=engine, batcher=batcher,
        dispatcher=dispatcher, manager=manager, metrics=metrics,
        serving_metrics=serving_metrics, server=server, port=port))
    server.shutdown()
    manager.shutdown()
    dispatcher.stop()
    engine.stop()
    batcher.close()
    server.server_close()


# ---------------------------------------------------------------------------
# HTTP helpers
# ---------------------------------------------------------------------------

def _req(port, method, path, body=None, headers=None, timeout=30):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=body, method=method,
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        raw = r.read()
        return r.status, json.loads(raw) if raw[:1] in (b"{", b"[") \
            else raw.decode()


def _raw_frames(frames):
    """(body, headers) for the zero-decode x-dfd-raw chunk wire."""
    h, w = frames[0].shape[:2]
    return (b"".join(np.ascontiguousarray(f).tobytes() for f in frames),
            {"Content-Type": "application/x-dfd-raw",
             "X-Frame-Width": str(w), "X-Frame-Height": str(h)})


def _open_stream(port, stream_id=None):
    body = json.dumps({"stream_id": stream_id}).encode() if stream_id \
        else None
    status, obj = _req(port, "POST", "/streams", body,
                       {"Content-Type": "application/json"} if body else {})
    assert status == 201
    return obj["stream_id"]


def _wait_scored(port, sid, n, timeout=20):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        _, st = _req(port, "GET", f"/streams/{sid}")
        if st["counters"]["windows_scored"] >= n:
            return st
        time.sleep(0.02)
    raise AssertionError(f"stream {sid} never scored {n} windows: {st}")


# ---------------------------------------------------------------------------
# session lifecycle + scoring
# ---------------------------------------------------------------------------

def test_session_lifecycle_and_window_scoring(stack):
    port = stack.port
    assert _req(port, "GET", "/healthz")[0] == 200
    assert _req(port, "GET", "/readyz")[0] == 200
    sid = _open_stream(port)
    assert sid in _req(port, "GET", "/streams")[1]["streams"]

    rng = np.random.default_rng(0)
    frames = [rng.integers(0, 255, (_SIZE, _SIZE, 3), dtype=np.uint8)
              for _ in range(4)]                      # 2 windows (hop=2)
    body, headers = _raw_frames(frames)
    status, ack = _req(port, "POST", f"/streams/{sid}/frames", body,
                       headers)
    assert status == 200
    assert ack["frames_accepted"] == 4 and ack["decode_errors"] == 0
    assert ack["windows_emitted"] == 2
    assert ack["verdict"] in ("real", "suspect", "fake")

    st = _wait_scored(port, sid, 2)
    assert st["schema"].startswith("dfd.streaming.status.v")
    assert st["counters"]["frames_ingested"] == 4
    assert len(st["active_tracks"]) == 1              # full_frame: 1 track
    assert st["tracks"]["0"]["windows"] == 2
    assert st["stream"]["windows"] == 2

    status, final = _req(port, "DELETE", f"/streams/{sid}")
    assert status == 200 and final["closed"]
    assert _req(port, "GET", "/streams")[1]["active"] == 0
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(port, "GET", f"/streams/{sid}")
    assert ei.value.code == 404


def test_window_scores_bit_identical_to_cli_clip_path(stack):
    """The acceptance bar: a streamed window's score must equal scoring
    the same 12-channel-layout clip through the CLI path bit-for-bit.
    Raw-wire frames (no JPEG) at a non-canvas size, so BOTH paths run the
    full geometric preprocess on identical pixels."""
    port = stack.port
    sid = _open_stream(port)
    rng = np.random.default_rng(42)
    frames = [rng.integers(0, 255, (48, 40, 3), dtype=np.uint8)
              for _ in range(_NUM)]                   # exactly one window
    body, headers = _raw_frames(frames)
    assert _req(port, "POST", f"/streams/{sid}/frames", body,
                headers)[1]["windows_emitted"] == 1
    st = _wait_scored(port, sid, 1)
    got = st["stream"]["last_score"]

    from deepfake_detection_tpu.params import prepare_canvas
    clip = normalize_concat([prepare_canvas(f, _SIZE) for f in frames],
                            _NUM)[None]
    cli = make_score_fn(stack.model, stack.engine._variables)
    want = float(np.asarray(cli(jnp.asarray(clip)))[0, 0])
    assert got == want, f"stream {got!r} != CLI {want!r}"
    _req(port, "DELETE", f"/streams/{sid}")


def test_planted_vector_drives_hysteresis_transitions(stack):
    """The bench's verdict acceptance vector, in-process: windows ride
    the REAL engine, but verdicts consume the planted real→fake flip —
    transitions must land exactly where the EMA math says."""
    cfg = dataclasses.replace(stack.cfg, verdict_vector="0.05*2,0.95*6")
    manager = StreamManager(cfg, stack.dispatcher, stack.metrics,
                            image_size=_SIZE, wire="float32")
    s = manager.create("planted")
    rng = np.random.default_rng(1)
    for i in range(16):                               # 8 windows (hop=2)
        s.ingest_arrays([rng.integers(0, 255, (_SIZE, _SIZE, 3),
                                      dtype=np.uint8)])
    deadline = time.monotonic() + 20
    while s.windows_scored < 8 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert s.windows_scored == 8
    st = s.status()
    assert st["verdict"] == "fake"
    # stream-scope transitions: ema crosses 0.5 at window 4, 0.8 at 8
    stream_events = [e for e in st["events"] if e["scope"] == "stream"]
    assert [(e["from"], e["to"], e["windows"]) for e in stream_events] == \
        [("real", "suspect", 4), ("suspect", "fake", 8)]
    # the per-track machine saw the same flip
    track_events = [e for e in st["events"] if e["scope"] == "track"]
    assert [e["to"] for e in track_events] == ["suspect", "fake"]
    manager.close("planted")


def test_multipart_mjpeg_chunk_and_decode_error_accounting(stack):
    import io

    from PIL import Image
    port = stack.port
    sid = _open_stream(port, "mjpeg-test")
    rng = np.random.default_rng(3)
    buf = io.BytesIO()
    Image.fromarray(rng.integers(0, 255, (40, 40, 3), dtype=np.uint8)
                    ).save(buf, "JPEG", quality=90)
    good = buf.getvalue()
    parts = [good, b"THIS IS NOT A JPEG"]
    body = b"".join(
        b"--frame\r\nContent-Type: image/jpeg\r\n\r\n" + p + b"\r\n"
        for p in parts) + b"--frame--\r\n"
    status, ack = _req(
        port, "POST", f"/streams/{sid}/frames", body,
        {"Content-Type": "multipart/x-mixed-replace; boundary=frame"})
    assert status == 200
    assert ack["frames_accepted"] == 1 and ack["decode_errors"] == 1
    _req(port, "DELETE", f"/streams/{sid}")


def test_http_error_paths(stack):
    port = stack.port
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(port, "GET", "/streams/doesnotexist")
    assert ei.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(port, "POST", "/streams/doesnotexist/frames", b"x",
             {"Content-Type": "application/octet-stream"})
    assert ei.value.code == 404
    sid = _open_stream(port, "dup")
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _open_stream(port, "dup")
        assert ei.value.code == 409
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(port, "POST", f"/streams/{sid}/frames", b"x" * 10,
                 {"Content-Type": "multipart/x-mixed-replace"})  # boundary?
        assert ei.value.code == 400
    finally:
        _req(port, "DELETE", f"/streams/{sid}")
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(port, "DELETE", "/streams/dup")
    assert ei.value.code == 404


def test_metrics_exposes_streaming_and_serving_catalogs(stack):
    status, text = _req(stack.port, "GET", "/metrics")
    assert status == 200
    # streaming catalog live alongside the serving one (one scrape = whole
    # pipeline), with the drop/shed counters present (never silent)
    for name in ("dfd_streaming_frames_ingested_total",
                 "dfd_streaming_windows_scored_total",
                 "dfd_streaming_windows_dropped_total",
                 "dfd_streaming_windows_shed_total",
                 "dfd_streaming_active_streams",
                 'dfd_streaming_latency_seconds_bucket{stage="score"',
                 "dfd_serving_batches_total",
                 "dfd_serving_backend_compiles_total"):
        assert name in text, name


def test_idle_stream_ttl_eviction(stack):
    cfg = dataclasses.replace(stack.cfg, stream_ttl_s=0.2)
    manager = StreamManager(cfg, stack.dispatcher, stack.metrics,
                            image_size=_SIZE, wire="float32")
    manager.create("idle")
    evicted0 = stack.metrics.streams_evicted_total.value
    manager.start_evictor()
    try:
        deadline = time.monotonic() + 10
        while manager.get("idle") is not None and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert manager.get("idle") is None, "idle stream never evicted"
        assert stack.metrics.streams_evicted_total.value == evicted0 + 1
    finally:
        manager.shutdown()


# ---------------------------------------------------------------------------
# fresh-interpreter runner e2e (the chaos-tier idiom: a native fault can
# at worst fail this one test)
# ---------------------------------------------------------------------------

_RUNNER_DRIVER = """
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
cache = os.environ.get("JAX_COMPILATION_CACHE_DIR")
if cache:
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
from deepfake_detection_tpu.runners.stream import main
main(sys.argv[1:])
"""


def test_runner_stream_subprocess_e2e(tmp_path):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_COMPILATION_CACHE_DIR"] = str(
        jax.config.jax_compilation_cache_dir or "")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = 18379
    proc = subprocess.Popen(
        [sys.executable, "-c", _RUNNER_DRIVER,
         "--model", _MODEL, "--image-size", str(_SIZE),
         "--img-num", str(_NUM), "--buckets", "1,4",
         "--port", str(port), "--verdict-vector", "0.05*2,0.95*6",
         "--event-log-dir", str(tmp_path)],
        cwd=repo, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    try:
        deadline = time.monotonic() + 120
        ready = False
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                break
            try:
                if _req(port, "GET", "/readyz", timeout=2)[0] == 200:
                    ready = True
                    break
            except (urllib.error.URLError, ConnectionError, OSError):
                time.sleep(0.2)
        assert ready, (f"runner never ready rc={proc.poll()}\n"
                       f"{proc.stderr.read() if proc.poll() is not None else ''}")

        sid = _open_stream(port, "e2e")
        rng = np.random.default_rng(5)
        frames = [rng.integers(0, 255, (_SIZE, _SIZE, 3), dtype=np.uint8)
                  for _ in range(16)]
        body, headers = _raw_frames(frames)
        status, ack = _req(port, "POST", f"/streams/{sid}/frames", body,
                           headers)
        assert status == 200 and ack["frames_accepted"] == 16
        st = _wait_scored(port, sid, 8, timeout=60)
        assert st["verdict"] == "fake"                # planted flip landed
        status, text = _req(port, "GET", "/metrics")
        assert "dfd_streaming_windows_scored_total" in text
        status, final = _req(port, "DELETE", f"/streams/{sid}")
        assert status == 200
        # schema-versioned events landed in the JSONL sink
        log = tmp_path / "e2e.events.jsonl"
        assert log.exists()
        events = [json.loads(ln) for ln in
                  log.read_text().strip().splitlines()]
        assert any(e["to"] == "fake" for e in events)
        assert all(e["schema"].startswith("dfd.streaming.verdict.v")
                   for e in events)
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


# ---------------------------------------------------------------------------
# ffmpeg soft dependency
# ---------------------------------------------------------------------------

def test_container_ingest_501_without_ffmpeg(stack):
    from deepfake_detection_tpu.streaming.ingest import FfmpegDemuxer
    if FfmpegDemuxer.available():
        pytest.skip("ffmpeg installed: the 501 soft-dep path is inert")
    sid = _open_stream(stack.port, "container")
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(stack.port, "POST", f"/streams/{sid}/frames",
                 b"\x00" * 64, {"Content-Type": "video/mp4"})
        assert ei.value.code == 501
        assert "ffmpeg" in json.loads(ei.value.read())["error"]
    finally:
        _req(stack.port, "DELETE", f"/streams/{sid}")


def test_ffmpeg_demuxer_roundtrip(stack):
    """Container chunks → frames via the per-session ffmpeg subprocess
    (runs only where the soft dependency is installed)."""
    from deepfake_detection_tpu.streaming.ingest import (FfmpegDemuxer,
                                                         decode_frame_bytes)
    if not FfmpegDemuxer.available():
        pytest.skip("no ffmpeg binary on PATH (soft dependency)")
    import io

    from PIL import Image
    rng = np.random.default_rng(8)
    raw = b"".join(
        _jpeg_bytes_for_ffmpeg(Image, io, rng) for _ in range(6))
    d = FfmpegDemuxer()
    d.feed(raw)                       # MJPEG in → MJPEG out (re-encoded)
    frames = d.poll_frames(wait_s=5.0) + d.close()
    assert len(frames) == 6
    for f in frames:
        arr = decode_frame_bytes(f)
        assert arr is not None and arr.shape[2] == 3


def _jpeg_bytes_for_ffmpeg(Image, io, rng):
    buf = io.BytesIO()
    Image.fromarray(rng.integers(0, 255, (32, 32, 3), dtype=np.uint8)
                    ).save(buf, "JPEG", quality=90)
    return buf.getvalue()


# ---------------------------------------------------------------------------
# bench smoke (slow tier: subprocess server + load phases)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_bench_stream_smoke(tmp_path):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "bench.md"
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "bench_stream.py"),
         "--smoke", "--image-size", "32", "--img-num", "2",
         "--buckets", "1,4", "--out", str(out)],
        cwd=repo, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    text = out.read_text()
    assert "PASS" in text                      # verdict probe
    assert "delta across every load/probe phase = **0**" in text


# ---------------------------------------------------------------------------
# container demux death over HTTP (ISSUE 10 satellite): counted per-stream
# error + reset, never a hang
# ---------------------------------------------------------------------------

def test_container_demux_death_counted_and_reset_over_http(
        stack, tmp_path, monkeypatch):
    """ffmpeg dying mid-stream surfaces as a 422 with the demuxer reset
    and ``dfd_streaming_demux_failures_total`` + the per-stream counter
    moving; the session stays usable and closes cleanly."""
    import io

    from PIL import Image
    from test_streaming import _stub_ffmpeg

    from deepfake_detection_tpu.streaming import ingest as ingest_mod

    stub = _stub_ffmpeg(tmp_path)

    class StubDemuxer(ingest_mod.FfmpegDemuxer):
        @staticmethod
        def available(binary="ffmpeg"):
            return True

        def __init__(self, binary="ffmpeg"):
            super().__init__(binary=str(stub))

    monkeypatch.setattr(ingest_mod, "FfmpegDemuxer", StubDemuxer)
    port = stack.port
    sid = _open_stream(port, "demux-kill")
    rng = np.random.default_rng(3)
    buf = io.BytesIO()
    Image.fromarray(rng.integers(0, 255, (_SIZE, _SIZE, 3),
                                 dtype=np.uint8)).save(buf, "JPEG",
                                                       quality=90)
    jpeg = buf.getvalue()
    headers = {"Content-Type": "video/mp4"}
    status, ack = _req(port, "POST", f"/streams/{sid}/frames",
                       jpeg * 2, headers)
    assert status == 200                      # passthrough stub: frames
    assert ack["frames_accepted"] == 2        # surface like real ffmpeg
    session = stack.manager.get(sid)
    failures0 = stack.metrics.demux_failures_total.value
    session.demuxer._proc.kill()              # ffmpeg dies mid-stream
    session.demuxer._proc.wait(timeout=10)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(port, "POST", f"/streams/{sid}/frames", jpeg, headers)
    assert ei.value.code == 422               # surfaced, not hung
    assert stack.metrics.demux_failures_total.value == failures0 + 1
    _, st = _req(port, "GET", f"/streams/{sid}")
    assert st["counters"]["demux_failures"] == 1
    assert session.demuxer is None            # reset for the next chunk
    # the stream stays usable: the next container chunk gets a fresh
    # demuxer, and close-flush is safe
    status, ack = _req(port, "POST", f"/streams/{sid}/frames", jpeg,
                       headers)
    assert status == 200 and ack["frames_accepted"] == 1
    status, final = _req(port, "DELETE", f"/streams/{sid}")
    assert status == 200 and final["counters"]["demux_failures"] == 1
