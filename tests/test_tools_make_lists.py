"""tools/make_lists.py against a tmpdir fixture tree (ISSUE 2 satellite)."""

import os
import sys

import numpy as np
import pytest
from PIL import Image

pytestmark = pytest.mark.smoke

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                "tools"))

from make_lists import contiguous_count, main, scan_clips  # noqa: E402


def _write_frames(clip_dir, indices, wh=16):
    os.makedirs(clip_dir, exist_ok=True)
    rng = np.random.default_rng(0)
    for i in indices:
        Image.fromarray(rng.integers(0, 255, (wh, wh, 3), dtype=np.uint8)
                        ).save(os.path.join(clip_dir, f"{i}.jpg"))


@pytest.fixture()
def tree(tmp_path):
    root = tmp_path / "data"
    _write_frames(str(root / "real" / "clip_a"), range(4))
    _write_frames(str(root / "real" / "clip_b"), range(6))
    # nested clip (DeeperForensics-style manipulation subdirs)
    _write_frames(str(root / "fake" / "manip_x" / "clip_c"), range(4))
    # short clip: 2 frames
    _write_frames(str(root / "fake" / "clip_short"), range(2))
    # gap: frames 0,1,3 — only 2 reachable
    _write_frames(str(root / "fake" / "clip_gap"), [0, 1, 3])
    # corrupt jpeg in an otherwise fine clip
    _write_frames(str(root / "fake" / "clip_bad"), range(4))
    with open(str(root / "fake" / "clip_bad" / "2.jpg"), "wb") as f:
        f.write(b"\xff\xd8\xff\xe0 truncated garbage")
    return str(root)


def test_lists_written_in_v3_format(tree):
    assert main([tree]) == 0
    with open(os.path.join(tree, "real_list.txt")) as f:
        real = dict(line.strip().split(":") for line in f)
    assert real == {"clip_a": "4", "clip_b": "6"}
    with open(os.path.join(tree, "fake_list.txt")) as f:
        fake = dict(line.strip().split(":") for line in f)
    assert fake[os.path.join("manip_x", "clip_c")] == "4"
    assert fake["clip_short"] == "2"
    assert fake["clip_gap"] == "2"         # dense prefix stops at the gap

    # the dataset layer consumes these files directly
    from deepfake_detection_tpu.data.dataset import read_clip_list
    clips = read_clip_list(os.path.join(tree, "real_list.txt"))
    assert [(c[0], c[1]) for c in clips] == [("clip_a", 4), ("clip_b", 6)]


def test_validate_flags_all_three_problem_kinds(tree, capsys):
    rc = main([tree, "--validate", "--strict"])
    assert rc == 1
    err = capsys.readouterr().err
    assert "missing frame 2.jpg" in err            # clip_gap
    assert "short clip" in err                     # clip_short (and gap)
    assert "corrupt JPEG" in err                   # clip_bad/2.jpg
    # non-strict validate reports but exits 0
    assert main([tree, "--validate"]) == 0


def test_out_dir_and_missing_class_dir(tmp_path, capsys):
    root = tmp_path / "only_real"
    _write_frames(str(root / "real" / "c"), range(4))
    out = tmp_path / "lists"
    os.makedirs(str(out))
    assert main([str(root), "--out-dir", str(out)]) == 0
    assert open(str(out / "real_list.txt")).read() == "c:4\n"
    assert open(str(out / "fake_list.txt")).read() == ""


def test_contiguous_count():
    assert contiguous_count([0, 1, 2, 3]) == 4
    assert contiguous_count([0, 1, 3]) == 2
    assert contiguous_count([1, 2]) == 0
    assert contiguous_count([]) == 0


def test_scan_clips_ignores_non_frame_files(tmp_path):
    clip = tmp_path / "real" / "c"
    _write_frames(str(clip), range(3))
    open(str(clip / "notes.txt"), "w").write("x")
    open(str(clip / "frame_07.jpg"), "w").write("x")   # not <i>.jpg
    clips = scan_clips(str(tmp_path / "real"))
    assert clips == {"c": [0, 1, 2]}
