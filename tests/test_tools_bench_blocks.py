"""tools/bench_blocks.py --smoke: the bench harness itself cannot rot.

One fresh-interpreter run of the full row matrix at seconds-scale shapes;
asserts every row family emits both implementations with sane numbers.
Performance is NOT asserted (CPU, interpreter Pallas) — the doc tables
only admit TPU-stamped rows, which is exactly what the ``interpret`` /
``device`` fields in each row exist to gate.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.smoke, pytest.mark.pallas]

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


@pytest.mark.slow   # tier-1 budget: subprocess bench smoke (~33s)
def test_bench_blocks_smoke_emits_full_matrix():
    # share the suite's persistent compilation cache (conftest.py): the
    # XLA step/stem programs dominate the smoke's runtime and cache across
    # runs; only the interpret-mode Pallas tracing re-pays every time
    cache = os.environ.get("JAX_COMPILATION_CACHE_DIR",
                           os.path.join(_REPO, ".jax_cache"))
    env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu",
               JAX_COMPILATION_CACHE_DIR=cache)
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "bench_blocks.py"),
         "--smoke"],
        capture_output=True, text=True, env=env, timeout=600, check=True)
    rows = [json.loads(ln) for ln in out.stdout.splitlines() if ln.strip()]
    assert any("note" in r for r in rows)       # CPU rows are flagged

    blocks = [r for r in rows if r.get("row") == "block"]
    assert {r["impl"] for r in blocks} == {"xla", "pallas"}
    assert not any("error" in r for r in blocks), blocks
    for r in blocks:
        assert r["fwd_ms"] > 0 and r["fwd_bwd_ms"] > 0
        # the interpreter stamp gates these rows out of the doc tables
        assert r["interpret"] == (r["impl"] == "pallas")

    stems = [r for r in rows if r.get("row") == "stem"]
    assert {r["impl"] for r in stems} == {"stride2", "s2d"}

    steps = [r for r in rows if r.get("row") == "step"]
    assert {r["impl"] for r in steps} == \
        {"baseline", "fused", "s2d", "fused+s2d"}
    assert not any("error" in r for r in steps), steps
