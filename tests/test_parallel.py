"""Distributed runtime tests on the 8-device CPU mesh (SURVEY.md §4)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepfake_detection_tpu.parallel import (batch_sharding, distribute_bn,
                                             fsdp_param_specs, full_attention,
                                             make_mesh, param_sharding,
                                             ring_attention,
                                             ring_self_attention, shard_batch,
                                             ulysses_attention)


class TestMesh:
    def test_default_1d(self, devices):
        mesh = make_mesh()
        assert mesh.axis_names == ("data",)
        assert mesh.shape["data"] == 8

    def test_2d_with_inference(self, devices):
        mesh = make_mesh((-1, 2), ("data", "model"))
        assert mesh.shape["data"] == 4
        assert mesh.shape["model"] == 2

    def test_bad_shape_raises(self, devices):
        with pytest.raises(AssertionError):
            make_mesh((3, 2), ("data", "model"))


class TestSharding:
    def test_batch_sharding_distributes_rows(self, devices):
        mesh = make_mesh()
        x = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)
        arr = shard_batch(x, mesh)
        assert arr.shape == (16, 4)
        assert len(arr.addressable_shards) == 8
        assert arr.addressable_shards[0].data.shape == (2, 4)
        np.testing.assert_array_equal(np.asarray(arr), x)

    def test_fsdp_specs(self, devices):
        mesh = make_mesh()
        params = {"big": jnp.zeros((1024, 256)), "small": jnp.zeros((7,)),
                  "odd": jnp.zeros((129, 3, 3, 129))}
        specs = fsdp_param_specs(params, mesh, min_size=1024)
        assert specs["big"] == P("data", None)   # largest dim divisible by 8
        assert specs["small"] == P()             # too small
        assert specs["odd"] == P()               # nothing divisible
        shardings = param_sharding(params, mesh, fsdp=True)
        assert isinstance(shardings["big"], NamedSharding)

    def test_pjit_dp_matmul(self, devices):
        mesh = make_mesh()
        w = jnp.ones((4, 2))
        x = shard_batch(np.ones((16, 4), np.float32), mesh)

        @functools.partial(jax.jit,
                           out_shardings=NamedSharding(mesh, P()))
        def step(w, x):
            return (x @ w).sum()

        assert float(step(w, x)) == 16 * 4 * 2


class TestDistributeBn:
    def test_replicated_identity(self):
        stats = {"mean": jnp.ones(4)}
        out = distribute_bn(stats, "reduce", inside_pjit=False)
        np.testing.assert_array_equal(np.asarray(out["mean"]), 1.0)

    def test_reduce_inside_shard_map(self, devices):
        from deepfake_detection_tpu.parallel._compat import shard_map
        mesh = make_mesh()

        def f(stats):
            return distribute_bn(stats, "reduce", inside_pjit=True)

        stats = {"mean": np.arange(8, dtype=np.float32).reshape(8, 1)}
        out = shard_map(f, mesh=mesh, in_specs=({"mean": P("data", None)},),
                        out_specs={"mean": P("data", None)})(stats)
        np.testing.assert_allclose(np.asarray(out["mean"]),
                                   np.full((8, 1), 3.5))

    def test_broadcast_inside_shard_map(self, devices):
        from deepfake_detection_tpu.parallel._compat import shard_map
        mesh = make_mesh()

        def f(stats):
            return distribute_bn(stats, "broadcast", inside_pjit=True)

        stats = {"mean": np.arange(8, dtype=np.float32).reshape(8, 1)}
        out = shard_map(f, mesh=mesh, in_specs=({"mean": P("data", None)},),
                        out_specs={"mean": P("data", None)})(stats)
        np.testing.assert_allclose(np.asarray(out["mean"]),
                                   np.zeros((8, 1)))  # rank 0's value


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, devices, causal):
        mesh = make_mesh()
        b, l, h, d = 2, 32, 4, 8
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
        ref = full_attention(q, k, v, causal=causal)
        out = ring_self_attention(q, k, v, mesh, seq_axis="data",
                                  causal=causal, impl="ring")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_ulysses_matches_full_attention(self, devices):
        mesh = make_mesh()
        b, l, h, d = 2, 32, 8, 4            # heads divisible by 8
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
        ref = full_attention(q, k, v)
        out = ring_self_attention(q, k, v, mesh, seq_axis="data",
                                  impl="ulysses")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_ring_jits_under_shard_map(self, devices):
        mesh = make_mesh()
        b, l, h, d = 1, 16, 2, 4
        x = jnp.ones((b, l, h, d), jnp.float32)
        f = jax.jit(lambda q, k, v: ring_self_attention(q, k, v, mesh))
        out = f(x, x, x)
        assert out.shape == (b, l, h, d)
