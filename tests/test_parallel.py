"""Distributed runtime tests on the 8-device CPU mesh (SURVEY.md §4)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepfake_detection_tpu.parallel import (batch_sharding, distribute_bn,
                                             fsdp_param_specs, full_attention,
                                             make_mesh, param_sharding,
                                             ring_attention,
                                             ring_self_attention, shard_batch,
                                             ulysses_attention)


class TestMesh:
    def test_default_1d(self, devices):
        mesh = make_mesh()
        assert mesh.axis_names == ("data",)
        assert mesh.shape["data"] == 8

    def test_2d_with_inference(self, devices):
        mesh = make_mesh((-1, 2), ("data", "model"))
        assert mesh.shape["data"] == 4
        assert mesh.shape["model"] == 2

    def test_bad_shape_raises(self, devices):
        with pytest.raises(AssertionError):
            make_mesh((3, 2), ("data", "model"))


class TestTrainMesh:
    def test_unified_axes_and_inference(self, devices):
        from deepfake_detection_tpu.parallel import (data_axis_name,
                                                     make_train_mesh)
        mesh = make_train_mesh()
        assert mesh.axis_names == ("batch", "model")
        assert mesh.shape["batch"] == 8 and mesh.shape["model"] == 1
        assert data_axis_name(mesh) == "batch"
        mesh2 = make_train_mesh(batch=-1, model=2)
        assert mesh2.shape["batch"] == 4 and mesh2.shape["model"] == 2

    def test_data_axis_name_legacy_and_fallback(self, devices):
        from deepfake_detection_tpu.parallel import data_axis_name
        assert data_axis_name(make_mesh()) == "data"
        assert data_axis_name(make_mesh((8,), ("replica",))) == "replica"

    def test_batch_sharding_resolves_mesh_axis(self, devices):
        from deepfake_detection_tpu.parallel import make_train_mesh
        sh = batch_sharding(make_train_mesh())
        assert sh.spec == P("batch")
        assert batch_sharding(make_mesh()).spec == P("data")


class TestTrainStateShardingTable:
    """The ISSUE 12 sharding-rule table: every TrainState leaf gets its
    NamedSharding, opt moments and EMA follow their params."""

    def _state(self, with_ema=False):
        from types import SimpleNamespace
        from deepfake_detection_tpu.models import create_model, init_model
        from deepfake_detection_tpu.optim import create_optimizer
        from deepfake_detection_tpu.train import create_train_state
        m = create_model("mnasnet_small", num_classes=2, in_chans=3)
        v = init_model(m, jax.random.PRNGKey(0), (2, 32, 32, 3),
                       training=True)
        tx = create_optimizer(SimpleNamespace(
            opt="rmsproptf", opt_eps=1e-3, momentum=0.9, weight_decay=0.0,
            lr=1e-3, decay_rate=0.9), inject=True)
        return create_train_state(v, tx, with_ema=with_ema)

    def test_default_rules_congruent_and_replicated(self, devices):
        from deepfake_detection_tpu.parallel import (make_train_mesh,
                                                     train_state_shardings)
        state = self._state(with_ema=True)
        mesh = make_train_mesh()
        sh = train_state_shardings(state, mesh)
        # congruent tree: one NamedSharding per leaf
        flat_s, tree_s = jax.tree.flatten(sh)
        flat_x, tree_x = jax.tree.flatten(state)
        assert tree_s == tree_x
        assert all(isinstance(s, NamedSharding) for s in flat_s)
        # pure DP: everything replicated
        assert all(s.spec == P() for s in flat_s)

    def test_fsdp_rule_propagates_to_moments_and_ema(self, devices):
        from deepfake_detection_tpu.parallel import (make_train_mesh,
                                                     train_state_shardings)
        state = self._state(with_ema=True)
        mesh = make_train_mesh()
        sh = train_state_shardings(state, mesh, fsdp=True)
        sharded_params = [s for s in jax.tree.leaves(sh.params)
                         if s.spec != P()]
        assert sharded_params, "no param leaf was FSDP-sharded"
        # the opt-state moments mirror the params tree → same specs
        p_specs = [s.spec for s in jax.tree.leaves(sh.params)]
        opt_named = [s.spec for s in jax.tree.leaves(sh.opt_state)
                     if s.spec != P()]
        assert opt_named, "no moment leaf followed its param's sharding"
        assert set(map(str, opt_named)) <= set(map(str, p_specs))
        ema_specs = [s.spec for s in jax.tree.leaves(sh.ema["params"])]
        assert ema_specs == p_specs
        # BN stats + step stay replicated regardless
        assert all(s.spec == P()
                   for s in jax.tree.leaves(sh.batch_stats))
        assert sh.step.spec == P()

    def test_existing_tp_placement_wins(self, devices):
        from deepfake_detection_tpu.parallel import (make_train_mesh,
                                                     train_state_shardings)
        from deepfake_detection_tpu.train.state import TrainState
        mesh = make_train_mesh(batch=-1, model=2)
        tp_sh = NamedSharding(mesh, P(None, "model"))
        params = {"w": jax.device_put(jnp.zeros((4, 8)), tp_sh),
                  "b": jnp.zeros((8,))}
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           batch_stats={}, opt_state=(), ema=None)
        sh = train_state_shardings(state, mesh)
        assert sh.params["w"].spec == P(None, "model")
        assert sh.params["b"].spec == P()

    def test_place_train_state_lays_out(self, devices):
        from deepfake_detection_tpu.parallel import (make_train_mesh,
                                                     place_train_state,
                                                     train_state_shardings)
        state = self._state()
        mesh = make_train_mesh()
        sh = train_state_shardings(state, mesh, fsdp=True)
        placed = place_train_state(state, sh)
        for leaf, want in zip(jax.tree.leaves(placed),
                              jax.tree.leaves(sh)):
            assert leaf.sharding == want, (leaf.sharding, want)


class TestSharding:
    def test_batch_sharding_distributes_rows(self, devices):
        mesh = make_mesh()
        x = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)
        arr = shard_batch(x, mesh)
        assert arr.shape == (16, 4)
        assert len(arr.addressable_shards) == 8
        assert arr.addressable_shards[0].data.shape == (2, 4)
        np.testing.assert_array_equal(np.asarray(arr), x)

    def test_fsdp_specs(self, devices):
        mesh = make_mesh()
        params = {"big": jnp.zeros((1024, 256)), "small": jnp.zeros((7,)),
                  "odd": jnp.zeros((129, 3, 3, 129))}
        specs = fsdp_param_specs(params, mesh, min_size=1024)
        assert specs["big"] == P("data", None)   # largest dim divisible by 8
        assert specs["small"] == P()             # too small
        assert specs["odd"] == P()               # nothing divisible
        shardings = param_sharding(params, mesh, fsdp=True)
        assert isinstance(shardings["big"], NamedSharding)

    def test_pjit_dp_matmul(self, devices):
        mesh = make_mesh()
        w = jnp.ones((4, 2))
        x = shard_batch(np.ones((16, 4), np.float32), mesh)

        @functools.partial(jax.jit,
                           out_shardings=NamedSharding(mesh, P()))
        def step(w, x):
            return (x @ w).sum()

        assert float(step(w, x)) == 16 * 4 * 2


class TestDistributeBn:
    def test_replicated_identity(self):
        stats = {"mean": jnp.ones(4)}
        out = distribute_bn(stats, "reduce", inside_pjit=False)
        np.testing.assert_array_equal(np.asarray(out["mean"]), 1.0)

    def test_reduce_inside_shard_map(self, devices):
        from deepfake_detection_tpu.parallel._compat import shard_map
        mesh = make_mesh()

        def f(stats):
            return distribute_bn(stats, "reduce", inside_pjit=True)

        stats = {"mean": np.arange(8, dtype=np.float32).reshape(8, 1)}
        out = shard_map(f, mesh=mesh, in_specs=({"mean": P("data", None)},),
                        out_specs={"mean": P("data", None)})(stats)
        np.testing.assert_allclose(np.asarray(out["mean"]),
                                   np.full((8, 1), 3.5))

    def test_broadcast_inside_shard_map(self, devices):
        from deepfake_detection_tpu.parallel._compat import shard_map
        mesh = make_mesh()

        def f(stats):
            return distribute_bn(stats, "broadcast", inside_pjit=True)

        stats = {"mean": np.arange(8, dtype=np.float32).reshape(8, 1)}
        out = shard_map(f, mesh=mesh, in_specs=({"mean": P("data", None)},),
                        out_specs={"mean": P("data", None)})(stats)
        np.testing.assert_allclose(np.asarray(out["mean"]),
                                   np.zeros((8, 1)))  # rank 0's value


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, devices, causal):
        mesh = make_mesh()
        b, l, h, d = 2, 32, 4, 8
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
        ref = full_attention(q, k, v, causal=causal)
        out = ring_self_attention(q, k, v, mesh, seq_axis="data",
                                  causal=causal, impl="ring")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_ulysses_matches_full_attention(self, devices):
        mesh = make_mesh()
        b, l, h, d = 2, 32, 8, 4            # heads divisible by 8
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
        ref = full_attention(q, k, v)
        out = ring_self_attention(q, k, v, mesh, seq_axis="data",
                                  impl="ulysses")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_ring_jits_under_shard_map(self, devices):
        mesh = make_mesh()
        b, l, h, d = 1, 16, 2, 4
        x = jnp.ones((b, l, h, d), jnp.float32)
        f = jax.jit(lambda q, k, v: ring_self_attention(q, k, v, mesh))
        out = f(x, x, x)
        assert out.shape == (b, l, h, d)
