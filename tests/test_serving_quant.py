"""PTQ serving-path tests (ISSUE 14): quantize/realize units, padded-
bucket bit-identity under bf16 and int8 (the PR 2 idiom), CLI-oracle
parity, and the quantized reload canary.

Fast tier (``quant`` marker): everything runs a small model at a tiny
canvas so the bucket compiles stay cheap and hit the persistent
compilation cache on reruns.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepfake_detection_tpu.models import create_model, init_model
from deepfake_detection_tpu.params import (make_score_fn,
                                           normalize_replicate,
                                           prepare_canvas)
from deepfake_detection_tpu.serving.engine import InferenceEngine
from deepfake_detection_tpu.serving.quant import (canonical_mode,
                                                  is_quantized_leaf,
                                                  quant_summary,
                                                  quantize_leaf,
                                                  quantize_tree,
                                                  realize_tree)

pytestmark = [pytest.mark.serving, pytest.mark.quant]

_MODEL = "mobilenetv3_small_100"
_SIZE = 24


def _perturbed_variables(model, size, chans, seed=0):
    variables = init_model(model, jax.random.PRNGKey(0),
                           (1, size, size, chans))
    rng = np.random.default_rng(seed)
    return jax.tree.map(
        lambda a: a + jnp.asarray(
            0.02 * rng.standard_normal(np.shape(a)).astype(np.float32)
        ).astype(a.dtype),
        variables)


def _canvases(n, size=_SIZE, seed=0):
    rng = np.random.default_rng(seed)
    return [prepare_canvas(
        rng.integers(0, 255, (40, 36, 3), dtype=np.uint8), size)
        for _ in range(n)]


# ---------------------------------------------------------------------------
# transform units
# ---------------------------------------------------------------------------

def test_canonical_mode_aliases():
    assert canonical_mode("float32") == "f32"
    assert canonical_mode("BF16") == "bf16"
    assert canonical_mode("bfloat16") == "bf16"
    assert canonical_mode("int8") == "int8"
    with pytest.raises(ValueError):
        canonical_mode("fp8")


def test_quantize_leaf_per_output_channel_scales():
    """Symmetric per-output-channel int8: the scale is the per-channel
    absmax / 127, and every dequantized element is within scale/2 of the
    original (round-to-nearest)."""
    rng = np.random.default_rng(3)
    # wildly different per-channel magnitudes: a per-TENSOR scale would
    # crush the small channels to zero
    w = rng.standard_normal((3, 3, 8, 4)).astype(np.float32)
    w *= np.asarray([1e-3, 1.0, 50.0, 0.1], np.float32)
    q, scale = quantize_leaf(w)
    assert q.dtype == np.int8 and scale.shape == (4,)
    np.testing.assert_allclose(scale, np.abs(w).max(axis=(0, 1, 2)) / 127,
                               rtol=1e-6)
    deq = q.astype(np.float32) * scale
    assert np.all(np.abs(deq - w) <= scale / 2 + 1e-9)
    # an all-zero output channel must not divide by zero
    w0 = np.zeros((2, 2, 4, 3), np.float32)
    q0, s0 = quantize_leaf(w0)
    assert np.all(q0 == 0) and np.all(s0 == 1.0)
    # a non-finite channel must get a NaN scale (dequant reproduces the
    # poison for the canary) — int8 casting would launder NaN/inf into
    # finite garbage the finite-scores gate cannot see
    wn = np.ones((2, 2, 4, 3), np.float32)
    wn[0, 0, 0, 0] = np.nan
    wn[0, 0, 0, 2] = np.inf
    qn, sn = quantize_leaf(wn)
    assert np.isnan(sn[0]) and np.isnan(sn[2]) and sn[1] == 1.0 / 127
    deq = qn.astype(np.float32) * sn
    assert np.isnan(deq[..., 0]).all() and np.isnan(deq[..., 2]).all()
    np.testing.assert_allclose(deq[..., 1], wn[..., 1], rtol=1e-6)


def test_quantize_tree_modes():
    model = create_model(_MODEL, num_classes=2, in_chans=3)
    v = _perturbed_variables(model, _SIZE, 3)
    # f32 is the identity (same object — no rebuild, no cast)
    assert quantize_tree(v, "f32") is v
    assert realize_tree(v) is v
    qb = quantize_tree(v, "bf16")
    sb = quant_summary(qb)
    assert sb["bf16_leaves"] > 0 and sb["quantized_leaves"] == 0
    # batch_stats stay f32 (numerically load-bearing)
    assert str(jax.tree.leaves(qb["batch_stats"])[0].dtype) == "float32"
    qi = quantize_tree(v, "int8")
    si = quant_summary(qi)
    assert si["quantized_leaves"] > 0 and si["bf16_leaves"] == 0
    # realize rebuilds the ORIGINAL tree structure with close values
    r = realize_tree(qi)
    flat_v, tree_v = jax.tree.flatten(v)
    flat_r, tree_r = jax.tree.flatten(r)
    assert tree_v == tree_r
    for a, b in zip(flat_v, flat_r):
        assert np.shape(a) == np.shape(b)
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=0, atol=0.05)


def test_int8_container_is_a_plain_pytree():
    """device_put / flatten / AOT avals all work on the container — the
    params-as-arguments machinery must not special-case quantization."""
    model = create_model(_MODEL, num_classes=2, in_chans=3)
    v = _perturbed_variables(model, _SIZE, 3)
    qi = jax.device_put(quantize_tree(v, "int8"))
    leaves = jax.tree.leaves(qi)
    assert any(l.dtype == jnp.int8 for l in leaves)
    assert any(is_quantized_leaf(l) for l in jax.tree.leaves(
        qi, is_leaf=is_quantized_leaf))


# ---------------------------------------------------------------------------
# padded-bucket bit-identity under quantized serving (the PR 2 idiom)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("wire", ["float32", "uint8"])
@pytest.mark.parametrize("dtype", ["bf16", "int8"])
def test_padded_bucket_bit_identity_quantized(wire, dtype):
    """Padding rows cannot perturb real rows on a quantized engine: the
    same 3 requests score bit-for-bit whether they ride a zero-padded
    bucket-4 batch or a full one — quantization changes the weights, not
    the row-independence of eval mode."""
    model = create_model(_MODEL, num_classes=2, in_chans=3)
    variables = _perturbed_variables(model, _SIZE, 3, seed=7)
    engine = InferenceEngine(model, variables, image_size=_SIZE,
                             img_num=1, buckets=(4,), wire=wire,
                             dtype=dtype)
    canvases = _canvases(4, seed=11)
    if wire == "float32":
        payloads = [normalize_replicate(c, 1) for c in canvases]
    else:
        payloads = canvases
    padded = engine.score_batch(payloads[:3])     # 3 -> bucket 4 + pad
    full = engine.score_batch(payloads)           # full bucket 4
    np.testing.assert_array_equal(padded, full[:3])
    assert np.isfinite(padded).all()
    assert np.allclose(padded.sum(axis=1), 1.0, atol=1e-5)


def test_quantized_scores_near_f32():
    """Sanity bound (the measured gate is tools/quant_parity.py): bf16
    and int8 serving scores stay close to f32 on the same engine
    geometry."""
    model = create_model(_MODEL, num_classes=2, in_chans=3)
    variables = _perturbed_variables(model, _SIZE, 3, seed=5)
    payloads = [normalize_replicate(c, 1) for c in _canvases(4, seed=3)]
    scores = {}
    for dtype in ("f32", "bf16", "int8"):
        engine = InferenceEngine(model, variables, image_size=_SIZE,
                                 img_num=1, buckets=(4,), dtype=dtype)
        scores[dtype] = engine.score_batch(payloads)
    np.testing.assert_allclose(scores["bf16"], scores["f32"], atol=0.02)
    np.testing.assert_allclose(scores["int8"], scores["f32"], atol=0.06)


# ---------------------------------------------------------------------------
# CLI oracle: runners/test.py --dtype === the engine's float32 wire
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", ["f32", "bf16", "int8"])
def test_cli_score_fn_bit_identical_to_engine_f32_wire(dtype):
    """`make_score_fn` over the quantized tree and the engine's float32-
    wire program are the same variables-as-argument trace — the CLI is
    the parity harness's non-server oracle, bit-identical at every
    dtype (not just f32)."""
    model = create_model(_MODEL, num_classes=2, in_chans=3)
    variables = _perturbed_variables(model, _SIZE, 3, seed=9)
    engine = InferenceEngine(model, variables, image_size=_SIZE,
                             img_num=1, buckets=(1,), wire="float32",
                             dtype=dtype)
    payload = normalize_replicate(_canvases(1, seed=2)[0], 1)
    got = engine.score_batch([payload])
    cli = make_score_fn(model, quantize_tree(variables, dtype))
    want = np.asarray(cli(jnp.asarray(payload[None])))
    np.testing.assert_array_equal(got, want)


def test_runners_test_dtype_flag_parses():
    """The --dtype surface exists and rejects junk (the heavy flagship
    CLI e2e stays out of the fast tier)."""
    from deepfake_detection_tpu.runners import test as test_runner
    with pytest.raises(SystemExit):
        test_runner.main(["--dtype", "fp8", "img.jpg"])


# ---------------------------------------------------------------------------
# quantized hot reload: canary gates the QUANTIZED candidate
# ---------------------------------------------------------------------------

def test_quantized_reload_swaps_and_matches_fresh_quantization():
    """An f32 checkpoint reloaded into an int8 engine serves the same
    scores as an engine freshly built from those weights at int8 — the
    reload path re-quantizes deterministically."""
    model = create_model(_MODEL, num_classes=2, in_chans=3)
    v1 = _perturbed_variables(model, _SIZE, 3, seed=1)
    v2 = _perturbed_variables(model, _SIZE, 3, seed=2)
    engine = InferenceEngine(model, v1, image_size=_SIZE, img_num=1,
                             buckets=(1,), dtype="int8")
    payload = normalize_replicate(_canvases(1, seed=4)[0], 1)
    before = engine.score_batch([payload])
    host_v2 = jax.tree.map(np.asarray, v2)
    engine.submit_reload(host_v2, source="<test>")
    engine._maybe_apply_reload()
    assert engine.reload_count == 1
    after = engine.score_batch([payload])
    assert not np.array_equal(before, after)
    oracle = InferenceEngine(model, v2, image_size=_SIZE, img_num=1,
                             buckets=(1,), dtype="int8")
    np.testing.assert_array_equal(after, oracle.score_batch([payload]))


def test_quantized_reload_canary_rejects_nan_checkpoint():
    """A poisoned f32 checkpoint must fail the QUANTIZED canary (the
    failure-mode table's 'quantized canary reject' row): weights roll
    back bit-identically, the counter moves."""
    model = create_model(_MODEL, num_classes=2, in_chans=3)
    v = _perturbed_variables(model, _SIZE, 3, seed=1)
    engine = InferenceEngine(model, v, image_size=_SIZE, img_num=1,
                             buckets=(1,), dtype="int8")
    payload = normalize_replicate(_canvases(1, seed=4)[0], 1)
    before = engine.score_batch([payload])
    host = jax.tree.map(np.asarray, v)
    nan_tree = jax.tree.map(
        lambda a: np.full_like(a, np.nan)
        if np.issubdtype(a.dtype, np.floating) else a, host)
    errors0 = engine.metrics.reload_errors_total.value
    canary0 = engine.metrics.reload_canary_failures_total.value
    engine.submit_reload(nan_tree, source="<nan>")
    engine._maybe_apply_reload()
    assert engine.reload_count == 0
    assert engine.metrics.reload_errors_total.value == errors0 + 1
    assert engine.metrics.reload_canary_failures_total.value == canary0 + 1
    np.testing.assert_array_equal(engine.score_batch([payload]), before)


def test_quantized_reload_canary_rejects_nan_kernels_only():
    """NaN confined to the KERNELS (the int8-quantized leaves, every
    other leaf healthy) must still fail the canary: quantize_leaf
    propagates a NaN scale instead of laundering the poison into finite
    int8 garbage that would score finite and commit the swap."""
    model = create_model(_MODEL, num_classes=2, in_chans=3)
    v = _perturbed_variables(model, _SIZE, 3, seed=1)
    engine = InferenceEngine(model, v, image_size=_SIZE, img_num=1,
                             buckets=(1,), dtype="int8")
    payload = normalize_replicate(_canvases(1, seed=4)[0], 1)
    before = engine.score_batch([payload])
    host = jax.tree.map(np.asarray, v)

    def poison(path, a):
        keys = [getattr(p, "key", None) for p in path]
        if "params" in keys and keys[-1] == "kernel" and a.ndim >= 2:
            return np.full_like(a, np.nan)
        return a

    nan_tree = jax.tree_util.tree_map_with_path(poison, host)
    errors0 = engine.metrics.reload_errors_total.value
    canary0 = engine.metrics.reload_canary_failures_total.value
    engine.submit_reload(nan_tree, source="<nan-kernels>")
    engine._maybe_apply_reload()
    assert engine.reload_count == 0
    assert engine.metrics.reload_errors_total.value == errors0 + 1
    assert engine.metrics.reload_canary_failures_total.value == canary0 + 1
    np.testing.assert_array_equal(engine.score_batch([payload]), before)
