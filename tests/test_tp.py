"""Tensor parallelism over a 2-D (data × model) mesh for the transformers.

GSPMD does the partitioning: we only annotate param shardings, jit the
unchanged model, and check numerics against the replicated run.
"""

from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepfake_detection_tpu.models import create_model, init_model
from deepfake_detection_tpu.parallel import (batch_sharding, shard_batch,
                                             transformer_tp_sharding,
                                             transformer_tp_specs)


@pytest.fixture()
def mesh2d(devices):
    return Mesh(np.asarray(devices).reshape(2, 4), ("data", "model"))


def test_specs_follow_megatron_pairing():
    m = create_model("vit_tiny_patch16_224", num_classes=2)
    v = init_model(m, jax.random.PRNGKey(0), (1, 64, 64, 3))
    specs = transformer_tp_specs(v["params"], axis="model", axis_size=4)
    blk = specs["blocks_0"]
    assert blk["attn"]["qkv"]["kernel"] == P(None, "model")
    assert blk["attn"]["qkv"]["bias"] == P("model")
    assert blk["attn"]["proj"]["kernel"] == P("model", None)
    assert blk["attn"]["proj"]["bias"] == P()
    assert blk["mlp_fc1"]["kernel"] == P(None, "model")
    assert blk["mlp_fc2"]["kernel"] == P("model", None)
    assert specs["patch_embed"]["kernel"] == P()      # replicated
    assert specs["norm"]["scale"] == P()


@pytest.mark.parametrize("name", ["vit_tiny_patch16_224",
                                  "timesformer_tiny_patch16_224"])
def test_tp_forward_matches_replicated(mesh2d, name):
    in_chans = 12 if name.startswith("timesformer") else 3
    m = create_model(name, num_classes=2, in_chans=in_chans)
    v = init_model(m, jax.random.PRNGKey(0), (2, 64, 64, in_chans))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 64, in_chans))
    ref = m.apply(v, x, training=False)

    shardings = transformer_tp_sharding(v["params"], mesh2d, axis="model")
    params_tp = jax.tree.map(jax.device_put, v["params"], shardings)
    x_tp = jax.device_put(x, batch_sharding(mesh2d, "data"))
    out = jax.jit(lambda p, x: m.apply({"params": p}, x,
                                       training=False))(params_tp, x_tp)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_tp_train_step(mesh2d):
    """dp×tp train step: batch on 'data', heads/hidden on 'model'; GSPMD
    keeps the optimizer update sharded like the params."""
    from deepfake_detection_tpu.losses import cross_entropy
    from deepfake_detection_tpu.optim import create_optimizer
    from deepfake_detection_tpu.train import (create_train_state,
                                              make_train_step)
    m = create_model("vit_tiny_patch16_224", num_classes=2)
    v = init_model(m, jax.random.PRNGKey(0), (2, 64, 64, 3))
    shardings = transformer_tp_sharding(v["params"], mesh2d, axis="model")
    v = {"params": jax.tree.map(jax.device_put, v["params"], shardings)}
    cfg = SimpleNamespace(opt="adamw", opt_eps=1e-8, momentum=0.9,
                          weight_decay=1e-5, lr=1e-4)
    tx = create_optimizer(cfg)
    state = create_train_state(v, tx)
    step = make_train_step(m, tx, cross_entropy, mesh=None,
                           bn_mode="global")
    x = jax.device_put(
        np.asarray(jax.random.normal(jax.random.PRNGKey(1),
                                     (4, 64, 64, 3))),
        batch_sharding(mesh2d, "data"))
    y = jax.device_put(np.arange(4) % 2, batch_sharding(mesh2d, "data"))
    state, metrics = step(state, x, y, jax.random.PRNGKey(2))
    assert np.isfinite(float(metrics["loss"]))
    # params stay TP-sharded after the update (no silent re-replication)
    k = state.params["blocks_0"]["attn"]["qkv"]["kernel"]
    assert "model" in str(k.sharding.spec)


_CLI_DRIVER = """
import json, os, sys
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
cache = os.environ.get("JAX_COMPILATION_CACHE_DIR")
if cache:
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
from deepfake_detection_tpu.runners.train import launch_main
out = launch_main(sys.argv[1:])
print("RESULT " + json.dumps({"best_metric": out["best_metric"]}))
"""


def _launch_cli(args):
    """Run the train CLI end-to-end in a FRESH interpreter.

    A fresh interpreter IS the artifact a CLI test should exercise — and
    process isolation means a native crash in the runner (the class of
    bug that donated-alias resume used to hit, see runners/train.py's
    resume ``_own`` note) can at worst fail this one test instead of
    killing the whole pytest process and every test after it."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)     # dark-relay guard (conftest)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_COMPILATION_CACHE_DIR"] = str(jax.config.jax_compilation_cache_dir or "")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, "-c", _CLI_DRIVER, *args],
                          cwd=repo, env=env, capture_output=True, text=True,
                          timeout=600)
    assert proc.returncode == 0, \
        f"CLI run failed rc={proc.returncode}\n{proc.stdout[-2000:]}\n" \
        f"{proc.stderr[-2000:]}"
    import json
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("RESULT ")][-1]
    return json.loads(line[len("RESULT "):])


@pytest.mark.slow   # tier-1 budget: fresh-interpreter CLI phases (~33s)
def test_tp_cli_e2e(tmp_path, devices):
    """--tp-size from the CLI: dp(2)xtp(4) synthetic smoke train."""
    out = _launch_cli([
        "--dataset", "synthetic", "--model", "vit_tiny_patch16_224",
        "--model-version", "", "--input-size-v2", "3,32,32",
        "--batch-size", "1", "--epochs", "1", "--opt", "adamw",
        "--lr", "1e-3", "--sched", "step", "--log-interval", "4",
        "--workers", "1", "--compute-dtype", "float32", "--tp-size", "4",
        "--output", str(tmp_path / "out")])
    assert out["best_metric"] is not None
    # resume re-applies the TP layout (restore rebuilds host arrays)
    run = next((tmp_path / "out").iterdir())
    out2 = _launch_cli([
        "--dataset", "synthetic", "--model", "vit_tiny_patch16_224",
        "--model-version", "", "--input-size-v2", "3,32,32",
        "--batch-size", "1", "--epochs", "2", "--opt", "adamw",
        "--lr", "1e-3", "--sched", "step", "--log-interval", "4",
        "--workers", "1", "--compute-dtype", "float32", "--tp-size", "4",
        "--resume", str(run / "model_best.ckpt"),
        "--output", str(tmp_path / "out2")])
    assert out2["best_metric"] is not None
