"""Multi-model engine + two-tier cascade tests (ISSUE 14).

Fast tier (``cascade`` marker): multi-model routing and A/B swaps run
real small models; the cascade fault matrix runs against a stub batcher
so every shed/deadline/engine-fault sequencing is deterministic (the
live-fault system drive is ``tools/chaos_serve.py --models``).
"""

import json
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepfake_detection_tpu.models import create_model, init_model
from deepfake_detection_tpu.params import normalize_replicate, prepare_canvas
from deepfake_detection_tpu.serving.batcher import (DeadlineExceeded,
                                                    MicroBatcher, QueueFull)
from deepfake_detection_tpu.serving.cascade import CascadeRouter
from deepfake_detection_tpu.serving.engine import InferenceEngine
from deepfake_detection_tpu.serving.http import (make_server,
                                                 serve_forever_in_thread)
from deepfake_detection_tpu.serving.metrics import ServingMetrics
from deepfake_detection_tpu.serving.resilience import (EngineStalled,
                                                       NonFiniteScores)

pytestmark = [pytest.mark.serving, pytest.mark.cascade]

_FLAGSHIP = "mobilenetv3_small_100"
_STUDENT = "vit_tiny_patch16_224"
_SIZE = 24          # flagship canvas
_S_SIZE = 32        # student canvas (vit patch16 needs a multiple of 16)


def _perturbed_variables(model, size, chans, seed=0):
    variables = init_model(model, jax.random.PRNGKey(0),
                           (1, size, size, chans))
    rng = np.random.default_rng(seed)
    return jax.tree.map(
        lambda a: a + jnp.asarray(
            0.02 * rng.standard_normal(np.shape(a)).astype(np.float32)
        ).astype(a.dtype),
        variables)


def _canvases(n, size=_SIZE, seed=0):
    rng = np.random.default_rng(seed)
    return [prepare_canvas(
        rng.integers(0, 255, (40, 36, 3), dtype=np.uint8), size)
        for _ in range(n)]


def _two_model_engine(metrics=None, buckets=(1, 4), warm=True,
                      student_size=_S_SIZE, student_dtype="f32"):
    flagship = create_model(_FLAGSHIP, num_classes=2, in_chans=3)
    fv = _perturbed_variables(flagship, _SIZE, 3, seed=1)
    engine = InferenceEngine(flagship, fv, image_size=_SIZE, img_num=1,
                             buckets=buckets, metrics=metrics,
                             model_id="flagship", warmup=False)
    student = create_model(_STUDENT, num_classes=2, in_chans=3)
    sv = _perturbed_variables(student, student_size, 3, seed=2)
    engine.add_model("student", student, sv, image_size=student_size,
                     dtype=student_dtype)
    if warm:
        engine.warmup()
    return engine


# ---------------------------------------------------------------------------
# multi-model engine
# ---------------------------------------------------------------------------

def test_multi_model_warmup_compiles_every_entry_and_routes():
    engine = _two_model_engine()
    # 2 buckets × 2 models on the float32 wire
    assert engine.compile_count == 4
    assert engine.model_ids() == ("flagship", "student")
    payloads = [normalize_replicate(c, 1) for c in _canvases(3, seed=5)]
    s_payloads = [normalize_replicate(c, 1)
                  for c in _canvases(3, _S_SIZE, seed=5)]
    sf = engine.score_batch(payloads, model_id="flagship")
    ss = engine.score_batch(s_payloads, model_id="student")
    assert sf.shape == ss.shape == (3, 2)
    assert not np.array_equal(sf, ss)          # different models answered
    # default routing = the primary (flagship) entry
    np.testing.assert_array_equal(engine.score_batch(payloads), sf)
    with pytest.raises(ValueError):
        engine.score_batch(payloads, model_id="nope")


@pytest.mark.slow   # tier-1 budget: duplicated full-parity sweep (~10 s,
# builds two extra solo engines); the fast tier keeps table==solo parity
# pinned via test_ab_swap_zero_recompiles_and_isolated's fresh-engine
# comparison and routing via test_multi_model_warmup_compiles_every_entry
def test_multi_model_scores_match_single_model_engines():
    """The table is a routing detail: each entry scores bit-identically
    to a dedicated single-model engine over the same weights (same
    programs, same buckets)."""
    engine = _two_model_engine()
    payloads = [normalize_replicate(c, 1) for c in _canvases(2, seed=8)]
    s_payloads = [normalize_replicate(c, 1)
                  for c in _canvases(2, _S_SIZE, seed=8)]
    flagship = create_model(_FLAGSHIP, num_classes=2, in_chans=3)
    solo_f = InferenceEngine(flagship,
                             _perturbed_variables(flagship, _SIZE, 3,
                                                  seed=1),
                             image_size=_SIZE, img_num=1, buckets=(1, 4))
    np.testing.assert_array_equal(
        engine.score_batch(payloads, model_id="flagship"),
        solo_f.score_batch(payloads))
    student = create_model(_STUDENT, num_classes=2, in_chans=3)
    solo_s = InferenceEngine(student,
                             _perturbed_variables(student, _S_SIZE, 3,
                                                  seed=2),
                             image_size=_S_SIZE, img_num=1,
                             buckets=(1, 4))
    np.testing.assert_array_equal(
        engine.score_batch(s_payloads, model_id="student"),
        solo_s.score_batch(s_payloads))


def test_cold_model_drops_readiness_until_warmed():
    """/readyz gating: adding a model to a READY engine must drop
    readiness until warmup covered the new entry — a cold model behind a
    ready endpoint would be the first silent mid-traffic compile."""
    flagship = create_model(_FLAGSHIP, num_classes=2, in_chans=3)
    fv = _perturbed_variables(flagship, _SIZE, 3, seed=1)
    engine = InferenceEngine(flagship, fv, image_size=_SIZE, img_num=1,
                             buckets=(1,), model_id="flagship")
    assert engine.ready
    student = create_model(_STUDENT, num_classes=2, in_chans=3)
    sv = _perturbed_variables(student, _S_SIZE, 3, seed=2)
    engine.add_model("student", student, sv, image_size=_S_SIZE)
    assert not engine.ready                    # one cold model => not ready
    engine.warmup()
    assert engine.ready


def test_rewarm_skips_cold_entry_instead_of_crashing():
    """A watchdog recovery racing a live add_model must skip the cold
    entry (its own warmup proves it), not KeyError on its empty compile
    cache and abort the recovery with the engine stuck not-ready."""
    flagship = create_model(_FLAGSHIP, num_classes=2, in_chans=3)
    fv = _perturbed_variables(flagship, _SIZE, 3, seed=1)
    engine = InferenceEngine(flagship, fv, image_size=_SIZE, img_num=1,
                             buckets=(1,), model_id="flagship")
    student = create_model(_STUDENT, num_classes=2, in_chans=3)
    sv = _perturbed_variables(student, _S_SIZE, 3, seed=2)
    engine.add_model("student", student, sv, image_size=_S_SIZE)
    rewarms0 = engine.metrics.rewarms_total.value
    engine._rewarm()                       # student entry is still cold
    assert engine.metrics.rewarms_total.value == rewarms0 + 1


def test_mixed_model_batch_splits_into_per_model_sub_batches():
    """One coalesced batch carrying both models' requests splits into
    per-model staged sub-batches; every request resolves with its own
    model's bucket scores, bit-identical to the direct path."""
    metrics = ServingMetrics()
    engine = _two_model_engine(metrics=metrics)
    batcher = MicroBatcher(max_batch=4, deadline_ms=20.0, max_queue=16,
                           metrics=metrics)
    payloads = [normalize_replicate(c, 1) for c in _canvases(2, seed=3)] \
        + [normalize_replicate(c, 1)
           for c in _canvases(2, _S_SIZE, seed=13)]
    want_f = engine.score_batch(payloads[:2], model_id="flagship")
    want_s = engine.score_batch(payloads[2:], model_id="student")
    # queue everything BEFORE the worker starts so all four coalesce
    # into ONE mixed batch deterministically
    reqs = [batcher.submit(p, timeout_s=10, model_id=m)
            for p, m in zip(payloads, ["flagship", "flagship",
                                       "student", "student"])]
    # an unknown model id riding the same coalesced batch must fail
    # alone (claimed + booked failed), never poison its co-batched
    # riders or feed the breaker a non-device failure
    bad = batcher.submit(payloads[0], timeout_s=10, model_id="nope")
    engine.start(batcher)
    try:
        got = [r.result(timeout=10) for r in reqs]
        np.testing.assert_array_equal(np.stack(got[:2]), want_f)
        np.testing.assert_array_equal(np.stack(got[2:]), want_s)
        with pytest.raises(ValueError, match="unknown model"):
            bad.result(timeout=10)
        assert metrics.model_book("failed", "nope") == 1
        assert metrics.failed_total.value == 1
    finally:
        engine.stop()
        batcher.close()


def test_per_model_books_balance_through_shed_and_deadline():
    """The model= labeled ledger holds the books identity per model
    through clean scores, sheds and queue-expired deadlines."""
    metrics = ServingMetrics()
    engine = _two_model_engine(metrics=metrics)
    batcher = MicroBatcher(max_batch=4, deadline_ms=5.0, max_queue=3,
                           metrics=metrics)
    payloads = [normalize_replicate(c, 1)
                for c in _canvases(3, _S_SIZE, seed=6)] \
        + [normalize_replicate(c, 1) for c in _canvases(1, seed=6)]
    r1 = batcher.submit(payloads[0], timeout_s=10, model_id="student")
    r2 = batcher.submit(payloads[1], timeout_s=10, model_id="student")
    # deadline: a flagship request that expires in-queue
    r3 = batcher.submit(payloads[3], timeout_s=0.001, model_id="flagship")
    # shed: the 3-slot queue is now full, the next student submit sheds
    with pytest.raises(QueueFull):
        batcher.submit(payloads[2], timeout_s=10, model_id="student")
    import time as _time
    _time.sleep(0.05)
    engine.start(batcher)
    try:
        assert r1.result(timeout=10).shape == (2,)
        assert r2.result(timeout=10).shape == (2,)
        with pytest.raises(DeadlineExceeded):
            r3.result(timeout=10)
    finally:
        engine.stop()
        batcher.close()
    for model in ("student", "flagship"):
        acc = metrics.model_book("accepted", model)
        resolved = (metrics.model_book("scored", model) +
                    metrics.model_book("shed", model) +
                    metrics.model_book("deadline", model) +
                    metrics.model_book("failed", model))
        assert acc == resolved, (model, acc, resolved)
    assert metrics.model_book("shed", "student") == 1
    assert metrics.model_book("deadline", "flagship") == 1


def test_ab_swap_zero_recompiles_and_isolated():
    """A/B weight swap on one table entry: zero backend compiles (the
    params-as-arguments path), the OTHER model's scores bit-unchanged,
    the swapped model matches a fresh engine over the new weights."""
    from deepfake_detection_tpu.serving.metrics import \
        backend_compile_count

    engine = _two_model_engine()
    payloads = [normalize_replicate(c, 1) for c in _canvases(2, seed=9)]
    s_payloads = [normalize_replicate(c, 1)
                  for c in _canvases(2, _S_SIZE, seed=9)]
    f_before = engine.score_batch(payloads, model_id="flagship")
    s_before = engine.score_batch(s_payloads, model_id="student")
    student = create_model(_STUDENT, num_classes=2, in_chans=3)
    new_sv = jax.tree.map(np.asarray,
                          _perturbed_variables(student, _S_SIZE, 3,
                                               seed=7))
    backend0 = backend_compile_count()
    compiles0 = engine.compile_count
    engine.submit_reload(new_sv, source="<ab>", model_id="student")
    engine._maybe_apply_reload()
    assert engine.reload_count == 1
    assert engine.compile_count == compiles0
    assert backend_compile_count() == backend0     # zero recompiles
    np.testing.assert_array_equal(
        engine.score_batch(payloads, model_id="flagship"), f_before)
    s_after = engine.score_batch(s_payloads, model_id="student")
    assert not np.array_equal(s_before, s_after)
    oracle = InferenceEngine(student, new_sv, image_size=_S_SIZE,
                             img_num=1, buckets=(1, 4))
    np.testing.assert_array_equal(s_after,
                                  oracle.score_batch(s_payloads))


def test_cross_model_shape_swap_rejected_loudly():
    """A checkpoint of the WRONG model's tree must be rejected (counted,
    scores bit-unchanged) — never silently served into the other slot."""
    engine = _two_model_engine()
    payloads = [normalize_replicate(c, 1)
                for c in _canvases(1, _S_SIZE, seed=4)]
    s_before = engine.score_batch(payloads, model_id="student")
    flagship_tree = jax.tree.map(np.asarray, engine.entry("flagship")
                                 .host_template)
    errors0 = engine.metrics.reload_errors_total.value
    engine.submit_reload(flagship_tree, source="<cross>",
                         model_id="student")
    engine._maybe_apply_reload()
    assert engine.reload_count == 0
    assert engine.metrics.reload_errors_total.value == errors0 + 1
    np.testing.assert_array_equal(
        engine.score_batch(payloads, model_id="student"), s_before)


# ---------------------------------------------------------------------------
# cascade router: deterministic fault matrix over a stub batcher
# ---------------------------------------------------------------------------

class _StubRequest:
    def __init__(self, outcome):
        self._outcome = outcome

    def result(self, timeout=None):
        if isinstance(self._outcome, Exception):
            raise self._outcome
        return self._outcome


class _StubBatcher:
    """Scripted per-model outcomes: each submit pops the next outcome for
    its model_id; an Exception instance raised at submit() time when
    wrapped in ('submit', exc)."""

    def __init__(self, outcomes):
        self.outcomes = outcomes            # model_id -> list
        self.submits = []

    def submit(self, array, timeout_s=None, model_id=None):
        self.submits.append(model_id)
        nxt = self.outcomes[model_id].pop(0)
        if isinstance(nxt, tuple) and nxt[0] == "submit":
            raise nxt[1]
        return _StubRequest(nxt)


def _router(batcher, metrics, low=0.4, high=0.8):
    return CascadeRouter(batcher, metrics, student_id="student",
                         flagship_id="flagship", low=low, high=high,
                         timeout_s=1.0)


def _books(m):
    return (m.cascade_triaged_total.value, m.cascade_cleared_total.value,
            m.cascade_escalated_total.value,
            m.cascade_flagship_scored_total.value,
            m.cascade_escalation_failed_total.value)


def test_cascade_clears_outside_band():
    m = ServingMetrics()
    b = _StubBatcher({"student": [np.asarray([0.1, 0.9])]})
    res = _router(b, m).score("canvas", lambda: pytest.fail(
        "flagship payload must not be prepared for a cleared clip"))
    assert res.tier == "student" and not res.escalated
    assert res.student_score == pytest.approx(0.1)
    assert _books(m) == (1, 1, 0, 0, 0)
    assert b.submits == ["student"]


def test_cascade_escalates_inside_band():
    m = ServingMetrics()
    b = _StubBatcher({"student": [np.asarray([0.5, 0.5])],
                      "flagship": [np.asarray([0.93, 0.07])]})
    res = _router(b, m).score("canvas", lambda: "flagship-payload")
    assert res.tier == "flagship" and res.escalated
    assert res.scores[0] == pytest.approx(0.93)
    assert _books(m) == (1, 0, 1, 1, 0)
    assert b.submits == ["student", "flagship"]
    assert m.cascade_latency["flagship"].snapshot()[2] == 1


def test_cascade_band_is_inclusive():
    m = ServingMetrics()
    r = _router(_StubBatcher({}), m, low=0.4, high=0.8)
    assert r.suspect(0.4) and r.suspect(0.8)
    assert not r.suspect(0.39999) and not r.suspect(0.80001)
    with pytest.raises(ValueError):
        _router(_StubBatcher({}), m, low=0.9, high=0.1)


@pytest.mark.parametrize("fault", [
    ("submit", QueueFull(8, 1.0)),           # flagship shed at submit
    DeadlineExceeded("expired"),             # flagship queue deadline
    EngineStalled("watchdog recovery"),      # crash-recovery fault
    NonFiniteScores("nan batch"),            # non-finite flagship batch
])
def test_cascade_escalation_failure_serves_student_verdict(fault):
    """Every flagship-phase failure mode degrades to the student verdict
    + counter — never a silent drop, never a client error for a clip the
    student already scored — and the cascade books stay exact."""
    m = ServingMetrics()
    b = _StubBatcher({"student": [np.asarray([0.6, 0.4])],
                      "flagship": [fault]})
    res = _router(b, m).score("canvas", lambda: "flagship-payload")
    assert res.tier == "student" and res.escalated
    assert res.escalation_error
    assert res.scores[0] == pytest.approx(0.6)
    assert _books(m) == (1, 0, 1, 0, 1)


def test_cascade_flagship_leg_gets_only_the_remaining_budget():
    """The two tiers share ONE timeout budget: a student phase that
    spends it all turns the escalation into a counted flagship-phase
    failure (student verdict served, flagship never submitted) — an
    escalated request can never take ~2x the deadline behind a 200."""
    import time as _time

    class _SlowStudentBatcher(_StubBatcher):
        def submit(self, array, timeout_s=None, model_id=None):
            req = super().submit(array, timeout_s=timeout_s,
                                 model_id=model_id)
            if model_id == "student":
                _time.sleep(0.05)       # burn the whole 0.02s budget
            return req

    m = ServingMetrics()
    b = _SlowStudentBatcher({"student": [np.asarray([0.6, 0.4])]})
    r = CascadeRouter(b, m, student_id="student", flagship_id="flagship",
                      low=0.4, high=0.8, timeout_s=0.02)
    res = r.score("canvas", lambda: "flagship-payload")
    assert res.tier == "student" and res.escalated
    assert "budget" in res.escalation_error
    assert b.submits == ["student"]     # flagship leg never submitted
    assert _books(m) == (1, 0, 1, 0, 1)


def test_cascade_result_carries_served_tier_timings():
    """CascadeResult.timings reports the SERVED request's queue/device
    timings (the HTTP layer surfaces them instead of zeros)."""
    class _TimedRequest(_StubRequest):
        timings = {"queue": 0.005, "device": 0.003}

    class _TimedBatcher(_StubBatcher):
        def submit(self, array, timeout_s=None, model_id=None):
            req = super().submit(array, timeout_s=timeout_s,
                                 model_id=model_id)
            return _TimedRequest(req._outcome)

    m = ServingMetrics()
    b = _TimedBatcher({"student": [np.asarray([0.6, 0.4]),
                                   np.asarray([0.9, 0.1])],
                       "flagship": [np.asarray([0.7, 0.3])]})
    res = _router(b, m).score("canvas", lambda: "flagship-payload")
    assert res.tier == "flagship"
    assert res.timings == {"queue": 0.005, "device": 0.003}
    res2 = _router(b, m, low=0.0, high=0.2).score("canvas", lambda: "x")
    assert res2.tier == "student" and res2.timings["device"] == 0.003


def test_cascade_student_phase_failures_propagate():
    """Student-phase faults mean the clip was never triaged: the error
    propagates (the per-model books own it) and NO cascade counter
    moves."""
    m = ServingMetrics()
    b = _StubBatcher({"student": [("submit", QueueFull(8, 1.0))]})
    with pytest.raises(QueueFull):
        _router(b, m).score("canvas", lambda: "unused")
    b2 = _StubBatcher({"student": [EngineStalled("recovery")]})
    with pytest.raises(EngineStalled):
        _router(b2, m).score("canvas", lambda: "unused")
    assert _books(m) == (0, 0, 0, 0, 0)


def test_cascade_books_balance_through_mixed_fault_sequence():
    """A seeded mixed sequence of clears, escalations, escalation faults
    and student faults: both identities hold exactly at every step."""
    m = ServingMetrics()
    rng = np.random.default_rng(0xCA5CADE)
    router = _router(_StubBatcher({}), m)
    for _ in range(200):
        roll = rng.uniform()
        p_student = float(rng.uniform())
        suspect = router.suspect(p_student)
        outcomes = {"student": [np.asarray([p_student, 1 - p_student])],
                    "flagship": []}
        if roll < 0.1:                     # student fault
            outcomes["student"] = [("submit", QueueFull(8, 1.0))]
        elif suspect and roll < 0.3:       # flagship fault
            outcomes["flagship"] = [EngineStalled("boom")]
        elif suspect:
            outcomes["flagship"] = [np.asarray([0.9, 0.1])]
        router.batcher = _StubBatcher(outcomes)
        try:
            router.score("canvas", lambda: "payload")
        except QueueFull:
            pass
        tri, clr, esc, fs, ef = _books(m)
        assert tri == clr + esc
        assert esc == fs + ef


# ---------------------------------------------------------------------------
# HTTP end-to-end: routing + cascade over a live localhost server
# ---------------------------------------------------------------------------

def _post(port, path, body, ctype, timeout=30):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=body,
                                 headers={"Content-Type": ctype})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _jpeg_bytes(seed=0, wh=48):
    import io

    from PIL import Image
    rng = np.random.default_rng(seed)
    buf = io.BytesIO()
    Image.fromarray(rng.integers(0, 255, (wh, wh, 3), dtype=np.uint8)
                    ).save(buf, "JPEG", quality=90)
    return buf.getvalue()


@pytest.mark.slow   # tier-1 budget: live-server drive (~5 s); the fast
# tier keeps the router fault matrix + engine routing units, and the
# two-model chaos e2e (test_chaos_serve_e2e) drives live HTTP cascade
def test_http_cascade_and_model_routing():
    metrics = ServingMetrics()
    engine = _two_model_engine(metrics=metrics)
    batcher = MicroBatcher(max_batch=4, deadline_ms=10.0, max_queue=16,
                           metrics=metrics)
    engine.start(batcher)
    # band [0, 1]: every triaged clip escalates -> deterministic tier
    cascade = CascadeRouter(batcher, metrics, student_id="student",
                            flagship_id="flagship", low=0.0, high=1.0,
                            timeout_s=10.0)
    server = make_server("127.0.0.1", 0, engine, batcher, metrics,
                         request_timeout_s=10.0, cascade=cascade)
    serve_forever_in_thread(server)
    port = server.server_address[1]
    try:
        jpeg = _jpeg_bytes(seed=3)
        # default route: cascade (always-escalate band -> flagship tier)
        status, out = _post(port, "/score", jpeg, "image/jpeg")
        assert status == 200
        assert out["model"] == "flagship"
        assert out["cascade"]["tier"] == "flagship"
        assert out["cascade"]["escalated"] is True
        assert 0.0 <= out["cascade"]["student_score"] <= 1.0
        # explicit model routing bypasses the cascade
        status, out_s = _post(port, "/score?model=student", jpeg,
                              "image/jpeg")
        assert status == 200 and out_s["model"] == "student"
        assert "cascade" not in out_s
        # JSON model field routes too, and matches the query param
        payload = json.dumps({"image_b64": __import__("base64")
                              .b64encode(jpeg).decode(),
                              "model": "student"}).encode()
        status, out_j = _post(port, "/score", payload, "application/json")
        assert status == 200 and out_j["model"] == "student"
        assert out_j["fake_score"] == out_s["fake_score"]
        # unknown model -> 400 naming the table
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, "/score?model=nope", jpeg, "image/jpeg")
        assert ei.value.code == 400
        assert "models" in json.loads(ei.value.read())
        # books: 1 triage escalated + 2 explicit student requests
        assert metrics.cascade_triaged_total.value == 1
        assert metrics.cascade_flagship_scored_total.value == 1
        assert metrics.model_book("scored", "student") >= 3
        # exposition carries the new families
        text = metrics.render_prometheus()
        assert "dfd_serving_cascade_triaged_total 1" in text
        assert 'dfd_serving_model_scored_total{model="student"}' in text
        assert ('dfd_serving_cascade_latency_seconds_count'
                '{tier="student"} 1') in text
    finally:
        server.shutdown()
        engine.stop()
        batcher.close()
        server.server_close()


def test_serve_config_cascade_surface():
    from deepfake_detection_tpu.config import ServeConfig
    cfg = ServeConfig.from_args([
        "--models", "student=vit_tiny_patch16_224,size=32,dtype=int8",
        "--cascade", "student", "--cascade-low", "0.3",
        "--cascade-high", "0.7", "--dtype", "bf16"])
    assert cfg.dtype == "bf16"
    specs = cfg.model_specs()
    assert specs[0]["id"] == "student"
    assert specs[0]["family"] == "vit_tiny_patch16_224"
    assert specs[0]["size"] == 32 and specs[0]["dtype"] == "int8"
    assert specs[0]["img_num"] == cfg.img_num     # inherited default
    with pytest.raises(ValueError):               # unknown cascade id
        ServeConfig(cascade="ghost")
    with pytest.raises(ValueError):               # inverted band
        ServeConfig(models="s=vit_tiny_patch16_224", cascade="s",
                    cascade_low=0.9, cascade_high=0.1)
    with pytest.raises(ValueError):               # img_num mismatch
        ServeConfig(models="s=vit_tiny_patch16_224,img_num=2",
                    cascade="s")
    with pytest.raises(ValueError):               # id collides w/ primary
        ServeConfig(models="efficientnet_deepfake_v4=resnet50")
    with pytest.raises(ValueError):               # bad dtype
        ServeConfig(dtype="fp8")
