"""Checkpoint conversion parity for every backbone family (round 5).

A reference user has torch checkpoints for ANY timm backbone (reference
helpers.py ``load_checkpoint``); ``convert_for_model``'s generic
structural matcher migrates them.  Each case random-inits the reference
torch model (with perturbed BN running stats), converts, and asserts
eval-mode logit parity at an EVEN input size — the size class where the
round-5 static-symmetric padding fix matters.

The matcher refuses partial conversions (every flax leaf must be covered,
every torch tensor must match exactly one leaf), so these tests also pin
the tree structures against the reference.

inception_v3 (the 21st parametrization) is special: the reference model
wraps torchvision, which this image does not ship, so the torch side
cannot be constructed — instead a synthetic state dict matching
torchvision's ``Inception3`` key/shape schema
(tools/inception_v3_fixture.py) drives the converter, with full-coverage
+ exact-shape + layout-value + finite-forward checks in place of logit
parity (ISSUE 2 satellite, VERDICT missing #5).
"""

import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                "tools"))

from dev_family_parity import (FAMILIES, run_family,  # noqa: E402
                               run_inception_v3_fixture)

# one ctor per distinct mapping path; duplicates of an already-covered
# rule set (gluon_resnet ≡ resnet, seresnext ≡ seresnet, …) are trimmed
# to keep slow-tier time bounded
_COVERED = [
    "resnet18", "resnet26d", "seresnet18", "densenet121", "dpn68",
    "xception", "inception_v3", "inception_v4", "inception_resnet_v2",
    "res2net50_26w_4s", "dla34", "skresnet18", "selecsls42b",
    "hrnet_w18_small", "gluon_xception65", "nasnetalarge", "pnasnet5large",
    "mobilenetv3_large_100", "mixnet_s", "efficientnet_cc_b0_4e",
    "tf_efficientnet_b0",
]
_CASES = [f for f in FAMILIES if f[1] in _COVERED]
assert len(_CASES) == len(_COVERED)


@pytest.mark.parametrize("mod,ctor,flax_name,size,atol", _CASES,
                         ids=[f[1] for f in _CASES])
def test_family_conversion_parity(mod, ctor, flax_name, size, atol):
    if ctor == "inception_v3":
        # torchvision-free fixture path (see module docstring)
        line = run_inception_v3_fixture(size)
    else:
        pytest.importorskip("torch")
        line = run_family(mod, ctor, flax_name, size, atol)
    assert line.startswith("OK"), line
