"""Cross-replica collective helpers.

TPU-native equivalents of the reference's explicit NCCL calls (SURVEY.md
§2.7):

* ``reduce_tensor`` (utils.py:256-260 — allreduce SUM / world) →
  :func:`pmean` inside the jitted step; XLA emits one fused all-reduce over
  ICI instead of a per-metric NCCL call per step.
* ``distribute_bn`` (utils.py:263-274 — epoch-boundary broadcast/reduce of
  BN running stats) → :func:`distribute_bn` over the batch-stats pytree.
  Under pjit with replicated state the 'broadcast' mode is an identity (all
  replicas already agree); 'reduce' averages, which is only meaningful when
  per-replica stats were tracked outside pjit (kept for API parity and for
  pmap-style runners).
* apex SyncBN (train.py:388-400) → ``bn_axis_name='data'`` on the model's
  BatchNorm (ops/norm.py) — a pmean inside the layer; nothing needed here.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["pmean", "psum", "distribute_bn", "tree_pmean"]


def pmean(x: Any, axis_name: str = "data") -> Any:
    """Cross-replica mean (replaces reduce_tensor)."""
    return lax.pmean(x, axis_name)


def psum(x: Any, axis_name: str = "data") -> Any:
    return lax.psum(x, axis_name)


def tree_pmean(tree: Any, axis_name: str = "data") -> Any:
    return jax.tree.map(lambda t: lax.pmean(t, axis_name), tree)


def distribute_bn(batch_stats: Any, mode: str = "",
                  axis_name: str = "data", inside_pjit: bool = False) -> Any:
    """Synchronise BN running stats across replicas (utils.py:263-274).

    ``mode``: '' (off) | 'broadcast' (rank-0 wins) | 'reduce' (average).
    Outside a collective context with replicated pjit state both modes are
    identities; inside pmap/shard_map pass ``inside_pjit=True`` to emit the
    collective.
    """
    if not mode:
        return batch_stats
    assert mode in ("broadcast", "reduce"), mode
    if not inside_pjit:
        # replicated pjit state: every replica already holds identical stats
        return batch_stats
    if mode == "reduce":
        return jax.tree.map(lambda t: lax.pmean(t, axis_name), batch_stats)
    # broadcast: select rank 0's value on every member
    def bcast(t):
        full = lax.all_gather(t, axis_name)
        return full[0]
    return jax.tree.map(bcast, batch_stats)
