"""Sharding specs and host→global array assembly.

The reference's distribution story is DDP: replicate the model, shard the
batch, allreduce gradients (apex ``delay_allreduce``, train.py:402).  Under
pjit the same program is expressed declaratively: annotate the batch as
sharded over ``'data'`` and parameters as replicated (or FSDP-sharded), and
XLA inserts the collectives over ICI/DCN.  This module holds the annotation
helpers so runners never spell out PartitionSpecs by hand.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["batch_sharding", "replicated_sharding", "fsdp_param_specs",
           "shard_batch", "param_sharding"]


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Leading (batch) dim sharded over the data axis, rest replicated."""
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def fsdp_param_specs(params: Any, mesh: Mesh, axis: str = "data",
                     min_size: int = 2 ** 16) -> Any:
    """ZeRO-3-style parameter sharding: shard the largest divisible dimension
    of each big leaf over ``axis``; small leaves stay replicated.

    No reference analog (the reference replicates everything); this is the
    TPU-native memory-scaling extension (``TrainConfig.fsdp``).
    """
    n = mesh.shape[axis]

    def spec(p):
        if p.size < min_size:
            return P()
        dims = list(p.shape)
        # prefer sharding the largest divisible dim
        order = sorted(range(len(dims)), key=lambda i: -dims[i])
        for i in order:
            if dims[i] % n == 0:
                out = [None] * len(dims)
                out[i] = axis
                return P(*out)
        return P()

    return jax.tree.map(spec, params)


def param_sharding(params: Any, mesh: Mesh, fsdp: bool = False,
                   axis: str = "data") -> Any:
    """NamedShardings for a param tree: replicated, or FSDP over ``axis``."""
    if not fsdp:
        rep = replicated_sharding(mesh)
        return jax.tree.map(lambda _: rep, params)
    specs = fsdp_param_specs(params, mesh, axis)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def put_process_local(x: Any, sharding: NamedSharding) -> Any:
    """One per-process host array → global sharded jax.Array.

    Single-process: a plain sharded device_put.  Multi-host: each process
    contributes ``global_batch / process_count`` leading rows via
    ``make_array_from_process_local_data``.
    """
    x = np.asarray(x)
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    global_shape = (x.shape[0] * jax.process_count(),) + x.shape[1:]
    return jax.make_array_from_process_local_data(sharding, x, global_shape)


def shard_batch(batch: Any, mesh: Mesh, axis: str = "data") -> Any:
    """Assemble per-process host arrays into a global batch-sharded array
    (replaces the per-process DataLoader shard of DDP)."""
    sharding = batch_sharding(mesh, axis)
    return jax.tree.map(lambda x: put_process_local(x, sharding), batch)
