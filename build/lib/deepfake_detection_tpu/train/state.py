"""Train state: one pytree carrying everything the train step mutates.

The reference scatters mutable training state across the torch module
(params + BN buffers), the optimizer object, apex AMP, and a deep-copied EMA
module.  Here it is a single immutable pytree — params, batch_stats,
opt_state, EMA — threaded through the jitted step with donated buffers, so
the whole update is in-place on device and checkpointing is one
``to_state_dict``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax

__all__ = ["TrainState", "create_train_state", "set_learning_rate",
           "get_learning_rate"]


class TrainState(flax.struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    batch_stats: Any
    opt_state: Any
    ema: Optional[Any] = None          # {'params':…, 'batch_stats':…} or None

    @property
    def variables(self):
        return {"params": self.params, "batch_stats": self.batch_stats}

    @property
    def ema_variables(self):
        return self.ema if self.ema is not None else self.variables


def create_train_state(variables: Any, tx: optax.GradientTransformation,
                       with_ema: bool = False) -> TrainState:
    from ..utils.ema import init_ema
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        ema=init_ema({"params": params, "batch_stats": batch_stats})
        if with_ema else None)


def _find_hyperparams(opt_state):
    """Locate the (path, InjectHyperparamsState) nodes holding hyperparams."""
    return [s for s in jax.tree.leaves(
        opt_state, is_leaf=lambda x: hasattr(x, "hyperparams"))
        if hasattr(s, "hyperparams")]


def set_learning_rate(state: TrainState, lr: float) -> TrainState:
    """Rewrite the injected learning rate (the reference's
    ``param_group['lr']`` rewrite, scheduler.py:81-85) without recompiling."""
    def rewrite(node):
        if hasattr(node, "hyperparams") and "learning_rate" in node.hyperparams:
            hp = dict(node.hyperparams)
            hp["learning_rate"] = jnp.asarray(
                lr, jnp.asarray(hp["learning_rate"]).dtype)
            return node._replace(hyperparams=hp)
        return node
    opt_state = jax.tree.map(
        rewrite, state.opt_state,
        is_leaf=lambda x: hasattr(x, "hyperparams"))
    return state.replace(opt_state=opt_state)


def get_learning_rate(state: TrainState) -> Optional[float]:
    nodes = _find_hyperparams(state.opt_state)
    for n in nodes:
        if "learning_rate" in n.hyperparams:
            return float(n.hyperparams["learning_rate"])
    return None
