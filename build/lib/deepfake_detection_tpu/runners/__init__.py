"""Entry-point runners (reference ``dfd/runners/``): train and test CLIs."""
