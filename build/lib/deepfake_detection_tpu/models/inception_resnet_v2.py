"""Inception-ResNet-V2 (Flax/NHWC).

Re-design of ``/root/reference/dfd/timm/models/inception_resnet_v2.py``
(355 LoC): stem (:185-195), Mixed_5b (:46-77), 10× Block35 scale .17
(:80-113), Mixed_6a (:116-135), 20× Block17 scale .10 (:138-164),
Mixed_7a (:167-195), 9× Block8 scale .20 + final no-relu Block8 (:198-230),
1536-dim head (:288-291), and the two entrypoints (:330-355).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..ops.conv import Conv2d
from ..ops.norm import BatchNorm2d
from ..ops.pool import SelectAdaptivePool2d, avg_pool2d_same
from ..registry import register_model
from .efficientnet import IMAGENET_INCEPTION_MEAN, IMAGENET_INCEPTION_STD

__all__ = ["InceptionResnetV2"]

_H = [(0, 0), (3, 3)]
_V = [(3, 3), (0, 0)]
_H3 = [(0, 0), (1, 1)]
_V3 = [(1, 1), (0, 0)]


def _cfg(**kwargs):
    cfg = dict(num_classes=1000, input_size=(3, 299, 299), pool_size=(8, 8),
               crop_pct=0.8975, interpolation="bicubic",
               mean=IMAGENET_INCEPTION_MEAN, std=IMAGENET_INCEPTION_STD,
               first_conv="conv2d_1a", classifier="classif")
    cfg.update(kwargs)
    return cfg


class _CB(nn.Module):
    """BasicConv2d (:34-45)."""
    out_chs: int
    kernel_size: Any = 3
    stride: int = 1
    padding: Any = "valid"
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = Conv2d(self.out_chs, self.kernel_size, stride=self.stride,
                   padding=self.padding, dtype=self.dtype, name="conv")(x)
        x = BatchNorm2d(**dict(self.bn or {}, dtype=self.dtype),
                        name="bn")(x, training=training)
        return nn.relu(x)


class InceptionResnetV2(nn.Module):
    """Reference InceptionResnetV2 (:233-327)."""
    num_classes: int = 1000
    in_chans: int = 3
    drop_rate: float = 0.0
    global_pool: str = "avg"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-3
    bn_axis_name: Optional[str] = None
    dtype: Any = None
    default_cfg: Any = None

    def _block35(self, x, bn, training, name, scale=0.17):
        cb = dict(bn=bn, dtype=self.dtype)
        b0 = _CB(32, 1, **cb, name=f"{name}_b0")(x, training=training)
        b1 = _CB(32, 1, **cb, name=f"{name}_b1_0")(x, training=training)
        b1 = _CB(32, 3, padding=1, **cb, name=f"{name}_b1_1")(
            b1, training=training)
        b2 = _CB(32, 1, **cb, name=f"{name}_b2_0")(x, training=training)
        b2 = _CB(48, 3, padding=1, **cb, name=f"{name}_b2_1")(
            b2, training=training)
        b2 = _CB(64, 3, padding=1, **cb, name=f"{name}_b2_2")(
            b2, training=training)
        out = Conv2d(320, 1, use_bias=True, dtype=self.dtype,
                     name=f"{name}_conv2d")(
            jnp.concatenate([b0, b1, b2], axis=-1))
        return nn.relu(out * scale + x)

    def _block17(self, x, bn, training, name, scale=0.10):
        cb = dict(bn=bn, dtype=self.dtype)
        b0 = _CB(192, 1, **cb, name=f"{name}_b0")(x, training=training)
        b1 = _CB(128, 1, **cb, name=f"{name}_b1_0")(x, training=training)
        b1 = _CB(160, (1, 7), padding=_H, **cb, name=f"{name}_b1_1")(
            b1, training=training)
        b1 = _CB(192, (7, 1), padding=_V, **cb, name=f"{name}_b1_2")(
            b1, training=training)
        out = Conv2d(1088, 1, use_bias=True, dtype=self.dtype,
                     name=f"{name}_conv2d")(
            jnp.concatenate([b0, b1], axis=-1))
        return nn.relu(out * scale + x)

    def _block8(self, x, bn, training, name, scale=0.20, relu=True):
        cb = dict(bn=bn, dtype=self.dtype)
        b0 = _CB(192, 1, **cb, name=f"{name}_b0")(x, training=training)
        b1 = _CB(192, 1, **cb, name=f"{name}_b1_0")(x, training=training)
        b1 = _CB(224, (1, 3), padding=_H3, **cb, name=f"{name}_b1_1")(
            b1, training=training)
        b1 = _CB(256, (3, 1), padding=_V3, **cb, name=f"{name}_b1_2")(
            b1, training=training)
        out = Conv2d(2080, 1, use_bias=True, dtype=self.dtype,
                     name=f"{name}_conv2d")(
            jnp.concatenate([b0, b1], axis=-1))
        out = out * scale + x
        return nn.relu(out) if relu else out

    @nn.compact
    def __call__(self, x, training: bool = False, features_only: bool = False,
                 pool: bool = True):
        assert x.shape[-1] == self.in_chans, (x.shape, self.in_chans)
        bn = dict(momentum=self.bn_momentum, eps=self.bn_eps,
                  axis_name=self.bn_axis_name)
        cb = dict(bn=bn, dtype=self.dtype)
        feats = []
        x = _CB(32, 3, 2, **cb, name="conv2d_1a")(x, training=training)
        x = _CB(32, 3, **cb, name="conv2d_2a")(x, training=training)
        x = _CB(64, 3, padding=1, **cb, name="conv2d_2b")(x,
                                                          training=training)
        feats.append(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = _CB(80, 1, **cb, name="conv2d_3b")(x, training=training)
        x = _CB(192, 3, **cb, name="conv2d_4a")(x, training=training)
        feats.append(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        # Mixed_5b (:46-77)
        b0 = _CB(96, 1, **cb, name="mixed_5b_b0")(x, training=training)
        b1 = _CB(48, 1, **cb, name="mixed_5b_b1_0")(x, training=training)
        b1 = _CB(64, 5, padding=2, **cb, name="mixed_5b_b1_1")(
            b1, training=training)
        b2 = _CB(64, 1, **cb, name="mixed_5b_b2_0")(x, training=training)
        b2 = _CB(96, 3, padding=1, **cb, name="mixed_5b_b2_1")(
            b2, training=training)
        b2 = _CB(96, 3, padding=1, **cb, name="mixed_5b_b2_2")(
            b2, training=training)
        b3 = _CB(64, 1, **cb, name="mixed_5b_b3")(
            avg_pool2d_same(x, (3, 3), (1, 1), count_include_pad=False),
            training=training)
        x = jnp.concatenate([b0, b1, b2, b3], axis=-1)
        for i in range(10):
            x = self._block35(x, bn, training, f"block35_{i}")
        feats.append(x)
        # Mixed_6a (:116-135)
        b0 = _CB(384, 3, 2, **cb, name="mixed_6a_b0")(x, training=training)
        b1 = _CB(256, 1, **cb, name="mixed_6a_b1_0")(x, training=training)
        b1 = _CB(256, 3, padding=1, **cb, name="mixed_6a_b1_1")(
            b1, training=training)
        b1 = _CB(384, 3, 2, **cb, name="mixed_6a_b1_2")(b1,
                                                        training=training)
        x = jnp.concatenate([
            b0, b1, nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")],
            axis=-1)
        for i in range(20):
            x = self._block17(x, bn, training, f"block17_{i}")
        feats.append(x)
        # Mixed_7a (:167-195)
        b0 = _CB(256, 1, **cb, name="mixed_7a_b0_0")(x, training=training)
        b0 = _CB(384, 3, 2, **cb, name="mixed_7a_b0_1")(b0,
                                                        training=training)
        b1 = _CB(256, 1, **cb, name="mixed_7a_b1_0")(x, training=training)
        b1 = _CB(288, 3, 2, **cb, name="mixed_7a_b1_1")(b1,
                                                        training=training)
        b2 = _CB(256, 1, **cb, name="mixed_7a_b2_0")(x, training=training)
        b2 = _CB(288, 3, padding=1, **cb, name="mixed_7a_b2_1")(
            b2, training=training)
        b2 = _CB(320, 3, 2, **cb, name="mixed_7a_b2_2")(b2,
                                                        training=training)
        x = jnp.concatenate([
            b0, b1, b2,
            nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")], axis=-1)
        for i in range(9):
            x = self._block8(x, bn, training, f"block8_{i}")
        x = self._block8(x, bn, training, "block8_final", scale=1.0,
                         relu=False)
        x = _CB(1536, 1, **cb, name="conv2d_7b")(x, training=training)
        feats.append(x)
        if features_only:
            return feats
        if not pool:
            return x
        x = SelectAdaptivePool2d(self.global_pool, name="global_pool")(x)
        if self.drop_rate > 0:
            x = nn.Dropout(rate=self.drop_rate,
                           deterministic=not training)(x)
        if self.num_classes <= 0:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        name="classif")(x)


def _register():
    for name in ("inception_resnet_v2", "ens_adv_inception_resnet_v2"):
        def fn(pretrained=False, *, _n=name, **kwargs):
            kwargs.pop("pretrained", None)
            kwargs.setdefault("default_cfg", _cfg())
            return InceptionResnetV2(**kwargs)
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__module__ = __name__
        fn.__doc__ = f"{name} (reference inception_resnet_v2.py entrypoint)."
        register_model(fn)


_register()
