"""Selective-Kernel networks SKResNet / SKResNeXt (Flax/NHWC).

Re-design of ``/root/reference/dfd/timm/models/sknet.py`` (237 LoC): the
``SelectiveKernelBasic`` (:44-90) and ``SelectiveKernelBottleneck`` (:92-140)
blocks plugged into the generic :class:`~.resnet.ResNet`, plus the 5
entrypoints (:143-237).  The SK conv itself lives in
``ops/attention.py:SelectiveKernelConv`` (reference selective_kernel.py:51).
"""

from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn

from ..ops.activations import get_act_fn
from ..ops.attention import SelectiveKernelConv, create_attn
from ..ops.conv import Conv2d
from ..ops.drop import DropPath
from ..ops.norm import BatchNorm2d
from ..registry import register_model
from .resnet import _Downsample, _cfg, register_block, ResNet

__all__ = ["SelectiveKernelBasic", "SelectiveKernelBottleneck"]


class SelectiveKernelBasic(nn.Module):
    """SK basic block (reference sknet.py:44-90): SK-conv 3×3 → plain 3×3."""
    planes: int
    stride: int = 1
    has_downsample: bool = False
    cardinality: int = 1
    base_width: int = 64
    sk_kwargs: Any = None
    reduce_first: int = 1
    dilation: int = 1
    first_dilation: Optional[int] = None
    act: str = "relu"
    attn_layer: Optional[str] = None
    avg_down: bool = False
    down_kernel_size: int = 1
    drop_block_rate: float = 0.0
    drop_block_gamma: float = 1.0
    drop_path_rate: float = 0.0
    zero_init_last_bn: bool = True
    bn: dict = None
    dtype: Any = None
    expansion = 1

    @nn.compact
    def __call__(self, x, training: bool = False):
        assert self.cardinality == 1 and self.base_width == 64
        act = get_act_fn(self.act)
        bn = dict(self.bn or {}, dtype=self.dtype)
        first_planes = self.planes // self.reduce_first
        outplanes = self.planes * self.expansion
        fd = self.first_dilation or self.dilation
        residual = x
        y = SelectiveKernelConv(first_planes, stride=self.stride,
                                dilation=fd, act=self.act, dtype=self.dtype,
                                **(self.sk_kwargs or {}),
                                name="conv1")(x, training=training)
        y = Conv2d(outplanes, 3, dilation=self.dilation, dtype=self.dtype,
                   name="conv2")(y)
        y = BatchNorm2d(**bn, name="bn2",
                        scale_init=nn.initializers.zeros
                        if self.zero_init_last_bn else None)(
            y, training=training)
        attn = create_attn(self.attn_layer, dtype=self.dtype, name="se")
        if attn is not None:
            y = attn(y)
        if self.drop_path_rate:
            y = DropPath(self.drop_path_rate, name="drop_path")(
                y, training=training)
        if self.has_downsample:
            residual = _Downsample(
                outplanes, self.down_kernel_size, self.stride, self.dilation,
                self.first_dilation, avg=self.avg_down, bn=self.bn,
                dtype=self.dtype, name="downsample")(x, training=training)
        return act(y + residual)


class SelectiveKernelBottleneck(nn.Module):
    """SK bottleneck (reference sknet.py:92-140): 1×1 → SK-conv → 1×1."""
    planes: int
    stride: int = 1
    has_downsample: bool = False
    cardinality: int = 1
    base_width: int = 64
    sk_kwargs: Any = None
    reduce_first: int = 1
    dilation: int = 1
    first_dilation: Optional[int] = None
    act: str = "relu"
    attn_layer: Optional[str] = None
    avg_down: bool = False
    down_kernel_size: int = 1
    drop_block_rate: float = 0.0
    drop_block_gamma: float = 1.0
    drop_path_rate: float = 0.0
    zero_init_last_bn: bool = True
    bn: dict = None
    dtype: Any = None
    expansion = 4

    @nn.compact
    def __call__(self, x, training: bool = False):
        act = get_act_fn(self.act)
        bn = dict(self.bn or {}, dtype=self.dtype)
        width = int(math.floor(self.planes * (self.base_width / 64))
                    * self.cardinality)
        first_planes = width // self.reduce_first
        outplanes = self.planes * self.expansion
        residual = x
        y = Conv2d(first_planes, 1, dtype=self.dtype, name="conv1")(x)
        y = BatchNorm2d(**bn, name="bn1")(y, training=training)
        y = act(y)
        y = SelectiveKernelConv(width, stride=self.stride,
                                dilation=self.first_dilation or self.dilation,
                                groups=self.cardinality, act=self.act,
                                dtype=self.dtype, **(self.sk_kwargs or {}),
                                name="conv2")(y, training=training)
        y = Conv2d(outplanes, 1, dtype=self.dtype, name="conv3")(y)
        y = BatchNorm2d(**bn, name="bn3",
                        scale_init=nn.initializers.zeros
                        if self.zero_init_last_bn else None)(
            y, training=training)
        attn = create_attn(self.attn_layer, dtype=self.dtype, name="se")
        if attn is not None:
            y = attn(y)
        if self.drop_path_rate:
            y = DropPath(self.drop_path_rate, name="drop_path")(
                y, training=training)
        if self.has_downsample:
            residual = _Downsample(
                outplanes, self.down_kernel_size, self.stride, self.dilation,
                self.first_dilation, avg=self.avg_down, bn=self.bn,
                dtype=self.dtype, name="downsample")(x, training=training)
        return act(y + residual)


register_block("sk_basic", SelectiveKernelBasic)
register_block("sk_bottleneck", SelectiveKernelBottleneck)

# the 18/34 variants split input channels across branches to keep params
# down (reference sknet.py:149-152)
_SK_SMALL = dict(min_attn_channels=16, attn_reduction=8, split_input=True)

# name: (block, layers, extra ResNet kwargs, sk_kwargs)
_SKNET_DEFS = {
    "skresnet18": ("sk_basic", (2, 2, 2, 2), {}, _SK_SMALL),
    "skresnet34": ("sk_basic", (3, 4, 6, 3), {}, _SK_SMALL),
    "skresnet50": ("sk_bottleneck", (3, 4, 6, 3), {},
                   dict(split_input=True)),
    "skresnet50d": ("sk_bottleneck", (3, 4, 6, 3),
                    dict(stem_width=32, stem_type="deep", avg_down=True),
                    dict(split_input=True)),
    "skresnext50_32x4d": ("sk_bottleneck", (3, 4, 6, 3),
                          dict(cardinality=32, base_width=4), None),
}


def _register():
    for name, (block, layers, extra, skk) in _SKNET_DEFS.items():
        def fn(pretrained=False, *, _block=block, _layers=layers,
               _extra=extra, _skk=skk, **kwargs):
            kwargs.pop("pretrained", None)
            ba = kwargs.pop("block_args", {})
            if _skk is not None:
                ba = {"sk_kwargs": dict(_skk), **ba}
            kwargs.setdefault("default_cfg", _cfg())
            # reference passes zero_init_last_bn=False for all SK nets
            kwargs.setdefault("zero_init_last_bn", False)
            return ResNet(block=_block, layers=tuple(_layers), block_args=ba,
                          **{**_extra, **kwargs})
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__module__ = __name__
        fn.__doc__ = f"{name} (reference sknet.py entrypoint)."
        register_model(fn)


_register()
