"""PNASNet-5-Large (Flax/NHWC).

Re-design of ``/root/reference/dfd/timm/models/pnasnet.py`` (398 LoC): the
5-branch progressive cell (``CellBase.cell_forward`` :155-183), stem cell
(:186-229), regular/reduction cells with optional factorized left-input
reduction (:230-293), and the 12-cell PNASNet5Large assembly (:296-380).

Pooling/padding notes: torch ``MaxPool2d(padding=1)`` pads −inf (XLA explicit
pool padding matches); the ``zero_pad`` shift pads literal zeros then crops,
reproduced verbatim; ``FactorizedReduction``'s stride-2 1×1 avg-pools are
plain ::2 subsampling.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..ops.conv import Conv2d
from ..ops.norm import BatchNorm2d
from ..ops.pool import SelectAdaptivePool2d
from ..registry import register_model

__all__ = ["PNASNet5Large"]


def _cfg(**kwargs):
    cfg = dict(num_classes=1000, input_size=(3, 331, 331),
               pool_size=(11, 11), crop_pct=0.875, interpolation="bicubic",
               mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5),
               first_conv="conv_0", classifier="last_linear")
    cfg.update(kwargs)
    return cfg


def _max_pool(x, stride: int, zero_pad: bool):
    """MaxPool(3, stride, padding=1[, zero_pad]) (reference :38-51)."""
    if zero_pad:
        x = jnp.pad(x, ((0, 0), (1, 0), (1, 0), (0, 0)))
    x = nn.max_pool(x, (3, 3), strides=(stride, stride),
                    padding=((1, 1), (1, 1)))
    if zero_pad:
        x = x[:, 1:, 1:, :]
    return x


class _SepConv(nn.Module):
    """SeparableConv2d dw→pw, no norm (:54-69)."""
    out_chs: int
    kernel_size: int
    stride: int = 1
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        in_chs = x.shape[-1]
        pad = self.kernel_size // 2
        x = Conv2d(in_chs, self.kernel_size, stride=self.stride, padding=pad,
                   groups=in_chs, dtype=self.dtype,
                   name="depthwise_conv2d")(x)
        return Conv2d(self.out_chs, 1, dtype=self.dtype,
                      name="pointwise_conv2d")(x)


class _BranchSeparables(nn.Module):
    """relu → sep(stride) → BN → relu → sep → BN (:72-101)."""
    out_chs: int
    kernel_size: int
    stride: int = 1
    stem_cell: bool = False
    zero_pad: bool = False
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        bn = dict(self.bn or {}, dtype=self.dtype)
        mid = self.out_chs if self.stem_cell else x.shape[-1]
        x = nn.relu(x)
        if self.zero_pad:
            x = jnp.pad(x, ((0, 0), (1, 0), (1, 0), (0, 0)))
        x = _SepConv(mid, self.kernel_size, self.stride, dtype=self.dtype,
                     name="separable_1")(x)
        if self.zero_pad:
            x = x[:, 1:, 1:, :]
        x = BatchNorm2d(**bn, name="bn_sep_1")(x, training=training)
        x = nn.relu(x)
        x = _SepConv(self.out_chs, self.kernel_size, 1, dtype=self.dtype,
                     name="separable_2")(x)
        return BatchNorm2d(**bn, name="bn_sep_2")(x, training=training)


class _ReluConvBn(nn.Module):
    """relu → conv(VALID) → BN (:104-117)."""
    out_chs: int
    kernel_size: int = 1
    stride: int = 1
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = nn.relu(x)
        x = Conv2d(self.out_chs, self.kernel_size, stride=self.stride,
                   padding="valid", dtype=self.dtype, name="conv")(x)
        return BatchNorm2d(**dict(self.bn or {}, dtype=self.dtype),
                           name="bn")(x, training=training)


class _FactorizedReduction(nn.Module):
    """Two offset stride-2 1×1 paths, concat, BN (:120-146)."""
    out_chs: int
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = nn.relu(x)
        p1 = Conv2d(self.out_chs // 2, 1, dtype=self.dtype,
                    name="path_1_conv")(x[:, ::2, ::2, :])
        x2 = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))[:, 1:, 1:, :]
        p2 = Conv2d(self.out_chs // 2, 1, dtype=self.dtype,
                    name="path_2_conv")(x2[:, ::2, ::2, :])
        out = jnp.concatenate([p1, p2], axis=-1)
        return BatchNorm2d(**dict(self.bn or {}, dtype=self.dtype),
                           name="final_path_bn")(out, training=training)


class _Cell(nn.Module):
    """Stem / regular / reduction cell (:186-293).  ``stem0`` selects the
    CellStem0 branch plan (left input is the raw stem conv)."""
    out_chs_left: int
    out_chs_right: int
    stem0: bool = False
    is_reduction: bool = False
    zero_pad: bool = False
    match_prev: bool = False
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x_left, x_right, training: bool = False):
        k = dict(bn=self.bn, dtype=self.dtype)
        stride = 2 if (self.is_reduction or self.stem0) else 1
        zp = self.zero_pad
        if self.stem0:
            raw_left = x_left
            x_right = _ReluConvBn(self.out_chs_right, **k, name="conv_1x1")(
                x_right, training=training)
            # comb0 operates on the RAW left input (:190-202)
            c0l = _BranchSeparables(self.out_chs_left, 5, 2, stem_cell=True,
                                    **k, name="comb_iter_0_left")(
                raw_left, training=training)
            c0r = _max_pool(raw_left, 2, False)
            c0r = Conv2d(self.out_chs_left, 1, dtype=self.dtype,
                         name="comb_iter_0_right_conv")(c0r)
            c0r = BatchNorm2d(**dict(self.bn or {}, dtype=self.dtype),
                              name="comb_iter_0_right_bn")(
                c0r, training=training)
            c4l = _BranchSeparables(self.out_chs_right, 3, 2, stem_cell=True,
                                    **k, name="comb_iter_4_left")(
                raw_left, training=training)
        else:
            if self.match_prev:
                x_left = _FactorizedReduction(
                    self.out_chs_left, **k, name="conv_prev_1x1")(
                    x_left, training=training)
            else:
                x_left = _ReluConvBn(self.out_chs_left, **k,
                                     name="conv_prev_1x1")(
                    x_left, training=training)
            x_right = _ReluConvBn(self.out_chs_right, **k, name="conv_1x1")(
                x_right, training=training)
            c0l = _BranchSeparables(self.out_chs_left, 5, stride,
                                    zero_pad=zp, **k,
                                    name="comb_iter_0_left")(
                x_left, training=training)
            c0r = _max_pool(x_left, stride, zp)
            c4l = _BranchSeparables(self.out_chs_left, 3, stride,
                                    zero_pad=zp, **k,
                                    name="comb_iter_4_left")(
                x_left, training=training)
        c0 = c0l + c0r
        c1l = _BranchSeparables(self.out_chs_right, 7, stride, zero_pad=zp,
                                **k, name="comb_iter_1_left")(
            x_right, training=training)
        c1r = _max_pool(x_right, stride, zp)
        c1 = c1l + c1r
        c2l = _BranchSeparables(self.out_chs_right, 5, stride, zero_pad=zp,
                                **k, name="comb_iter_2_left")(
            x_right, training=training)
        c2r = _BranchSeparables(self.out_chs_right, 3, stride, zero_pad=zp,
                                **k, name="comb_iter_2_right")(
            x_right, training=training)
        c2 = c2l + c2r
        c3l = _BranchSeparables(self.out_chs_right, 3, 1, **k,
                                name="comb_iter_3_left")(
            c2, training=training)
        c3 = c3l + _max_pool(x_right, stride, zp)
        if self.is_reduction or self.stem0:
            c4r = _ReluConvBn(self.out_chs_right, 1, stride, **k,
                              name="comb_iter_4_right")(
                x_right, training=training)
        else:
            c4r = x_right
        c4 = c4l + c4r
        return jnp.concatenate([c0, c1, c2, c3, c4], axis=-1)


class PNASNet5Large(nn.Module):
    """Reference PNASNet5Large (:296-380)."""
    num_classes: int = 1000
    in_chans: int = 3
    drop_rate: float = 0.5
    global_pool: str = "avg"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-3
    bn_axis_name: Optional[str] = None
    dtype: Any = None
    default_cfg: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False, features_only: bool = False,
                 pool: bool = True):
        assert x.shape[-1] == self.in_chans, (x.shape, self.in_chans)
        bn = dict(momentum=self.bn_momentum, eps=self.bn_eps,
                  axis_name=self.bn_axis_name)
        k = dict(bn=bn, dtype=self.dtype)
        conv0 = Conv2d(96, 3, stride=2, padding="valid", dtype=self.dtype,
                       name="conv_0_conv")(x)
        conv0 = BatchNorm2d(**dict(bn, dtype=self.dtype),
                            name="conv_0_bn")(conv0, training=training)
        stem0 = _Cell(54, 54, stem0=True, **k,
                      name="cell_stem_0")(conv0, conv0, training=training)
        stem1 = _Cell(108, 108, is_reduction=True, match_prev=True, **k,
                      name="cell_stem_1")(conv0, stem0, training=training)
        c0 = _Cell(216, 216, match_prev=True, **k,
                   name="cell_0")(stem0, stem1, training=training)
        c1 = _Cell(216, 216, **k, name="cell_1")(stem1, c0,
                                                 training=training)
        c2 = _Cell(216, 216, **k, name="cell_2")(c0, c1, training=training)
        c3 = _Cell(216, 216, **k, name="cell_3")(c1, c2, training=training)
        c4 = _Cell(432, 432, is_reduction=True, zero_pad=True, **k,
                   name="cell_4")(c2, c3, training=training)
        c5 = _Cell(432, 432, match_prev=True, **k,
                   name="cell_5")(c3, c4, training=training)
        c6 = _Cell(432, 432, **k, name="cell_6")(c4, c5, training=training)
        c7 = _Cell(432, 432, **k, name="cell_7")(c5, c6, training=training)
        c8 = _Cell(864, 864, is_reduction=True, **k,
                   name="cell_8")(c6, c7, training=training)
        c9 = _Cell(864, 864, match_prev=True, **k,
                   name="cell_9")(c7, c8, training=training)
        c10 = _Cell(864, 864, **k, name="cell_10")(c8, c9, training=training)
        c11 = _Cell(864, 864, **k, name="cell_11")(c9, c10,
                                                   training=training)
        x = nn.relu(c11)
        if features_only:
            return [stem0, c3, c7, c11, x]
        if not pool:
            return x
        x = SelectAdaptivePool2d(self.global_pool, name="global_pool")(x)
        if self.drop_rate > 0:
            x = nn.Dropout(rate=self.drop_rate,
                           deterministic=not training)(x)
        if self.num_classes <= 0:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        name="last_linear")(x)


@register_model
def pnasnet5large(pretrained=False, **kwargs):
    """pnasnet5large (reference pnasnet.py:383-397)."""
    kwargs.pop("pretrained", None)
    kwargs.setdefault("default_cfg", _cfg())
    kwargs.setdefault("drop_rate", 0.5)
    return PNASNet5Large(**kwargs)
