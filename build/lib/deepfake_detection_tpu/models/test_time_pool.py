"""Test-time pooling (reference ``layers/test_time_pool.py:12-35``).

At inference sizes larger than the train size, classify every ``pool×pool``
window and avg+max-pool the per-window logits instead of pooling features
once.  Functional re-design: rather than mutating the model (the reference
deletes the fc and grafts a 1×1 conv), :func:`test_time_pool_apply` runs the
unpooled feature forward and applies the classifier kernel as a 1×1
convolution — numerically identical, no surgery.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Tuple

import jax.numpy as jnp
from jax import lax

from ..ops.pool import global_pool_nhwc

_logger = logging.getLogger(__name__)

__all__ = ["test_time_pool_apply", "apply_test_time_pool"]


def test_time_pool_apply(model, variables: Dict[str, Any], x,
                         original_pool: int = 7,
                         classifier: str = "classifier") -> jnp.ndarray:
    """Forward with test-time pooling (reference TestTimePoolHead.forward).

    ``classifier`` names the head params (``default_cfg['classifier']``);
    a Dense (features, classes) kernel is used as a 1×1 conv over the
    window-pooled feature map.
    """
    feat = model.apply(variables, x, training=False, pool=False)
    p = original_pool
    feat = lax.reduce_window(
        feat, 0.0, lax.add, (1, p, p, 1), (1, 1, 1, 1), "VALID") / (p * p)
    head = variables["params"][classifier]
    kernel, bias = head["kernel"], head.get("bias")
    if kernel.ndim == 2:                       # Dense → 1×1 conv
        kernel = kernel[None, None]
    logits = lax.conv_general_dilated(
        feat, kernel.astype(feat.dtype), (1, 1), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if bias is not None:
        logits = logits + bias.astype(logits.dtype)
    return global_pool_nhwc(logits, "avgmax")


def apply_test_time_pool(model, config: Dict[str, Any],
                         no_test_pool: bool = False) -> Tuple[Any, bool]:
    """Decide whether TTA pooling applies (reference :35-45): input larger
    than the model's default train size in both dims.  Returns
    ``(original_pool, enabled)`` for use with :func:`test_time_pool_apply`."""
    cfg = getattr(model, "default_cfg", None) or {}
    if no_test_pool or not cfg:
        return None, False
    want = config.get("input_size", ())
    have = cfg.get("input_size", ())
    if len(want) == 3 and len(have) == 3 and \
            want[-1] > have[-1] and want[-2] > have[-2]:
        pool = cfg.get("pool_size", (7, 7))
        pool = pool[0] if isinstance(pool, (tuple, list)) else pool
        _logger.info("Target input size %s > pretrained default %s, "
                     "using test time pooling", want[-2:], have[-2:])
        return pool, True
    return None, False
