"""HRNet — High-Resolution Network (Flax/NHWC).

Re-design of ``/root/reference/dfd/timm/models/hrnet.py`` (804 LoC): parallel
multi-resolution branches with repeated cross-resolution fusion
(``HighResolutionModule`` :394-516), transition layers that widen/deepen the
branch set (:609-634), the classification head that re-expands C/2C/4C/8C to
128/256/512/1024 then 2048 (:572-607), and the 9 ``hrnet_w*`` entrypoints.
Branch blocks are this package's ResNet Basic/Bottleneck blocks, exactly as
the reference reuses its resnet.py blocks (:25).

TPU notes: branch lists are static Python lists of arrays (one trace per
resolution); nearest-neighbour upsampling in the fuse step is a free
``jnp.repeat``; the whole multi-branch graph fuses into one XLA program.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ..ops.conv import Conv2d
from ..ops.norm import BatchNorm2d
from ..ops.pool import SelectAdaptivePool2d
from ..registry import register_model
from .efficientnet import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD
from .resnet import BasicBlock, Bottleneck

__all__ = ["HighResolutionNet"]


def _cfg(**kwargs):
    cfg = dict(num_classes=1000, input_size=(3, 224, 224), pool_size=(7, 7),
               crop_pct=0.875, interpolation="bilinear",
               mean=IMAGENET_DEFAULT_MEAN, std=IMAGENET_DEFAULT_STD,
               first_conv="conv1", classifier="classifier")
    cfg.update(kwargs)
    return cfg


def _upsample_nearest(x, factor: int):
    return jnp.repeat(jnp.repeat(x, factor, axis=1), factor, axis=2)


class _ConvBnRelu(nn.Module):
    out_chs: int
    kernel_size: int = 3
    stride: int = 1
    relu: bool = True
    use_bias: bool = False
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = Conv2d(self.out_chs, self.kernel_size, stride=self.stride,
                   use_bias=self.use_bias, dtype=self.dtype, name="conv")(x)
        x = BatchNorm2d(**dict(self.bn or {}, dtype=self.dtype),
                        name="bn")(x, training=training)
        return nn.relu(x) if self.relu else x


class _HRModule(nn.Module):
    """HighResolutionModule (reference :394-516): per-branch residual blocks
    then all-to-all fusion (upsample high→low index, strided-conv chains
    low→high index, SUM)."""
    num_branches: int
    block: str                       # 'basic' | 'bottleneck'
    num_blocks: Sequence[int]
    num_channels: Sequence[int]      # post-expansion channels per branch
    multi_scale_output: bool = True
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, xs, training: bool = False):
        block_cls = BasicBlock if self.block == "basic" else Bottleneck
        planes = [c // block_cls.expansion for c in self.num_channels]
        ys = []
        for bi in range(self.num_branches):
            x = xs[bi]
            for li in range(self.num_blocks[bi]):
                need_ds = li == 0 and x.shape[-1] != self.num_channels[bi]
                x = block_cls(planes=planes[bi], has_downsample=need_ds,
                              zero_init_last_bn=False, bn=self.bn,
                              dtype=self.dtype,
                              name=f"branch{bi}_{li}")(x, training=training)
            ys.append(x)
        if self.num_branches == 1:
            return ys
        out = []
        n_out = self.num_branches if self.multi_scale_output else 1
        for i in range(n_out):
            y = None
            for j in range(self.num_branches):
                if j == i:
                    t = ys[j]
                elif j > i:
                    # 1×1 to target chs, BN, nearest ×2^(j-i) (:470-474)
                    t = _ConvBnRelu(self.num_channels[i], 1, relu=False,
                                    bn=self.bn, dtype=self.dtype,
                                    name=f"fuse{i}_{j}")(ys[j],
                                                         training=training)
                    t = _upsample_nearest(t, 2 ** (j - i))
                else:
                    # chain of stride-2 3×3s (:476-489)
                    t = ys[j]
                    for k in range(i - j):
                        last = k == i - j - 1
                        chs = self.num_channels[i] if last \
                            else self.num_channels[j]
                        t = _ConvBnRelu(chs, 3, 2, relu=not last, bn=self.bn,
                                        dtype=self.dtype,
                                        name=f"fuse{i}_{j}_{k}")(
                            t, training=training)
                y = t if y is None else y + t
            out.append(nn.relu(y))
        return out


class HighResolutionNet(nn.Module):
    """Generic HRNet classifier (reference :522-744)."""
    stage1: Tuple[int, int] = (4, 64)        # (blocks, channels), BOTTLENECK
    channels: Sequence[int] = (18, 36, 72, 144)   # BASIC branch widths
    num_blocks: int = 4                       # per branch, stages 2-4
    modules: Sequence[int] = (1, 4, 3)        # HR modules in stages 2/3/4
    stem_width: int = 64
    num_classes: int = 1000
    in_chans: int = 3
    drop_rate: float = 0.0
    global_pool: str = "avg"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None
    default_cfg: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False, features_only: bool = False,
                 pool: bool = True):
        assert x.shape[-1] == self.in_chans, (x.shape, self.in_chans)
        bn = dict(momentum=self.bn_momentum, eps=self.bn_eps,
                  axis_name=self.bn_axis_name)
        # stem: two stride-2 3×3s (:529-534)
        x = _ConvBnRelu(self.stem_width, 3, 2, bn=bn, dtype=self.dtype,
                        name="conv1")(x, training=training)
        x = _ConvBnRelu(64, 3, 2, bn=bn, dtype=self.dtype,
                        name="conv2")(x, training=training)
        # layer1: Bottleneck stack (:536-541)
        s1_blocks, s1_chs = self.stage1
        for li in range(s1_blocks):
            need_ds = li == 0 and x.shape[-1] != s1_chs * 4
            x = Bottleneck(planes=s1_chs, has_downsample=need_ds,
                           zero_init_last_bn=False, bn=bn, dtype=self.dtype,
                           name=f"layer1_{li}")(x, training=training)

        xs = [x]
        for si in range(3):                       # stages 2, 3, 4
            n_br = si + 2
            chs = list(self.channels[:n_br])      # BASIC expansion = 1
            # transition (:609-634): adapt existing branches, spawn new ones
            new_xs = []
            for bi in range(n_br):
                if bi < len(xs):
                    if xs[bi].shape[-1] != chs[bi]:
                        new_xs.append(_ConvBnRelu(
                            chs[bi], 3, bn=bn, dtype=self.dtype,
                            name=f"transition{si + 1}_{bi}")(
                            xs[bi], training=training))
                    else:
                        new_xs.append(xs[bi])
                else:
                    t = xs[-1]
                    for j in range(bi + 1 - len(xs)):
                        out_c = chs[bi] if j == bi - len(xs) else t.shape[-1]
                        t = _ConvBnRelu(out_c, 3, 2, bn=bn, dtype=self.dtype,
                                        name=f"transition{si + 1}_{bi}_{j}")(
                            t, training=training)
                    new_xs.append(t)
            xs = new_xs
            for mi in range(self.modules[si]):
                xs = _HRModule(n_br, "basic", (self.num_blocks,) * n_br,
                               tuple(chs), bn=bn, dtype=self.dtype,
                               name=f"stage{si + 2}_{mi}")(
                    xs, training=training)
        if features_only:
            return xs
        # classification head (:572-607): incre to 128/256/512/1024,
        # stride-2 downsample chain with SUM, final 1×1 to 2048
        head_chs = (32, 64, 128, 256)
        y = None
        for bi, t in enumerate(xs):
            need_ds = t.shape[-1] != head_chs[bi] * 4
            t = Bottleneck(planes=head_chs[bi], has_downsample=need_ds,
                           zero_init_last_bn=False, bn=bn, dtype=self.dtype,
                           name=f"incre{bi}")(t, training=training)
            if bi > 0:
                y = t + _ConvBnRelu(head_chs[bi] * 4, 3, 2, use_bias=True,
                                    bn=bn, dtype=self.dtype,
                                    name=f"downsamp{bi - 1}")(
                    y, training=training)
            else:
                y = t
        y = _ConvBnRelu(2048, 1, use_bias=True, bn=bn, dtype=self.dtype,
                        name="final_layer")(y, training=training)
        if not pool:
            return y
        y = SelectAdaptivePool2d(self.global_pool, name="global_pool")(y)
        if self.drop_rate > 0.0:
            y = nn.Dropout(rate=self.drop_rate,
                           deterministic=not training)(y)
        if self.num_classes <= 0:
            return y
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        name="classifier")(y)


# name: (stage1 (blocks, chs), base width, per-branch blocks, modules/stage)
# extracted from the reference cfg_cls tables (hrnet.py:80-390)
_HRNET_DEFS = {
    "hrnet_w18_small": ((1, 32), 16, 2, (1, 1, 1)),
    "hrnet_w18_small_v2": ((2, 64), 18, 2, (1, 3, 2)),
    "hrnet_w18": ((4, 64), 18, 4, (1, 4, 3)),
    "hrnet_w30": ((4, 64), 30, 4, (1, 4, 3)),
    "hrnet_w32": ((4, 64), 32, 4, (1, 4, 3)),
    "hrnet_w40": ((4, 64), 40, 4, (1, 4, 3)),
    "hrnet_w44": ((4, 64), 44, 4, (1, 4, 3)),
    "hrnet_w48": ((4, 64), 48, 4, (1, 4, 3)),
    "hrnet_w64": ((4, 64), 64, 4, (1, 4, 3)),
}


def _register():
    for name, (s1, w, nb, mods) in _HRNET_DEFS.items():
        def fn(pretrained=False, *, _s1=s1, _w=w, _nb=nb, _mods=mods,
               **kwargs):
            kwargs.pop("pretrained", None)
            kwargs.setdefault("default_cfg", _cfg())
            return HighResolutionNet(
                stage1=_s1, channels=(_w, _w * 2, _w * 4, _w * 8),
                num_blocks=_nb, modules=_mods, **kwargs)
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__module__ = __name__
        fn.__doc__ = f"{name} (reference hrnet.py entrypoint)."
        register_model(fn)


_register()
