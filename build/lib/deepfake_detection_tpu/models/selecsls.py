"""SelecSLS (Flax/NHWC).

Re-design of ``/root/reference/dfd/timm/models/selecsls.py`` (294 LoC): the
``SelecSLSBlock`` (:66-93) — three conv pairs whose intermediate outputs are
concatenated, with a cross-block skip feature threaded alongside the main
stream — the :class:`SelecSLS` net (:96-157), per-variant feature/head config
tables (:160-260), and the 5 entrypoints (:262-294).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ..ops.conv import Conv2d
from ..ops.norm import BatchNorm2d
from ..ops.pool import SelectAdaptivePool2d
from ..registry import register_model
from .efficientnet import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD

__all__ = ["SelecSLS"]


def _cfg(**kwargs):
    cfg = dict(num_classes=1000, input_size=(3, 224, 224), pool_size=(4, 4),
               crop_pct=0.875, interpolation="bilinear",
               mean=IMAGENET_DEFAULT_MEAN, std=IMAGENET_DEFAULT_STD,
               first_conv="stem", classifier="fc")
    cfg.update(kwargs)
    return cfg


class _ConvBn(nn.Module):
    """conv → BN → ReLU (reference conv_bn, selecsls.py:55-63)."""
    out_chs: int
    kernel_size: int = 3
    stride: int = 1
    dilation: int = 1
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = Conv2d(self.out_chs, self.kernel_size, stride=self.stride,
                   dilation=self.dilation, dtype=self.dtype, name="conv")(x)
        x = BatchNorm2d(**dict(self.bn or {}, dtype=self.dtype),
                        name="bn")(x, training=training)
        return nn.relu(x)


class _SelecSLSBlock(nn.Module):
    """Reference SelecSLSBlock (:66-93): d1=3×3(s), d2=1×1·3×3, d3=1×1·3×3;
    concat [d1,d2,d3(,skip)] → 1×1.  First block of a stage starts a new skip
    stream; later blocks carry it through."""
    skip_chs: int
    mid_chs: int
    out_chs: int
    is_first: bool
    stride: int
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, skip, training: bool = False):
        k = dict(bn=self.bn, dtype=self.dtype)
        d1 = _ConvBn(self.mid_chs, 3, self.stride, **k, name="conv1")(
            x, training=training)
        d2 = _ConvBn(self.mid_chs // 2, 3, **k, name="conv3")(
            _ConvBn(self.mid_chs, 1, **k, name="conv2")(
                d1, training=training), training=training)
        d3 = _ConvBn(self.mid_chs // 2, 3, **k, name="conv5")(
            _ConvBn(self.mid_chs, 1, **k, name="conv4")(
                d2, training=training), training=training)
        if self.is_first:
            out = _ConvBn(self.out_chs, 1, **k, name="conv6")(
                jnp.concatenate([d1, d2, d3], axis=-1), training=training)
            return out, out
        out = _ConvBn(self.out_chs, 1, **k, name="conv6")(
            jnp.concatenate([d1, d2, d3, skip], axis=-1), training=training)
        return out, skip


# variant → (features, head, num_features); rows are
# (skip_chs, mid_chs, out_chs, is_first, stride) / (out_chs, k, stride)
# (reference selecsls.py:160-247; in_chs is implicit in NHWC)
_FEATS_42 = [(0, 64, 64, True, 2), (64, 64, 128, False, 1),
             (0, 144, 144, True, 2), (144, 144, 288, False, 1),
             (0, 304, 304, True, 2), (304, 304, 480, False, 1)]
_FEATS_60 = [(0, 64, 64, True, 2), (64, 64, 128, False, 1),
             (0, 128, 128, True, 2), (128, 128, 128, False, 1),
             (128, 128, 288, False, 1), (0, 288, 288, True, 2),
             (288, 288, 288, False, 1), (288, 288, 288, False, 1),
             (288, 288, 416, False, 1)]
_FEATS_84 = [(0, 64, 64, True, 2), (64, 64, 144, False, 1),
             (0, 144, 144, True, 2), (144, 144, 144, False, 1),
             (144, 144, 144, False, 1), (144, 144, 144, False, 1),
             (144, 144, 304, False, 1), (0, 304, 304, True, 2),
             (304, 304, 304, False, 1), (304, 304, 304, False, 1),
             (304, 304, 304, False, 1), (304, 304, 304, False, 1),
             (304, 304, 512, False, 1)]

_VARIANTS = {
    "selecsls42": (_FEATS_42, [(960, 3, 2), (1024, 3, 1), (1024, 3, 2),
                               (1280, 1, 1)], 1280),
    "selecsls42b": (_FEATS_42, [(960, 3, 2), (1024, 3, 1), (1280, 3, 2),
                                (1024, 1, 1)], 1024),
    "selecsls60": (_FEATS_60, [(756, 3, 2), (1024, 3, 1), (1024, 3, 2),
                               (1280, 1, 1)], 1280),
    "selecsls60b": (_FEATS_60, [(756, 3, 2), (1024, 3, 1), (1280, 3, 2),
                                (1024, 1, 1)], 1024),
    "selecsls84": (_FEATS_84, [(960, 3, 2), (1024, 3, 1), (1024, 3, 2),
                               (1280, 3, 1)], 1280),
}


class SelecSLS(nn.Module):
    """Generic SelecSLS net (reference :96-157)."""
    features: Sequence[Tuple]
    head: Sequence[Tuple]
    num_features: int = 1280
    num_classes: int = 1000
    in_chans: int = 3
    drop_rate: float = 0.0
    global_pool: str = "avg"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None
    default_cfg: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False, features_only: bool = False,
                 pool: bool = True):
        assert x.shape[-1] == self.in_chans, (x.shape, self.in_chans)
        bn = dict(momentum=self.bn_momentum, eps=self.bn_eps,
                  axis_name=self.bn_axis_name)
        x = _ConvBn(32, 3, 2, bn=bn, dtype=self.dtype, name="stem")(
            x, training=training)
        skip = x
        stage_feats = []
        for i, (skip_chs, mid, out, first, stride) in enumerate(
                self.features):
            x, skip = _SelecSLSBlock(
                skip_chs, mid, out, first, stride, bn=bn, dtype=self.dtype,
                name=f"features_{i}")(x, skip, training=training)
            stage_feats.append(x)
        for i, (out, k, stride) in enumerate(self.head):
            x = _ConvBn(out, k, stride, bn=bn, dtype=self.dtype,
                        name=f"head_{i}")(x, training=training)
        stage_feats.append(x)
        if features_only:
            return stage_feats
        if not pool:
            return x
        x = SelectAdaptivePool2d(self.global_pool, name="global_pool")(x)
        if self.drop_rate > 0.0:
            x = nn.Dropout(rate=self.drop_rate,
                           deterministic=not training)(x)
        if self.num_classes <= 0:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)


def _register():
    for name, (feats, head, num_features) in _VARIANTS.items():
        def fn(pretrained=False, *, _f=feats, _h=head, _nf=num_features,
               **kwargs):
            kwargs.pop("pretrained", None)
            kwargs.setdefault("default_cfg", _cfg())
            return SelecSLS(features=tuple(_f), head=tuple(_h),
                            num_features=_nf, **kwargs)
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__module__ = __name__
        fn.__doc__ = f"{name} (reference selecsls.py entrypoint)."
        register_model(fn)


_register()
