"""Gluon Modified-Aligned Xception 65/71 (Flax/NHWC).

Re-design of ``/root/reference/dfd/timm/models/gluon_xception.py`` (468 LoC):
``SeparableConv2d`` dw→BN→pw (:84-113), the flexible ``Block`` (:116-177:
grow_first / start_with_relu / is_last variants), ``Xception65`` (:179-307:
entry 3 blocks, 16 middle blocks, exit block20 + 3 separable convs to 2048)
and ``Xception71`` (:309-445: deeper entry flow), with output_stride 8/16/32
dilation plumbing.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn

from ..ops.conv import Conv2d
from ..ops.norm import BatchNorm2d
from ..ops.pool import SelectAdaptivePool2d
from ..registry import register_model
from .efficientnet import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD

__all__ = ["GluonXception"]


def _cfg(**kwargs):
    cfg = dict(num_classes=1000, input_size=(3, 299, 299), pool_size=(10, 10),
               crop_pct=0.875, interpolation="bicubic",
               mean=IMAGENET_DEFAULT_MEAN, std=IMAGENET_DEFAULT_STD,
               first_conv="conv1", classifier="fc")
    cfg.update(kwargs)
    return cfg


class _SepConv(nn.Module):
    """SeparableConv2d: depthwise → BN → pointwise (:84-113)."""
    out_chs: int
    kernel_size: int = 3
    stride: int = 1
    dilation: int = 1
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        in_chs = x.shape[-1]
        x = Conv2d(in_chs, self.kernel_size, stride=self.stride,
                   dilation=self.dilation, groups=in_chs, dtype=self.dtype,
                   name="conv_dw")(x)
        x = BatchNorm2d(**dict(self.bn or {}, dtype=self.dtype),
                        name="bn")(x, training=training)
        return Conv2d(self.out_chs, 1, dtype=self.dtype, name="conv_pw")(x)


class _Block(nn.Module):
    """Reference Block (:116-177)."""
    planes: int
    num_reps: int
    stride: int = 1
    dilation: int = 1
    start_with_relu: bool = True
    grow_first: bool = True
    is_last: bool = False
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        bn = dict(self.bn or {}, dtype=self.dtype)
        inplanes = x.shape[-1]
        if self.planes != inplanes or self.stride != 1:
            skip = Conv2d(self.planes, 1, stride=self.stride,
                          dtype=self.dtype, name="skip_conv")(x)
            skip = BatchNorm2d(**bn, name="skip_bn")(skip, training=training)
        else:
            skip = x
        y = x
        idx = 1
        filters = inplanes
        if self.grow_first:
            if self.start_with_relu:
                y = nn.relu(y)
            y = _SepConv(self.planes, 3, 1, self.dilation, bn=self.bn,
                         dtype=self.dtype, name=f"conv{idx}")(
                y, training=training)
            y = BatchNorm2d(**bn, name=f"bn{idx}")(y, training=training)
            filters = self.planes
            idx += 1
        for _ in range(self.num_reps - 1):
            if self.grow_first or self.start_with_relu:
                y = nn.relu(y)
            y = _SepConv(filters, 3, 1, self.dilation, bn=self.bn,
                         dtype=self.dtype, name=f"conv{idx}")(
                y, training=training)
            y = BatchNorm2d(**bn, name=f"bn{idx}")(y, training=training)
            idx += 1
        if not self.grow_first:
            y = nn.relu(y)
            y = _SepConv(self.planes, 3, 1, self.dilation, bn=self.bn,
                         dtype=self.dtype, name=f"conv{idx}")(
                y, training=training)
            y = BatchNorm2d(**bn, name=f"bn{idx}")(y, training=training)
            idx += 1
        if self.stride != 1 or self.is_last:
            y = nn.relu(y)
            y = _SepConv(self.planes, 3,
                         self.stride if self.stride != 1 else 1,
                         1 if self.stride != 1 else self.dilation,
                         bn=self.bn, dtype=self.dtype,
                         name=f"conv{idx}")(y, training=training)
            y = BatchNorm2d(**bn, name=f"bn{idx}")(y, training=training)
        return y + skip


class GluonXception(nn.Module):
    """Xception65/71 (reference :179-307, :309-445); ``deep_entry`` selects
    the 71 variant's 3-block entry flow at stride 1/2/2."""
    deep_entry: bool = False
    output_stride: int = 32
    num_classes: int = 1000
    in_chans: int = 3
    drop_rate: float = 0.0
    global_pool: str = "avg"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    bn_axis_name: Optional[str] = None
    dtype: Any = None
    default_cfg: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False, features_only: bool = False,
                 pool: bool = True):
        assert x.shape[-1] == self.in_chans, (x.shape, self.in_chans)
        bn = dict(momentum=self.bn_momentum, eps=self.bn_eps,
                  axis_name=self.bn_axis_name)
        bnd = dict(bn, dtype=self.dtype)
        if self.output_stride == 32:
            b3_stride, b20_stride, mid_d, exit_d = 2, 2, 1, (1, 1)
        elif self.output_stride == 16:
            b3_stride, b20_stride, mid_d, exit_d = 2, 1, 1, (1, 2)
        else:
            assert self.output_stride == 8
            b3_stride, b20_stride, mid_d, exit_d = 1, 1, 2, (2, 4)
        blk = dict(bn=bn, dtype=self.dtype)
        feats = []
        x = Conv2d(32, 3, stride=2, dtype=self.dtype, name="conv1")(x)
        x = BatchNorm2d(**bnd, name="bn1")(x, training=training)
        x = nn.relu(x)
        x = Conv2d(64, 3, dtype=self.dtype, name="conv2")(x)
        x = BatchNorm2d(**bnd, name="bn2")(x, training=training)
        x = nn.relu(x)
        x = _Block(128, 2, stride=2, start_with_relu=False, **blk,
                   name="block1")(x, training=training)
        x = nn.relu(x)      # "add relu here" (:281)
        feats.append(x)
        if self.deep_entry:    # Xception71 (:348-357)
            x = _Block(256, 2, stride=1, start_with_relu=False, **blk,
                       name="block2_0")(x, training=training)
            x = _Block(256, 2, stride=2, start_with_relu=False, **blk,
                       name="block2_1")(x, training=training)
            x = _Block(728, 2, stride=2, start_with_relu=False, **blk,
                       name="block2_2")(x, training=training)
        else:                  # Xception65 (:219-221)
            x = _Block(256, 2, stride=2, start_with_relu=False, **blk,
                       name="block2")(x, training=training)
        feats.append(x)
        x = _Block(728, 2, stride=b3_stride, is_last=True, **blk,
                   name="block3")(x, training=training)
        for i in range(4, 20):     # middle flow (:226-230)
            x = _Block(728, 3, dilation=mid_d, **blk,
                       name=f"block{i}")(x, training=training)
        feats.append(x)
        x = _Block(1024, 2, stride=b20_stride, dilation=exit_d[0],
                   grow_first=False, is_last=True, **blk,
                   name="block20")(x, training=training)
        x = nn.relu(x)
        for i, chs in [(3, 1536), (4, 1536), (5, 2048)]:
            x = _SepConv(chs, 3, 1, exit_d[1], bn=bn, dtype=self.dtype,
                         name=f"conv{i}")(x, training=training)
            x = BatchNorm2d(**bnd, name=f"bn{i}")(x, training=training)
            x = nn.relu(x)
        feats.append(x)
        if features_only:
            return feats
        if not pool:
            return x
        x = SelectAdaptivePool2d(self.global_pool, name="global_pool")(x)
        if self.drop_rate > 0:
            x = nn.Dropout(rate=self.drop_rate,
                           deterministic=not training)(x)
        if self.num_classes <= 0:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)


def _register():
    for name, deep in (("gluon_xception65", False), ("gluon_xception71", True)):
        def fn(pretrained=False, *, _deep=deep, **kwargs):
            kwargs.pop("pretrained", None)
            kwargs.setdefault("default_cfg", _cfg())
            return GluonXception(deep_entry=_deep, **kwargs)
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__module__ = __name__
        fn.__doc__ = f"{name} (reference gluon_xception.py entrypoint)."
        register_model(fn)


_register()
