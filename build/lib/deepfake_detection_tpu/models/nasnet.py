"""NASNet-A-Large (Flax/NHWC).

Re-design of ``/root/reference/dfd/timm/models/nasnet.py`` (620 LoC): the six
cell types — CellStem0 (:132-179), CellStem1 with factorized-reduction path
(:182-253), FirstCell (:255-322), NormalCell (:324-375), ReductionCell0 with
zero-pad-shifted branches (:377-431), ReductionCell1 (:432-485) — and the
6-@-4032 ``NASNetALarge`` assembly (:487-608).

Pooling matches torch semantics exactly: explicit (1,1) padding (−inf for
max, masked mean for ``count_include_pad=False`` avg), and the Pad variants'
zero-pad-then-crop shift is reproduced verbatim.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..ops.conv import Conv2d
from ..ops.norm import BatchNorm2d
from ..ops.pool import SelectAdaptivePool2d
from ..registry import register_model

__all__ = ["NASNetALarge"]

_P1 = ((1, 1), (1, 1))


def _cfg(**kwargs):
    cfg = dict(num_classes=1000, input_size=(3, 331, 331),
               pool_size=(11, 11), crop_pct=0.875, interpolation="bicubic",
               mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5),
               first_conv="conv0", classifier="last_linear")
    cfg.update(kwargs)
    return cfg


def _max_pool(x, stride=2, pad_shift=False):
    """MaxPool2d(3, stride, padding=1) (+ MaxPoolPad shift, :28-39)."""
    if pad_shift:
        x = jnp.pad(x, ((0, 0), (1, 0), (1, 0), (0, 0)))
    x = nn.max_pool(x, (3, 3), strides=(stride, stride), padding=_P1)
    return x[:, 1:, 1:, :] if pad_shift else x


def _avg_pool(x, stride=1, pad_shift=False):
    """AvgPool2d(3, stride, padding=1, count_include_pad=False)
    (+ AvgPoolPad shift, :42-53)."""
    if pad_shift:
        x = jnp.pad(x, ((0, 0), (1, 0), (1, 0), (0, 0)))
    s = nn.avg_pool(x, (3, 3), strides=(stride, stride), padding=_P1)
    ones = jnp.ones(x.shape[:-1] + (1,), x.dtype)
    c = nn.avg_pool(ones, (3, 3), strides=(stride, stride), padding=_P1)
    out = s / c
    return out[:, 1:, 1:, :] if pad_shift else out


class _BranchSep(nn.Module):
    """BranchSeparables (:72-129): relu → sep(stride) → BN → relu → sep → BN.
    ``stem`` maps in→out in the first separable; ``pad_shift`` is the
    BranchSeparablesReduction zero-pad/crop variant."""
    out_chs: int
    kernel_size: int
    stride: int = 1
    stem: bool = False
    pad_shift: bool = False
    bn: dict = None
    dtype: Any = None

    def _sep(self, x, out_chs, stride, name):
        in_chs = x.shape[-1]
        pad = self.kernel_size // 2
        x = Conv2d(in_chs, self.kernel_size, stride=stride, padding=pad,
                   groups=in_chs, dtype=self.dtype,
                   name=f"{name}_dw")(x)
        return Conv2d(out_chs, 1, dtype=self.dtype, name=f"{name}_pw")(x)

    @nn.compact
    def __call__(self, x, training: bool = False):
        bn = dict(self.bn or {}, dtype=self.dtype)
        mid = self.out_chs if self.stem else x.shape[-1]
        x = nn.relu(x)
        if self.pad_shift:
            x = jnp.pad(x, ((0, 0), (1, 0), (1, 0), (0, 0)))
        x = self._sep(x, mid, self.stride, "separable_1")
        if self.pad_shift:
            x = x[:, 1:, 1:, :]
        x = BatchNorm2d(**bn, name="bn_sep_1")(x, training=training)
        x = nn.relu(x)
        x = self._sep(x, self.out_chs, 1, "separable_2")
        return BatchNorm2d(**bn, name="bn_sep_2")(x, training=training)


class _ReluConvBn(nn.Module):
    """relu → 1×1 conv → BN (the cells' conv_1x1 blocks)."""
    out_chs: int
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = nn.relu(x)
        x = Conv2d(self.out_chs, 1, dtype=self.dtype, name="conv")(x)
        return BatchNorm2d(**dict(self.bn or {}, dtype=self.dtype),
                           name="bn")(x, training=training)


class _Factorized(nn.Module):
    """relu → two offset stride-2 1×1 paths → concat → BN (:193-201)."""
    out_chs_half: int
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = nn.relu(x)
        p1 = Conv2d(self.out_chs_half, 1, dtype=self.dtype,
                    name="path_1_conv")(x[:, ::2, ::2, :])
        x2 = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))[:, 1:, 1:, :]
        p2 = Conv2d(self.out_chs_half, 1, dtype=self.dtype,
                    name="path_2_conv")(x2[:, ::2, ::2, :])
        out = jnp.concatenate([p1, p2], axis=-1)
        return BatchNorm2d(**dict(self.bn or {}, dtype=self.dtype),
                           name="final_path_bn")(out, training=training)


class NASNetALarge(nn.Module):
    """Reference NASNetALarge (6 @ 4032) (:487-608)."""
    num_classes: int = 1000
    in_chans: int = 3
    stem_size: int = 96
    num_features: int = 4032
    channel_multiplier: int = 2
    drop_rate: float = 0.0
    global_pool: str = "avg"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-3
    bn_axis_name: Optional[str] = None
    dtype: Any = None
    default_cfg: Any = None

    def _stem0(self, x, chs, bn, training, name):
        k = dict(bn=bn, dtype=self.dtype)
        x1 = _ReluConvBn(chs, **k, name=f"{name}_conv_1x1")(
            x, training=training)
        c0 = _BranchSep(chs, 5, 2, **k, name=f"{name}_c0l")(
            x1, training=training) + \
            _BranchSep(chs, 7, 2, stem=True, **k, name=f"{name}_c0r")(
                x, training=training)
        c1 = _max_pool(x1) + _BranchSep(chs, 7, 2, stem=True, **k,
                                        name=f"{name}_c1r")(
            x, training=training)
        c2 = _avg_pool(x1, 2) + _BranchSep(chs, 5, 2, stem=True, **k,
                                           name=f"{name}_c2r")(
            x, training=training)
        c3 = _avg_pool(c0) + c1
        c4 = _BranchSep(chs, 3, 1, **k, name=f"{name}_c4l")(
            c0, training=training) + _max_pool(x1)
        return jnp.concatenate([c1, c2, c3, c4], axis=-1)

    def _cell(self, x_left, x_right, out_l, out_r, bn, training, name,
              kind="normal"):
        """stem1/first/normal/reduction0/reduction1 common 5-branch plan."""
        k = dict(bn=bn, dtype=self.dtype)
        red = kind in ("stem1", "reduction0", "reduction1")
        stride = 2 if red else 1
        shift = kind == "reduction0"
        if kind in ("first", "stem1"):
            # left input goes through the factorized-reduction path
            x_left = _Factorized(out_l, **k, name=f"{name}_prev")(
                x_left, training=training)
        else:
            x_left = _ReluConvBn(out_l, **k, name=f"{name}_conv_prev_1x1")(
                x_left, training=training)
        x_right = _ReluConvBn(out_r, **k, name=f"{name}_conv_1x1")(
            x_right, training=training)
        if red:
            # reduction plan (:405-430, stem1 :218-252 with left/right roles
            # swapped relative to the naming here — see call sites)
            c0 = _BranchSep(out_r, 5, 2, pad_shift=shift, **k,
                            name=f"{name}_c0l")(x_right, training=training) \
                + _BranchSep(out_r, 7, 2, pad_shift=shift, **k,
                             name=f"{name}_c0r")(x_left, training=training)
            c1 = _max_pool(x_right, 2, shift) + \
                _BranchSep(out_r, 7, 2, pad_shift=shift, **k,
                           name=f"{name}_c1r")(x_left, training=training)
            c2 = _avg_pool(x_right, 2, shift) + \
                _BranchSep(out_r, 5, 2, pad_shift=shift, **k,
                           name=f"{name}_c2r")(x_left, training=training)
            c3 = _avg_pool(c0) + c1
            c4 = _BranchSep(out_r, 3, 1, pad_shift=shift, **k,
                            name=f"{name}_c4l")(c0, training=training) + \
                _max_pool(x_right, 2, shift)
            return jnp.concatenate([c1, c2, c3, c4], axis=-1)
        # normal/first plan (:288-322, :351-375)
        c0 = _BranchSep(out_r, 5, 1, **k, name=f"{name}_c0l")(
            x_right, training=training) + \
            _BranchSep(out_r if kind == "first" else out_l, 3, 1, **k,
                       name=f"{name}_c0r")(x_left, training=training)
        c1 = _BranchSep(out_r if kind == "first" else out_l, 5, 1, **k,
                        name=f"{name}_c1l")(x_left, training=training) + \
            _BranchSep(out_r if kind == "first" else out_l, 3, 1, **k,
                       name=f"{name}_c1r")(x_left, training=training)
        c2 = _avg_pool(x_right) + x_left
        c3 = _avg_pool(x_left) + _avg_pool(x_left)
        c4 = _BranchSep(out_r, 3, 1, **k, name=f"{name}_c4l")(
            x_right, training=training) + x_right
        return jnp.concatenate([x_left, c0, c1, c2, c3, c4], axis=-1)

    @nn.compact
    def __call__(self, x, training: bool = False, features_only: bool = False,
                 pool: bool = True):
        assert x.shape[-1] == self.in_chans, (x.shape, self.in_chans)
        bn = dict(momentum=self.bn_momentum, eps=self.bn_eps,
                  axis_name=self.bn_axis_name)
        ch = self.num_features // 24
        cm = self.channel_multiplier
        conv0 = Conv2d(self.stem_size, 3, stride=2, padding="valid",
                       dtype=self.dtype, name="conv0_conv")(x)
        conv0 = BatchNorm2d(**dict(bn, dtype=self.dtype),
                            name="conv0_bn")(conv0, training=training)
        stem0 = self._stem0(conv0, ch // cm ** 2, bn, training,
                            "cell_stem_0")
        # stem1: left = factorized(conv0), right = conv_1x1(stem0); the
        # reference names them right/left respectively (:218-229) — branch
        # roles below match its forward exactly
        stem1 = self._cell(conv0, stem0, ch // cm // 2, ch // cm, bn,
                           training, "cell_stem_1", kind="stem1")
        prev, cur = stem0, stem1
        feats = []
        for si in range(3):
            mult = cm ** si
            for ci in range(6):
                kind = "first" if ci == 0 else "normal"
                ol = (ch * mult // 2) if ci == 0 else ch * mult
                nxt = self._cell(prev, cur, ol, ch * mult, bn, training,
                                 f"cell_{si * 6 + ci}", kind=kind)
                prev, cur = cur, nxt
            feats.append(cur)
            if si < 2:
                # the FirstCell after a reduction skips back to the cell
                # BEFORE the reduction's own input (reference :577-581:
                # cell_6(x_reduction_cell_0, x_cell_4)) — prev is unchanged
                red = self._cell(
                    prev, cur, ch * mult * 2, ch * mult * 2, bn, training,
                    f"reduction_cell_{si}", kind=f"reduction{si}")
                cur = red
        x = nn.relu(cur)
        feats[-1] = x
        if features_only:
            return feats
        if not pool:
            return x
        x = SelectAdaptivePool2d(self.global_pool, name="global_pool")(x)
        if self.drop_rate > 0:
            x = nn.Dropout(rate=self.drop_rate,
                           deterministic=not training)(x)
        if self.num_classes <= 0:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        name="last_linear")(x)


@register_model
def nasnetalarge(pretrained=False, **kwargs):
    """nasnetalarge (reference nasnet.py:611-620)."""
    kwargs.pop("pretrained", None)
    kwargs.setdefault("default_cfg", _cfg())
    return NASNetALarge(**kwargs)
