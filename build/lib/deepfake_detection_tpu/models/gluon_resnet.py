"""Gluon ResNet / ResNeXt / SE-ResNeXt / SENet variants (Flax/NHWC).

Re-design of ``/root/reference/dfd/timm/models/gluon_resnet.py`` (373 LoC):
all 24 entrypoints are parameterizations of the generic
:class:`~.resnet.ResNet` — the Gluon stem letters map as
v1b = plain, v1c = deep stem (32), v1d = deep stem + avg-down,
v1e = deep stem (64) + avg-down, v1s = deep stem (64)
(reference gluon_resnet.py:120-240).
"""

from __future__ import annotations

from ..registry import register_model
from .resnet import ResNet, _cfg

__all__ = []

_V1C = dict(stem_width=32, stem_type="deep")
_V1D = dict(stem_width=32, stem_type="deep", avg_down=True)
_V1E = dict(stem_width=64, stem_type="deep", avg_down=True)
_V1S = dict(stem_width=64, stem_type="deep")

# name: (block, layers, extra kwargs)
_GLUON_DEFS = {
    "gluon_resnet18_v1b": ("basic", (2, 2, 2, 2), {}),
    "gluon_resnet34_v1b": ("basic", (3, 4, 6, 3), {}),
    "gluon_resnet50_v1b": ("bottleneck", (3, 4, 6, 3), {}),
    "gluon_resnet101_v1b": ("bottleneck", (3, 4, 23, 3), {}),
    "gluon_resnet152_v1b": ("bottleneck", (3, 8, 36, 3), {}),
    "gluon_resnet50_v1c": ("bottleneck", (3, 4, 6, 3), _V1C),
    "gluon_resnet101_v1c": ("bottleneck", (3, 4, 23, 3), _V1C),
    "gluon_resnet152_v1c": ("bottleneck", (3, 8, 36, 3), _V1C),
    "gluon_resnet50_v1d": ("bottleneck", (3, 4, 6, 3), _V1D),
    "gluon_resnet101_v1d": ("bottleneck", (3, 4, 23, 3), _V1D),
    "gluon_resnet152_v1d": ("bottleneck", (3, 8, 36, 3), _V1D),
    "gluon_resnet50_v1e": ("bottleneck", (3, 4, 6, 3), _V1E),
    "gluon_resnet101_v1e": ("bottleneck", (3, 4, 23, 3), _V1E),
    "gluon_resnet152_v1e": ("bottleneck", (3, 8, 36, 3), _V1E),
    "gluon_resnet50_v1s": ("bottleneck", (3, 4, 6, 3), _V1S),
    "gluon_resnet101_v1s": ("bottleneck", (3, 4, 23, 3), _V1S),
    "gluon_resnet152_v1s": ("bottleneck", (3, 8, 36, 3), _V1S),
    "gluon_resnext50_32x4d": ("bottleneck", (3, 4, 6, 3),
                              dict(cardinality=32, base_width=4)),
    "gluon_resnext101_32x4d": ("bottleneck", (3, 4, 23, 3),
                               dict(cardinality=32, base_width=4)),
    "gluon_resnext101_64x4d": ("bottleneck", (3, 4, 23, 3),
                               dict(cardinality=64, base_width=4)),
    "gluon_seresnext50_32x4d": ("bottleneck", (3, 4, 6, 3),
                                dict(cardinality=32, base_width=4,
                                     attn_layer="se")),
    "gluon_seresnext101_32x4d": ("bottleneck", (3, 4, 23, 3),
                                 dict(cardinality=32, base_width=4,
                                      attn_layer="se")),
    "gluon_seresnext101_64x4d": ("bottleneck", (3, 4, 23, 3),
                                 dict(cardinality=64, base_width=4,
                                      attn_layer="se")),
    # gluon_senet154 (reference :360-371): deep stem, 3×3 downsample convs,
    # width halved in the first bottleneck conv
    "gluon_senet154": ("bottleneck", (3, 8, 36, 3),
                       dict(cardinality=64, base_width=4, stem_type="deep",
                            down_kernel_size=3, block_reduce_first=2,
                            attn_layer="se")),
}


def _register():
    for name, (block, layers, extra) in _GLUON_DEFS.items():
        def fn(pretrained=False, *, _block=block, _layers=layers,
               _extra=extra, **kwargs):
            kwargs.pop("pretrained", None)
            kwargs.setdefault("default_cfg", _cfg(interpolation="bicubic"))
            return ResNet(block=_block, layers=tuple(_layers),
                          **{**_extra, **kwargs})
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__module__ = __name__
        fn.__doc__ = f"{name} (reference gluon_resnet.py entrypoint)."
        register_model(fn)


_register()
