"""Inception-V3 (Flax/NHWC, native).

The reference (``/root/reference/dfd/timm/models/inception_v3.py``, 120 LoC)
wraps ``torchvision.models.Inception3`` and registers 4 weight variants
(:71-120).  Torch isn't part of this stack, so the architecture itself
(torchvision inception.py lineage: stem, InceptionA/B/C/D/E mixes, optional
aux head) is implemented here natively; the entrypoint surface matches the
reference — ``inception_v3`` builds the aux head, the tf/adv/gluon variants
don't.

TPU notes: the asymmetric 1×7/7×1 factorized convs map to MXU-friendly
(1,7)/(7,1) windows; all VALID-padding stem convs are explicit so spatial
math matches torchvision exactly (299×299 → 8×8×2048).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..ops.conv import Conv2d
from ..ops.norm import BatchNorm2d
from ..ops.pool import SelectAdaptivePool2d, avg_pool2d_same
from ..registry import register_model
from .efficientnet import (IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD,
                           IMAGENET_INCEPTION_MEAN, IMAGENET_INCEPTION_STD)

__all__ = ["InceptionV3"]


def _cfg(**kwargs):
    cfg = dict(num_classes=1000, input_size=(3, 299, 299), pool_size=(8, 8),
               crop_pct=0.875, interpolation="bicubic",
               mean=IMAGENET_INCEPTION_MEAN, std=IMAGENET_INCEPTION_STD,
               first_conv="conv0", classifier="fc")
    cfg.update(kwargs)
    return cfg


class _ConvBn(nn.Module):
    """BasicConv2d: conv(bias=False) → BN(eps=1e-3) → ReLU."""
    out_chs: int
    kernel_size: Any = 3
    stride: int = 1
    padding: Any = "valid"
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = Conv2d(self.out_chs, self.kernel_size, stride=self.stride,
                   padding=self.padding, dtype=self.dtype, name="conv")(x)
        x = BatchNorm2d(**dict(self.bn or {}, dtype=self.dtype),
                        name="bn")(x, training=training)
        return nn.relu(x)


def _avgpool3(x):
    """3×3 stride-1 avg pool, pad 1, count_include_pad (torch default)."""
    return avg_pool2d_same(x, (3, 3), (1, 1), count_include_pad=True)


class InceptionV3(nn.Module):
    """Inception3 (torchvision lineage; reference registers it wholesale)."""
    num_classes: int = 1000
    in_chans: int = 3
    aux_logits: bool = False
    drop_rate: float = 0.5
    global_pool: str = "avg"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-3
    bn_axis_name: Optional[str] = None
    dtype: Any = None
    default_cfg: Any = None

    def _mix_a(self, x, pool_chs, bn, training, name):
        b1 = _ConvBn(64, 1, bn=bn, dtype=self.dtype,
                     name=f"{name}_b1x1")(x, training=training)
        b5 = _ConvBn(48, 1, bn=bn, dtype=self.dtype,
                     name=f"{name}_b5x5_1")(x, training=training)
        b5 = _ConvBn(64, 5, padding=2, bn=bn, dtype=self.dtype,
                     name=f"{name}_b5x5_2")(b5, training=training)
        b3 = _ConvBn(64, 1, bn=bn, dtype=self.dtype,
                     name=f"{name}_b3x3dbl_1")(x, training=training)
        b3 = _ConvBn(96, 3, padding=1, bn=bn, dtype=self.dtype,
                     name=f"{name}_b3x3dbl_2")(b3, training=training)
        b3 = _ConvBn(96, 3, padding=1, bn=bn, dtype=self.dtype,
                     name=f"{name}_b3x3dbl_3")(b3, training=training)
        bp = _ConvBn(pool_chs, 1, bn=bn, dtype=self.dtype,
                     name=f"{name}_bpool")(_avgpool3(x), training=training)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)

    def _mix_b(self, x, bn, training, name):
        b3 = _ConvBn(384, 3, stride=2, bn=bn, dtype=self.dtype,
                     name=f"{name}_b3x3")(x, training=training)
        bd = _ConvBn(64, 1, bn=bn, dtype=self.dtype,
                     name=f"{name}_b3x3dbl_1")(x, training=training)
        bd = _ConvBn(96, 3, padding=1, bn=bn, dtype=self.dtype,
                     name=f"{name}_b3x3dbl_2")(bd, training=training)
        bd = _ConvBn(96, 3, stride=2, bn=bn, dtype=self.dtype,
                     name=f"{name}_b3x3dbl_3")(bd, training=training)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, bd, bp], axis=-1)

    def _mix_c(self, x, c7, bn, training, name):
        h = [(0, 0), (3, 3)]      # 1×7 pad
        v = [(3, 3), (0, 0)]      # 7×1 pad
        b1 = _ConvBn(192, 1, bn=bn, dtype=self.dtype,
                     name=f"{name}_b1x1")(x, training=training)
        b7 = _ConvBn(c7, 1, bn=bn, dtype=self.dtype,
                     name=f"{name}_b7x7_1")(x, training=training)
        b7 = _ConvBn(c7, (1, 7), padding=h, bn=bn, dtype=self.dtype,
                     name=f"{name}_b7x7_2")(b7, training=training)
        b7 = _ConvBn(192, (7, 1), padding=v, bn=bn, dtype=self.dtype,
                     name=f"{name}_b7x7_3")(b7, training=training)
        bd = _ConvBn(c7, 1, bn=bn, dtype=self.dtype,
                     name=f"{name}_b7x7dbl_1")(x, training=training)
        bd = _ConvBn(c7, (7, 1), padding=v, bn=bn, dtype=self.dtype,
                     name=f"{name}_b7x7dbl_2")(bd, training=training)
        bd = _ConvBn(c7, (1, 7), padding=h, bn=bn, dtype=self.dtype,
                     name=f"{name}_b7x7dbl_3")(bd, training=training)
        bd = _ConvBn(c7, (7, 1), padding=v, bn=bn, dtype=self.dtype,
                     name=f"{name}_b7x7dbl_4")(bd, training=training)
        bd = _ConvBn(192, (1, 7), padding=h, bn=bn, dtype=self.dtype,
                     name=f"{name}_b7x7dbl_5")(bd, training=training)
        bp = _ConvBn(192, 1, bn=bn, dtype=self.dtype,
                     name=f"{name}_bpool")(_avgpool3(x), training=training)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)

    def _mix_d(self, x, bn, training, name):
        b3 = _ConvBn(192, 1, bn=bn, dtype=self.dtype,
                     name=f"{name}_b3x3_1")(x, training=training)
        b3 = _ConvBn(320, 3, stride=2, bn=bn, dtype=self.dtype,
                     name=f"{name}_b3x3_2")(b3, training=training)
        b7 = _ConvBn(192, 1, bn=bn, dtype=self.dtype,
                     name=f"{name}_b7x7x3_1")(x, training=training)
        b7 = _ConvBn(192, (1, 7), padding=[(0, 0), (3, 3)], bn=bn,
                     dtype=self.dtype,
                     name=f"{name}_b7x7x3_2")(b7, training=training)
        b7 = _ConvBn(192, (7, 1), padding=[(3, 3), (0, 0)], bn=bn,
                     dtype=self.dtype,
                     name=f"{name}_b7x7x3_3")(b7, training=training)
        b7 = _ConvBn(192, 3, stride=2, bn=bn, dtype=self.dtype,
                     name=f"{name}_b7x7x3_4")(b7, training=training)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, b7, bp], axis=-1)

    def _mix_e(self, x, bn, training, name):
        b1 = _ConvBn(320, 1, bn=bn, dtype=self.dtype,
                     name=f"{name}_b1x1")(x, training=training)
        b3 = _ConvBn(384, 1, bn=bn, dtype=self.dtype,
                     name=f"{name}_b3x3_1")(x, training=training)
        b3 = jnp.concatenate([
            _ConvBn(384, (1, 3), padding=[(0, 0), (1, 1)], bn=bn,
                    dtype=self.dtype,
                    name=f"{name}_b3x3_2a")(b3, training=training),
            _ConvBn(384, (3, 1), padding=[(1, 1), (0, 0)], bn=bn,
                    dtype=self.dtype,
                    name=f"{name}_b3x3_2b")(b3, training=training),
        ], axis=-1)
        bd = _ConvBn(448, 1, bn=bn, dtype=self.dtype,
                     name=f"{name}_b3x3dbl_1")(x, training=training)
        bd = _ConvBn(384, 3, padding=1, bn=bn, dtype=self.dtype,
                     name=f"{name}_b3x3dbl_2")(bd, training=training)
        bd = jnp.concatenate([
            _ConvBn(384, (1, 3), padding=[(0, 0), (1, 1)], bn=bn,
                    dtype=self.dtype,
                    name=f"{name}_b3x3dbl_3a")(bd, training=training),
            _ConvBn(384, (3, 1), padding=[(1, 1), (0, 0)], bn=bn,
                    dtype=self.dtype,
                    name=f"{name}_b3x3dbl_3b")(bd, training=training),
        ], axis=-1)
        bp = _ConvBn(192, 1, bn=bn, dtype=self.dtype,
                     name=f"{name}_bpool")(_avgpool3(x), training=training)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)

    @nn.compact
    def __call__(self, x, training: bool = False, features_only: bool = False,
                 pool: bool = True, return_aux: bool = False):
        assert x.shape[-1] == self.in_chans, (x.shape, self.in_chans)
        bn = dict(momentum=self.bn_momentum, eps=self.bn_eps,
                  axis_name=self.bn_axis_name)
        cb = dict(bn=bn, dtype=self.dtype)
        feats = []
        x = _ConvBn(32, 3, stride=2, **cb, name="conv0")(x, training=training)
        x = _ConvBn(32, 3, **cb, name="conv1")(x, training=training)
        x = _ConvBn(64, 3, padding=1, **cb, name="conv2")(x,
                                                          training=training)
        feats.append(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = _ConvBn(80, 1, **cb, name="conv3")(x, training=training)
        x = _ConvBn(192, 3, **cb, name="conv4")(x, training=training)
        feats.append(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = self._mix_a(x, 32, bn, training, "mixed_5b")
        x = self._mix_a(x, 64, bn, training, "mixed_5c")
        x = self._mix_a(x, 64, bn, training, "mixed_5d")
        feats.append(x)
        x = self._mix_b(x, bn, training, "mixed_6a")
        x = self._mix_c(x, 128, bn, training, "mixed_6b")
        x = self._mix_c(x, 160, bn, training, "mixed_6c")
        x = self._mix_c(x, 160, bn, training, "mixed_6d")
        x = self._mix_c(x, 192, bn, training, "mixed_6e")
        feats.append(x)
        aux = None
        if self.aux_logits:
            # aux head off Mixed_6e; params always built, output opt-in
            a = nn.avg_pool(x, (5, 5), strides=(3, 3), padding="VALID")
            a = _ConvBn(128, 1, **cb, name="aux_conv0")(a, training=training)
            a = _ConvBn(768, 5, **cb, name="aux_conv1")(a, training=training)
            a = jnp.mean(a, axis=(1, 2))
            aux = nn.Dense(self.num_classes, dtype=self.dtype,
                           name="aux_fc")(a)
        x = self._mix_d(x, bn, training, "mixed_7a")
        x = self._mix_e(x, bn, training, "mixed_7b")
        x = self._mix_e(x, bn, training, "mixed_7c")
        feats.append(x)
        if features_only:
            return feats
        if not pool:
            return x
        x = SelectAdaptivePool2d(self.global_pool, name="global_pool")(x)
        if self.drop_rate > 0:
            x = nn.Dropout(rate=self.drop_rate,
                           deterministic=not training)(x)
        if self.num_classes <= 0:
            return x
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)
        return (x, aux) if (return_aux and aux is not None) else x


# variant: (aux_logits, cfg overrides)  (reference inception_v3.py:9-60)
_V3_DEFS = {
    "inception_v3": (True, {}),
    "tf_inception_v3": (False, dict(num_classes=1001)),
    "adv_inception_v3": (False, dict(num_classes=1001)),
    "gluon_inception_v3": (False, dict(mean=IMAGENET_DEFAULT_MEAN,
                                       std=IMAGENET_DEFAULT_STD)),
}


def _register():
    for name, (aux, over) in _V3_DEFS.items():
        def fn(pretrained=False, *, _aux=aux, _over=over, **kwargs):
            kwargs.pop("pretrained", None)
            kwargs.setdefault("aux_logits", _aux)
            kwargs.setdefault("drop_rate", 0.0)   # reference asserts 0 (:63-67)
            kwargs.setdefault("default_cfg", _cfg(**_over))
            return InceptionV3(**kwargs)
        fn.__name__ = name
        fn.__qualname__ = name
        fn.__module__ = __name__
        fn.__doc__ = f"{name} (reference inception_v3.py entrypoint)."
        register_model(fn)


_register()
