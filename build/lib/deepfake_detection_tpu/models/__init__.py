"""Model zoo (reference layer L2, ``dfd/timm/models/``).

Importing this package registers every model family's entrypoints.
"""

from ..registry import (is_model, is_model_in_modules, list_models,
                        list_modules, model_entrypoint, register_model)
from . import efficientnet  # noqa: F401  (registers entrypoints)
from .efficientnet import EfficientNet
from .factory import (create_deepfake_model, create_deepfake_model_v3,
                      create_deepfake_model_v4, create_model,
                      create_model_and_params, init_model)
from .helpers import (load_checkpoint, load_pretrained, load_state_dict,
                      resume_checkpoint, save_model_checkpoint)

# Families added as they land; each import registers its entrypoints.
for _mod in ("resnet", "xception", "senet", "vit", "mobilenetv3", "densenet",
             "inception_v3", "inception_v4", "inception_resnet_v2", "dpn",
             "hrnet", "dla", "res2net", "sknet", "selecsls", "nasnet",
             "pnasnet", "gluon_resnet", "gluon_xception", "timesformer",
             "video"):
    try:
        __import__(f"{__name__}.{_mod}")
    except ModuleNotFoundError as e:      # tolerate only a missing family
        if e.name != f"{__name__}.{_mod}":
            raise                         # real import error inside a family
