"""EfficientNet arch-string DSL decoder + stage builder.

Re-implements the reference's block-definition mini-language
(``/root/reference/dfd/timm/models/efficientnet_builder.py``): strings like
``ir_r2_k3_s2_e6_c24_se0.25`` decode to block-arg dicts (`_decode_block_str`
:20), stage depths scale with ceil-truncation (`_scale_stage_depth` :139),
and ``decode_arch_def`` (:177) yields the per-stage block-arg lists that the
model assembles.  This DSL is the single source of truth for every
EfficientNet/MixNet/MNasNet/FBNet/MobileNetV3 variant including the custom
``efficientnet_deepfake_v3/_v4`` configs.

The builder here is pure Python producing a flat list of (stage_idx,
block-kwargs) configs — the Flax model instantiates modules from it.  Stride→
dilation conversion for reduced ``output_stride`` (builder.py:330-339) and
per-block linearly-scaled drop_path (builder.py:229) happen at this level.
"""

from __future__ import annotations

import math
import re
from copy import deepcopy
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .efficientnet_blocks import round_channels

__all__ = ["decode_arch_def", "build_block_configs", "round_channels"]

_ACT_ABBREV = {
    "re": "relu",
    "r6": "relu6",
    "hs": "hard_swish",
    "sw": "swish",
    "mi": "mish",
}


def _parse_ksize(ss: str):
    """'3' → 3; '3.5.7' → [3, 5, 7] (mixed conv)."""
    if "." in ss:
        return [int(k) for k in ss.split(".")]
    return int(ss)


def _decode_block_str(block_str: str) -> Tuple[Dict[str, Any], int]:
    """One block string → (block kwargs, num_repeat) (builder.py:20-137).

    Grammar: ``<type>_<opts>`` with opts ``r<int>`` repeat, ``k<ks>`` kernel,
    ``s<int>`` stride, ``e<float>`` expansion, ``c<int>`` out chs, ``se<float>``
    SE ratio, ``cc<int>`` condconv experts, ``fc<int>`` fake in-chs (EdgeTPU),
    ``d<int>`` dilation, ``n<act>`` activation override, ``noskip`` flag,
    ``a`` (pw act, 'dsa' type suffix).
    """
    ops = block_str.split("_")
    block_type = ops[0]
    options: Dict[str, str] = {}
    noskip = False
    act: Optional[str] = None
    for op in ops[1:]:
        if op == "noskip":
            noskip = True
        elif op.startswith("n"):
            act = _ACT_ABBREV.get(op[1:], op[1:])
        else:
            splits = re.split(r"(\d.*)", op)
            if len(splits) >= 2:
                options[splits[0]] = splits[1]
    num_repeat = int(options.get("r", 1))
    common = dict(
        pad_type="",
        noskip=noskip,
        stride=int(options.get("s", 1)),
        dilation=int(options.get("d", 1)),
    )
    if act is not None:
        common["act"] = act
    if block_type in ("ir", "ds", "dsa"):
        common["dw_kernel_size"] = _parse_ksize(options.get("k", "3"))
    if "c" in options:
        common["out_chs"] = int(options["c"])
    if "se" in options:
        common["se_ratio"] = float(options["se"])

    if block_type == "ir":
        args = dict(common,
                    block_type="ir",
                    exp_ratio=float(options.get("e", 1.0)),
                    exp_kernel_size=_parse_ksize(options.get("a", "1"))
                    if "a" in options else 1,
                    pw_kernel_size=_parse_ksize(options.get("p", "1"))
                    if "p" in options else 1)
        if "cc" in options:
            args["block_type"] = "cc"
            args["num_experts"] = int(options["cc"])
    elif block_type in ("ds", "dsa"):
        args = dict(common, block_type="ds", pw_act=(block_type == "dsa"))
    elif block_type == "er":
        args = dict(common,
                    block_type="er",
                    exp_kernel_size=int(options.get("k", 3)),
                    exp_ratio=float(options.get("e", 1.0)),
                    fake_in_chs=int(options.get("fc", 0)))
    elif block_type == "cn":
        args = dict(common, block_type="cn",
                    kernel_size=_parse_ksize(options.get("k", "3")))
    else:
        raise ValueError(f"Unknown block type {block_type!r} in {block_str!r}")
    return args, num_repeat


def _scale_stage_depth(stack_args: List[Dict], repeats: List[int],
                       depth_multiplier: float = 1.0,
                       depth_trunc: str = "ceil") -> List[Dict]:
    """Scale a stage's total depth, distributing across its block defs
    back-to-front (builder.py:139-174)."""
    num_repeat = sum(repeats)
    if depth_trunc == "round":
        num_repeat_scaled = max(1, round(num_repeat * depth_multiplier))
    else:
        num_repeat_scaled = int(math.ceil(num_repeat * depth_multiplier))
    repeats_scaled: List[int] = []
    for r in repeats[::-1]:
        rs = max(1, round(r / num_repeat * num_repeat_scaled))
        repeats_scaled.append(rs)
        num_repeat -= r
        num_repeat_scaled -= rs
    repeats_scaled = repeats_scaled[::-1]
    sa_scaled: List[Dict] = []
    for ba, rep in zip(stack_args, repeats_scaled):
        sa_scaled.extend([deepcopy(ba) for _ in range(rep)])
    return sa_scaled


def decode_arch_def(arch_def: Sequence[Sequence[str]],
                    depth_multiplier: float = 1.0,
                    depth_trunc: str = "ceil",
                    experts_multiplier: int = 1,
                    fix_first_last: bool = False) -> List[List[Dict]]:
    """Arch-def (list of stage string-lists) → per-stage block-kwargs lists
    (builder.py:177-191).  ``fix_first_last`` exempts stem/tail stages from
    depth scaling (MobileNetV3 behavior)."""
    arch_args: List[List[Dict]] = []
    for stack_idx, block_strings in enumerate(arch_def):
        stack_args: List[Dict] = []
        repeats: List[int] = []
        for block_str in block_strings:
            ba, rep = _decode_block_str(block_str)
            if ba.get("num_experts", 0) > 0 and experts_multiplier > 1:
                ba["num_experts"] *= experts_multiplier
            stack_args.append(ba)
            repeats.append(rep)
        if fix_first_last and (stack_idx == 0 or stack_idx == len(arch_def) - 1):
            arch_args.append(_scale_stage_depth(stack_args, repeats, 1.0, depth_trunc))
        else:
            arch_args.append(_scale_stage_depth(stack_args, repeats,
                                                depth_multiplier, depth_trunc))
    return arch_args


def build_block_configs(block_args: List[List[Dict]],
                        channel_multiplier: float = 1.0,
                        channel_divisor: int = 8,
                        channel_min: Optional[int] = None,
                        output_stride: int = 32,
                        drop_path_rate: float = 0.0,
                        default_act: Any = "relu",
                        ) -> List[List[Dict]]:
    """Finalize per-block kwargs: channel rounding, stride→dilation conversion
    for ``output_stride`` (builder.py:330-339), per-block linearly-scaled
    drop_path (builder.py:229), repeat-stride semantics (only the first block
    of a stage strides)."""
    total_blocks = sum(len(s) for s in block_args)
    out: List[List[Dict]] = []
    block_idx = 0
    current_stride = 2  # after stem
    current_dilation = 1
    for stage in block_args:
        stage_out: List[Dict] = []
        for i, ba in enumerate(stage):
            ba = deepcopy(ba)
            if "out_chs" in ba:
                ba["out_chs"] = round_channels(ba["out_chs"], channel_multiplier,
                                               channel_divisor, channel_min)
            if "fake_in_chs" in ba and ba["fake_in_chs"]:
                ba["fake_in_chs"] = round_channels(ba["fake_in_chs"],
                                                   channel_multiplier,
                                                   channel_divisor, channel_min)
            stride = ba.get("stride", 1) if i == 0 else 1
            next_dilation = current_dilation
            if stride > 1:
                next_stride = current_stride * stride
                if next_stride > output_stride:
                    # absorb stride into dilation to hold output_stride; the
                    # striding block itself keeps the old dilation
                    next_dilation = current_dilation * stride
                    stride = 1
                else:
                    current_stride = next_stride
            ba["stride"] = stride
            ba["dilation"] = current_dilation
            current_dilation = next_dilation
            ba.setdefault("act", default_act)
            ba["drop_path_rate"] = drop_path_rate * block_idx / total_blocks
            stage_out.append(ba)
            block_idx += 1
        out.append(stage_out)
    return out
