"""Generic intermediate-feature extraction (reference ``feature_hooks.py:5``).

The reference registers torch forward hooks on named modules and harvests
their outputs.  The functional flax equivalent is
``capture_intermediates``: every module's outputs are recorded into an
``intermediates`` collection during ``apply``, no mutation or registration
required — and unlike torch hooks it composes with ``jit``.

This generalizes the per-model ``features_only=True`` paths (which return
the stage pyramid) to ANY named submodule.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Sequence, Tuple

from flax.traverse_util import flatten_dict

__all__ = ["extract_features"]


def extract_features(model, variables: Dict[str, Any], x,
                     names: Sequence[str] = (),
                     filter_fn: Callable[[str], bool] = None,
                     **apply_kwargs) -> Tuple[Any, Dict[str, Any]]:
    """Run ``model.apply`` capturing named submodule outputs.

    ``names`` are module-path prefixes (e.g. ``"blocks_2_1"`` or
    ``"conv_stem"``); ``filter_fn`` receives the dotted path for custom
    selection.  Returns ``(output, {path: feature})``.
    """
    match = filter_fn or (
        (lambda p: any(p == n or p.startswith(n + ".") for n in names))
        if names else (lambda p: True))

    out, mods = model.apply(
        variables, x, training=False,
        capture_intermediates=lambda mdl, _:
            match("/".join(mdl.path).replace("/", ".")),
        mutable=["intermediates"], **apply_kwargs)
    flat = flatten_dict(mods["intermediates"], sep=".")
    feats = {}
    for key, value in flat.items():
        path = key[: -len(".__call__")] if key.endswith(".__call__") else key
        # flax stores a tuple of outputs per call
        feats[path] = value[0] if isinstance(value, tuple) else value
    return out, feats
