"""Inception-V4 (Flax/NHWC).

Re-design of ``/root/reference/dfd/timm/models/inception_v4.py`` (308 LoC):
stem (Mixed_3a/4a/5a, :42-88), 4× Inception_A (:91-118), Reduction_A
(:121-139), 7× Inception_B (:142-177), Reduction_B (:180-202),
3× Inception_C (:205-249), 1536-dim head (:252-303).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..ops.conv import Conv2d
from ..ops.norm import BatchNorm2d
from ..ops.pool import SelectAdaptivePool2d, avg_pool2d_same
from ..registry import register_model
from .efficientnet import IMAGENET_INCEPTION_MEAN, IMAGENET_INCEPTION_STD

__all__ = ["InceptionV4"]

_H = [(0, 0), (3, 3)]       # 1×7 padding
_V = [(3, 3), (0, 0)]       # 7×1 padding
_H3 = [(0, 0), (1, 1)]      # 1×3
_V3 = [(1, 1), (0, 0)]      # 3×1


def _cfg(**kwargs):
    cfg = dict(num_classes=1000, input_size=(3, 299, 299), pool_size=(8, 8),
               crop_pct=0.875, interpolation="bicubic",
               mean=IMAGENET_INCEPTION_MEAN, std=IMAGENET_INCEPTION_STD,
               first_conv="features_0", classifier="last_linear")
    cfg.update(kwargs)
    return cfg


def _avgpool3(x):
    # count_include_pad=False (reference :108 etc.)
    return avg_pool2d_same(x, (3, 3), (1, 1), count_include_pad=False)


class _CB(nn.Module):
    """BasicConv2d: conv(bias=False) → BN(eps=1e-3) → ReLU (:27-39)."""
    out_chs: int
    kernel_size: Any = 3
    stride: int = 1
    padding: Any = "valid"
    bn: dict = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = Conv2d(self.out_chs, self.kernel_size, stride=self.stride,
                   padding=self.padding, dtype=self.dtype, name="conv")(x)
        x = BatchNorm2d(**dict(self.bn or {}, dtype=self.dtype),
                        name="bn")(x, training=training)
        return nn.relu(x)


class InceptionV4(nn.Module):
    """Reference InceptionV4 (:252-303)."""
    num_classes: int = 1000
    in_chans: int = 3
    drop_rate: float = 0.0
    global_pool: str = "avg"
    bn_momentum: float = 0.1
    bn_eps: float = 1e-3
    bn_axis_name: Optional[str] = None
    dtype: Any = None
    default_cfg: Any = None

    def _ia(self, x, bn, training, name):
        """Inception_A (:91-118)."""
        cb = dict(bn=bn, dtype=self.dtype)
        b0 = _CB(96, 1, **cb, name=f"{name}_b0")(x, training=training)
        b1 = _CB(96, 3, padding=1, **cb, name=f"{name}_b1_1")(
            _CB(64, 1, **cb, name=f"{name}_b1_0")(x, training=training),
            training=training)
        b2 = _CB(64, 1, **cb, name=f"{name}_b2_0")(x, training=training)
        b2 = _CB(96, 3, padding=1, **cb, name=f"{name}_b2_1")(
            b2, training=training)
        b2 = _CB(96, 3, padding=1, **cb, name=f"{name}_b2_2")(
            b2, training=training)
        b3 = _CB(96, 1, **cb, name=f"{name}_b3")(_avgpool3(x),
                                                 training=training)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)

    def _ib(self, x, bn, training, name):
        """Inception_B (:142-177)."""
        cb = dict(bn=bn, dtype=self.dtype)
        b0 = _CB(384, 1, **cb, name=f"{name}_b0")(x, training=training)
        b1 = _CB(192, 1, **cb, name=f"{name}_b1_0")(x, training=training)
        b1 = _CB(224, (1, 7), padding=_H, **cb, name=f"{name}_b1_1")(
            b1, training=training)
        b1 = _CB(256, (7, 1), padding=_V, **cb, name=f"{name}_b1_2")(
            b1, training=training)
        b2 = _CB(192, 1, **cb, name=f"{name}_b2_0")(x, training=training)
        b2 = _CB(192, (7, 1), padding=_V, **cb, name=f"{name}_b2_1")(
            b2, training=training)
        b2 = _CB(224, (1, 7), padding=_H, **cb, name=f"{name}_b2_2")(
            b2, training=training)
        b2 = _CB(224, (7, 1), padding=_V, **cb, name=f"{name}_b2_3")(
            b2, training=training)
        b2 = _CB(256, (1, 7), padding=_H, **cb, name=f"{name}_b2_4")(
            b2, training=training)
        b3 = _CB(128, 1, **cb, name=f"{name}_b3")(_avgpool3(x),
                                                  training=training)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)

    def _ic(self, x, bn, training, name):
        """Inception_C (:205-249)."""
        cb = dict(bn=bn, dtype=self.dtype)
        b0 = _CB(256, 1, **cb, name=f"{name}_b0")(x, training=training)
        b1 = _CB(384, 1, **cb, name=f"{name}_b1_0")(x, training=training)
        b1 = jnp.concatenate([
            _CB(256, (1, 3), padding=_H3, **cb, name=f"{name}_b1_1a")(
                b1, training=training),
            _CB(256, (3, 1), padding=_V3, **cb, name=f"{name}_b1_1b")(
                b1, training=training)], axis=-1)
        b2 = _CB(384, 1, **cb, name=f"{name}_b2_0")(x, training=training)
        b2 = _CB(448, (3, 1), padding=_V3, **cb, name=f"{name}_b2_1")(
            b2, training=training)
        b2 = _CB(512, (1, 3), padding=_H3, **cb, name=f"{name}_b2_2")(
            b2, training=training)
        b2 = jnp.concatenate([
            _CB(256, (1, 3), padding=_H3, **cb, name=f"{name}_b2_3a")(
                b2, training=training),
            _CB(256, (3, 1), padding=_V3, **cb, name=f"{name}_b2_3b")(
                b2, training=training)], axis=-1)
        b3 = _CB(256, 1, **cb, name=f"{name}_b3")(_avgpool3(x),
                                                  training=training)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)

    @nn.compact
    def __call__(self, x, training: bool = False, features_only: bool = False,
                 pool: bool = True):
        assert x.shape[-1] == self.in_chans, (x.shape, self.in_chans)
        bn = dict(momentum=self.bn_momentum, eps=self.bn_eps,
                  axis_name=self.bn_axis_name)
        cb = dict(bn=bn, dtype=self.dtype)
        feats = []
        x = _CB(32, 3, 2, **cb, name="features_0")(x, training=training)
        x = _CB(32, 3, **cb, name="features_1")(x, training=training)
        x = _CB(64, 3, padding=1, **cb, name="features_2")(x,
                                                           training=training)
        feats.append(x)
        # Mixed_3a (:42-52)
        x = jnp.concatenate([
            nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID"),
            _CB(96, 3, 2, **cb, name="mixed_3a_conv")(x, training=training),
        ], axis=-1)
        # Mixed_4a (:55-75)
        b0 = _CB(64, 1, **cb, name="mixed_4a_b0_0")(x, training=training)
        b0 = _CB(96, 3, **cb, name="mixed_4a_b0_1")(b0, training=training)
        b1 = _CB(64, 1, **cb, name="mixed_4a_b1_0")(x, training=training)
        b1 = _CB(64, (1, 7), padding=_H, **cb, name="mixed_4a_b1_1")(
            b1, training=training)
        b1 = _CB(64, (7, 1), padding=_V, **cb, name="mixed_4a_b1_2")(
            b1, training=training)
        b1 = _CB(96, 3, **cb, name="mixed_4a_b1_3")(b1, training=training)
        x = jnp.concatenate([b0, b1], axis=-1)
        feats.append(x)
        # Mixed_5a (:78-88)
        x = jnp.concatenate([
            _CB(192, 3, 2, **cb, name="mixed_5a_conv")(x, training=training),
            nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID"),
        ], axis=-1)
        for i in range(4):
            x = self._ia(x, bn, training, f"inception_a_{i}")
        feats.append(x)
        # Reduction_A (:121-139)
        b0 = _CB(384, 3, 2, **cb, name="reduction_a_b0")(x, training=training)
        b1 = _CB(192, 1, **cb, name="reduction_a_b1_0")(x, training=training)
        b1 = _CB(224, 3, padding=1, **cb, name="reduction_a_b1_1")(
            b1, training=training)
        b1 = _CB(256, 3, 2, **cb, name="reduction_a_b1_2")(
            b1, training=training)
        x = jnp.concatenate([
            b0, b1, nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")],
            axis=-1)
        for i in range(7):
            x = self._ib(x, bn, training, f"inception_b_{i}")
        feats.append(x)
        # Reduction_B (:180-202)
        b0 = _CB(192, 1, **cb, name="reduction_b_b0_0")(x, training=training)
        b0 = _CB(192, 3, 2, **cb, name="reduction_b_b0_1")(
            b0, training=training)
        b1 = _CB(256, 1, **cb, name="reduction_b_b1_0")(x, training=training)
        b1 = _CB(256, (1, 7), padding=_H, **cb, name="reduction_b_b1_1")(
            b1, training=training)
        b1 = _CB(320, (7, 1), padding=_V, **cb, name="reduction_b_b1_2")(
            b1, training=training)
        b1 = _CB(320, 3, 2, **cb, name="reduction_b_b1_3")(
            b1, training=training)
        x = jnp.concatenate([
            b0, b1, nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")],
            axis=-1)
        for i in range(3):
            x = self._ic(x, bn, training, f"inception_c_{i}")
        feats.append(x)
        if features_only:
            return feats
        if not pool:
            return x
        x = SelectAdaptivePool2d(self.global_pool, name="global_pool")(x)
        if self.drop_rate > 0:
            x = nn.Dropout(rate=self.drop_rate,
                           deterministic=not training)(x)
        if self.num_classes <= 0:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        name="last_linear")(x)


@register_model
def inception_v4(pretrained=False, **kwargs):
    """inception_v4 (reference inception_v4.py:306-308)."""
    kwargs.pop("pretrained", None)
    kwargs.setdefault("default_cfg", _cfg())
    return InceptionV4(**kwargs)
