"""Logging setup (reference ``dfd/timm/utils.py:343-357``)."""

from __future__ import annotations

import logging

__all__ = ["FormatterNoInfo", "setup_default_logging"]


class FormatterNoInfo(logging.Formatter):
    """INFO records print bare; other levels keep 'LEVEL: msg' (:343-349)."""

    def __init__(self, fmt: str = "%(levelname)s: %(message)s"):
        super().__init__(fmt)

    def format(self, record: logging.LogRecord) -> str:
        if record.levelno == logging.INFO:
            return str(record.getMessage())
        return super().format(record)


def setup_default_logging(default_level: int = logging.INFO) -> None:
    console_handler = logging.StreamHandler()
    console_handler.setFormatter(FormatterNoInfo())
    logging.root.addHandler(console_handler)
    logging.root.setLevel(default_level)
