"""Exponential moving average of model state — a pure pytree transform.

Replaces ``ModelEma`` (``/root/reference/dfd/timm/utils.py:277-340``), which
deep-copies the torch module and mutates its state dict each step.  Here the
EMA is just another pytree in the train state, updated functionally *inside*
the jitted train step (so it costs one fused multiply-add over the weights,
overlapped with the step; the reference pays a separate kernel launch per
tensor every step).

Like the reference, the EMA tracks *everything* in the model state — params
and batch-norm running stats — with decay 0.9998 by default (train.py:208).
"""

from __future__ import annotations

from typing import Any

import jax

__all__ = ["init_ema", "update_ema"]


def init_ema(variables: Any) -> Any:
    """EMA state starts as a copy of the model state (reference :306).

    A *real* copy, not aliased references — the train step donates its input
    state, and donating the same underlying buffer via both ``params`` and
    ``ema`` is an error (and undefined behavior when it isn't caught).
    """
    return jax.tree.map(jax.numpy.copy, variables)


def update_ema(ema: Any, variables: Any, decay: float = 0.9998) -> Any:
    """``ema = decay * ema + (1 - decay) * new`` per leaf (reference :331-340).

    Jit-safe; call inside the train step.
    """
    return jax.tree.map(
        lambda e, v: e * decay + (1.0 - decay) * v.astype(e.dtype)
        if hasattr(e, "dtype") and jax.numpy.issubdtype(e.dtype, jax.numpy.inexact)
        else v,
        ema, variables)
