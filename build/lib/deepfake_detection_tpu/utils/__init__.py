"""Training utilities (SURVEY.md §2.6): metrics, EMA, reporting, logging."""

from .ema import init_ema, update_ema
from .log import FormatterNoInfo, setup_default_logging
from .metrics import AverageMeter, accuracy, auc, masked_mean
from .summary import get_outdir, natural_key, plot_csv, update_summary
