"""Global model registry.

TPU-native re-design of the reference's decorator registry
(``/root/reference/dfd/timm/models/registry.py:14-93``): model names map to
entrypoint callables that build Flax modules.  The registry is the single
namespace through which every backbone — EfficientNet, ResNet, Xception, ViT,
… — is constructed, so runner code never imports model files directly.
"""

from __future__ import annotations

import fnmatch
import re
import sys
from typing import Callable, Dict, List, Set

__all__ = [
    "register_model",
    "list_models",
    "is_model",
    "model_entrypoint",
    "list_modules",
    "is_model_in_modules",
]

_model_entrypoints: Dict[str, Callable] = {}
_model_to_module: Dict[str, str] = {}
_module_to_models: Dict[str, Set[str]] = {}


def register_model(fn: Callable) -> Callable:
    """Decorator: registers ``fn`` under its function name.

    The entrypoint signature convention is
    ``fn(pretrained: bool = False, **kwargs) -> flax Module``.
    """
    name = fn.__name__
    module_name = fn.__module__.split(".")[-1]
    if name in _model_entrypoints:
        raise ValueError(f"Model {name!r} is already registered "
                         f"(by module {_model_to_module[name]!r})")
    _model_entrypoints[name] = fn
    _model_to_module[name] = module_name
    _module_to_models.setdefault(module_name, set()).add(name)
    # mirror onto the defining module's __all__ for introspection
    mod = sys.modules.get(fn.__module__)
    if mod is not None:
        if hasattr(mod, "__all__"):
            if name not in mod.__all__:
                mod.__all__.append(name)
        else:
            mod.__all__ = [name]
    return fn


def _natural_key(s: str):
    return [int(t) if t.isdigit() else t for t in re.split(r"(\d+)", s.lower())]


def list_models(filter: str = "", module: str = "", exclude_filters=()) -> List[str]:
    """All registered model names, optionally glob-filtered / module-scoped."""
    if module:
        names = list(_module_to_models.get(module, set()))
    else:
        names = list(_model_entrypoints.keys())
    if filter:
        names = fnmatch.filter(names, filter)
    if exclude_filters:
        if isinstance(exclude_filters, str):
            exclude_filters = [exclude_filters]
        for xf in exclude_filters:
            drop = set(fnmatch.filter(names, xf))
            names = [n for n in names if n not in drop]
    return sorted(names, key=_natural_key)


def is_model(name: str) -> bool:
    return name in _model_entrypoints


def model_entrypoint(name: str) -> Callable:
    try:
        return _model_entrypoints[name]
    except KeyError:
        raise KeyError(
            f"Unknown model {name!r}. Known models: {list_models()[:20]} ...") from None


def list_modules() -> List[str]:
    return sorted(_module_to_models.keys())


def is_model_in_modules(name: str, modules) -> bool:
    assert isinstance(modules, (tuple, list, set))
    return any(name in _module_to_models.get(m, set()) for m in modules)
