// Native data-loader core: threaded JPEG decode (file → RGB) for the input
// pipeline.
//
// Role in the framework: SURVEY.md §7 hard part #4 — the flagship config
// feeds 4 JPEG frames per sample at 600²×3 each; at ≥70% MFU the host must
// decode ~50 MB/s/chip of JPEG without stalling device dispatch.  The
// reference leans on torch's C++ DataLoader worker processes (multiprocess
// fork + pickle IPC).  Here the equivalent is an in-process C++ thread pool:
// decode happens outside the GIL (ctypes releases it during the call), frames
// of one clip decode in parallel, and there is no serialization overhead.
//
// Functionality:
//   * libjpeg decode with DCT-domain scaling (scale_denom ∈ {1,2,4,8}):
//     decoding directly to 1/2, 1/4, 1/8 size is ~4/16/64× cheaper than
//     decode-then-resize, which the PIL path (and the reference) pays.
//   * persistent worker pool with a simple mutex/condvar work queue.
//   * pure C ABI (no pybind11 in this image) — consumed via ctypes from
//     deepfake_detection_tpu/data/native.py.
//
// Build: g++ -O3 -shared -fPIC dfd_native.cc -ljpeg -lpthread
// (driven by data/native.py on first import; see _build_library there).

#include <cstddef>
#include <cstdio>

#include <jpeglib.h>  // requires size_t/FILE declared first

#include <csetjmp>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <condition_variable>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// single-image decode
// ---------------------------------------------------------------------------

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* err = reinterpret_cast<ErrorMgr*>(cinfo->err);
  longjmp(err->jump, 1);
}

void silent_output(j_common_ptr) {}  // drop libjpeg warnings from stderr

// Decode a JPEG byte buffer to tightly-packed RGB8.  Returns a malloc'd
// buffer (caller frees via dfd_free) or nullptr on any decode error.
uint8_t* decode_buffer(const uint8_t* data, size_t size, int scale_denom,
                       int* out_w, int* out_h) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  jerr.pub.output_message = silent_output;
  // volatile: modified between setjmp and longjmp — without it the
  // error-path free() would see an indeterminate value and leak every
  // corrupt frame's row buffer
  uint8_t* volatile out = nullptr;

  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    std::free(out);
    return nullptr;
  }

  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(size));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return nullptr;
  }
  cinfo.out_color_space = JCS_RGB;
  cinfo.scale_num = 1;
  cinfo.scale_denom = scale_denom > 0 ? scale_denom : 1;
  // trade fidelity knobs the same direction PIL's draft mode does
  cinfo.dct_method = JDCT_ISLOW;
  jpeg_start_decompress(&cinfo);

  const int w = static_cast<int>(cinfo.output_width);
  const int h = static_cast<int>(cinfo.output_height);
  const int stride = w * 3;
  out = static_cast<uint8_t*>(std::malloc(static_cast<size_t>(stride) * h));
  if (!out) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return nullptr;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out + static_cast<size_t>(cinfo.output_scanline) * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  *out_w = w;
  *out_h = h;
  return out;
}

uint8_t* decode_file(const char* path, int scale_denom, int* w, int* h) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  long len = std::ftell(f);
  if (len <= 0) {
    std::fclose(f);
    return nullptr;
  }
  std::fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> buf(static_cast<size_t>(len));
  size_t got = std::fread(buf.data(), 1, buf.size(), f);
  std::fclose(f);
  if (got != buf.size()) return nullptr;
  return decode_buffer(buf.data(), buf.size(), scale_denom, w, h);
}

}  // namespace

void dfd_free(uint8_t* p) { std::free(p); }

uint8_t* dfd_decode_jpeg(const uint8_t* data, size_t size, int scale_denom,
                         int* out_w, int* out_h) {
  return decode_buffer(data, size, scale_denom, out_w, out_h);
}

uint8_t* dfd_decode_jpeg_file(const char* path, int scale_denom, int* out_w,
                              int* out_h) {
  return decode_file(path, scale_denom, out_w, out_h);
}

// ---------------------------------------------------------------------------
// worker pool
// ---------------------------------------------------------------------------

namespace {

class Pool {
 public:
  explicit Pool(int n) : stop_(false) {
    for (int i = 0; i < n; ++i)
      workers_.emplace_back([this] { Run(); });
  }

  ~Pool() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  void Submit(std::function<void()> fn) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      q_.push(std::move(fn));
    }
    cv_.notify_one();
  }

 private:
  void Run() {
    for (;;) {
      std::function<void()> fn;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !q_.empty(); });
        if (stop_ && q_.empty()) return;
        fn = std::move(q_.front());
        q_.pop();
      }
      fn();
    }
  }

  std::vector<std::thread> workers_;
  std::queue<std::function<void()>> q_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stop_;
};

struct Latch {
  explicit Latch(int n) : count(n) {}
  void Done() {
    std::unique_lock<std::mutex> lk(mu);
    if (--count == 0) cv.notify_all();
  }
  void Wait() {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [this] { return count == 0; });
  }
  int count;
  std::mutex mu;
  std::condition_variable cv;
};

}  // namespace

void* dfd_pool_new(int n_threads) {
  if (n_threads < 1) n_threads = 1;
  return new Pool(n_threads);
}

void dfd_pool_free(void* pool) { delete static_cast<Pool*>(pool); }

// Decode n files in parallel on the pool; blocks until all complete.
// outs[i] = malloc'd RGB buffer or nullptr; ws/hs filled per image.
void dfd_pool_decode_files(void* pool, int n, const char** paths,
                           int scale_denom, uint8_t** outs, int* ws,
                           int* hs) {
  Pool* p = static_cast<Pool*>(pool);
  Latch latch(n);
  for (int i = 0; i < n; ++i) {
    p->Submit([&, i] {
      outs[i] = decode_file(paths[i], scale_denom, &ws[i], &hs[i]);
      latch.Done();
    });
  }
  latch.Wait();
}

// Same, over in-memory buffers.
void dfd_pool_decode_buffers(void* pool, int n, const uint8_t** datas,
                             const size_t* sizes, int scale_denom,
                             uint8_t** outs, int* ws, int* hs) {
  Pool* p = static_cast<Pool*>(pool);
  Latch latch(n);
  for (int i = 0; i < n; ++i) {
    p->Submit([&, i] {
      outs[i] = decode_buffer(datas[i], sizes[i], scale_denom, &ws[i],
                              &hs[i]);
      latch.Done();
    });
  }
  latch.Wait();
}

}  // extern "C"
