"""Native (C++) runtime components; Python bindings live in data/native.py."""
