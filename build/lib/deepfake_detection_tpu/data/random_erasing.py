"""Device-side batched RandomErasing (jit-safe, static shapes).

Re-design of ``/root/reference/dfd/timm/data/random_erasing.py:18-101``
('Random Erasing Data Augmentation', Zhong et al.).  The reference runs a
Python loop over batch elements on the GPU with data-dependent rectangle
shapes; under XLA every shape must be static, so the rectangle is realised as
a boolean mask built from ``iota`` comparisons and the erase is a ``where`` —
one fused elementwise op over the batch, vmapped over samples and frames.

Semantics parity:

* modes ``const`` (zeros), ``rand`` (per-channel normal), ``pixel``
  (per-pixel normal) (:6-15);
* per-sample erase probability, count ∈ [min_count, max_count], area
  fraction ∈ [min_area, max_area] / count, log-uniform aspect (:64-80);
* the reference's 10-attempt rejection loop (:70-80) becomes 10 *parallel*
  candidates with first-valid selection — identical acceptance distribution,
  no data-dependent control flow;
* multi-frame: each 3-channel frame slice of the 12-channel clip is erased
  independently (:96-100);
* ``num_splits``: the first ``B // num_splits`` samples (the clean aug split)
  are skipped (:91).

Layout is NHWC: ``(B, H, W, 3*img_num)``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["random_erasing", "RandomErasing"]

_NUM_ATTEMPTS = 10


def _one_erase(key: jax.Array, frame: jnp.ndarray, probability: float,
               min_area: float, max_area: float, log_aspect_min: float,
               log_aspect_max: float, mode: str, min_count: int,
               max_count: int, enabled) -> jnp.ndarray:
    """Erase one (H, W, C) frame. ``enabled`` is a traced bool (aug-split)."""
    h_img, w_img, chans = frame.shape
    area = h_img * w_img
    k_gate, k_count, k_boxes, k_fill = jax.random.split(key, 4)

    do_erase = (jax.random.uniform(k_gate) < probability) & enabled
    count = jax.random.randint(k_count, (), min_count, max_count + 1)

    out = frame
    for c in range(max_count):
        k_box = jax.random.fold_in(k_boxes, c)
        ka, kr, kt, kl = jax.random.split(k_box, 4)
        # 10 parallel candidates, take the first whose rect fits (:70-80)
        target_area = jax.random.uniform(
            ka, (_NUM_ATTEMPTS,), minval=min_area, maxval=max_area
        ) * area / count
        aspect = jnp.exp(jax.random.uniform(
            kr, (_NUM_ATTEMPTS,), minval=log_aspect_min, maxval=log_aspect_max))
        hh = jnp.round(jnp.sqrt(target_area * aspect)).astype(jnp.int32)
        ww = jnp.round(jnp.sqrt(target_area / aspect)).astype(jnp.int32)
        valid = (ww < w_img) & (hh < h_img)
        pick = jnp.argmax(valid)  # first True (argmax of bools)
        h = hh[pick]
        w = ww[pick]
        ok = valid[pick] & (c < count) & do_erase
        top = jnp.floor(jax.random.uniform(kt) * (h_img - h + 1)).astype(jnp.int32)
        left = jnp.floor(jax.random.uniform(kl) * (w_img - w + 1)).astype(jnp.int32)
        rows = jnp.arange(h_img)[:, None]
        cols = jnp.arange(w_img)[None, :]
        mask = ((rows >= top) & (rows < top + h) &
                (cols >= left) & (cols < left + w) & ok)[..., None]
        k_f = jax.random.fold_in(k_fill, c)
        if mode == "pixel":
            fill = jax.random.normal(k_f, frame.shape, frame.dtype)
        elif mode == "rand":
            fill = jnp.broadcast_to(
                jax.random.normal(k_f, (1, 1, chans), frame.dtype), frame.shape)
        else:  # const
            fill = jnp.zeros_like(frame)
        out = jnp.where(mask, fill, out)
    return out


@functools.partial(jax.jit, static_argnames=(
    "probability", "min_area", "max_area", "min_aspect", "max_aspect", "mode",
    "min_count", "max_count", "num_splits", "img_num"))
def random_erasing(key: jax.Array, images: jnp.ndarray,
                   probability: float = 0.5, min_area: float = 0.02,
                   max_area: float = 1 / 3, min_aspect: float = 0.3,
                   max_aspect: Optional[float] = None, mode: str = "const",
                   min_count: int = 1, max_count: Optional[int] = None,
                   num_splits: int = 0, img_num: int = 1) -> jnp.ndarray:
    """Erase random rectangles from a normalized NHWC batch."""
    import math
    b, h, w, c = images.shape
    max_aspect = max_aspect or 1.0 / min_aspect
    max_count = max_count or min_count
    la_min, la_max = math.log(min_aspect), math.log(max_aspect)
    assert c % img_num == 0, (c, img_num)
    cpf = c // img_num
    batch_start = b // num_splits if num_splits > 1 else 0

    frames = images.reshape(b, h, w, img_num, cpf)
    frames = jnp.moveaxis(frames, 3, 1)          # (B, img_num, H, W, cpf)
    keys = jax.random.split(key, b * img_num).reshape(b, img_num, 2)
    enabled = (jnp.arange(b) >= batch_start)[:, None].repeat(img_num, 1)

    erase = functools.partial(
        _one_erase, probability=probability, min_area=min_area,
        max_area=max_area, log_aspect_min=la_min, log_aspect_max=la_max,
        mode=mode, min_count=min_count, max_count=max_count)
    out = jax.vmap(jax.vmap(lambda k, f, e: erase(k, f, enabled=e)))(
        keys, frames, enabled)
    return jnp.moveaxis(out, 1, 3).reshape(b, h, w, c)


class RandomErasing:
    """Stateful-looking wrapper mirroring the reference constructor signature
    (random_erasing.py:38-60); holds only static config."""

    def __init__(self, probability: float = 0.5, min_area: float = 0.02,
                 max_area: float = 1 / 3, min_aspect: float = 0.3,
                 max_aspect: Optional[float] = None, mode: str = "const",
                 min_count: int = 1, max_count: Optional[int] = None,
                 num_splits: int = 0, img_num: int = 1):
        mode = (mode or "const").lower()
        assert mode in ("const", "rand", "pixel"), mode
        self.kwargs = dict(
            probability=probability, min_area=min_area, max_area=max_area,
            min_aspect=min_aspect, max_aspect=max_aspect, mode=mode,
            min_count=min_count, max_count=max_count, num_splits=num_splits,
            img_num=img_num)

    def __call__(self, key: jax.Array, images: jnp.ndarray) -> jnp.ndarray:
        return random_erasing(key, images, **self.kwargs)
