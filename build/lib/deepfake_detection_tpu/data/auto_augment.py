"""AutoAugment / RandAugment / AugMix (PIL, explicit RNG).

Parity with ``/root/reference/dfd/timm/data/auto_augment.py`` (817 LoC): the
16-op pool (:58-175), magnitude→argument maps (:180-255), the AutoAugment
policy tables (v0/original/originalr, :300-490), ``AutoAugment`` (:495),
``RandAugment`` (:616), ``AugMixAugment`` (:705), and the config-string
parsers (``rand_augment_transform`` :631, ``auto_augment_transform``,
``augment_and_mix_transform``).  Policy data originates from the AutoAugment
(Cubuk et al. 2018), RandAugment (Cubuk et al. 2019) and AugMix (Hendrycks et
al. 2020) papers.

All randomness flows through the ``numpy.random.Generator`` passed per call —
no global ``random`` state (see data/transforms.py docstring).
"""

from __future__ import annotations

import math
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
from PIL import Image, ImageEnhance, ImageOps

__all__ = ["AutoAugment", "RandAugment", "AugMixAugment",
           "auto_augment_transform", "rand_augment_transform",
           "augment_and_mix_transform", "AugmentOp"]

_MAX_LEVEL = 10.0
_FILL = (128, 128, 128)
_INTERP = (Image.BILINEAR, Image.BICUBIC)


def _interpolation(kwargs: Dict, rng: np.random.Generator):
    interp = kwargs.pop("resample", _INTERP)
    if isinstance(interp, (list, tuple)):
        return interp[rng.integers(len(interp))]
    return interp


# ---------------------------------------------------------------------------
# Image ops
# ---------------------------------------------------------------------------

def shear_x(img, factor, rng, **kw):
    return img.transform(img.size, Image.AFFINE, (1, factor, 0, 0, 1, 0),
                         resample=_interpolation(kw, rng), **kw)


def shear_y(img, factor, rng, **kw):
    return img.transform(img.size, Image.AFFINE, (1, 0, 0, factor, 1, 0),
                         resample=_interpolation(kw, rng), **kw)


def translate_x_rel(img, pct, rng, **kw):
    pixels = pct * img.size[0]
    return img.transform(img.size, Image.AFFINE, (1, 0, pixels, 0, 1, 0),
                         resample=_interpolation(kw, rng), **kw)


def translate_y_rel(img, pct, rng, **kw):
    pixels = pct * img.size[1]
    return img.transform(img.size, Image.AFFINE, (1, 0, 0, 0, 1, pixels),
                         resample=_interpolation(kw, rng), **kw)


def translate_x_abs(img, pixels, rng, **kw):
    return img.transform(img.size, Image.AFFINE, (1, 0, pixels, 0, 1, 0),
                         resample=_interpolation(kw, rng), **kw)


def translate_y_abs(img, pixels, rng, **kw):
    return img.transform(img.size, Image.AFFINE, (1, 0, 0, 0, 1, pixels),
                         resample=_interpolation(kw, rng), **kw)


def rotate(img, degrees, rng, **kw):
    return img.rotate(degrees, resample=_interpolation(kw, rng),
                      fillcolor=kw.get("fillcolor"))


def auto_contrast(img, rng, **kw):
    return ImageOps.autocontrast(img)


def invert(img, rng, **kw):
    return ImageOps.invert(img)


def equalize(img, rng, **kw):
    return ImageOps.equalize(img)


def solarize(img, thresh, rng, **kw):
    return ImageOps.solarize(img, thresh)


def solarize_add(img, add, rng, thresh=128, **kw):
    lut = [min(255, i + add) if i < thresh else i for i in range(256)]
    if img.mode in ("L", "RGB"):
        return img.point(lut * 3 if img.mode == "RGB" else lut)
    return img


def posterize(img, bits, rng, **kw):
    if bits >= 8:
        return img
    return ImageOps.posterize(img, bits)


def contrast(img, factor, rng, **kw):
    return ImageEnhance.Contrast(img).enhance(factor)


def color(img, factor, rng, **kw):
    return ImageEnhance.Color(img).enhance(factor)


def brightness(img, factor, rng, **kw):
    return ImageEnhance.Brightness(img).enhance(factor)


def sharpness(img, factor, rng, **kw):
    return ImageEnhance.Sharpness(img).enhance(factor)


def _randomly_negate(v, rng) -> float:
    return -v if rng.random() > 0.5 else v


# ---------------------------------------------------------------------------
# Level → arg maps (reference :180-255)
# ---------------------------------------------------------------------------

def _rotate_level(level, rng, hp):
    return (_randomly_negate((level / _MAX_LEVEL) * 30.0, rng),)


def _enhance_level(level, rng, hp):
    return ((level / _MAX_LEVEL) * 1.8 + 0.1,)


def _enhance_increasing_level(level, rng, hp):
    return (1.0 + _randomly_negate((level / _MAX_LEVEL) * 0.9, rng),)


def _shear_level(level, rng, hp):
    return (_randomly_negate((level / _MAX_LEVEL) * 0.3, rng),)


def _translate_abs_level(level, rng, hp):
    return (_randomly_negate(
        (level / _MAX_LEVEL) * float(hp.get("translate_const", 250)), rng),)


def _translate_rel_level(level, rng, hp):
    return (_randomly_negate(
        (level / _MAX_LEVEL) * hp.get("translate_pct", 0.45), rng),)


def _posterize_level(level, rng, hp):
    return (int((level / _MAX_LEVEL) * 4),)


def _posterize_increasing_level(level, rng, hp):
    return (4 - int((level / _MAX_LEVEL) * 4),)


def _posterize_original_level(level, rng, hp):
    return (int((level / _MAX_LEVEL) * 4) + 4,)


def _solarize_level(level, rng, hp):
    return (int((level / _MAX_LEVEL) * 256),)


def _solarize_increasing_level(level, rng, hp):
    return (256 - int((level / _MAX_LEVEL) * 256),)


def _solarize_add_level(level, rng, hp):
    return (int((level / _MAX_LEVEL) * 110),)


def _none(level, rng, hp):
    return ()


LEVEL_TO_ARG: Dict[str, Callable] = {
    "AutoContrast": _none, "Equalize": _none, "Invert": _none,
    "Rotate": _rotate_level,
    "Posterize": _posterize_level,
    "PosterizeIncreasing": _posterize_increasing_level,
    "PosterizeOriginal": _posterize_original_level,
    "Solarize": _solarize_level,
    "SolarizeIncreasing": _solarize_increasing_level,
    "SolarizeAdd": _solarize_add_level,
    "Color": _enhance_level, "ColorIncreasing": _enhance_increasing_level,
    "Contrast": _enhance_level, "ContrastIncreasing": _enhance_increasing_level,
    "Brightness": _enhance_level,
    "BrightnessIncreasing": _enhance_increasing_level,
    "Sharpness": _enhance_level,
    "SharpnessIncreasing": _enhance_increasing_level,
    "ShearX": _shear_level, "ShearY": _shear_level,
    "TranslateX": _translate_abs_level, "TranslateY": _translate_abs_level,
    "TranslateXRel": _translate_rel_level,
    "TranslateYRel": _translate_rel_level,
}

NAME_TO_OP: Dict[str, Callable] = {
    "AutoContrast": auto_contrast, "Equalize": equalize, "Invert": invert,
    "Rotate": rotate,
    "Posterize": posterize, "PosterizeIncreasing": posterize,
    "PosterizeOriginal": posterize,
    "Solarize": solarize, "SolarizeIncreasing": solarize,
    "SolarizeAdd": solarize_add,
    "Color": color, "ColorIncreasing": color,
    "Contrast": contrast, "ContrastIncreasing": contrast,
    "Brightness": brightness, "BrightnessIncreasing": brightness,
    "Sharpness": sharpness, "SharpnessIncreasing": sharpness,
    "ShearX": shear_x, "ShearY": shear_y,
    "TranslateX": translate_x_abs, "TranslateY": translate_y_abs,
    "TranslateXRel": translate_x_rel, "TranslateYRel": translate_y_rel,
}

_GEOMETRIC = {"Rotate", "ShearX", "ShearY", "TranslateX", "TranslateY",
              "TranslateXRel", "TranslateYRel"}


class AugmentOp:
    """One (op, probability, magnitude) triple (reference :258-297)."""

    def __init__(self, name: str, prob: float = 0.5, magnitude: float = 10,
                 hparams: Optional[Dict] = None):
        hparams = hparams or {}
        self.name = name
        self.aug_fn = NAME_TO_OP[name]
        self.level_fn = LEVEL_TO_ARG[name]
        self.prob = prob
        self.magnitude = magnitude
        self.hparams = dict(hparams)
        self.kwargs: Dict[str, Any] = {}
        if name in _GEOMETRIC:
            self.kwargs["fillcolor"] = hparams.get("img_mean", _FILL)
            if "interpolation" in hparams:
                from .transforms import pil_interp
                self.kwargs["resample"] = pil_interp(hparams["interpolation"])
        # magnitude noise: mstd sampled per call; mstd=inf → uniform
        self.magnitude_std = self.hparams.get("magnitude_std", 0)
        self.magnitude_max = self.hparams.get("magnitude_max", _MAX_LEVEL)

    def __call__(self, img, rng: np.random.Generator):
        if self.prob < 1.0 and rng.random() > self.prob:
            return img
        magnitude = self.magnitude
        if self.magnitude_std:
            if self.magnitude_std == float("inf"):
                magnitude = rng.uniform(0, magnitude)
            elif self.magnitude_std > 0:
                magnitude = rng.normal(magnitude, self.magnitude_std)
        magnitude = max(0.0, min(float(self.magnitude_max), magnitude))
        args = self.level_fn(magnitude, rng, self.hparams)
        return self.aug_fn(img, *args, rng, **dict(self.kwargs))


# ---------------------------------------------------------------------------
# AutoAugment policies (policy data from the AutoAugment paper / TF impl)
# ---------------------------------------------------------------------------

def _policy_v0() -> List[List[Tuple[str, float, int]]]:
    return [
        [("Equalize", 0.8, 1), ("ShearY", 0.8, 4)],
        [("Color", 0.4, 9), ("Equalize", 0.6, 3)],
        [("Color", 0.4, 1), ("Rotate", 0.6, 8)],
        [("Solarize", 0.8, 3), ("Equalize", 0.4, 7)],
        [("Solarize", 0.4, 2), ("Solarize", 0.6, 2)],
        [("Color", 0.2, 0), ("Equalize", 0.8, 8)],
        [("Equalize", 0.4, 8), ("SolarizeAdd", 0.8, 3)],
        [("ShearX", 0.2, 9), ("Rotate", 0.6, 8)],
        [("Color", 0.6, 1), ("Equalize", 1.0, 2)],
        [("Invert", 0.4, 9), ("Rotate", 0.6, 0)],
        [("Equalize", 1.0, 9), ("ShearY", 0.6, 3)],
        [("Color", 0.4, 7), ("Equalize", 0.6, 0)],
        [("Posterize", 0.4, 6), ("AutoContrast", 0.4, 7)],
        [("Solarize", 0.6, 8), ("Color", 0.6, 9)],
        [("Solarize", 0.2, 4), ("Rotate", 0.8, 9)],
        [("Rotate", 1.0, 7), ("TranslateYRel", 0.8, 9)],
        [("ShearX", 0.0, 0), ("Solarize", 0.8, 4)],
        [("ShearY", 0.8, 0), ("Color", 0.6, 4)],
        [("Color", 1.0, 0), ("Rotate", 0.6, 2)],
        [("Equalize", 0.8, 4), ("Equalize", 0.0, 8)],
        [("Equalize", 1.0, 4), ("AutoContrast", 0.6, 2)],
        [("ShearY", 0.4, 7), ("SolarizeAdd", 0.6, 7)],
        [("Posterize", 0.8, 2), ("Solarize", 0.6, 10)],
        [("Solarize", 0.6, 8), ("Equalize", 0.6, 1)],
        [("Color", 0.8, 6), ("Rotate", 0.4, 5)],
    ]


def _policy_original() -> List[List[Tuple[str, float, int]]]:
    return [
        [("PosterizeOriginal", 0.4, 8), ("Rotate", 0.6, 9)],
        [("Solarize", 0.6, 5), ("AutoContrast", 0.6, 5)],
        [("Equalize", 0.8, 8), ("Equalize", 0.6, 3)],
        [("PosterizeOriginal", 0.6, 7), ("PosterizeOriginal", 0.6, 6)],
        [("Equalize", 0.4, 7), ("Solarize", 0.2, 4)],
        [("Equalize", 0.4, 4), ("Rotate", 0.8, 8)],
        [("Solarize", 0.6, 3), ("Equalize", 0.6, 7)],
        [("PosterizeOriginal", 0.8, 5), ("Equalize", 1.0, 2)],
        [("Rotate", 0.2, 3), ("Solarize", 0.6, 8)],
        [("Equalize", 0.6, 8), ("PosterizeOriginal", 0.4, 6)],
        [("Rotate", 0.8, 8), ("Color", 0.4, 0)],
        [("Rotate", 0.4, 9), ("Equalize", 0.6, 2)],
        [("Equalize", 0.0, 7), ("Equalize", 0.8, 8)],
        [("Invert", 0.6, 4), ("Equalize", 1.0, 8)],
        [("Color", 0.6, 4), ("Contrast", 1.0, 8)],
        [("Rotate", 0.8, 8), ("Color", 1.0, 2)],
        [("Color", 0.8, 8), ("Solarize", 0.8, 7)],
        [("Sharpness", 0.4, 7), ("Invert", 0.6, 8)],
        [("ShearX", 0.6, 5), ("Equalize", 1.0, 9)],
        [("Color", 0.4, 0), ("Equalize", 0.6, 3)],
        [("Equalize", 0.4, 7), ("Solarize", 0.2, 4)],
        [("Solarize", 0.6, 5), ("AutoContrast", 0.6, 5)],
        [("Invert", 0.6, 4), ("Equalize", 1.0, 8)],
        [("Color", 0.6, 4), ("Contrast", 1.0, 8)],
        [("Equalize", 0.8, 8), ("Equalize", 0.6, 3)],
    ]


def _policy_originalr() -> List[List[Tuple[str, float, int]]]:
    # 'original' with research-style increasing posterize (reference
    # auto_augment.py policy_originalr)
    return [[("PosterizeIncreasing", p, m) if n == "PosterizeOriginal"
             else (n, p, m) for n, p, m in sub] for sub in _policy_original()]


_POLICIES = {"v0": _policy_v0, "original": _policy_original,
             "originalr": _policy_originalr}


class AutoAugment:
    """Pick one random sub-policy per image and apply it (reference :495)."""

    def __init__(self, policy: str = "v0", hparams: Optional[Dict] = None):
        table = _POLICIES[policy]()
        self.policy = [[AugmentOp(n, p, m, hparams) for n, p, m in sub]
                       for sub in table]

    def __call__(self, img, rng: np.random.Generator):
        sub = self.policy[rng.integers(len(self.policy))]
        for op in sub:
            img = op(img, rng)
        return img


def auto_augment_transform(config_str: str, hparams: Optional[Dict] = None
                           ) -> AutoAugment:
    """Parse e.g. ``'original-mstd0.5'`` (reference parser semantics)."""
    config = config_str.split("-")
    policy = config[0]
    hparams = dict(hparams or {})
    for c in config[1:]:
        cs = re.split(r"(\d.*)", c)
        if len(cs) < 2:
            continue
        key, val = cs[:2]
        if key == "mstd":
            hparams["magnitude_std"] = float(val)
    return AutoAugment(policy, hparams)


# ---------------------------------------------------------------------------
# RandAugment
# ---------------------------------------------------------------------------

_RAND_TRANSFORMS = [
    "AutoContrast", "Equalize", "Invert", "Rotate", "Posterize", "Solarize",
    "SolarizeAdd", "Color", "Contrast", "Brightness", "Sharpness", "ShearX",
    "ShearY", "TranslateXRel", "TranslateYRel",
]

_RAND_INCREASING_TRANSFORMS = [
    "AutoContrast", "Equalize", "Invert", "Rotate", "PosterizeIncreasing",
    "SolarizeIncreasing", "SolarizeAdd", "ColorIncreasing",
    "ContrastIncreasing", "BrightnessIncreasing", "SharpnessIncreasing",
    "ShearX", "ShearY", "TranslateXRel", "TranslateYRel",
]

# weights from the reference's _RAND_CHOICE_WEIGHTS_0 (index-aligned)
_RAND_CHOICE_WEIGHTS_0 = {
    "Rotate": 0.3, "ShearX": 0.2, "ShearY": 0.2, "TranslateXRel": 0.1,
    "TranslateYRel": 0.1, "Color": 0.025, "Sharpness": 0.025,
    "AutoContrast": 0.025, "Solarize": 0.005, "SolarizeAdd": 0.005,
    "Contrast": 0.005, "Brightness": 0.005, "Equalize": 0.005,
    "Posterize": 0.0, "Invert": 0.0,
}


class RandAugment:
    """Apply ``num_layers`` ops drawn (optionally weighted) from the pool
    (reference :616-629)."""

    def __init__(self, ops: Sequence[AugmentOp], num_layers: int = 2,
                 choice_weights: Optional[np.ndarray] = None):
        self.ops = list(ops)
        self.num_layers = num_layers
        self.choice_weights = choice_weights

    def __call__(self, img, rng: np.random.Generator):
        picks = rng.choice(
            len(self.ops), self.num_layers,
            replace=self.choice_weights is None, p=self.choice_weights)
        for i in picks:
            img = self.ops[i](img, rng)
        return img


def rand_augment_transform(config_str: str, hparams: Optional[Dict] = None
                           ) -> RandAugment:
    """Parse e.g. ``'rand-m9-mstd0.5-inc1'`` (reference :631-680)."""
    magnitude = _MAX_LEVEL
    num_layers = 2
    hparams = dict(hparams or {})
    transforms = _RAND_TRANSFORMS
    weight_idx = None
    config = config_str.split("-")
    assert config[0] == "rand"
    for c in config[1:]:
        cs = re.split(r"(\d.*)", c)
        if len(cs) < 2:
            continue
        key, val = cs[:2]
        if key == "mstd":
            v = float(val)
            if v > 100:
                v = float("inf")
            hparams["magnitude_std"] = v
        elif key == "mmax":
            hparams["magnitude_max"] = float(val)
        elif key == "inc":
            if bool(val):
                transforms = _RAND_INCREASING_TRANSFORMS
        elif key == "m":
            magnitude = float(val)
        elif key == "n":
            num_layers = int(val)
        elif key == "w":
            weight_idx = int(val)
    ops = [AugmentOp(name, prob=0.5, magnitude=magnitude, hparams=hparams)
           for name in transforms]
    choice_weights = None
    if weight_idx is not None:
        w = np.asarray([_RAND_CHOICE_WEIGHTS_0[name] for name in transforms])
        choice_weights = w / w.sum()
    return RandAugment(ops, num_layers, choice_weights)


# ---------------------------------------------------------------------------
# AugMix
# ---------------------------------------------------------------------------

_AUGMIX_TRANSFORMS = [
    "AutoContrast", "ColorIncreasing", "ContrastIncreasing",
    "BrightnessIncreasing", "SharpnessIncreasing", "Equalize", "Rotate",
    "PosterizeIncreasing", "SolarizeIncreasing", "ShearX", "ShearY",
    "TranslateXRel", "TranslateYRel",
]


class AugMixAugment:
    """AugMix: width-way mixture of random augmentation chains blended back
    into the source image (reference :705-760)."""

    def __init__(self, ops: Sequence[AugmentOp], alpha: float = 1.0,
                 width: int = 3, depth: int = -1, blended: bool = False):
        self.ops = list(ops)
        self.alpha = alpha
        self.width = width
        self.depth = depth
        self.blended = blended

    def _apply_basic(self, img, mixing_weights, m, rng):
        img_shape = img.size[1], img.size[0], len(img.getbands())  # (H, W, C)
        mixed = np.zeros(img_shape, dtype=np.float32)
        for mw in mixing_weights:
            depth = self.depth if self.depth > 0 else int(rng.integers(1, 4))
            picks = rng.choice(len(self.ops), depth, replace=True)
            img_aug = img
            for i in picks:
                img_aug = self.ops[i](img_aug, rng)
            mixed += mw * np.asarray(img_aug, dtype=np.float32)
        np.clip(mixed, 0, 255.0, out=mixed)
        mixed = Image.fromarray(mixed.astype(np.uint8))
        return Image.blend(img, mixed, m)

    def __call__(self, img, rng: np.random.Generator):
        mixing_weights = np.float32(rng.dirichlet([self.alpha] * self.width))
        m = np.float32(rng.beta(self.alpha, self.alpha))
        return self._apply_basic(img, mixing_weights, m, rng)


def augment_and_mix_transform(config_str: str, hparams: Optional[Dict] = None
                              ) -> AugMixAugment:
    """Parse e.g. ``'augmix-m5-w4-d2'`` (reference :763-800)."""
    magnitude = 3
    width = 3
    depth = -1
    alpha = 1.0
    blended = False
    hparams = dict(hparams or {})
    config = config_str.split("-")
    assert config[0] == "augmix"
    for c in config[1:]:
        cs = re.split(r"(\d.*)", c)
        if len(cs) < 2:
            continue
        key, val = cs[:2]
        if key == "mstd":
            hparams["magnitude_std"] = float(val)
        elif key == "m":
            magnitude = float(val)
        elif key == "w":
            width = int(val)
        elif key == "d":
            depth = int(val)
        elif key == "a":
            alpha = float(val)
        elif key == "b":
            blended = bool(val)
    hparams.setdefault("magnitude_std", float("inf"))
    ops = [AugmentOp(name, prob=1.0, magnitude=magnitude, hparams=hparams)
           for name in _AUGMIX_TRANSFORMS]
    return AugMixAugment(ops, alpha=alpha, width=width, depth=depth,
                         blended=blended)
