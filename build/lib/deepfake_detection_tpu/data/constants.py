"""Dataset normalization constants (reference ``dfd/timm/data/constants.py:1-7``)."""

DEFAULT_CROP_PCT = 0.875
IMAGENET_DEFAULT_MEAN = (0.485, 0.456, 0.406)
IMAGENET_DEFAULT_STD = (0.229, 0.224, 0.225)
IMAGENET_INCEPTION_MEAN = (0.5, 0.5, 0.5)
IMAGENET_INCEPTION_STD = (0.5, 0.5, 0.5)
IMAGENET_DPN_MEAN = (124 / 255, 117 / 255, 104 / 255)
IMAGENET_DPN_STD = tuple([1 / (.0167 * 255)] * 3)
