"""deepfake_detection_tpu — TPU-native deepfake-detection training framework.

A ground-up JAX/XLA/Flax re-design with the capabilities of the reference
PyTorch stack at ``/root/reference`` (TARTRL/Deepfake_Detection): the timm-style
model zoo + factory/registry, the 4-frame deepfake data pipeline, TF-parity
optimizers/schedulers, and a pjit/mesh distributed training runtime replacing
apex-DDP/NCCL.
"""

__version__ = "0.1.0"

from . import registry
from .config import ClusterConfig, ServerSpec, TrainConfig
from .registry import list_models, model_entrypoint, register_model
