"""Classification losses.

Parity with ``/root/reference/dfd/timm/loss/`` (cross_entropy.py:6-40,
jsd.py:8-39) plus the reference's loss-selection precedence from the train
runner (``dfd/runners/train.py:506-520``): jsd > mixup(soft-target) >
label-smoothing > plain CE.

All losses are pure jnp functions of ``(logits, target)`` → scalar, so they
jit/grad/vmap and live inside the compiled train step.  Optional
``weight=None`` mask argument supports the padded-eval-batch pattern (TPU
static shapes: pad the last batch and zero out the padding's contribution).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

__all__ = [
    "cross_entropy", "label_smoothing_cross_entropy",
    "soft_target_cross_entropy", "jsd_cross_entropy", "create_loss_fn",
    "one_hot",
]


from .utils.metrics import masked_mean as _masked_mean  # canonical helper


def one_hot(labels: jnp.ndarray, num_classes: int,
            on_value: float = 1.0, off_value: float = 0.0) -> jnp.ndarray:
    """Smoothing-aware one-hot (reference mixup.py:5-8)."""
    oh = jax.nn.one_hot(labels, num_classes)
    return oh * on_value + (1.0 - oh) * off_value


def cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray,
                  weight: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Plain CE with integer labels (torch ``nn.CrossEntropyLoss`` analog)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return _masked_mean(nll, weight)


def label_smoothing_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray,
                                  smoothing: float = 0.1,
                                  weight: Optional[jnp.ndarray] = None
                                  ) -> jnp.ndarray:
    """NLL with label smoothing (cross_entropy.py:6-27):
    ``(1-s) * nll + s * mean(-logp)``."""
    assert smoothing < 1.0
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    smooth = -logp.mean(axis=-1)
    return _masked_mean((1.0 - smoothing) * nll + smoothing * smooth, weight)


def soft_target_cross_entropy(logits: jnp.ndarray, target: jnp.ndarray,
                              weight: Optional[jnp.ndarray] = None
                              ) -> jnp.ndarray:
    """CE against soft targets, used under mixup (cross_entropy.py:29-37)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return _masked_mean((-target * logp).sum(axis=-1), weight)


def jsd_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray,
                      num_splits: int = 3, alpha: float = 12.0,
                      smoothing: float = 0.1) -> jnp.ndarray:
    """AugMix JSD consistency loss (jsd.py:8-39).

    Batch is ``num_splits`` stacked views (clean first).  CE on the clean
    split only, plus ``alpha *`` mean KL(p_i ‖ mixture) over all splits.
    """
    split = logits.shape[0] // num_splits
    assert split * num_splits == logits.shape[0]
    clean_logits = logits[:split]
    if smoothing and smoothing > 0:
        loss = label_smoothing_cross_entropy(clean_logits, labels[:split],
                                             smoothing)
    else:
        loss = cross_entropy(clean_logits, labels[:split])
    probs = jax.nn.softmax(logits.reshape(num_splits, split, -1), axis=-1)
    logp_mix = jnp.log(jnp.clip(probs.mean(axis=0), 1e-7, 1.0))
    # torch F.kl_div(input=logq, target=p, 'batchmean') = sum p*(logp-logq)/B
    kl = (probs * (jnp.log(jnp.clip(probs, 1e-7, 1.0)) - logp_mix[None]))
    kl = kl.sum(axis=(1, 2)) / split
    return loss + alpha * kl.mean()


def create_loss_fn(cfg) -> Callable:
    """Loss precedence from the reference runner (train.py:506-520)."""
    if getattr(cfg, "jsd", False):
        ns = getattr(cfg, "aug_splits", 0)
        # without view splits the JSD slicing silently corrupts the loss
        # (reference train.py:507 asserts the same)
        assert ns > 1, "--jsd requires --aug-splits > 1"
        return lambda logits, target, weight=None: jsd_cross_entropy(
            logits, target, num_splits=ns, smoothing=cfg.smoothing)
    if getattr(cfg, "mixup", 0.0) > 0:
        # soft targets come from the mixup collate
        return soft_target_cross_entropy
    if getattr(cfg, "smoothing", 0.0) > 0:
        return lambda logits, target, weight=None: \
            label_smoothing_cross_entropy(logits, target, cfg.smoothing,
                                          weight)
    return cross_entropy
