"""Lookahead optimizer wrapper (k steps forward, 1 step back).

Reference: ``/root/reference/dfd/timm/optim/lookahead.py:10`` — selected by the
``lookahead_`` optimizer-name prefix (``optim_factory.py:96-98``).

Unlike ``optax.lookahead`` (which requires a special two-copy parameter
pytree), this wrapper keeps the slow weights in optimizer *state*, so it
composes with a plain Flax ``TrainState``: every ``sync_period`` steps the
emitted update is rewritten so the applied parameters land on
``slow + alpha * (fast - slow)``, and the slow copy is refreshed.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax


class LookaheadState(NamedTuple):
    inner: Any
    slow_params: Any
    step: jax.Array


def lookahead(inner: optax.GradientTransformation,
              sync_period: int = 6,
              alpha: float = 0.5) -> optax.GradientTransformation:
    """Wrap ``inner`` with Lookahead slow/fast weight averaging."""

    def init_fn(params):
        return LookaheadState(
            inner=inner.init(params),
            slow_params=jax.tree.map(jnp.asarray, params),
            step=jnp.zeros([], jnp.int32),
        )

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("lookahead requires params")
        fast_updates, inner_state = inner.update(updates, state.inner, params)
        step = state.step + 1
        sync = (step % sync_period) == 0
        # On sync steps the applied params land on slow + alpha*(fast_new-slow)
        # and the slow copy moves there too; otherwise plain inner update.
        target = jax.tree.map(
            lambda fu, p, slow: slow + alpha * (p + fu - slow),
            fast_updates, params, state.slow_params)
        new_updates = jax.tree.map(
            lambda t, p, fu: jnp.where(sync, t - p, fu),
            target, params, fast_updates)
        new_slow = jax.tree.map(
            lambda t, slow: jnp.where(sync, t, slow),
            target, state.slow_params)
        return new_updates, LookaheadState(inner=inner_state,
                                           slow_params=new_slow, step=step)

    return optax.GradientTransformation(init_fn, update_fn)
