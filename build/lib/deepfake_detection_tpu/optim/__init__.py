from .factory import create_optimizer, weight_decay_mask
from .lookahead import lookahead
from .rmsprop_tf import rmsprop_tf

__all__ = ["create_optimizer", "weight_decay_mask", "lookahead", "rmsprop_tf"]
