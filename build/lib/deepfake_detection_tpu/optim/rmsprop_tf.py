"""TF-parity RMSprop as an optax gradient transformation.

The reference's workhorse optimizer (``--opt rmsproptf``) is ``RMSpropTF``
(``/root/reference/dfd/timm/optim/rmsprop_tf.py:5-122``), a deliberate
re-implementation of TensorFlow's RMSprop semantics.  It differs from both
torch and optax RMSprop in three ways that matter for checkpoint-equivalent
convergence (SURVEY.md §7 hard part 1):

1. the squared-gradient accumulator initialises to **ones**, not zeros
   (reference :80) — this damps the first steps instead of amplifying them;
2. epsilon is added **inside** the square root (``sqrt(avg + eps)``,
   reference :105-107), not outside;
3. with momentum, the **learning rate is folded into the momentum buffer**
   (``buf = m*buf + lr*g/rms``, reference :112-114) the way TF accumulates it,
   rather than scaling the buffer by lr at apply time.

Because of (3) the learning rate participates in optimizer *state*, so this
transformation takes ``learning_rate`` directly and emits final parameter
deltas (use with ``optax.apply_updates``).  Wrap in
``optax.inject_hyperparams`` to reschedule lr between steps — the runner does
this and overwrites ``state.hyperparams['learning_rate']`` from the scheduler.

TPU notes: the whole update is elementwise → XLA fuses it into a handful of
HBM-bandwidth-bound kernels inside the jitted train step; nothing to hand-tune.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Union

import chex
import jax
import jax.numpy as jnp
import optax


class RMSpropTFState(NamedTuple):
    square_avg: Any
    momentum_buffer: Any   # zeros-shaped pytree even when momentum == 0
    grad_avg: Any          # only meaningful when centered=True


def rmsprop_tf(
    learning_rate: Union[float, jax.Array],
    alpha: float = 0.9,
    eps: float = 1e-10,
    momentum: float = 0.9,
    centered: bool = False,
    lr_in_momentum: bool = True,
) -> optax.GradientTransformation:
    """TF-semantics RMSprop.  Returns deltas already scaled by ``-lr``.

    Coupled (L2) weight decay is expressed by chaining
    ``optax.add_decayed_weights`` *before* this transform (the reference adds
    ``wd * p`` to the gradient before the accumulator update, :91-95);
    decoupled decay by chaining it after.
    """

    def init_fn(params):
        return RMSpropTFState(
            square_avg=jax.tree.map(jnp.ones_like, params),
            momentum_buffer=jax.tree.map(jnp.zeros_like, params),
            grad_avg=(jax.tree.map(jnp.zeros_like, params) if centered
                      else optax.EmptyState()),
        )

    def update_fn(updates, state, params=None):
        del params
        lr = learning_rate
        one_minus_alpha = 1.0 - alpha

        # square_avg <- square_avg + (1-alpha) * (g^2 - square_avg)
        square_avg = jax.tree.map(
            lambda sa, g: sa + one_minus_alpha * (jnp.square(g) - sa),
            state.square_avg, updates)

        if centered:
            grad_avg = jax.tree.map(
                lambda ga, g: ga + one_minus_alpha * (g - ga),
                state.grad_avg, updates)
            rms = jax.tree.map(
                lambda sa, ga: jnp.sqrt(sa - jnp.square(ga) + eps),
                square_avg, grad_avg)
        else:
            grad_avg = state.grad_avg
            rms = jax.tree.map(lambda sa: jnp.sqrt(sa + eps), square_avg)

        if momentum > 0:
            if lr_in_momentum:
                buf = jax.tree.map(
                    lambda b, g, r: momentum * b + lr * g / r,
                    state.momentum_buffer, updates, rms)
                deltas = jax.tree.map(lambda b: -b, buf)
            else:
                buf = jax.tree.map(
                    lambda b, g, r: momentum * b + g / r,
                    state.momentum_buffer, updates, rms)
                deltas = jax.tree.map(lambda b: -lr * b, buf)
        else:
            buf = state.momentum_buffer
            deltas = jax.tree.map(lambda g, r: -lr * g / r, updates, rms)

        return deltas, RMSpropTFState(square_avg=square_avg,
                                      momentum_buffer=buf,
                                      grad_avg=grad_avg)

    return optax.GradientTransformation(init_fn, update_fn)
