"""Optimizer factory (optax).

Replaces ``/root/reference/dfd/timm/optim/optim_factory.py:26-100``: the same
name-dispatch surface (sgd / adam / adamw / nadam / radam / adadelta / rmsprop
/ rmsproptf / novograd / nvnovograd, with a ``lookahead_`` prefix), the same
weight-decay parameter split (1-dim params and biases excluded,
``optim_factory.py:11-23``), and the same adamw/radam weight-decay/lr
compensation (:29-33).

The apex ``fused*`` variants (:78-91) dissolve on TPU: every optimizer here is
a pure elementwise pytree transform that XLA fuses inside the jitted train
step, so ``fusedsgd``/``fusedadam``/… alias to their plain counterparts
(``fusedlamb`` → ``optax.lamb``).

The returned transformation is wrapped in ``optax.inject_hyperparams`` so the
scheduler can rewrite ``opt_state.hyperparams['learning_rate']`` between steps
without recompiling (the reference mutates ``param_group['lr']`` the same way,
``scheduler/scheduler.py:81-85``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import optax

from .lookahead import lookahead
from .nvnovograd import nvnovograd
from .rmsprop_tf import rmsprop_tf

__all__ = ["create_optimizer", "weight_decay_mask"]


def weight_decay_mask(params) -> Any:
    """True for leaves that should be decayed: ndim > 1 and not a bias.

    Mirrors ``add_weight_decay`` (optim_factory.py:11-23): 1-dim params (all
    norm scales/biases) and ``bias`` leaves are exempt.  In Flax trees biases
    are 1-dim, so the ndim test subsumes the name test; kept explicit anyway.
    """
    return jax.tree.map(lambda p: getattr(p, "ndim", 0) > 1, params)


def _base_optimizer(name: str, learning_rate, *, opt_eps: float,
                    momentum: float, weight_decay: float,
                    mask) -> optax.GradientTransformation:
    """Build one optimizer by (already lowercased, prefix-stripped) name."""
    wd = weight_decay

    if name == "sgd":
        # reference uses nesterov=True (optim_factory.py:48-50)
        tx = optax.chain(
            optax.add_decayed_weights(wd, mask) if wd else optax.identity(),
            optax.sgd(learning_rate, momentum=momentum, nesterov=True),
        )
    elif name == "adam":
        tx = optax.chain(
            optax.add_decayed_weights(wd, mask) if wd else optax.identity(),
            optax.adam(learning_rate, eps=opt_eps),
        )
    elif name == "adamw":
        tx = optax.adamw(learning_rate, eps=opt_eps, weight_decay=wd,
                         mask=mask)
    elif name == "nadam":
        tx = optax.chain(
            optax.add_decayed_weights(wd, mask) if wd else optax.identity(),
            optax.nadam(learning_rate, eps=opt_eps),
        )
    elif name == "radam":
        tx = optax.chain(
            optax.add_decayed_weights(wd, mask) if wd else optax.identity(),
            optax.radam(learning_rate, eps=opt_eps),
        )
    elif name == "adadelta":
        tx = optax.chain(
            optax.add_decayed_weights(wd, mask) if wd else optax.identity(),
            optax.adadelta(learning_rate, eps=opt_eps),
        )
    elif name == "rmsprop":
        # torch-style: eps outside sqrt, zero-init accumulator
        tx = optax.chain(
            optax.add_decayed_weights(wd, mask) if wd else optax.identity(),
            optax.rmsprop(learning_rate, decay=0.9, eps=opt_eps,
                          momentum=momentum),
        )
    elif name == "rmsproptf":
        # TF-parity variant; coupled L2 decay goes before the accumulator
        # update, exactly as the reference folds wd into the grad (:91-95)
        tx = optax.chain(
            optax.add_decayed_weights(wd, mask) if wd else optax.identity(),
            rmsprop_tf(learning_rate, alpha=0.9, eps=opt_eps,
                       momentum=momentum),
        )
    elif name in ("novograd", "nvnovograd"):
        # two DISTINCT reference implementations: novograd.py:12 (optax's
        # matches) vs NVIDIA's nvnovograd.py:13 (per-tensor scalar ‖g‖² EMA
        # seeded from the first step — optim/nvnovograd.py here).
        # Neither takes a mask; partition leaves so 1-dim params and biases
        # stay undecayed (reference add_weight_decay, optim_factory.py:35-37).
        # Both normalize per-leaf, so the split is exact.
        def _make(weight_decay):
            if name == "nvnovograd":
                return nvnovograd(learning_rate, eps=opt_eps,
                                  weight_decay=weight_decay)
            return optax.novograd(learning_rate, eps=opt_eps,
                                  weight_decay=weight_decay)
        if wd and mask is not None:
            def _labels(params):
                m = mask(params) if callable(mask) else mask
                return jax.tree.map(
                    lambda b: "decay" if b else "no_decay", m)
            tx = optax.multi_transform(
                {"decay": _make(wd), "no_decay": _make(0.0)}, _labels)
        else:
            tx = _make(wd)
    elif name == "lamb":
        tx = optax.lamb(learning_rate, eps=opt_eps, weight_decay=wd,
                        mask=mask)
    else:
        raise ValueError(f"Invalid optimizer {name!r}")
    return tx


def create_optimizer(cfg, params=None, learning_rate: Optional[float] = None,
                     filter_bias_and_bn: bool = True,
                     inject: bool = True) -> optax.GradientTransformation:
    """Build the optimizer from a TrainConfig-like object.

    ``cfg`` needs: opt, opt_eps, momentum, weight_decay, and (if
    ``learning_rate`` not given) lr.  ``params`` is only used to note that
    masks are structural (callable masks are used, so params may be None).
    """
    del params
    opt_name = cfg.opt.lower()
    weight_decay = cfg.weight_decay
    lr = learning_rate if learning_rate is not None else cfg.lr
    assert lr is not None, "learning rate must be resolved before create_optimizer"

    # adamw/radam wd compensation (optim_factory.py:29-33): the reference keeps
    # the *effective* decay constant w.r.t. lr by pre-dividing.
    if ("adamw" in opt_name or "radam" in opt_name) and weight_decay and lr:
        weight_decay = weight_decay / lr

    parts = opt_name.split("_")
    base_name = parts[-1]
    # apex fused variants alias to plain ones (XLA fuses for free)
    if base_name.startswith("fused"):
        base_name = base_name[len("fused"):] or "sgd"
        base_name = {"adamw": "adamw", "adam": "adam", "sgd": "sgd",
                     "lamb": "lamb", "novograd": "novograd"}.get(base_name,
                                                                 base_name)

    known = ("sgd", "adam", "adamw", "nadam", "radam", "adadelta", "rmsprop",
             "rmsproptf", "novograd", "nvnovograd", "lamb")
    if base_name not in known:
        raise ValueError(f"Invalid optimizer {cfg.opt!r}")

    mask = weight_decay_mask if (filter_bias_and_bn and weight_decay) else None

    def make(learning_rate):
        tx = _base_optimizer(base_name, learning_rate, opt_eps=cfg.opt_eps,
                             momentum=cfg.momentum,
                             weight_decay=weight_decay, mask=mask)
        if len(parts) > 1 and parts[0] == "lookahead":
            tx = lookahead(tx)
        return tx

    if inject:
        return optax.inject_hyperparams(make)(learning_rate=lr)
    return make(lr)
