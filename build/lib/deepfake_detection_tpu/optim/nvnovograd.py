"""NVIDIA NovoGrad as an optax gradient transformation.

The reference ships TWO distinct NovoGrads: ``optim/novograd.py:12`` (norm
state pre-initialized from the first gradient outside the step loop) and
NVIDIA's ``optim/nvnovograd.py:13`` — this file implements the latter
exactly:

* per-tensor scalar second moment ``exp_avg_sq`` = EMA of ‖g‖², initialized
  to the FIRST step's ‖g‖² (reference :96-99);
* ``g ← g / (sqrt(exp_avg_sq) + eps) + wd·p`` (coupled decay on the
  normalized gradient, :105-111);
* first moment ``exp_avg ← β₁·exp_avg + g`` with NO (1-β₁) factor unless
  ``grad_averaging`` (:112-114);
* no bias correction; ``p ← p − lr·exp_avg`` (:116).

Returns final deltas (already scaled by −lr) like
:func:`~.rmsprop_tf.rmsprop_tf`; weight decay is built in (it must apply to
the *normalized* gradient, so it cannot be chained externally).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Union

import jax
import jax.numpy as jnp
import optax


class NvNovoGradState(NamedTuple):
    exp_avg: Any       # first moment, per-leaf pytree
    exp_avg_sq: Any    # per-leaf SCALAR ‖g‖² EMA
    step: jnp.ndarray


def nvnovograd(
    learning_rate: Union[float, jax.Array],
    b1: float = 0.95,
    b2: float = 0.98,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    grad_averaging: bool = False,
) -> optax.GradientTransformation:
    """NVIDIA NovoGrad (reference nvnovograd.py:13-118, sans amsgrad)."""

    def init_fn(params):
        return NvNovoGradState(
            exp_avg=jax.tree.map(jnp.zeros_like, params),
            exp_avg_sq=jax.tree.map(
                lambda p: jnp.zeros((), jnp.float32), params),
            step=jnp.zeros((), jnp.int32),
        )

    def update_fn(updates, state, params=None):
        assert params is not None or weight_decay == 0.0, \
            "nvnovograd with weight_decay needs params"
        lr = learning_rate

        norms = jax.tree.map(
            lambda g: jnp.sum(jnp.square(g).astype(jnp.float32)), updates)
        # a still-zero accumulator copies ‖g‖² instead of blending — the
        # reference checks the per-tensor value, not the step counter
        # (:96-99), so an all-zero first gradient stays unseeded
        exp_avg_sq = jax.tree.map(
            lambda v, n: jnp.where(v == 0.0, n, b2 * v + (1.0 - b2) * n),
            state.exp_avg_sq, norms)

        def _normalized(g, v, p):
            g = g / (jnp.sqrt(v) + eps).astype(g.dtype)
            if weight_decay:
                g = g + weight_decay * p
            if grad_averaging:
                g = g * (1.0 - b1)
            return g

        p_tree = params if params is not None else updates
        normed = jax.tree.map(_normalized, updates, exp_avg_sq, p_tree)
        exp_avg = jax.tree.map(lambda m, g: b1 * m + g,
                               state.exp_avg, normed)
        deltas = jax.tree.map(lambda m: -lr * m, exp_avg)
        return deltas, NvNovoGradState(exp_avg=exp_avg,
                                       exp_avg_sq=exp_avg_sq,
                                       step=state.step + 1)

    return optax.GradientTransformation(init_fn, update_fn)
