from .factory import create_scheduler
from .schedules import (CosineSchedule, PlateauSchedule, Scheduler,
                        StepSchedule, TanhSchedule)

__all__ = ["create_scheduler", "Scheduler", "StepSchedule", "CosineSchedule",
           "TanhSchedule", "PlateauSchedule"]
