"""Scheduler factory.

Parity with ``/root/reference/dfd/timm/scheduler/scheduler_factory.py:7-78``:
maps ``--sched step|cosine|tanh|plateau`` to a scheduler and returns
``(scheduler, num_epochs)`` where cosine/tanh extend ``num_epochs`` by the
cycle length + cooldown (:38,:55).  ``--lr-noise`` fractions of total epochs
become absolute noise-range thresholds (:10-17).
"""

from __future__ import annotations

from typing import Optional, Tuple

from .schedules import (CosineSchedule, PlateauSchedule, Scheduler,
                        StepSchedule, TanhSchedule)

__all__ = ["create_scheduler"]


def create_scheduler(cfg, base_lr: Optional[float] = None
                     ) -> Tuple[Optional[Scheduler], int]:
    num_epochs = cfg.epochs
    lr = base_lr if base_lr is not None else cfg.lr
    assert lr is not None

    noise_range = None
    if getattr(cfg, "lr_noise", None) is not None:
        n = cfg.lr_noise
        if isinstance(n, (list, tuple)):
            noise_range = [x * num_epochs for x in n]
            if len(noise_range) == 1:
                noise_range = noise_range[0]
        else:
            noise_range = n * num_epochs

    noise_kw = dict(noise_range_t=noise_range,
                    noise_pct=getattr(cfg, "lr_noise_pct", 0.67),
                    noise_std=getattr(cfg, "lr_noise_std", 1.0),
                    noise_seed=getattr(cfg, "seed", 42))

    sched = None
    if cfg.sched == "cosine":
        sched = CosineSchedule(
            lr, t_initial=num_epochs, t_mul=1.0, lr_min=cfg.min_lr,
            decay_rate=cfg.decay_rate, warmup_lr_init=cfg.warmup_lr,
            warmup_t=cfg.warmup_epochs, cycle_limit=1, **noise_kw)
        num_epochs = sched.get_cycle_length() + cfg.cooldown_epochs
    elif cfg.sched == "tanh":
        sched = TanhSchedule(
            lr, t_initial=num_epochs, t_mul=1.0, lr_min=cfg.min_lr,
            warmup_lr_init=cfg.warmup_lr, warmup_t=cfg.warmup_epochs,
            cycle_limit=1, **noise_kw)
        num_epochs = sched.get_cycle_length() + cfg.cooldown_epochs
    elif cfg.sched == "step":
        sched = StepSchedule(
            lr, decay_t=cfg.decay_epochs, decay_rate=cfg.decay_rate,
            warmup_lr_init=cfg.warmup_lr, warmup_t=cfg.warmup_epochs,
            **noise_kw)
    elif cfg.sched == "plateau":
        sched = PlateauSchedule(
            lr, decay_rate=cfg.decay_rate, patience_t=cfg.patience_epochs,
            lr_min=cfg.min_lr, warmup_lr_init=cfg.warmup_lr,
            warmup_t=cfg.warmup_epochs, cooldown_t=cfg.cooldown_epochs)
    return sched, num_epochs
