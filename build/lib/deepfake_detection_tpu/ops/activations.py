"""Activation functions + name resolver.

Replaces the reference's activation zoo (``/root/reference/dfd/timm/models/layers/
activations.py``).  The reference implements memory-efficient Swish/Mish via
custom autograd + TorchScript (activations.py:16-75); under XLA that machinery
is unnecessary — fusion and rematerialisation make ``jax.nn.silu`` exactly as
cheap — so everything here is a plain function the compiler fuses into the
surrounding matmul/conv.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

__all__ = ["get_act_fn", "swish", "mish", "hard_swish", "hard_sigmoid",
           "hard_mish", "sigmoid", "ACT_FNS"]


def swish(x):
    """SiLU / Swish: x * sigmoid(x) (activations.py:16-40)."""
    return jax.nn.silu(x)


def mish(x):
    """x * tanh(softplus(x)) (activations.py:43-75)."""
    return x * jnp.tanh(jax.nn.softplus(x))


def sigmoid(x):
    return jax.nn.sigmoid(x)


def hard_swish(x):
    """x * relu6(x+3)/6 (activations.py:141-154)."""
    return x * jax.nn.relu6(x + 3.0) / 6.0


def hard_sigmoid(x):
    """relu6(x+3)/6 (activations.py:157-164)."""
    return jax.nn.relu6(x + 3.0) / 6.0


def hard_mish(x):
    return 0.5 * x * jnp.clip(x + 2.0, 0.0, 2.0)


ACT_FNS = {
    "swish": swish,
    "silu": swish,
    "mish": mish,
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "elu": jax.nn.elu,
    "gelu": jax.nn.gelu,
    "leaky_relu": jax.nn.leaky_relu,
    "sigmoid": sigmoid,
    "tanh": jnp.tanh,
    "hard_swish": hard_swish,
    "hard_sigmoid": hard_sigmoid,
    "hard_mish": hard_mish,
    "identity": lambda x: x,
    None: lambda x: x,
}


def get_act_fn(name) -> Callable:
    """Resolve an activation by name; callables pass through unchanged."""
    if callable(name):
        return name
    if name in ACT_FNS:
        return ACT_FNS[name]
    raise KeyError(f"Unknown activation {name!r}; known: {sorted(k for k in ACT_FNS if k)}")
