"""Channel/spatial attention modules: SE, ECA, CBAM, Selective-Kernel.

Replaces ``layers/{se,eca,cbam,selective_kernel,create_attn}.py``.  All operate
on NHWC; the squeeze path is a global mean (one HBM pass) and the excite path
is tiny matmuls XLA fuses with the surrounding scale.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from .activations import get_act_fn
from .conv import Conv2d
from .norm import BatchNorm2d


def make_divisible(v: int, divisor: int = 8, min_value: Optional[int] = None) -> int:
    """Round channels to hardware-friendly multiples (efficientnet_blocks.py:55)."""
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class SEModule(nn.Module):
    """Classic squeeze-and-excitation (se.py:4-25)."""
    reduction: int = 16
    act: str = "relu"
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        chs = x.shape[-1]
        rd = max(chs // self.reduction, 8)
        s = jnp.mean(x, axis=(1, 2), keepdims=True)
        s = Conv2d(rd, 1, use_bias=True, dtype=self.dtype, name="fc1")(s)
        s = get_act_fn(self.act)(s)
        s = Conv2d(chs, 1, use_bias=True, dtype=self.dtype, name="fc2")(s)
        return x * jax.nn.sigmoid(s)


class EcaModule(nn.Module):
    """Efficient channel attention (eca.py:41-73): 1-D conv over the channel
    descriptor instead of a bottleneck MLP."""
    kernel_size: Optional[int] = None
    gamma: int = 2
    beta: int = 1
    dtype: Any = None

    def _ksize(self, chs: int) -> int:
        if self.kernel_size is not None:
            return self.kernel_size
        t = int(abs(math.log(chs, 2) + self.beta) / self.gamma)
        k = max(t if t % 2 else t + 1, 3)
        return k

    @nn.compact
    def __call__(self, x):
        chs = x.shape[-1]
        k = self._ksize(chs)
        s = jnp.mean(x, axis=(1, 2))            # (B, C)
        s = nn.Conv(features=1, kernel_size=(k,), padding="SAME",
                    use_bias=False, dtype=self.dtype,
                    name="conv")(s[..., None])   # (B, C, 1)
        s = jax.nn.sigmoid(s[..., 0])
        return x * s[:, None, None, :]


class CecaModule(nn.Module):
    """ECA with circular channel padding (eca.py:75-108)."""
    kernel_size: Optional[int] = None
    gamma: int = 2
    beta: int = 1
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        chs = x.shape[-1]
        k = EcaModule._ksize(self, chs)
        s = jnp.mean(x, axis=(1, 2))[..., None]      # (B, C, 1)
        pad = (k - 1) // 2
        s = jnp.concatenate([s[:, -pad:], s, s[:, :pad]], axis=1)
        s = nn.Conv(features=1, kernel_size=(k,), padding="VALID",
                    use_bias=False, dtype=self.dtype, name="conv")(s)
        s = jax.nn.sigmoid(s[..., 0])
        return x * s[:, None, None, :]


class ChannelAttn(nn.Module):
    """CBAM channel gate (cbam.py:16-39): shared MLP over avg- and max-pooled
    descriptors, summed, sigmoid."""
    reduction: int = 16
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        chs = x.shape[-1]
        rd = chs // self.reduction
        fc1 = Conv2d(rd, 1, use_bias=False, dtype=self.dtype, name="fc1")
        fc2 = Conv2d(chs, 1, use_bias=False, dtype=self.dtype, name="fc2")
        avg = jnp.mean(x, axis=(1, 2), keepdims=True)
        mx = jnp.max(x, axis=(1, 2), keepdims=True)
        attn = fc2(jax.nn.relu(fc1(avg))) + fc2(jax.nn.relu(fc1(mx)))
        return x * jax.nn.sigmoid(attn)


class LightChannelAttn(ChannelAttn):
    """Light CBAM channel gate (cbam.py:42-55): 50/50 avg+max pooled input."""

    @nn.compact
    def __call__(self, x):
        chs = x.shape[-1]
        rd = chs // self.reduction
        pooled = 0.5 * jnp.mean(x, axis=(1, 2), keepdims=True) \
            + 0.5 * jnp.max(x, axis=(1, 2), keepdims=True)
        attn = Conv2d(chs, 1, use_bias=False, dtype=self.dtype, name="fc2")(
            jax.nn.relu(Conv2d(rd, 1, use_bias=False, dtype=self.dtype,
                               name="fc1")(pooled)))
        return x * jax.nn.sigmoid(attn)


class SpatialAttn(nn.Module):
    """CBAM spatial gate (cbam.py:58-72): [mean_c, max_c] → 7×7 conv → sigmoid."""
    kernel_size: int = 7
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        stat = jnp.concatenate([jnp.mean(x, axis=-1, keepdims=True),
                                jnp.max(x, axis=-1, keepdims=True)], axis=-1)
        attn = Conv2d(1, self.kernel_size, use_bias=False, dtype=self.dtype,
                      name="conv")(stat)
        return x * jax.nn.sigmoid(attn)


class LightSpatialAttn(nn.Module):
    """Light CBAM spatial gate (cbam.py:75-87): 50/50 mean+max single map."""
    kernel_size: int = 7
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        stat = 0.5 * jnp.mean(x, axis=-1, keepdims=True) \
            + 0.5 * jnp.max(x, axis=-1, keepdims=True)
        attn = Conv2d(1, self.kernel_size, use_bias=False, dtype=self.dtype,
                      name="conv")(stat)
        return x * jax.nn.sigmoid(attn)


class CbamModule(nn.Module):
    """Channel then spatial attention (cbam.py:90-100)."""
    reduction: int = 16
    spatial_kernel: int = 7
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        x = ChannelAttn(self.reduction, dtype=self.dtype, name="channel")(x)
        return SpatialAttn(self.spatial_kernel, dtype=self.dtype, name="spatial")(x)


class LightCbamModule(nn.Module):
    reduction: int = 16
    spatial_kernel: int = 7
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        x = LightChannelAttn(self.reduction, dtype=self.dtype, name="channel")(x)
        return LightSpatialAttn(self.spatial_kernel, dtype=self.dtype,
                                name="spatial")(x)


class SelectiveKernelConv(nn.Module):
    """SK conv (selective_kernel.py:51-118): parallel branches with different
    kernels/dilations, branch-wise attention over a shared descriptor."""
    out_chs: int
    kernel_size: Sequence[int] = (3, 5)
    stride: int = 1
    dilation: int = 1
    groups: int = 1
    attn_reduction: int = 16
    min_attn_channels: int = 32
    keep_3x3: bool = True
    split_input: bool = False
    act: str = "relu"
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        act = get_act_fn(self.act)
        kernel_sizes = list(self.kernel_size)
        dilations = [self.dilation] * len(kernel_sizes)
        if self.keep_3x3:
            # larger kernels become dilated 3x3s (selective_kernel.py:63-69)
            dilations = [max(self.dilation * (k - 1) // 2, 1)
                         for k in kernel_sizes]
            kernel_sizes = [3] * len(kernel_sizes)
        n = len(kernel_sizes)
        in_chs = x.shape[-1]
        if self.split_input:
            assert in_chs % n == 0
            splits = jnp.split(x, n, axis=-1)
        else:
            splits = [x] * n
        feats = []
        for i, (ks, dil, xi) in enumerate(zip(kernel_sizes, dilations, splits)):
            g = self.groups if self.groups > 0 else 1
            y = Conv2d(self.out_chs, ks, self.stride, dilation=dil,
                       groups=min(g, self.out_chs), dtype=self.dtype,
                       name=f"path_{i}_conv")(xi)
            y = BatchNorm2d(dtype=self.dtype, name=f"path_{i}_bn")(y, training=training)
            feats.append(act(y))
        stacked = jnp.stack(feats, axis=1)          # (B, n, H, W, C)
        summed = jnp.sum(stacked, axis=1)
        attn_chs = max(self.out_chs // self.attn_reduction, self.min_attn_channels)
        s = jnp.mean(summed, axis=(1, 2), keepdims=True)
        s = Conv2d(attn_chs, 1, use_bias=False, dtype=self.dtype, name="attn_fc")(s)
        s = act(BatchNorm2d(dtype=self.dtype, name="attn_bn")(s, training=training))
        s = Conv2d(self.out_chs * n, 1, use_bias=False, dtype=self.dtype,
                   name="attn_sel")(s)              # (B,1,1,C*n)
        B = x.shape[0]
        s = s.reshape(B, 1, 1, n, self.out_chs).transpose(0, 3, 1, 2, 4)
        attn = jax.nn.softmax(s, axis=1)
        return jnp.sum(stacked * attn, axis=1)


def create_attn(attn_type, **kwargs) -> Optional[nn.Module]:
    """Name → module dispatch (create_attn.py:11-35)."""
    if attn_type is None or attn_type == "":
        return None
    if callable(attn_type) and not isinstance(attn_type, str):
        return attn_type(**kwargs)
    table = {
        "se": SEModule,
        "eca": EcaModule,
        "ceca": CecaModule,
        "cbam": CbamModule,
        "lcbam": LightCbamModule,
    }
    name = attn_type.lower()
    if name not in table:
        raise KeyError(f"Unknown attention {attn_type!r}")
    return table[name](**kwargs)
