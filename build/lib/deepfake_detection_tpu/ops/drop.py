"""Stochastic-depth and DropBlock regularizers.

Replaces ``/root/reference/dfd/timm/models/layers/drop.py`` (drop_path :84,
DropBlock2d :24-81).  JAX version takes explicit PRNG keys — inside flax
modules use the 'dropout' rng collection.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def drop_path(x, rng, drop_prob: float = 0.0):
    """Per-sample stochastic depth (drop.py:84-97): zero the whole residual
    branch for a random subset of samples, rescale survivors by 1/keep."""
    if drop_prob <= 0.0:
        return x
    keep_prob = 1.0 - drop_prob
    shape = (x.shape[0],) + (1,) * (x.ndim - 1)
    mask = jax.random.bernoulli(rng, keep_prob, shape).astype(x.dtype)
    return x / keep_prob * mask


class DropPath(nn.Module):
    """Module wrapper so blocks can call drop path with the flax 'dropout' rng."""
    drop_prob: float = 0.0

    @nn.compact
    def __call__(self, x, training: bool = False):
        if not training or self.drop_prob <= 0.0:
            return x
        return drop_path(x, self.make_rng("dropout"), self.drop_prob)


def drop_block_2d(x, rng, drop_prob: float = 0.1, block_size: int = 7,
                  gamma_scale: float = 1.0, with_noise: bool = False):
    """DropBlock (drop.py:24-81) on NHWC input: bernoulli-seed valid centers,
    dilate to block_size squares via max-pool, zero + renormalize."""
    if drop_prob <= 0.0:
        return x
    B, H, W, C = x.shape
    total = H * W
    clipped = min(block_size, min(H, W))
    gamma = (gamma_scale * drop_prob * total / (clipped ** 2) /
             ((H - clipped + 1) * (W - clipped + 1)))
    seed_rng, noise_rng = jax.random.split(rng)
    seeds = jax.random.bernoulli(seed_rng, gamma, (B, H, W, C)).astype(x.dtype)
    # restrict seeds to valid centers so blocks stay inside the map
    h = jnp.arange(H)
    w = jnp.arange(W)
    valid_h = ((h >= clipped // 2) & (h < H - (clipped - 1) // 2)).astype(x.dtype)
    valid_w = ((w >= clipped // 2) & (w < W - (clipped - 1) // 2)).astype(x.dtype)
    seeds = seeds * valid_h[None, :, None, None] * valid_w[None, None, :, None]
    # dilate seeds into blocks
    block_mask = nn.max_pool(seeds, (clipped, clipped), strides=(1, 1),
                             padding="SAME")
    keep = 1.0 - block_mask
    if with_noise:
        noise = jax.random.normal(noise_rng, x.shape, x.dtype)
        return x * keep + noise * block_mask
    normalize = (keep.size / jnp.clip(keep.sum(), 1.0)).astype(x.dtype)
    return x * keep * normalize


class DropBlock2d(nn.Module):
    drop_prob: float = 0.1
    block_size: int = 7
    gamma_scale: float = 1.0
    with_noise: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        if not training or self.drop_prob <= 0.0:
            return x
        return drop_block_2d(x, self.make_rng("dropout"), self.drop_prob,
                             self.block_size, self.gamma_scale, self.with_noise)


class Dropout(nn.Module):
    """Plain dropout with the same training-flag convention as the rest of ops."""
    rate: float = 0.0

    @nn.compact
    def __call__(self, x, training: bool = False):
        return nn.Dropout(rate=self.rate, deterministic=not training)(x)
