"""Normalization layers.

TPU-native replacements for the reference's BN stack:

* ``BatchNorm2d`` — wraps ``flax.linen.BatchNorm``; accepts **torch-convention
  momentum** (running = (1-m)*running + m*batch, default 0.1; the canonical
  deepfake run uses ``--bn-momentum 0.001``) and converts to flax convention.
  Passing ``axis_name`` turns it into cross-replica (sync) BN — the one-liner
  that replaces both apex ``convert_syncbn_model`` (train.py:388-400) *and* the
  epoch-boundary ``distribute_bn`` broadcast/reduce (utils.py:263-274), because
  batch stats are then always computed over the global batch.
* ``SplitBatchNorm2d`` — AdvProp auxiliary BN (layers/split_batchnorm.py:18-38):
  first 1/N of the batch through the main BN, remaining chunks through aux BNs.
* ``GroupNorm`` re-export for norm-free/group-norm model variants.

Reference BN defaults: torch (momentum .1, eps 1e-5); TF-ported weights need
``BN_MOMENTUM_TF_DEFAULT=0.01`` / ``BN_EPS_TF_DEFAULT=1e-3``
(efficientnet_blocks.py:13-15).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

BN_MOMENTUM_TF_DEFAULT = 0.01
BN_EPS_TF_DEFAULT = 1e-3
BN_MOMENTUM_PT_DEFAULT = 0.1
BN_EPS_PT_DEFAULT = 1e-5


def resolve_bn_args(kwargs: dict) -> dict:
    """Fold bn_tf/bn_momentum/bn_eps kwargs into explicit momentum/eps
    (efficientnet_blocks.py:22-30); momentum stays torch-convention here."""
    bn_args = {}
    if kwargs.pop("bn_tf", False):
        bn_args = dict(momentum=BN_MOMENTUM_TF_DEFAULT, eps=BN_EPS_TF_DEFAULT)
    bn_momentum = kwargs.pop("bn_momentum", None)
    if bn_momentum is not None:
        bn_args["momentum"] = bn_momentum
    bn_eps = kwargs.pop("bn_eps", None)
    if bn_eps is not None:
        bn_args["eps"] = bn_eps
    return bn_args


class BatchNorm2d(nn.Module):
    """NHWC batch norm with torch-style momentum and optional cross-replica sync.

    When ``axis_name`` is set (e.g. 'data' under shard_map/pjit with a named
    mesh axis), batch statistics are pmean-reduced across that axis — global-
    batch statistics, i.e. SyncBN.
    """
    momentum: float = BN_MOMENTUM_PT_DEFAULT   # torch convention
    eps: float = BN_EPS_PT_DEFAULT
    use_scale: bool = True
    use_bias: bool = True
    axis_name: Optional[str] = None
    dtype: Any = None
    scale_init: Any = None          # e.g. zeros for zero-init-last-BN blocks

    @nn.compact
    def __call__(self, x, training: bool = False):
        kwargs = {}
        if self.scale_init is not None:
            kwargs["scale_init"] = self.scale_init
        return nn.BatchNorm(
            use_running_average=not training,
            momentum=1.0 - self.momentum,
            epsilon=self.eps,
            use_scale=self.use_scale,
            use_bias=self.use_bias,
            axis_name=self.axis_name,
            dtype=self.dtype,
            name="bn",
            **kwargs,
        )(x)


class SplitBatchNorm2d(nn.Module):
    """AdvProp split BN (layers/split_batchnorm.py:18-38).

    Training: batch is chunked into ``num_splits`` equal parts; chunk 0 uses
    the primary BN, chunk i uses aux BN i.  Eval: everything through primary.
    """
    num_splits: int = 2
    momentum: float = BN_MOMENTUM_PT_DEFAULT
    eps: float = BN_EPS_PT_DEFAULT
    axis_name: Optional[str] = None
    dtype: Any = None

    def setup(self):
        assert self.num_splits >= 2
        mk = lambda name: BatchNorm2d(momentum=self.momentum, eps=self.eps,
                                      axis_name=self.axis_name, dtype=self.dtype,
                                      name=name)
        self.main_bn = mk("main")
        self.aux_bns = [mk(f"aux{i}") for i in range(self.num_splits - 1)]

    def __call__(self, x, training: bool = False):
        if not training:
            return self.main_bn(x, training=False)
        split = x.shape[0] // self.num_splits
        assert split * self.num_splits == x.shape[0], \
            "batch size must be divisible by num_splits"
        parts = [self.main_bn(x[:split], training=True)]
        for i, bn in enumerate(self.aux_bns):
            parts.append(bn(x[(i + 1) * split:(i + 2) * split], training=True))
        return jnp.concatenate(parts, axis=0)


class GroupNorm(nn.Module):
    """GroupNorm for the norm-free deepfake variants (efficientnet.py:354-430)."""
    num_groups: int = 32
    eps: float = 1e-5
    dtype: Any = None

    @nn.compact
    def __call__(self, x, training: bool = False):
        del training
        return nn.GroupNorm(num_groups=self.num_groups, epsilon=self.eps,
                            dtype=self.dtype, name="gn")(x)


class Identity(nn.Module):
    """No-op norm for use_norm=False paths (efficientnet.py:385)."""

    @nn.compact
    def __call__(self, x, training: bool = False):
        del training
        return x
