"""Distributed runtime: mesh, sharding, collectives, sequence parallelism.

TPU-native replacement for the reference's NCCL/apex-DDP layer (SURVEY.md
§2.7) plus first-class long-context support (ring / Ulysses attention).
"""

from .collectives import distribute_bn, pmean, psum, tree_pmean
from .mesh import (BATCH_AXIS, MODEL_AXIS, data_axis_name,
                   initialize_distributed, local_batch_size, make_mesh,
                   make_train_mesh, process_count, process_index)
from .ring_attention import (full_attention, ring_attention,
                             ring_flash_attention, ring_self_attention,
                             ulysses_attention)
from .ep import condconv_ep_sharding, condconv_ep_specs
from .pp import gpipe_apply, gpipe_transformer_tower, pipeline_sharding, \
    stack_block_params
from .tp import transformer_tp_sharding, transformer_tp_specs
from .sharding import (batch_sharding, fsdp_param_specs, own_and_place,
                       param_sharding, place_train_state, put_process_local,
                       replicated_sharding, shard_batch,
                       train_state_shardings)
