"""jax version compatibility for the parallelism layer.

This container family pins jax anywhere from 0.4.x to current; the manual
(shard_map) API surface moved twice along the way.  One shim module so the
call sites stay one-line imports and the fallbacks die in one place when
the pre-0.6 floor is dropped:

* ``shard_map`` — ``jax.shard_map`` (0.6+) vs
  ``jax.experimental.shard_map.shard_map`` (same API).
* ``axis_size`` — ``lax.axis_size`` vs ``psum(1, axis)``, which inside
  shard_map constant-folds to a static Python int on pre-0.6 jax.
* ``pcast_varying`` — ``lax.pcast(x, axis, to="varying")``; pre-0.6 jax
  has no varying-manual-axes type system, so marking is a no-op there.
* ``shard_map_check_kwargs`` — the replication/vma checker kwarg was
  renamed ``check_rep`` → ``check_vma``.
"""

from __future__ import annotations

import inspect
from typing import Any, Dict

from jax import lax

try:
    from jax import shard_map
except ImportError:                      # pre-0.6: experimental home
    from jax.experimental.shard_map import shard_map  # noqa: F401

__all__ = ["axis_size", "coordination_client", "distributed_is_initialized",
           "pcast_varying", "shard_map", "shard_map_check_kwargs"]


def coordination_client():
    """The process's jax.distributed coordination-service client, or None
    when uninitialized.  The only sanctioned accessor for the private
    ``jax._src.distributed.global_state`` surface — version drift lands
    here, not in callers."""
    from jax._src import distributed
    return distributed.global_state.client


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()``; absent pre-0.5 — fall back to
    probing the coordination-service client the initialize() call owns."""
    import jax
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    return coordination_client() is not None


def axis_size(axis_name: str) -> int:
    size = getattr(lax, "axis_size", None)
    if size is not None:
        return size(axis_name)
    return lax.psum(1, axis_name)


def pcast_varying(x: Any, axis_name: str) -> Any:
    pcast = getattr(lax, "pcast", None)
    return x if pcast is None else pcast(x, axis_name, to="varying")


def shard_map_check_kwargs(enabled: bool) -> Dict[str, bool]:
    """``{check_vma: enabled}`` or the legacy ``{check_rep: enabled}``."""
    name = "check_vma" if "check_vma" in \
        inspect.signature(shard_map).parameters else "check_rep"
    return {name: enabled}
