"""Sharding specs, the TrainState sharding-rule table, and host→global
array assembly.

The reference's distribution story is DDP: replicate the model, shard the
batch, allreduce gradients (apex ``delay_allreduce``, train.py:402).  Under
GSPMD the same program is expressed declaratively: annotate the batch as
sharded over the batch axis and parameters as replicated (or FSDP/TP-
sharded), and XLA inserts the collectives over ICI/DCN.  This module holds
the annotation helpers so runners never spell out PartitionSpecs by hand —
:func:`train_state_shardings` is the ONE rule table that decides the
``NamedSharding`` of every TrainState leaf (params / BN stats / optimizer
moments / EMA / step counter), and :func:`place_train_state` lays a freshly
built or restored state onto the mesh accordingly.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import data_axis_name

__all__ = ["batch_sharding", "replicated_sharding", "fsdp_param_specs",
           "shard_batch", "param_sharding", "train_state_shardings",
           "place_train_state", "own_and_place"]


def batch_sharding(mesh: Mesh, axis: Optional[str] = None) -> NamedSharding:
    """Leading (batch) dim sharded over the data axis, rest replicated.

    ``axis=None`` resolves the mesh's own data axis (``'batch'`` on the
    unified mesh, ``'data'`` on legacy layouts)."""
    return NamedSharding(mesh, P(axis or data_axis_name(mesh)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def fsdp_param_specs(params: Any, mesh: Mesh, axis: str = "data",
                     min_size: int = 2 ** 16) -> Any:
    """ZeRO-3-style parameter sharding: shard the largest divisible dimension
    of each big leaf over ``axis``; small leaves stay replicated.

    No reference analog (the reference replicates everything); this is the
    TPU-native memory-scaling extension (``TrainConfig.fsdp``).
    """
    n = mesh.shape[axis]

    def spec(p):
        if p.size < min_size:
            return P()
        dims = list(p.shape)
        # prefer sharding the largest divisible dim
        order = sorted(range(len(dims)), key=lambda i: -dims[i])
        for i in order:
            if dims[i] % n == 0:
                out = [None] * len(dims)
                out[i] = axis
                return P(*out)
        return P()

    return jax.tree.map(spec, params)


def param_sharding(params: Any, mesh: Mesh, fsdp: bool = False,
                   axis: str = "data") -> Any:
    """NamedShardings for a param tree: replicated, or FSDP over ``axis``."""
    if not fsdp:
        rep = replicated_sharding(mesh)
        return jax.tree.map(lambda _: rep, params)
    specs = fsdp_param_specs(params, mesh, axis)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def train_state_shardings(state: Any, mesh: Mesh, fsdp: bool = False,
                          axis: Optional[str] = None) -> Any:
    """The sharding-rule table: a NamedSharding per TrainState leaf.

    Rules (ISSUE 12 — one table instead of per-path conventions):

    * **params** — a leaf that already carries a ``NamedSharding`` with a
      non-trivial spec keeps it (tensor/expert-parallel placement applied
      at model build wins); otherwise FSDP-sharded over the batch axis
      when ``fsdp`` else replicated.
    * **opt_state / EMA** — any subtree whose structure mirrors the params
      tree (Adam/RMSProp moments, the EMA params stream) inherits the
      params shardings leaf-for-leaf; everything else (step counts,
      injected hyperparams, EMA batch_stats) is replicated.
    * **batch_stats / step** — replicated: BN running stats are pmean-
      merged inside the step and must stay one logical copy.

    Returns a pytree congruent with ``state`` (usable as jit
    in/out_shardings and as the :func:`place_train_state` target).
    """
    axis = axis or data_axis_name(mesh)
    rep = replicated_sharding(mesh)
    params = state.params
    base = param_sharding(params, mesh, fsdp=fsdp, axis=axis)

    def keep_existing(p, b):
        sh = getattr(p, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.spec != P():
            return NamedSharding(mesh, sh.spec)   # re-anchor to THIS mesh
        return b

    params_sh = jax.tree.map(keep_existing, params, base)
    params_def = jax.tree.structure(params)

    def is_params_like(node):
        if node is None:
            return False
        try:
            return jax.tree.structure(node) == params_def
        except Exception:  # noqa: BLE001 — non-pytree nodes are not params
            return False

    def follow_params(tree):
        # substitute the params sharding tree wholesale under any
        # params-shaped subtree; every other leaf is replicated
        return jax.tree.map(
            lambda n: params_sh if is_params_like(n) else rep,
            tree, is_leaf=is_params_like)

    return state.replace(
        step=rep,
        params=params_sh,
        batch_stats=jax.tree.map(lambda _: rep, state.batch_stats),
        opt_state=follow_params(state.opt_state),
        ema=follow_params(state.ema) if state.ema is not None else None)


def place_train_state(state: Any, shardings: Any) -> Any:
    """Lay a TrainState onto the mesh per the sharding table.

    Every leaf routes through :func:`own_and_place`: single-process this
    is a per-leaf ``device_put`` (with numpy leaves copied into JAX-owned
    buffers first — never a host alias a donating step could free);
    multi-process each host holds a full replica of host-local leaves and
    global arrays are assembled shard-by-shard via
    ``make_array_from_callback`` (a plain cross-host ``device_put`` of
    non-addressable shards is not a thing); leaves already carrying their
    target sharding (tp-placed params) pass through untouched.
    """
    return jax.tree.map(own_and_place, state, shardings)


def own_and_place(leaf: Any, sh: Optional[NamedSharding]) -> Any:
    """One leaf onto its target sharding, as a JAX-OWNED buffer.

    The single implementation of the ownership discipline both state
    placement and checkpoint restore rely on: a host numpy leaf must
    never enter the donating train step as a zero-copy alias of host
    memory (the CPU backend aliases suitably-aligned buffers; donation
    then frees memory numpy owns — the PR 2 native-SIGSEGV class), and a
    cross-host layout cannot be ``device_put`` from a host array at all
    (non-addressable shards) — it is assembled per-shard from this
    host's full copy, with ``jnp.array`` inside the callback keeping
    every shard an owned copy.  ``sh=None`` leaves placement alone but
    still takes ownership of numpy leaves.
    """
    import jax.numpy as jnp

    if sh is not None and jax.process_count() > 1:
        if isinstance(leaf, jax.Array) and leaf.sharding == sh:
            return leaf
        a = np.asarray(leaf)
        return jax.make_array_from_callback(
            a.shape, sh, lambda idx: jnp.array(a[idx]))
    if isinstance(leaf, np.ndarray):
        leaf = jnp.array(leaf)            # device-owned copy
    return jax.device_put(leaf, sh) if sh is not None else leaf


def put_process_local(x: Any, sharding: NamedSharding) -> Any:
    """One per-process host array → global sharded jax.Array.

    Single-process: a plain sharded device_put.  Multi-host: each process
    contributes ``global_batch / process_count`` leading rows via
    ``make_array_from_process_local_data``.
    """
    x = np.asarray(x)
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    global_shape = (x.shape[0] * jax.process_count(),) + x.shape[1:]
    return jax.make_array_from_process_local_data(sharding, x, global_shape)


def shard_batch(batch: Any, mesh: Mesh, axis: Optional[str] = None) -> Any:
    """Assemble per-process host arrays into a global batch-sharded array
    (replaces the per-process DataLoader shard of DDP)."""
    sharding = batch_sharding(mesh, axis)
    return jax.tree.map(lambda x: put_process_local(x, sharding), batch)
