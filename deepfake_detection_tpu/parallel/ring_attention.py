"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has no sequence models — its "temporal" axis is 4 frames
channel-concatenated (SURVEY.md §5) — but long-context attention is a
first-class requirement for the TPU framework (it backs the ViT/TimeSformer
stretch configs in BASELINE.json).  Two standard schemes, both expressed over
a mesh axis with XLA collectives riding ICI:

* **Ring attention** (Liu et al. 2023, blockwise; PAPERS.md): each device
  holds one sequence block of Q/K/V.  K/V blocks rotate around the ring via
  ``lax.ppermute`` while each device accumulates its queries' attention with
  a numerically-stable online softmax (flash-attention style running max /
  denominator).  Communication overlaps with the block matmuls; memory is
  O(L/n) per device.
* **Ulysses** (DeepSpeed-Ulysses): ``all_to_all`` re-shards from
  sequence-split to head-split, runs *local* full attention on the head
  shard, and re-shards back.  Cheaper collectives for moderate L, requires
  heads % n == 0.

Both are plain functions over *local* blocks with an ``axis_name`` — usable
directly inside ``shard_map``; :func:`ring_self_attention` wraps the
shard_map boilerplate over a mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import _compat
from ._compat import shard_map

__all__ = ["ring_attention", "ring_flash_attention", "ulysses_attention",
           "ring_self_attention", "full_attention"]


def full_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   causal: bool = False, scale: Optional[float] = None
                   ) -> jnp.ndarray:
    """Reference dense attention (single device) for parity tests.

    Shapes: (B, L, H, D) → (B, L, H, D).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        mask = jnp.arange(lk)[None, :] > jnp.arange(lq)[:, None]
        s = jnp.where(mask[None, None], -jnp.inf, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str, causal: bool = False,
                   scale: Optional[float] = None) -> jnp.ndarray:
    """Blockwise ring attention over local (B, L_local, H, D) blocks.

    Call inside ``shard_map`` with the sequence dim sharded over
    ``axis_name``.  K/V rotate ``axis_size`` times; accumulation is float32.
    """
    n = _compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    b, lq, h, d = q.shape
    lk = k.shape[1]

    q32 = q.astype(jnp.float32) * scale
    q_pos = idx * lq + jnp.arange(lq)                      # global query rows
    perm = [(i, (i + 1) % n) for i in range(n)]

    def accumulate(t, k_blk, v_blk, acc, m, l):
        """Fold block (idx - t) mod n into the online-softmax accumulators."""
        src = (idx - t) % n
        s = jnp.einsum("bqhd,bkhd->bhqk", q32,
                       k_blk.astype(jnp.float32))          # (B,H,Lq,Lk)
        if causal:
            k_pos = src * lk + jnp.arange(lk)
            mask = k_pos[None, :] > q_pos[:, None]          # (Lq, Lk)
            s = jnp.where(mask[None, None], -jnp.inf, s)
        m_blk = jnp.max(s, axis=-1)                         # (B,H,Lq)
        m_new = jnp.maximum(m, m_blk)
        # guard fully-masked rows (m_new == -inf) against NaNs
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
        acc_new = acc * corr.transpose(0, 2, 1)[..., None] + pv
        return acc_new, m_new, l_new

    def body(t, carry):
        k_blk, v_blk, acc, m, l = carry
        acc, m, l = accumulate(t, k_blk, v_blk, acc, m, l)
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return k_nxt, v_nxt, acc, m, l

    # mark the fresh accumulators as device-varying over the ring axis so the
    # fori_loop carry type matches the (sharded, hence varying) K/V blocks
    # (pre-0.6 jax has no varying-manual-axes type system — no-op there)
    def vary(x):
        return _compat.pcast_varying(x, axis_name)
    acc0 = vary(jnp.zeros((b, lq, h, d), jnp.float32))
    m0 = vary(jnp.full((b, h, lq), -jnp.inf, jnp.float32))
    l0 = vary(jnp.zeros((b, h, lq), jnp.float32))
    # n-1 rotated steps, then fold the final resident block without the dead
    # trailing ppermute pair
    k_f, v_f, acc, m, l = lax.fori_loop(0, n - 1, body,
                                        (k, v, acc0, m0, l0))
    acc, m, l = accumulate(n - 1, k_f, v_f, acc, m, l)
    l = jnp.maximum(l, 1e-20)
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _merge_blocks(o, lse, o_b, lse_b):
    """Fold a new normalized block result into the running (o, lse).

    Given per-block outputs already normalized by their own softmax
    denominators ``l_i = exp(lse_i)``, the exact combination is
    ``o = (l₁·o₁ + l₂·o₂) / (l₁ + l₂)`` — computed in log-space for
    stability.  This is how independently-flash-attended KV blocks compose
    (same identity FlashAttention-2 uses across its K tiles).
    """
    m = jnp.maximum(lse, lse_b)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    w = jnp.where(jnp.isneginf(lse), 0.0, jnp.exp(lse - m_safe))
    w_b = jnp.where(jnp.isneginf(lse_b), 0.0, jnp.exp(lse_b - m_safe))
    tot = jnp.maximum(w + w_b, 1e-30)
    o_new = (w[..., None] * o + w_b[..., None] * o_b) / tot[..., None]
    return o_new, m_safe + jnp.log(tot)


def ring_flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                         axis_name: str, causal: bool = False,
                         scale: Optional[float] = None,
                         block_q: int = 128, block_k: int = 128,
                         interpret: Optional[bool] = None) -> jnp.ndarray:
    """Ring attention with fused Pallas flash blocks (the TPU production
    path; :func:`ring_attention` is the pure-XLA reference).

    Same calling convention as :func:`ring_attention` — local
    ``(B, L_local, H, D)`` blocks inside ``shard_map``, K/V rotating via
    ``lax.ppermute`` — but each resident block is attended by the
    flash-attention kernel (ops/flash_attention.py), so the (Lq, Lk) score
    tile never leaves VMEM: O(L_local) HBM traffic per step instead of the
    XLA path's materialized per-block score matrices.  Per-block results
    merge via the log-space identity in :func:`_merge_blocks`.

    The backward is the ring schedule from the Ring Attention paper
    (PAPERS.md): dK/dV accumulators travel the ring *with* their K/V blocks
    (arriving home after the full cycle with every device's contribution)
    while dQ accumulates locally; each per-block gradient is the Pallas
    backward kernel pair, reusing the forward's global logsumexp.
    """
    from ..ops.flash_attention import (_bwd_dkv, _bwd_dq, _fwd, _round_up)

    n = _compat.axis_size(axis_name)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale_ = scale if scale is not None else d ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q = min(block_q, _round_up(lq, 128))
    block_k = min(block_k, _round_up(lk, 128))
    lpq, lpk = _round_up(lq, block_q), _round_up(lk, block_k)
    dp = _round_up(d, 128)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def prep(x, l, lp):                     # (B, l, H, D) -> (BH, lp, Dp)
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, l, d)
        return jnp.pad(x, ((0, 0), (0, lp - l), (0, dp - d)))

    def unprep(x, l):                       # (BH, lp, Dp) -> (B, l, H, D)
        x = x[:, :l, :d].reshape(b, h, l, d)
        return jnp.transpose(x, (0, 2, 1, 3))

    def vary(x):
        return _compat.pcast_varying(x, axis_name)

    # K/V (and dK/dV in the backward) travel the ring in their raw
    # (B, l, H, D) layout: the ppermute link is the scarce ICI resource,
    # and padding to (BH, lp, 128·k) is a cheap *local* copy done fresh at
    # each step inside the kernel call.
    #
    # The device's ring position enters as a (float) operand, not a closure:
    # custom_vjp functions must not close over traced values.
    def _block_fwd(t, idx, qp, k_blk, v_blk):
        src = (idx - t) % n
        o_b, lse_b = _fwd(qp, prep(k_blk, lk, lpk), prep(v_blk, lk, lpk),
                          scale_, block_q, block_k, causal, lk, interpret,
                          q_off=idx * lq, kv_off=src * lk)
        return o_b, lse_b[:, :, 0]       # lse arrives lane-replicated

    @jax.custom_vjp
    def _op(idx_f, q, k, v):
        out, _ = _op_fwd(idx_f, q, k, v)
        return out

    def _op_fwd(idx_f, q, k, v):
        idx = idx_f.astype(jnp.int32)
        qp = prep(q, lq, lpq)

        def body(t, carry):
            k_blk, v_blk, o, lse = carry
            o_b, lse_b = _block_fwd(t, idx, qp, k_blk, v_blk)
            o, lse = _merge_blocks(o, lse, o_b.astype(jnp.float32), lse_b)
            return (lax.ppermute(k_blk, axis_name, perm),
                    lax.ppermute(v_blk, axis_name, perm), o, lse)

        o0 = vary(jnp.zeros((b * h, lpq, dp), jnp.float32))
        lse0 = vary(jnp.full((b * h, lpq), -jnp.inf, jnp.float32))
        # n-1 rotated steps + final resident block (no dead trailing permute)
        k_f, v_f, o, lse = lax.fori_loop(0, n - 1, body, (k, v, o0, lse0))
        o_b, lse_b = _block_fwd(n - 1, idx, qp, k_f, v_f)
        o, lse = _merge_blocks(o, lse, o_b.astype(jnp.float32), lse_b)
        out_p = o.astype(q.dtype)
        return unprep(out_p, lq), (idx_f, q, k, v, out_p, lse)

    def _op_bwd(res, g):
        from ..ops.flash_attention import _LANES, _delta
        idx_f, q, k, v, out_p, lse2 = res
        idx = idx_f.astype(jnp.int32)
        qp = prep(q, lq, lpq)
        do = prep(g, lq, lpq).astype(jnp.float32)
        delta = _delta(do, out_p)
        # kernels expect the lane-replicated lse layout
        lse = jnp.broadcast_to(lse2[..., None], (*lse2.shape, _LANES))

        def body(t, carry):
            k_blk, v_blk, dk_blk, dv_blk, dq = carry
            src = (idx - t) % n
            kp_t = prep(k_blk, lk, lpk)
            vp_t = prep(v_blk, lk, lpk)
            dk_p, dv_p = _bwd_dkv(qp, kp_t, vp_t, do, lse, delta, scale_,
                                  block_q, block_k, causal, lk, interpret,
                                  q_off=idx * lq, kv_off=src * lk)
            dq_p = _bwd_dq(qp, kp_t, vp_t, do, lse, delta, scale_,
                           block_q, block_k, causal, lk, interpret,
                           q_off=idx * lq, kv_off=src * lk)
            # dK/dV ride the ring with their block (raw layout, f32): after
            # the full cycle each block is home with every device's
            # contribution
            return (lax.ppermute(k_blk, axis_name, perm),
                    lax.ppermute(v_blk, axis_name, perm),
                    lax.ppermute(dk_blk + unprep(dk_p, lk), axis_name, perm),
                    lax.ppermute(dv_blk + unprep(dv_p, lk), axis_name, perm),
                    dq + dq_p)

        dk0 = vary(jnp.zeros((b, lk, h, d), jnp.float32))
        dv0 = vary(jnp.zeros((b, lk, h, d), jnp.float32))
        dq0 = vary(jnp.zeros((b * h, lpq, dp), jnp.float32))
        _, _, dk, dv, dq = lax.fori_loop(
            0, n, body, (k, v, dk0, dv0, dq0))
        return (jnp.zeros_like(idx_f), unprep(dq, lq).astype(q.dtype),
                dk.astype(k.dtype), dv.astype(v.dtype))

    _op.defvjp(_op_fwd, _op_bwd)
    idx_f = lax.axis_index(axis_name).astype(jnp.float32)
    return _op(idx_f, q, k, v).astype(q.dtype)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      axis_name: str, causal: bool = False,
                      scale: Optional[float] = None) -> jnp.ndarray:
    """All-to-all sequence parallelism over local (B, L_local, H, D) blocks.

    Re-shards seq→heads, runs dense local attention on H/n heads over the
    full sequence, re-shards back.  Requires ``H % axis_size == 0``.
    """
    n = _compat.axis_size(axis_name)
    assert q.shape[2] % n == 0, f"heads {q.shape[2]} not divisible by {n}"

    def to_heads(x):  # (B, L/n, H, D) -> (B, L, H/n, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_seq(x):    # (B, L, H/n, D) -> (B, L/n, H, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    out = full_attention(to_heads(q), to_heads(k), to_heads(v),
                         causal=causal, scale=scale)
    return to_seq(out)


def ring_self_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        mesh: Mesh, seq_axis: str = "data",
                        causal: bool = False,
                        impl: str = "ring") -> jnp.ndarray:
    """shard_map wrapper: global (B, L, H, D) arrays, sequence sharded over
    ``seq_axis`` of ``mesh``; batch replicated across that axis.

    ``impl='ring_flash'`` fuses each per-block attention into the Pallas
    flash kernel (the TPU production path).  Off-TPU its shard_map sets
    ``check_vma=False`` because the Pallas *interpreter* mixes its own
    non-varying block counters with varying refs, which the vma checker
    rejects — on TPU (compiled Mosaic) the check stays on.
    """
    fn = {"ring": ring_attention, "ring_flash": ring_flash_attention,
          "ulysses": ulysses_attention}[impl]
    spec = P(None, seq_axis, None, None)
    interpreted_flash = (impl == "ring_flash"
                         and jax.default_backend() != "tpu")
    sharded = shard_map(
        functools.partial(fn, axis_name=seq_axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        **_compat.shard_map_check_kwargs(not interpreted_flash))
    return sharded(q, k, v)
