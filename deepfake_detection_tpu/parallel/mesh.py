"""Device mesh construction + multi-host initialization.

TPU-native replacement for the reference's NCCL process-group setup
(``/root/reference/dfd/runners/train.py:279-282``: ``init_process_group('nccl',
file://<shared_nfs_file>)`` with rank arithmetic from a JSON server map,
``server_json.py:25-45``).  Here:

* :func:`initialize_distributed` wraps ``jax.distributed.initialize`` — the
  coordinator address replaces the shared-file rendezvous; on TPU pods the
  runtime discovers topology natively and the call is a no-op-safe default.
  The legacy server-JSON still works: hostname → process_id mapping comes
  from :class:`~deepfake_detection_tpu.config.ClusterConfig`.
* :func:`make_mesh` builds the ``jax.sharding.Mesh`` every sharded
  computation runs over.  Default is a 1-D ``('data',)`` mesh (pure DP — the
  only strategy the reference has, SURVEY.md §2.7); any shape/axis tuple
  works for dp×fsdp×tp×sp meshes.  Axis order maps the *innermost* axis to
  the fastest ICI links, so put model/tensor axes last.
* :func:`make_train_mesh` is the unified GSPMD training mesh (ISSUE 12):
  ONE logical 2-D ``('batch', 'model')`` mesh under which the train step is
  a plain ``jax.jit`` with ``NamedSharding`` annotations — the same program
  compiles for 1 chip and a v5e-256 pod without code changes (SNIPPETS.md
  [1]–[3]).  ``data_axis_name`` resolves which axis the global batch shards
  over so loaders/steps work on both the unified and legacy axis layouts.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

_logger = logging.getLogger(__name__)

__all__ = ["initialize_distributed", "make_mesh", "make_train_mesh",
           "data_axis_name", "local_batch_size",
           "process_count", "process_index", "BATCH_AXIS", "MODEL_AXIS"]

#: canonical axis names of the unified 2-D training mesh.  ``BATCH_AXIS``
#: carries pure data parallelism (and FSDP parameter sharding); MODEL_AXIS
#: carries tensor/expert parallelism.  Innermost (= fastest ICI) axis last.
BATCH_AXIS = "batch"
MODEL_AXIS = "model"


def initialize_distributed(cluster=None, hostname: Optional[str] = None,
                           local_rank: int = 0, retries: Optional[int] = None,
                           backoff: float = 2.0) -> None:
    """Multi-host JAX runtime init (replaces NCCL file rendezvous).

    ``cluster`` is a :class:`ClusterConfig` (or None).  Single-process setups
    return immediately.  Safe to call multiple times (subsequent calls
    no-op).

    The rendezvous is retried with exponential backoff (``retries``
    attempts, default 4, env-overridable via ``DFD_INIT_RETRIES``): after a
    preemption the restart wrapper relaunches hosts at skewed times, and a
    coordinator that is itself still being rescheduled must not turn every
    late-arriving worker's bounded connect timeout into a permanent abort.
    The LAST failure still raises — a genuinely unreachable coordinator on
    a required multi-host setup must abort the job (swallowing it would
    silently train N isolated copies).
    """
    if cluster is None or cluster.world_size <= 1:
        return
    # NOTE: must run before anything touches the XLA backend (so no
    # jax.process_count()/jax.devices() here — they'd initialize it and make
    # the distributed init fail).
    from ._compat import distributed_is_initialized
    if distributed_is_initialized():
        return  # already initialized (e.g. by the TPU pod runtime)
    kwargs = {}
    if cluster.coordinator_address:
        kwargs["coordinator_address"] = cluster.coordinator_address
        kwargs["num_processes"] = cluster.world_size
        kwargs["process_id"] = cluster.process_id(hostname, local_rank)
    if retries is None:
        retries = int(os.environ.get("DFD_INIT_RETRIES", "4"))
    attempts = max(1, retries)
    delay = 1.0
    for attempt in range(attempts):
        try:
            jax.distributed.initialize(**kwargs)
            break
        except Exception as e:  # noqa: BLE001 — re-raised on the last try
            if attempt == attempts - 1:
                raise
            _logger.warning(
                "jax.distributed.initialize failed (attempt %d/%d: %r); "
                "retrying in %.1fs", attempt + 1, attempts, e, delay)
            time.sleep(delay)
            delay = min(delay * backoff, 30.0)
    _logger.info("jax.distributed initialized: process %d/%d",
                 jax.process_index(), jax.process_count())


def make_mesh(mesh_shape: Optional[Sequence[int]] = None,
              axis_names: Sequence[str] = ("data",),
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a device mesh.

    Defaults to all devices on one ``'data'`` axis.  ``mesh_shape`` must
    multiply out to the device count; ``-1`` in one position infers it.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if mesh_shape is None:
        mesh_shape = (n,) + (1,) * (len(axis_names) - 1)
    shape = list(mesh_shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = n // known
    assert int(np.prod(shape)) == n, \
        f"mesh shape {shape} != device count {n}"
    assert len(shape) == len(axis_names), (shape, axis_names)
    return Mesh(np.asarray(devices).reshape(shape), tuple(axis_names))


def make_train_mesh(batch: int = -1, model: int = 1,
                    devices: Optional[Sequence] = None) -> Mesh:
    """The ONE 2-D ``('batch', 'model')`` mesh unified training runs over.

    ``batch=-1`` infers the data-parallel extent from the device count so
    the same call works from 1 chip to a full pod; ``model`` is the
    tensor-parallel extent (1 = pure DP).  Every sharding rule in
    :func:`~deepfake_detection_tpu.parallel.sharding.train_state_shardings`
    names these axes, and the train step is a plain ``jax.jit`` over them —
    no per-topology code.
    """
    return make_mesh((batch, model), (BATCH_AXIS, MODEL_AXIS),
                     devices=devices)


def data_axis_name(mesh: Mesh) -> str:
    """The mesh axis the global batch shards over.

    ``'batch'`` on the unified mesh, ``'data'`` on legacy 1-D / explicit
    ``--mesh-axes`` layouts, else the first (outermost) axis — so loader
    sharding and the train step agree on any mesh a user can construct.
    """
    names = tuple(mesh.axis_names)
    for cand in (BATCH_AXIS, "data"):
        if cand in names:
            return cand
    return names[0]


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def local_batch_size(global_batch_size: int) -> int:
    """Per-host batch for a data-sharded global batch."""
    assert global_batch_size % jax.process_count() == 0, \
        (global_batch_size, jax.process_count())
    return global_batch_size // jax.process_count()
