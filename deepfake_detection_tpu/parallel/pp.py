"""Pipeline parallelism (GPipe schedule) over a ``stage`` mesh axis.

The reference has no PP (SURVEY.md §2.7).  This is the TPU-native
formulation: no scheduler process, no send/recv framework — the schedule is
a ``lax.scan`` whose body every stage executes simultaneously (SPMD), with
activations hopping stage→stage+1 through ``lax.ppermute`` over ICI.  The
*backward* pipeline is not written at all: ``ppermute`` is linear and its
autodiff transpose is the reverse permute, so differentiating the scan
yields the reverse-order pipeline schedule automatically.

Layout: a depth-``D`` tower of homogeneous blocks is split into ``S``
stages of ``D/S`` blocks.  Per-block param trees are stacked on a leading
dim and sharded ``P('stage')`` — each device materialises only its own
stage's blocks (1/S of the tower's params), applying them with an inner
``lax.scan``.

Schedule (M microbatches, steps t = 0..S+M-2): at step t stage ``s`` works
on microbatch ``t - s`` when that index is valid.  SPMD executes every
stage every step (the classic (S-1)/(S-1+M) bubble shows up as wasted
FLOPs, amortised away by larger M); validity is a ``jnp.where`` select so
the program stays uniform across devices.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import _compat
from ._compat import shard_map

__all__ = ["gpipe_apply", "gpipe_transformer_tower",
           "pipeline_sharding", "stack_block_params"]


def stack_block_params(block_params: list) -> Any:
    """Stack per-block param trees (blocks_0..blocks_{D-1}) on a leading
    dim: list of D trees → one tree with (D, ...) leaves."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *block_params)


def pipeline_sharding(stacked: Any, mesh: Mesh, axis: str = "stage") -> Any:
    """NamedShardings putting the leading (stage-major) dim on ``axis``."""
    sh = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda _: sh, stacked)


def gpipe_apply(block_apply: Callable, stacked_params: Any, x: jnp.ndarray,
                axis_name: str, num_microbatches: int) -> jnp.ndarray:
    """Run the pipelined tower over ``x``.  Call inside ``shard_map``.

    ``block_apply(params_i, x) -> x`` applies ONE block.  ``stacked_params``
    is the local stage's slice: (D/S, ...) leaves.  ``x`` is the full local
    batch (B, ...); it is split into ``num_microbatches`` equal chunks.
    Output is valid on every stage (the last stage's results are summed
    across the axis — all other stages contribute zeros).
    """
    s_count = _compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m_count = num_microbatches
    b = x.shape[0]
    assert b % m_count == 0, f"batch {b} % microbatches {m_count} != 0"
    mb = b // m_count
    micro = x.reshape((m_count, mb) + x.shape[1:])

    def apply_stage(params, h):
        def body(h, p_i):
            return block_apply(p_i, h), None
        h, _ = lax.scan(body, h, params)
        return h

    fwd_perm = [(i, i + 1) for i in range(s_count - 1)]

    def step(carry, t):
        buf, outs = carry
        m = t - idx                       # microbatch this stage works on
        valid = jnp.logical_and(m >= 0, m < m_count)
        y = apply_stage(stacked_params, buf)
        y = jnp.where(valid, y, buf)
        # last stage banks its finished microbatch (select keeps the
        # program uniform across stages — no divergent control flow)
        outs_new = lax.dynamic_update_index_in_dim(
            outs, y, jnp.clip(m, 0, m_count - 1), 0)
        take = jnp.logical_and(valid, idx == s_count - 1)
        outs = jnp.where(take, outs_new, outs)
        # hop forward; stage 0 receives zeros from the (absent) source
        nxt = lax.ppermute(y, axis_name, fwd_perm)
        # stage 0 injects the next microbatch instead
        inj = lax.dynamic_index_in_dim(
            micro, jnp.clip(t + 1, 0, m_count - 1), 0, keepdims=False)
        buf = jnp.where(idx == 0, inj, nxt)
        return (buf, outs), None

    # stage 0 starts on microbatch 0; other stages start on zeros (the
    # where() against the varying stage index already marks buf varying);
    # outs starts as plain zeros and must be marked varying for the scan
    # carry type to be stable
    buf0 = jnp.where(idx == 0, micro[0], jnp.zeros_like(micro[0]))
    outs0 = _compat.pcast_varying(jnp.zeros_like(micro), axis_name)
    (_, outs), _ = lax.scan(step, (buf0, outs0),
                            jnp.arange(s_count + m_count - 1))
    # only the last stage holds real outputs; psum broadcasts them
    outs = lax.psum(jnp.where(idx == s_count - 1, outs, 0.0), axis_name)
    return outs.reshape((b,) + x.shape[1:])


def gpipe_transformer_tower(mesh: Mesh, block_apply: Callable,
                            stacked_params: Any, x: jnp.ndarray,
                            num_microbatches: int,
                            axis: str = "stage") -> jnp.ndarray:
    """shard_map wrapper: ``stacked_params`` leaves are (D, ...) global
    arrays sharded over ``axis``; ``x`` replicated."""
    fn = functools.partial(gpipe_apply, block_apply,
                           axis_name=axis, num_microbatches=num_microbatches)
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis), stacked_params), P()),
        out_specs=P())(stacked_params, x)
