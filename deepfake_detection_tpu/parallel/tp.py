"""Tensor parallelism for the transformer families (GSPMD-style).

The reference has no TP (SURVEY.md §2.7: DP is its only strategy); this is
the TPU-native scale-out extension for the ViT/TimeSformer families.  It is
deliberately *not* a Megatron-style rewrite of the layers: on TPU the
idiomatic mechanism is to annotate parameter shardings over a ``model`` mesh
axis and let GSPMD partition the einsums and insert the all-reduces over ICI
("How to Scale Your Model" recipe: pick a mesh, annotate, let XLA insert
collectives).

Sharding rules follow the Megatron pairing so each block needs exactly one
all-reduce per attention and one per MLP.  This relies on vit.py's
head-major fused-qkv layout (the 3C output dim reshapes to (H, 3, D)): the
column sharding on 3C then lands on the head dim and propagates through the
reshape whenever ``H % tp_size == 0``; timm's (3, H, D) layout would instead
put the sharding under a leading factor 3 and force GSPMD to insert an extra
all-gather/reshard per attention.

* column-parallel (output feature dim sharded): ``qkv`` and ``mlp_fc1``
  kernels/biases — each device computes its own head/hidden shard;
* row-parallel (input feature dim sharded): ``proj`` and ``mlp_fc2``
  kernels — partial sums that GSPMD all-reduces; their biases replicate;
* everything else (embeddings, norms, head) replicates.

Works for any param tree whose Dense layers use the vit.py naming
(``qkv``/``proj``/``mlp_fc1``/``mlp_fc2``) — ViT and TimeSformer both do.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["transformer_tp_specs", "transformer_tp_sharding"]

# Dense-layer name → (kernel spec builder) role
_COLUMN = ("qkv", "mlp_fc1")      # shard output features
_ROW = ("proj", "mlp_fc2")        # shard input features


def _leaf_spec(path, leaf, axis: str, n: int) -> P:
    names = [getattr(k, "key", getattr(k, "name", "")) for k in path]
    layer = names[-2] if len(names) >= 2 else ""
    kind = names[-1]
    if layer in _COLUMN:
        if kind == "kernel" and leaf.shape[-1] % n == 0:
            return P(None, axis)           # (in, out·/n)
        if kind == "bias" and leaf.shape[-1] % n == 0:
            return P(axis)
    if layer in _ROW:
        if kind == "kernel" and leaf.shape[0] % n == 0:
            return P(axis, None)           # (in·/n, out) — partial sums
        # row-parallel bias replicates (added once after the all-reduce)
    return P()


def transformer_tp_specs(params: Any, axis: str, axis_size: int) -> Any:
    """PartitionSpec tree implementing the rules above.

    ``axis_size`` (the mesh extent of ``axis``) is required: the rules only
    shard dims divisible by it, so a wrong size silently changes layouts.
    """
    return jax.tree_util.tree_map_with_path(
        lambda p, l: _leaf_spec(p, l, axis, axis_size), params)


def transformer_tp_sharding(params: Any, mesh: Mesh,
                            axis: str = "model") -> Any:
    """NamedSharding tree for a ViT/TimeSformer param tree over ``mesh``.

    Combine with ``batch_sharding(mesh, 'data')`` for 2-D (dp × tp) meshes:
    batch rides the ``data`` axis, heads/hidden ride ``axis``.
    """
    specs = transformer_tp_specs(params, axis, mesh.shape[axis])
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))
