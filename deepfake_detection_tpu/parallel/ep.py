"""Expert parallelism for CondConv families (GSPMD-style).

The reference's only "experts" are CondConv's per-sample kernel mixtures,
computed locally on one GPU (SURVEY.md §2.7: "not distributed MoE").  On
TPU the expert bank is a natural shard axis: the (E, kh, kw, i, o) weight
splits over a mesh axis so each device holds E/n experts, the routing
einsum ``be,ehwio->bhwio`` produces per-shard partial mixtures, and GSPMD
inserts ONE all-reduce to combine them — distributed expert storage and
compute without touching the layer code.

This pays off when the expert bank dominates parameter memory (CondConv
multiplies every targeted conv's params by E — the cc_b1_8e bank is 8× its
convs) while activations stay data-sharded.

Identification is structural, not name-path-based: CondConv's parameters
are the only ``weight`` leaves with a leading expert rank (ndim 5:
(E, kh, kw, in, out)) and the only ``bias`` leaves with ndim 2 ((E, out)).
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["condconv_ep_specs", "condconv_ep_sharding"]


def _leaf_spec(path, leaf, axis: str, n: int) -> P:
    name = getattr(path[-1], "key", getattr(path[-1], "name", "")) \
        if path else ""
    if name == "weight" and leaf.ndim == 5 and leaf.shape[0] % n == 0:
        return P(axis)                       # experts sharded, rest local
    if name == "bias" and leaf.ndim == 2 and leaf.shape[0] % n == 0:
        return P(axis)
    return P()


def condconv_ep_specs(params: Any, axis: str, axis_size: int) -> Any:
    """PartitionSpec tree: expert banks sharded over ``axis``, rest
    replicated.  ``axis_size`` must be the mesh extent of ``axis`` (experts
    not divisible by it stay replicated)."""
    return jax.tree_util.tree_map_with_path(
        lambda p, l: _leaf_spec(p, l, axis, axis_size), params)


def condconv_ep_sharding(params: Any, mesh: Mesh,
                         axis: str = "model") -> Any:
    """NamedSharding tree for a CondConv model's param tree over ``mesh``.

    Rides the same ``model`` axis TP uses by default, so a 2-D
    ``(data, model)`` mesh serves dp×ep exactly like dp×tp.
    """
    specs = condconv_ep_specs(params, axis, mesh.shape[axis])
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))
