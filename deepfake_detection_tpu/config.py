"""Configuration system.

Replaces the reference's three config mechanisms with one dataclass tree:

* the ~60-flag argparse surface (``/root/reference/dfd/runners/train.py:55-235``),
* the two-stage ``--config`` YAML-overrides-defaults parse (``train.py:238-249``),
* the cluster-topology JSON (``/root/reference/dfd/server_json.py``).

Every field keeps the reference flag's name (dashes→underscores) and default so
a reference user can map their launch scripts 1:1.  ``TrainConfig.from_args``
reproduces the two-stage semantics: YAML file (if given) resets defaults, CLI
flags override YAML.  The resolved config serialises back to YAML
(``args.yaml`` parity, ``train.py:251-253``).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import socket
from dataclasses import dataclass, field, fields
from typing import Any, Dict, List, Optional, Sequence, Tuple

try:
    import yaml
    _HAS_YAML = True
except ImportError:  # pragma: no cover
    _HAS_YAML = False


# ---------------------------------------------------------------------------
# Cluster topology (server_json.py parity)
# ---------------------------------------------------------------------------

@dataclass
class ServerSpec:
    """One host in the cluster map (``server_json.py:25-45``)."""
    name: str
    gpus: str = ""           # kept for config-file compatibility; unused on TPU
    local_size: int = 1      # processes on this host
    start_rank: int = 0      # first global process index on this host


@dataclass
class ClusterConfig:
    """Topology for multi-host runs.

    On TPU pods ``jax.distributed.initialize`` discovers topology natively, so
    this config is only needed to (a) run the same JSON files the reference
    shipped (``scripts/train_server_config.json``) and (b) drive explicit
    coordinator-based init on non-pod clusters.
    """
    servers: List[ServerSpec] = field(default_factory=list)
    world_size: int = 1
    share_file: str = ""                 # legacy rendezvous file (unused)
    coordinator_address: Optional[str] = None  # "host:port" for jax.distributed

    @classmethod
    def from_json(cls, path: str) -> "ClusterConfig":
        with open(path) as f:
            raw = json.load(f)
        servers = [ServerSpec(
            name=s.get("name", ""),
            gpus=str(s.get("gpus", "")),
            local_size=int(s.get("local_size", 1)),
            start_rank=int(s.get("start_rank", 0)),
        ) for s in raw.get("servers", [])]
        return cls(servers=servers,
                   world_size=int(raw.get("world_size", 1)),
                   share_file=raw.get("share_file", ""),
                   coordinator_address=raw.get("coordinator_address"))

    def local_spec(self, hostname: Optional[str] = None) -> ServerSpec:
        """Match this host against the server map (``server_json.py:29-30``)."""
        hostname = hostname or socket.gethostname()
        for s in self.servers:
            if s.name == hostname:
                return s
        raise LookupError(
            f"hostname {hostname!r} not found in cluster config "
            f"(servers: {[s.name for s in self.servers]})")

    def process_id(self, hostname: Optional[str] = None, local_rank: int = 0) -> int:
        return self.local_spec(hostname).start_rank + local_rank


# ---------------------------------------------------------------------------
# Training config (train.py argparse parity)
# ---------------------------------------------------------------------------

def _tuple_of_ints(s) -> Optional[Tuple[int, ...]]:
    """Parse ``--input-size-v2 "12,600,600"`` style strings (config.py:17-21)."""
    if s is None or s == "":
        return None
    if isinstance(s, (tuple, list)):
        return tuple(int(x) for x in s)
    return tuple(int(x) for x in str(s).split(","))


# ---------------------------------------------------------------------------
# Shared dataclass→CLI machinery (TrainConfig + ServeConfig): one flag per
# field (dashes), bools as store_true, and the reference's two-stage parse
# semantics — a ``-c`` YAML file resets defaults, CLI flags override it.
# ---------------------------------------------------------------------------

def _convert_field(field_, v):
    """Coerce a CLI string to the field's annotated type (defaults of
    ``None`` carry no type, so the annotation is authoritative)."""
    ann = str(field_.type)
    default = field_.default
    if isinstance(default, bool) or ann == "bool":
        return bool(v)
    if not isinstance(v, str):
        return v
    if "Tuple[float" in ann:
        return tuple(float(x) for x in v.split(","))
    if "Tuple[int" in ann:
        return _tuple_of_ints(v)
    if "Tuple[str" in ann:
        return tuple(x for x in v.split(",") if x)
    if "float" in ann or isinstance(default, float):
        return float(v)
    if "int" in ann or (isinstance(default, int)
                        and not isinstance(default, bool)):
        return int(v)
    return v


def _dataclass_parser(cls, description: str) -> argparse.ArgumentParser:
    """Argparse surface generated from a config dataclass."""
    p = argparse.ArgumentParser(description=description)
    p.add_argument("-c", "--config", default="", metavar="FILE",
                   help="YAML config; its values reset defaults, CLI "
                        "overrides")
    for f_ in fields(cls):
        flag = "--" + f_.name.replace("_", "-")
        if f_.type == "bool" or isinstance(f_.default, bool):
            p.add_argument(flag, action="store_true", default=None,
                           dest=f_.name)
            continue
        p.add_argument(flag, default=None, dest=f_.name)
    return p


def _two_stage_parse(cls, argv: Optional[Sequence[str]],
                     parser: argparse.ArgumentParser):
    """YAML resets defaults, CLI overrides (train.py:238-249)."""
    ns, _ = parser.parse_known_args(argv)
    base = cls.from_yaml(ns.config) if ns.config else cls()
    out = dataclasses.asdict(base)
    hints = {f_.name: f_ for f_ in fields(cls)}
    for k, v in vars(ns).items():
        if k == "config" or v is None or k not in hints:
            continue
        out[k] = _convert_field(hints[k], v)
    return cls.from_dict(out)


@dataclass
class TrainConfig:
    # --- data ---
    data: str = ""                       # root dir(s), ':'-separated for multi-dir
    eval_data: str = ""                  # separate eval root(s); default: split from train
    dataset: str = "deepfake_v3"         # deepfake_v3 | folder | synthetic
    train_split: float = 0.95            # seeded train/val split fraction
    split_seed: int = 42
    label_balance: bool = False          # fake-bucket balancing (dataset.py:460-491)
    noise_fake: float = 0.0              # label-flip prob for fakes (dataset.py:520-521)
    img_num: int = 4                     # frames per clip
    workers: int = 8
    pin_memory: bool = False
    prefetch_depth: int = 2
    # host input-pipeline backend: 'thread' = in-process pool (GIL-release
    # scaling), 'shm' = spawned worker processes writing into a shared-
    # memory ring of batch slabs (zero-copy collate; data/shm_ring.py)
    loader_backend: str = "thread"
    ring_depth: int = 4                  # shm backend: batch slabs in flight
    worker_heartbeat: float = 120.0      # shm backend: stalled-worker kill (s)
    # packed pre-decoded dataset cache (tools/pack_dataset.py): mmap-read
    # fixed-stride uint8 clips instead of decoding JPEGs every epoch.
    # Replaces the decode STAGE only — composes with either loader backend,
    # and batches are bit-identical to the decode path at matching pack
    # resolution (data/packed.py)
    data_packed: str = ""                # pack dir ("" = decode JPEGs)
    pack_image_size: int = 0             # expected pack resolution (0 = any)

    # --- model ---
    model: str = "efficientnet_deepfake_v4"
    model_version: str = "v4"            # create_deepfake_model | _v3 | _v4 selection
    pretrained: bool = False
    initial_checkpoint: str = ""
    resume: str = ""
    no_resume_opt: bool = False
    # sharded (Orbax) checkpointing: collective per-host shard writes +
    # resharding restore — no rank-0 full-model gather (beyond reference)
    ckpt_sharded: bool = False
    num_classes: int = 2
    gp: str = "avg"                      # global pool: avg|max|avgmax|catavgmax
    in_chans: Optional[int] = None       # derived from input_size if None
    drop: float = 0.0
    drop_path: Optional[float] = None
    drop_block: Optional[float] = None
    bn_tf: bool = False
    bn_momentum: Optional[float] = None
    bn_eps: Optional[float] = None

    # --- input geometry ---
    input_size: Optional[Tuple[int, ...]] = None      # (C,H,W) — reference order
    input_size_v2: Optional[Tuple[int, ...]] = None   # (12,600,600) string flag
    img_size: Optional[int] = None
    crop_pct: Optional[float] = None
    mean: Optional[Tuple[float, ...]] = None
    std: Optional[Tuple[float, ...]] = None
    interpolation: str = ""

    # --- optimization ---
    opt: str = "rmsproptf"
    opt_eps: float = 1e-8
    momentum: float = 0.9
    weight_decay: float = 1e-5
    lr: Optional[float] = None           # if None: batch*world*basic_lr (train.py:814)
    basic_lr: float = 5e-7
    sched: str = "step"
    epochs: int = 200
    start_epoch: Optional[int] = None
    decay_epochs: float = 2.0
    decay_rate: float = 0.92
    warmup_lr: float = 1e-4
    warmup_epochs: int = 0
    cooldown_epochs: int = 10
    patience_epochs: int = 10
    lr_noise: Optional[Tuple[float, ...]] = None
    lr_noise_pct: float = 0.67
    lr_noise_std: float = 1.0
    lr_cycle_mul: float = 1.0
    lr_cycle_limit: int = 1
    min_lr: float = 1e-5
    batch_size: int = 3
    clip_grad: Optional[float] = None

    # --- augmentation ---
    no_aug: bool = False
    scale: Tuple[float, float] = (0.08, 1.0)
    ratio: Tuple[float, float] = (3. / 4., 4. / 3.)
    hflip: float = 0.5
    vflip: float = 0.0
    color_jitter: float = 0.4
    aa: Optional[str] = None             # AutoAugment / RandAugment policy string
    aug_splits: int = 0
    jsd: bool = False
    reprob: float = 0.0                  # RandomErasing prob
    remode: str = "const"
    recount: int = 1
    remax: float = 0.4                   # max erase area fraction
    resplit: bool = False
    mixup: float = 0.0
    mixup_off_epoch: int = 0
    smoothing: float = 0.1
    train_interpolation: str = "random"
    # multi-frame (deepfake) specific
    rotate_range: float = 0.0
    blur_prob: float = 0.0
    flicker: float = 0.0
    # 'on' moves the remaining host augment — the fused geometric warp,
    # per-frame Gaussian blur, and the mixup blend — into the loader's
    # jitted device prologue, keyed by the same absolute (seed, epoch,
    # index) RNG streams (data/device_augment.py); the host then only
    # memcpys raw source clips into slabs.  'off' keeps the host chain
    # (the parity escape hatch).  Host-only stages (AugMix aug-splits,
    # hue jitter) fall back to the host chain with a log line.
    augment_device: str = "off"

    # --- batch norm ---
    sync_bn: bool = False
    # '' | 'broadcast' | 'reduce' — accepted for launch-script parity; the
    # TPU build pmean's BN stats inside every step (train/steps.py), which
    # strictly supersedes the reference's per-epoch distribute_bn
    dist_bn: str = ""

    split_bn: bool = False

    # --- EMA ---
    model_ema: bool = False
    model_ema_decay: float = 0.9998

    # --- precision / compile ---
    amp: bool = False                    # reference flag; maps to bf16 compute on TPU
    compute_dtype: str = "bfloat16"      # bfloat16 | float32
    param_dtype: str = "float32"

    # --- fault tolerance (train/resilience.py) ---
    # consult the run dir's recovery snapshots at startup and fast-forward
    # to the exact (epoch, batch) loop position (bit-continuous resume);
    # implies a STABLE output dir (no -N auto-increment) — name runs with
    # --experiment when launching many
    auto_resume: bool = False
    # non-finite loss/grad-norm policy inside the jitted step:
    # 'skip' selects the pre-step state (params/moments/EMA/stats
    # untouched), 'off' reproduces the reference (poisoned update applied)
    guard_nonfinite: str = "skip"
    guard_spike_window: int = 0     # rolling robust-stats window (0 = off)
    guard_spike_zmax: float = 8.0   # spike threshold in MAD-scaled z units
    guard_rewind_after: int = 3     # K consecutive bad steps → rewind
    guard_rewind_limit: int = 2     # rewind budget per run
    # seconds without a completed step before the stall watchdog dumps all
    # thread stacks and aborts with exit code 85 (0 = off)
    watchdog_timeout: float = 0.0

    # --- observability (deepfake_detection_tpu/obs) ---
    # the telemetry tracker (per-step time breakdown, throughput/MFU
    # gauges, JSONL event log in the run dir) is DEFAULT ON — it rides the
    # existing drain cadence with zero extra device syncs; this opts out
    no_telemetry: bool = False
    # stdlib trainer HTTP endpoint: GET /metrics (Prometheus text) +
    # /healthz while the run is live (0 = off)
    metrics_port: int = 0
    # on-demand profiler capture window, in steps: SIGUSR2 or
    # `touch <outdir>/PROFILE` traces the next N steps on a RUNNING job,
    # rank-0-gated (0 disables the triggers)
    profile_capture: int = 20

    # --- misc / infra ---
    # jax persistent compilation cache dir ("" = off): repeat runs of an
    # unchanged (program, jax/jaxlib, backend, topology) skip XLA
    # backend compilation — re-tracing/lowering still happens, which is
    # why serving layers an AOT executable store on top (PERF.md §9)
    compile_cache_dir: str = ""
    seed: int = 42
    log_interval: int = 50
    profile: int = 0      # trace N train steps with jax.profiler (SURVEY §5)
    recovery_interval: int = 0
    save_images: bool = False
    output: str = "./output"
    eval_metric: str = "loss"
    eval_crop: str = "random"  # random = reference parity; center = deterministic eval
    # host-pipeline parity escape hatches (default: TPU-fast paths — one
    # native warp for the geometric chain, jitter/flicker on device)
    host_color_jitter: bool = False
    host_geom: bool = False
    tta: int = 0
    use_multi_epochs_loader: bool = False
    json_file: str = ""                  # cluster topology JSON
    local_rank: int = 0
    experiment: str = ""

    # --- parallelism (TPU-native; no reference analog) ---
    # default mesh: the unified 2-D ('batch': n_devices, 'model': 1) GSPMD
    # mesh (parallel/mesh.py make_train_mesh); explicit --mesh-shape/
    # --mesh-axes select a legacy layout verbatim
    mesh_shape: Optional[Tuple[int, ...]] = None
    mesh_axes: Tuple[str, ...] = ("data",)
    fsdp: bool = False          # shard params (+moments/EMA) over the
    # batch axis per the sharding-rule table (train_state_shardings)
    grad_accum: int = 1  # microbatches accumulated per optimizer step
    tp_size: int = 1     # model-axis extent for transformer tensor
    # parallelism: builds a (data, model) 2-D mesh and applies the
    # Megatron-paired shardings from parallel/tp.py (ViT/TimeSformer)
    checkpoint_policy: str = "none"      # remat policy: none|full|dots
    # transformer attention kernel: "" = model default (full). 'flash' runs
    # the Pallas kernels; 'ring'/'ring_flash'/'ulysses' are sequence-
    # parallel and need an sp mesh — library-level for now (models/vit.py)
    attn_impl: str = ""
    # --- step-time optimization layer (PERF.md post-fusion roofline) ---
    # 'pallas' routes the EfficientNet-family dw → BN → act stages through
    # the fused VMEM-resident kernel (ops/depthwise_pallas.py); 'off' keeps
    # the stock XLA lowering.  Numerically equivalent either way (≤2 ulp,
    # tests/test_depthwise_pallas.py); the parameter tree is identical.
    fused_depthwise: str = "off"
    # rewrite the stride-2 stem as a stride-1 conv over 2×2 pixel-shuffled
    # input (MLPerf s2d trick) — the shuffle runs in the DeviceLoader
    # prologue; checkpoints stay bit-compatible via a pure weight reshape
    stem_s2d: bool = False

    # ------------------------------------------------------------------
    def __post_init__(self):
        for f_ in ("input_size", "input_size_v2", "lr_noise"):
            v = getattr(self, f_)
            if isinstance(v, str):
                setattr(self, f_, _tuple_of_ints(v) if f_ != "lr_noise"
                        else tuple(float(x) for x in v.split(",")))
        if isinstance(self.scale, list):
            self.scale = tuple(self.scale)
        if isinstance(self.ratio, list):
            self.ratio = tuple(self.ratio)
        if int(self.grad_accum) < 1:
            raise ValueError(f"--grad-accum must be >= 1, "
                             f"got {self.grad_accum}")
        if self.checkpoint_policy not in ("none", "full", "dots"):
            raise ValueError("checkpoint_policy must be none|full|dots, got "
                             f"{self.checkpoint_policy!r}")
        if self.loader_backend not in ("thread", "shm"):
            raise ValueError("loader_backend must be thread|shm, got "
                             f"{self.loader_backend!r}")
        if self.guard_nonfinite not in ("off", "skip"):
            raise ValueError("guard_nonfinite must be off|skip, got "
                             f"{self.guard_nonfinite!r}")
        if self.augment_device not in ("off", "on"):
            raise ValueError("augment_device must be off|on, got "
                             f"{self.augment_device!r}")
        if self.augment_device == "on" and self.host_geom:
            raise ValueError("--augment-device on renders the geometric "
                             "warp on device; it conflicts with the "
                             "--host-geom parity escape hatch — pick one")
        if self.augment_device == "on" and self.host_color_jitter:
            raise ValueError("--augment-device on leaves no host transform "
                             "stage for --host-color-jitter to run in — "
                             "pick one")
        if self.fused_depthwise not in ("off", "pallas"):
            raise ValueError("fused_depthwise must be off|pallas, got "
                             f"{self.fused_depthwise!r}")
        if int(self.ring_depth) < 3:
            raise ValueError("--ring-depth must be >= 3 (double buffering "
                             f"needs one spare slab), got {self.ring_depth}")
        if int(self.pack_image_size) < 0:
            raise ValueError("--pack-image-size must be >= 0, got "
                             f"{self.pack_image_size}")
        if self.pack_image_size and not self.data_packed:
            raise ValueError("--pack-image-size only makes sense with "
                             "--data-packed (it asserts the pack's "
                             "resolution, not a resize)")
        if not 0 <= int(self.metrics_port) <= 65535:
            raise ValueError(f"--metrics-port must be 0..65535, got "
                             f"{self.metrics_port}")
        if int(self.profile_capture) < 0:
            raise ValueError(f"--profile-capture must be >= 0, got "
                             f"{self.profile_capture}")

    # ------------------------------------------------------------------
    @property
    def resolved_input_size(self) -> Tuple[int, int, int]:
        """(C, H, W) with the v2 string flag taking priority (config.py:12-24)."""
        if self.input_size_v2:
            return tuple(self.input_size_v2)  # type: ignore
        if self.input_size:
            return tuple(self.input_size)     # type: ignore
        if self.img_size:
            return (3, self.img_size, self.img_size)
        return (3, 224, 224)

    @property
    def resolved_in_chans(self) -> int:
        return self.in_chans if self.in_chans is not None else self.resolved_input_size[0]

    def resolved_lr(self, world_size: int) -> float:
        """Linear LR scaling rule (``train.py:814``)."""
        if self.lr is not None:
            return self.lr
        return self.batch_size * world_size * self.basic_lr

    # ------------------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def to_yaml(self) -> str:
        if _HAS_YAML:
            return yaml.safe_dump(self.to_dict(), default_flow_style=False)
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrainConfig":
        known = {f_.name for f_ in fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    @classmethod
    def from_yaml(cls, path: str) -> "TrainConfig":
        with open(path) as f:
            if _HAS_YAML:
                d = yaml.safe_load(f)
            else:
                d = json.load(f)
        return cls.from_dict(d or {})

    # ------------------------------------------------------------------
    @classmethod
    def argument_parser(cls) -> argparse.ArgumentParser:
        """Argparse surface generated from the dataclass (flag-name parity)."""
        p = _dataclass_parser(cls, "TPU deepfake-detection training")
        p.add_argument("-b", dest="batch_size", default=None)
        return p

    @classmethod
    def from_args(cls, argv: Optional[Sequence[str]] = None) -> "TrainConfig":
        """Two-stage parse: YAML resets defaults, CLI overrides (train.py:238-249)."""
        return _two_stage_parse(cls, argv, cls.argument_parser())


# ---------------------------------------------------------------------------
# Serving config (runners/serve.py)
# ---------------------------------------------------------------------------

#: serving PTQ dtypes (canonical + accepted aliases; serving/quant.py
#: owns the transform — config stays jax-free so only the names live here)
_QUANT_DTYPES = {"f32": "f32", "float32": "f32",
                 "bf16": "bf16", "bfloat16": "bf16", "int8": "int8"}


def _canon_quant_dtype(s: str, flag: str) -> str:
    try:
        return _QUANT_DTYPES[str(s).lower()]
    except KeyError:
        raise ValueError(f"{flag} must be one of f32|bf16|int8 (aliases "
                         f"float32, bfloat16), got {s!r}") from None


def parse_model_spec(spec: str, *, default_size: int,
                     default_img_num: int) -> Dict[str, Any]:
    """One ``--models`` entry → spec dict.

    Grammar: ``id=family[,path=CKPT][,size=N][,img_num=K][,dtype=D]
    [,reload=DIR]`` — the first token names the table id and the model
    family; the rest override the primary model's geometry/dtype
    defaults.  Example::

        student=mobilenetv3_small_100,size=224,dtype=int8
    """
    parts = [p.strip() for p in spec.split(",") if p.strip()]
    if not parts or "=" not in parts[0]:
        raise ValueError(f"--models entry {spec!r} must start with "
                         f"id=family")
    model_id, family = parts[0].split("=", 1)
    out: Dict[str, Any] = {"id": model_id.strip(),
                           "family": family.strip(), "path": "",
                           "size": int(default_size),
                           "img_num": int(default_img_num),
                           "dtype": "f32", "reload": ""}
    if not out["id"] or not out["family"]:
        raise ValueError(f"--models entry {spec!r}: empty id or family")
    for part in parts[1:]:
        if "=" not in part:
            raise ValueError(f"--models entry {spec!r}: {part!r} is not "
                             f"key=value")
        k, v = part.split("=", 1)
        k, v = k.strip(), v.strip()
        if k == "path" or k == "reload":
            out[k] = v
        elif k == "size" or k == "img_num":
            out[k] = int(v)
            if out[k] < 1:
                raise ValueError(f"--models entry {spec!r}: {k} must be "
                                 f">= 1")
        elif k == "dtype":
            out[k] = _canon_quant_dtype(v, f"--models {out['id']} dtype")
        else:
            raise ValueError(f"--models entry {spec!r}: unknown key "
                             f"{k!r} (path|size|img_num|dtype|reload)")
    return out


@dataclass
class ServeConfig:
    """Knob surface of the dynamic-batching inference server.

    Same conventions as :class:`TrainConfig`: every field is a
    ``--dashed-flag``, a YAML ``-c`` file resets defaults, CLI overrides.
    The batch **buckets** are the compile cache: every entry is AOT-warmed
    at startup and every device call pads to one of them — a request mix
    can never trigger a mid-traffic recompile.
    """
    # --- network ---
    host: str = "127.0.0.1"
    port: int = 8377

    # --- model (mirrors runners/test.py) ---
    model: str = "efficientnet_deepfake_v4"
    model_path: str = ""                 # msgpack file or sharded ckpt dir;
    # empty serves a seed-0 random init (bench/demo, like test.py)
    use_ema: bool = False                # prefer the EMA stream on load
    image_size: int = 600                # canvas side (params.py flagship 600)
    img_num: int = 4                     # frame replication => in_chans 3*num
    num_classes: int = 2

    # host→device wire format: 'float32' ships the fully CLI-preprocessed
    # tensor (server scores == runners/test.py bit-for-bit); 'uint8' ships
    # the uint8 canvas and normalizes/replicates inside the batched device
    # call (4·img_num× less transfer; ulp-level drift vs the CLI)
    wire: str = "float32"
    # multi-frame clips on the uint8 wire need a SECOND compiled
    # executable per bucket (≈2× warmup); a deployment that only ever
    # scores single frames can opt out (float32 wire serves clips for
    # free either way, so this flag is a no-op there)
    single_frame_only: bool = False

    # --- post-training quantization (serving/quant.py) ---
    # serving dtype of the PRIMARY model's device-resident weights:
    # 'f32' = reference parity, 'bf16' = params cast, 'int8' = weight-only
    # per-output-channel symmetric kernels, dequant fused into the
    # compiled call.  Checkpoints on disk (incl. hot reloads) stay f32;
    # tools/quant_parity.py measures the score drift/AUC bounds
    dtype: str = "f32"

    # --- multi-model serving (ISSUE 14) ---
    # extra model-table entries, ';'-separated specs:
    #   id=family[,path=CKPT][,size=N][,img_num=K][,dtype=D][,reload=DIR]
    # every entry is AOT-warmed before /readyz; POST /score routes via
    # its 'model' field / ?model= query param (default: the flagship)
    models: str = ""

    # --- two-tier cascade (serving/cascade.py) ---
    # model-table id of the triage student ("" = no cascade).  When set,
    # un-routed requests score student-first; student fake scores inside
    # [cascade_low, cascade_high] escalate to the flagship, everything
    # else returns the student verdict.  The student must share the
    # flagship's img_num (same clips flow through both tiers)
    cascade: str = ""
    cascade_low: float = 0.2
    cascade_high: float = 0.8

    # --- micro-batching / compile cache ---
    buckets: Tuple[int, ...] = (1, 4, 16, 64)
    batch_deadline_ms: float = 5.0       # partial-batch flush window
    max_queue: int = 128                 # load-shed (429) past this depth
    request_timeout_ms: float = 2000.0   # per-request deadline (504)

    # --- hot weight reload ---
    reload_dir: str = ""                 # "" disables the watcher
    reload_interval_s: float = 5.0
    # golden-batch canary score-drift tolerance for hot reloads: new
    # weights whose canary scores move more than this (max abs diff vs
    # the serving weights on the same input) are rejected; < 0 disables
    # the drift gate (finiteness + shape always gate)
    reload_drift_tol: float = -1.0

    # --- resilience (serving/resilience.py) ---
    # stuck-batch watchdog: a device batch older than this fails its
    # requests 503, restarts the engine worker and re-warms every bucket
    # (readiness drops until done); 0 disables
    watchdog_timeout_s: float = 30.0
    # circuit breaker: this many CONSECUTIVE batch failures open it
    # (immediate 503 + Retry-After at the HTTP edge); 0 disables
    breaker_threshold: int = 5
    breaker_open_s: float = 5.0          # open cooldown before the
    # half-open probe batch
    # bounded uniform jitter added to shed Retry-After values (a constant
    # synchronizes every shed client into one thundering-herd resend)
    retry_jitter_s: float = 2.0

    # --- verdict cache (cache/, ISSUE 17) ---
    # bounded LRU+TTL dedup tier keyed (content_hash, model_id,
    # checkpoint_fingerprint): a repeat of an already-scored clip resolves
    # without entering a bucket, concurrent copies of one clip coalesce
    # into ONE dispatch.  0 entries disables the tier entirely
    cache_entries: int = 0
    cache_ttl_s: float = 300.0
    # opt-in near-dup perceptual index (dHash/aHash over the downsampled
    # canvas, Hamming-radius probe): a near hit serves a DIFFERENT clip's
    # verdict by construction — its own knob, its own hit counter, never
    # conflated with exact hits
    cache_near_dup: bool = False
    cache_near_radius: int = 3

    # --- observability ---
    throughput_window_s: float = 30.0

    # --- CPU-host tuning ---
    # Cap XLA's CPU backend to one eigen thread.  Small models gain
    # nothing from intra-op threading (measured: vit-tiny b16 23 ms both
    # ways on this class of host) and the freed cores go to request
    # decode/preprocess — worth 2× served throughput on a 2-core box.
    # Leave off for large models, where intra-op threads do pay.
    single_thread_xla: bool = False

    # --- warm start (ISSUE 19) ---
    # persistent AOT executable store: a replica spawn deserializes its
    # bucket executables from this dir instead of re-paying XLA
    # compilation (serving/warmstart.py; "" disables).  Safe by
    # construction: key mismatch / corrupt entry = counted fallback to a
    # fresh compile, and a golden-batch canary gates every store hit.
    warmstart_dir: str = ""
    # fallback tier underneath the AOT store: jax's own persistent
    # compilation cache (caches HLO→binary, still re-traces; PERF.md §9)
    compile_cache_dir: str = ""
    # staged readiness: warm the first priority bucket, report /readyz
    # 200 in phase "degraded" serving the warm subset, finish the rest
    # in background (the scraper routes degraded capacity as ready)
    warm_staged: bool = False
    # comma-separated bucket warm order ("" = smallest-first); must be a
    # subset of --buckets
    warm_priority: str = ""
    # concurrent bucket compiles during warmup (0 = auto, 1 = serial)
    warm_parallel: int = 0

    # ------------------------------------------------------------------
    def warm_priority_buckets(self) -> Tuple[int, ...]:
        s = str(self.warm_priority).strip()
        return _tuple_of_ints(s) if s else ()

    def __post_init__(self):
        if isinstance(self.buckets, str):
            self.buckets = _tuple_of_ints(self.buckets)
        self.buckets = tuple(sorted(set(int(b) for b in self.buckets)))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"--buckets must be positive ints, got "
                             f"{self.buckets}")
        if self.batch_deadline_ms < 0:
            raise ValueError("--batch-deadline-ms must be >= 0")
        if self.max_queue < self.buckets[-1]:
            raise ValueError(
                f"--max-queue ({self.max_queue}) below the largest bucket "
                f"({self.buckets[-1]}) could never fill a full batch")
        if self.img_num < 1:
            raise ValueError("--img-num must be >= 1")
        if self.wire not in ("float32", "uint8"):
            raise ValueError(f"--wire must be float32|uint8, "
                             f"got {self.wire!r}")
        if self.watchdog_timeout_s < 0 or self.retry_jitter_s < 0:
            raise ValueError("--watchdog-timeout-s / --retry-jitter-s "
                             "must be >= 0")
        if self.breaker_threshold < 0:
            raise ValueError("--breaker-threshold must be >= 0 (0 = off)")
        if self.breaker_open_s <= 0:
            raise ValueError("--breaker-open-s must be > 0")
        if int(self.cache_entries) < 0:
            raise ValueError(f"--cache-entries must be >= 0 (0 = off), "
                             f"got {self.cache_entries}")
        if float(self.cache_ttl_s) <= 0:
            raise ValueError(f"--cache-ttl-s must be > 0, got "
                             f"{self.cache_ttl_s}")
        if not 0 <= int(self.cache_near_radius) <= 8:
            raise ValueError(f"--cache-near-radius must be in [0, 8], "
                             f"got {self.cache_near_radius}")
        if int(self.warm_parallel) < 0:
            raise ValueError("--warm-parallel must be >= 0 (0 = auto)")
        bad = [b for b in self.warm_priority_buckets()
               if b not in self.buckets]
        if bad:
            raise ValueError(f"--warm-priority buckets {bad} not in "
                             f"--buckets {self.buckets}")
        self.dtype = _canon_quant_dtype(self.dtype, "--dtype")
        specs = self.model_specs()          # validates the grammar
        ids = [s["id"] for s in specs]
        if len(set(ids)) != len(ids):
            raise ValueError(f"--models ids must be unique, got {ids}")
        if self.model in ids:
            raise ValueError(f"--models id {self.model!r} collides with "
                             f"the primary --model")
        if not 0.0 <= float(self.cascade_low) <= \
                float(self.cascade_high) <= 1.0:
            raise ValueError(
                f"--cascade-low/--cascade-high must satisfy 0 <= low <= "
                f"high <= 1, got [{self.cascade_low}, "
                f"{self.cascade_high}]")
        if self.cascade:
            by_id = {s["id"]: s for s in specs}
            if self.cascade not in by_id:
                raise ValueError(
                    f"--cascade {self.cascade!r} must name a --models "
                    f"entry (got {sorted(by_id) or 'none'})")
            if by_id[self.cascade]["img_num"] != self.img_num:
                raise ValueError(
                    f"--cascade student img_num "
                    f"{by_id[self.cascade]['img_num']} != flagship "
                    f"img_num {self.img_num}: the same clips must flow "
                    f"through both tiers")

    def model_specs(self) -> List[Dict[str, Any]]:
        """Parsed ``--models`` entries (see :func:`parse_model_spec`)."""
        return [parse_model_spec(s, default_size=self.image_size,
                                 default_img_num=self.img_num)
                for s in str(self.models).split(";") if s.strip()]

    @property
    def max_batch_size(self) -> int:
        return self.buckets[-1]

    @property
    def in_chans(self) -> int:
        return 3 * self.img_num

    # ------------------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServeConfig":
        known = {f_.name for f_ in fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    @classmethod
    def from_yaml(cls, path: str) -> "ServeConfig":
        with open(path) as f:
            d = yaml.safe_load(f) if _HAS_YAML else json.load(f)
        return cls.from_dict(d or {})

    @classmethod
    def argument_parser(cls) -> argparse.ArgumentParser:
        return _dataclass_parser(
            cls, "dynamic-batching deepfake-detection inference server")

    @classmethod
    def from_args(cls, argv: Optional[Sequence[str]] = None) -> "ServeConfig":
        """Two-stage parse: YAML resets defaults, CLI overrides (the
        TrainConfig.from_args semantics)."""
        return _two_stage_parse(cls, argv, cls.argument_parser())


# ---------------------------------------------------------------------------
# Backfill config (runners/backfill.py)
# ---------------------------------------------------------------------------

@dataclass
class BackfillConfig:
    """Knob surface of the corpus-scale offline backfill runner.

    Same conventions as :class:`TrainConfig`/:class:`ServeConfig`: every
    field is a ``--dashed-flag``, a YAML ``-c`` file resets defaults, CLI
    overrides.  There is deliberately no deadline, queue or wire knob —
    backfill always runs the uint8 wire at ONE fixed batch bucket (the
    saturation shape), and concurrency comes from launching more worker
    processes against the same ``--out`` run dir.
    """
    # --- work ---
    manifest: str = ""                   # tools/make_lists.py --manifest
    out: str = ""                        # shared run dir (leases/, done/,
    # verdicts/, telemetry JSONL)
    data_packed: str = ""                # packed cache (zero-decode path)
    data: str = ""                       # v3 list roots, ':'-separated
    # (decode path; exactly one of data_packed/data)

    # --- model (mirrors runners/serve.py) ---
    model: str = "efficientnet_deepfake_v4"
    model_path: str = ""
    use_ema: bool = False
    num_classes: int = 2
    # raw-tree decode geometry: frames per clip and the canonical square
    # resample (0 keeps native resolution, which must then be uniform);
    # a packed source carries both in its index and ignores these
    frames: int = 4
    image_size: int = 0

    # --- pipeline ---
    batch_size: int = 16                 # THE bucket: one AOT compile,
    # partial shard tails pad up to it
    workers: int = 0                     # decode/memcpy threads
    # (0 = cpu count)
    stem_s2d: bool = False               # fold the s2d pixel shuffle into
    # the compiled prologue (EfficientNet family; PERF.md §6)

    # --- leasing ---
    lease_ttl_s: float = 600.0           # a lease not heartbeaten for
    # this long belonged to a dead host and may be re-leased; must
    # exceed the worst single-batch wall time
    worker_name: str = ""                # lease owner + telemetry file
    # suffix (default: <hostname>-<pid>)
    max_shards: int = 0                  # stop this worker after N
    # shards (0 = run to corpus completion; smoke/test hook)

    # --- dedup (cache/, ISSUE 17) ---
    # content-hash dedup pass over pack shards: clips whose canonical
    # pixel bytes already occur earlier in the manifest skip the device
    # and book a skipped_dup verdict row pointing at the canonical clip
    # (books: manifest == scored + failed + skipped_dup).  Packed source
    # only — the hash reads the mmap slabs without decoding
    dedup: bool = False

    # --- warm start (ISSUE 19; semantics as on ServeConfig) ---
    # every backfill worker re-pays THE bucket compile at launch without
    # this; the store key folds in the mesh/sharding signature, so a
    # topology change is a miss, never a wrong executable
    warmstart_dir: str = ""
    compile_cache_dir: str = ""

    # ------------------------------------------------------------------
    def __post_init__(self):
        # required-field checks live in validate_required(): the two-stage
        # parse (and YAML overlays) construct an all-defaults instance
        # before the CLI values land
        if int(self.batch_size) < 1:
            raise ValueError(f"--batch-size must be >= 1, got "
                             f"{self.batch_size}")
        if int(self.frames) < 1:
            raise ValueError(f"--frames must be >= 1, got {self.frames}")
        if float(self.lease_ttl_s) <= 0:
            raise ValueError(f"--lease-ttl-s must be > 0, got "
                             f"{self.lease_ttl_s}")
        if int(self.image_size) < 0 or int(self.max_shards) < 0 or \
                int(self.workers) < 0:
            raise ValueError("--image-size / --max-shards / --workers "
                             "must be >= 0")

    def validate_required(self) -> "BackfillConfig":
        """The launch-surface checks (run by ``from_args`` and the
        runner): what work, where, from which source."""
        if not self.manifest:
            raise ValueError("--manifest is required (build one with "
                             "tools/make_lists.py --manifest)")
        if not self.out:
            raise ValueError("--out is required (the shared run dir)")
        if bool(self.data_packed) == bool(self.data):
            raise ValueError("exactly one of --data-packed / --data "
                             "must be given (the clip source)")
        if self.dedup and not self.data_packed:
            raise ValueError("--dedup needs --data-packed (the dedup "
                             "index hashes pack slabs without decoding)")
        return self

    # ------------------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "BackfillConfig":
        known = {f_.name for f_ in fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    @classmethod
    def from_yaml(cls, path: str) -> "BackfillConfig":
        with open(path) as f:
            d = yaml.safe_load(f) if _HAS_YAML else json.load(f)
        return cls.from_dict(d or {})

    @classmethod
    def argument_parser(cls) -> argparse.ArgumentParser:
        return _dataclass_parser(
            cls, "corpus-scale offline backfill scoring runner")

    @classmethod
    def from_args(cls, argv: Optional[Sequence[str]] = None
                  ) -> "BackfillConfig":
        return _two_stage_parse(
            cls, argv, cls.argument_parser()).validate_required()


# ---------------------------------------------------------------------------
# Fleet router config (runners/router.py)
# ---------------------------------------------------------------------------

@dataclass
class RouterConfig:
    """Knob surface of the fleet replica router.

    Same conventions as the other configs: every field is a
    ``--dashed-flag``, a YAML ``-c`` file resets defaults, CLI
    overrides.  The router attaches to running replicas
    (``--replicas url,url``) and/or spawns its own local fleet
    (``--spawn N`` children of ``--spawn-runner`` with
    ``--replica-args`` passed through) — both sets join one registry.
    """
    # --- network ---
    host: str = "127.0.0.1"
    port: int = 8380                     # serve=8377, stream=8378

    # --- fleet membership ---
    replicas: str = ""                   # comma list of replica URLs
    # (host:port or http://host:port) to attach to
    spawn: int = 0                       # local replica children to spawn
    spawn_runner: str = "serve"          # serve | stream
    replica_args: str = ""               # extra CLI for every spawned
    # replica (shlex-split), e.g. "--model ... --single-thread-xla"

    # --- health (fleet/controller.py scraper) ---
    scrape_interval_s: float = 0.5
    health_fail_after: int = 3           # consecutive scrape failures
    # before a replica is marked down
    scrape_timeout_s: float = 2.0

    # --- routing (fleet/router.py) ---
    virtual_nodes: int = 64              # hash-ring vnodes per replica
    route_retries: int = 2               # failover attempts past the
    # first replica on shed/transport error (stateless traffic only)
    upstream_timeout_s: float = 30.0
    # router-level shed Retry-After: base + uniform [0, jitter) — the
    # serving stack's anti-thundering-herd idiom at the fleet edge
    shed_retry_after_s: float = 1.0
    retry_jitter_s: float = 2.0

    # --- data plane (fleet/dataplane.py) ---
    data_plane: str = "evloop"           # evloop | threads — the relay
    # hot path: a selectors-based event loop (the ~5x relays/s plane) or
    # the original thread-per-connection fallback
    relay_workers: int = 1               # evloop shards accepting on the
    # same port via SO_REUSEPORT (>1 needs kernel support; threads
    # plane ignores it)
    idle_timeout_s: float = 60.0         # close keep-alive connections
    # silent this long (counted dfd_router_idle_closed_total)
    header_timeout_s: float = 10.0       # slowloris bound: a request
    # head must arrive whole within this window (408 + close)
    max_buffer_bytes: int = 1 << 20      # per-connection relay buffer
    # bound: larger responses stream with backpressure (evloop); a
    # stalled reader whose buffer stays full between requests is shed

    # --- migration (fleet/migrate.py) ---
    migrate_timeout_s: float = 30.0      # per-stream export/restore bound
    drain_on_exit: bool = False          # drain spawned replicas' streams
    # before terminating them on shutdown

    # --- edge verdict cache (cache/, ISSUE 17) ---
    # optional response cache for POST /score at the routing tier, keyed
    # by raw body digest + the fleet weights-epoch (the set of per-model
    # checkpoint fingerprints scraped off every replica's /readyz): a
    # mixed-fingerprint rollout changes the epoch and bypasses the cache
    # until the fleet converges.  0 entries disables the edge probe
    edge_cache_entries: int = 0
    edge_cache_ttl_s: float = 2.0

    # --- autoscaling (fleet/autoscaler.py, ISSUE 18) ---
    # the SLO-driven control loop: sample the fleet every
    # --autoscale-interval-s, scale up when the router p99 / shed rate /
    # per-replica depth breach for --autoscale-up-samples consecutive
    # ticks, scale in (drain-first, lossless) after
    # --autoscale-down-samples idle ticks; decisions are deterministic
    # from the recorded sample trace (--autoscale-trace + the golden
    # replay test pin it)
    autoscale: bool = False
    slo_p99_ms: float = 250.0            # the breach line
    min_replicas: int = 1                # hard floor (dead children
    # re-spawn to it even with no load)
    max_replicas: int = 4                # capacity slots shared with
    # the backfill tenant
    autoscale_interval_s: float = 1.0
    autoscale_up_samples: int = 2
    autoscale_down_samples: int = 5
    autoscale_up_cooldown_s: float = 5.0
    autoscale_down_cooldown_s: float = 15.0
    autoscale_shed_high: float = 0.01    # shed fraction breach line
    autoscale_depth_high: float = 8.0    # per-replica depth breach line
    autoscale_depth_low: float = 1.0     # per-replica depth idle line
    autoscale_trace: str = ""            # JSONL decision trace path
    # (sample + decision per tick; replayable via
    # fleet.autoscaler.replay_trace)
    spawn_grace_s: float = 900.0         # a spawned child is *warming*,
    # not down, until it binds its port or this window expires
    settle_timeout_s: float = 20.0       # scale-in: bounded wait for a
    # drained replica's inflight to reach zero before terminate
    # standby pool (ISSUE 19): keep N fully-warmed but UNREGISTERED
    # replicas parked (counted as neither ready nor warming) so a
    # scale-up is a registry promotion in milliseconds instead of a
    # cold spawn; standbys occupy capacity slots (max_replicas) and the
    # backfill tenant's slot math counts them
    standby_replicas: int = 0

    # --- backfill tenant (ISSUE 18): idle capacity runs backfill ---
    backfill_tenant: str = ""            # manifest path (enables the
    # tenant: idle capacity slots run runners/backfill.py workers that
    # yield on a traffic spike via SIGTERM -> exit-75 lease release)
    backfill_out: str = ""               # the tenant's shared run dir
    backfill_args: str = ""              # extra CLI for every tenant
    # worker (shlex-split), e.g. "--data-packed ... --model ..."
    backfill_max_workers: int = 0        # cap (0 = all idle slots)
    backfill_yield_timeout_s: float = 30.0

    # ------------------------------------------------------------------
    def __post_init__(self):
        if self.spawn_runner not in ("serve", "stream"):
            raise ValueError(f"--spawn-runner must be serve|stream, got "
                             f"{self.spawn_runner!r}")
        if int(self.spawn) < 0:
            raise ValueError(f"--spawn must be >= 0, got {self.spawn}")
        if int(self.virtual_nodes) < 1:
            raise ValueError(f"--virtual-nodes must be >= 1, got "
                             f"{self.virtual_nodes}")
        if int(self.route_retries) < 0:
            raise ValueError(f"--route-retries must be >= 0, got "
                             f"{self.route_retries}")
        if int(self.health_fail_after) < 1:
            raise ValueError(f"--health-fail-after must be >= 1, got "
                             f"{self.health_fail_after}")
        if self.data_plane not in ("evloop", "threads"):
            raise ValueError(f"--data-plane must be evloop|threads, got "
                             f"{self.data_plane!r}")
        if int(self.relay_workers) < 1:
            raise ValueError(f"--relay-workers must be >= 1, got "
                             f"{self.relay_workers}")
        if int(self.max_buffer_bytes) < 4096:
            raise ValueError(f"--max-buffer-bytes must be >= 4096, got "
                             f"{self.max_buffer_bytes}")
        if int(self.edge_cache_entries) < 0:
            raise ValueError(f"--edge-cache-entries must be >= 0 "
                             f"(0 = off), got {self.edge_cache_entries}")
        if float(self.edge_cache_ttl_s) <= 0:
            raise ValueError(f"--edge-cache-ttl-s must be > 0, got "
                             f"{self.edge_cache_ttl_s}")
        for name in ("scrape_interval_s", "scrape_timeout_s",
                     "upstream_timeout_s", "migrate_timeout_s",
                     "shed_retry_after_s", "idle_timeout_s",
                     "header_timeout_s"):
            if float(getattr(self, name)) <= 0:
                raise ValueError(f"--{name.replace('_', '-')} must be "
                                 f"> 0, got {getattr(self, name)}")
        if float(self.retry_jitter_s) < 0:
            raise ValueError(f"--retry-jitter-s must be >= 0, got "
                             f"{self.retry_jitter_s}")
        if int(self.min_replicas) < 1:
            raise ValueError(f"--min-replicas must be >= 1, got "
                             f"{self.min_replicas}")
        if int(self.max_replicas) < int(self.min_replicas):
            raise ValueError(
                f"--max-replicas ({self.max_replicas}) must be >= "
                f"--min-replicas ({self.min_replicas})")
        if int(self.autoscale_up_samples) < 1 or \
                int(self.autoscale_down_samples) < 1:
            raise ValueError("--autoscale-up-samples / "
                             "--autoscale-down-samples must be >= 1")
        if float(self.autoscale_depth_low) > \
                float(self.autoscale_depth_high):
            raise ValueError("--autoscale-depth-low must be <= "
                             "--autoscale-depth-high (the hysteresis "
                             "dead band)")
        if int(self.backfill_max_workers) < 0:
            raise ValueError(f"--backfill-max-workers must be >= 0, "
                             f"got {self.backfill_max_workers}")
        if int(self.standby_replicas) < 0:
            raise ValueError(f"--standby-replicas must be >= 0, got "
                             f"{self.standby_replicas}")
        if int(self.standby_replicas) > 0 and not self.autoscale:
            raise ValueError("--standby-replicas needs --autoscale "
                             "(the autoscaler owns the standby pool)")
        for name in ("slo_p99_ms", "autoscale_interval_s",
                     "spawn_grace_s", "settle_timeout_s",
                     "backfill_yield_timeout_s"):
            if float(getattr(self, name)) <= 0:
                raise ValueError(f"--{name.replace('_', '-')} must be "
                                 f"> 0, got {getattr(self, name)}")
        for name in ("autoscale_up_cooldown_s",
                     "autoscale_down_cooldown_s",
                     "autoscale_shed_high", "autoscale_depth_low"):
            if float(getattr(self, name)) < 0:
                raise ValueError(f"--{name.replace('_', '-')} must be "
                                 f">= 0, got {getattr(self, name)}")

    def replica_urls(self) -> List[str]:
        return [u.strip() for u in str(self.replicas).split(",")
                if u.strip()]

    def validate_required(self) -> "RouterConfig":
        """Launch-surface check (two-stage parse builds an all-defaults
        instance first): the router needs a fleet to route over."""
        if not self.replica_urls() and int(self.spawn) < 1:
            raise ValueError("give the router a fleet: --replicas "
                             "url[,url...] and/or --spawn N")
        if self.backfill_tenant and not self.autoscale:
            raise ValueError("--backfill-tenant needs --autoscale (the "
                             "control loop is the tenant's scheduler)")
        if self.backfill_tenant and not self.backfill_out:
            raise ValueError("--backfill-tenant needs --backfill-out "
                             "(the tenant's shared run dir)")
        return self

    # ------------------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RouterConfig":
        known = {f_.name for f_ in fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    @classmethod
    def from_yaml(cls, path: str) -> "RouterConfig":
        with open(path) as f:
            d = yaml.safe_load(f) if _HAS_YAML else json.load(f)
        return cls.from_dict(d or {})

    @classmethod
    def argument_parser(cls) -> argparse.ArgumentParser:
        return _dataclass_parser(
            cls, "fleet replica router (shared-nothing scale-out)")

    @classmethod
    def from_args(cls, argv: Optional[Sequence[str]] = None
                  ) -> "RouterConfig":
        return _two_stage_parse(
            cls, argv, cls.argument_parser()).validate_required()


# ---------------------------------------------------------------------------
# Streaming config (runners/stream.py)
# ---------------------------------------------------------------------------

@dataclass
class StreamConfig(ServeConfig):
    """Knob surface of the streaming-video scoring server.

    Extends :class:`ServeConfig` (the engine/batcher knobs are the same
    machinery) with the stream-pipeline stages: face localization +
    tracking, temporal windowing, per-stream verdict hysteresis, and
    session lifecycle.  ``from_dict``/``from_yaml``/``from_args`` are
    inherited — every new field is a ``--dashed-flag``.
    """
    port: int = 8378                     # one above the serving default

    # --- face localization + tracking (streaming/tracker.py) ---
    # 'full_frame' (deterministic built-in, pre-cropped parity) or
    # 'callable:<module>:<attr>' plugging in a model-backed detector
    localizer: str = "full_frame"
    track_iou_min: float = 0.3           # greedy-IoU association floor
    track_ema_alpha: float = 0.6         # box smoothing (1.0 = raw boxes)
    track_max_coast: int = 10            # missed frames before track death
    track_min_hits: int = 1              # detections before a track scores
    crop_margin: float = 0.15            # face-box expansion before crop

    # --- temporal windowing (streaming/windows.py) ---
    window_stride: int = 1               # in-window frame spacing
    window_hop: int = 0                  # pushes between windows (0 = tile:
    # img_num*stride, non-overlapping)
    max_inflight_windows: int = 4        # per-stream bound; beyond it the
    # OLDEST pending window is dropped (drop-oldest backpressure)

    # --- host fast path (streaming/ring.py, ISSUE 20) ---
    # 'ring' = frame-once lifecycle: per-track preallocated crop rings,
    # one prepare_canvas + one sha256 per crop, zero-copy FrameStack
    # window payloads gathered straight into the engine's batch slab.
    # 'concat' = the historical standalone-canvas + np.concatenate path
    # (in-tree parity and bench reference)
    assembly: str = "ring"
    # consecutive-duplicate elision (frozen/low-motion streams): frames
    # whose encoded bytes match their predecessor skip decode, and a
    # window whose clip content equals the track's previous window skips
    # submission — both counted (frames_dup_elided / windows_dup_elided),
    # never silent.  Off by default: with it off the emitted-window
    # stream is exactly the pre-fast-path one
    dedup_frames: bool = False

    # --- verdict hysteresis (streaming/verdict.py) ---
    verdict_ema_alpha: float = 0.3       # EMA over window scores
    suspect_enter: float = 0.5
    suspect_exit: float = 0.35
    fake_enter: float = 0.8
    fake_exit: float = 0.65
    verdict_min_windows: int = 1         # EMA warmup before verdicts move

    # --- session lifecycle (streaming/ingest.py) ---
    max_streams: int = 64
    stream_ttl_s: float = 120.0          # idle eviction (0 = never)
    event_log_dir: str = ""              # per-stream verdict-event JSONL
    # session durability: snapshot per-stream tracker + verdict-machine +
    # window-position state here on shutdown/SIGTERM and restore on the
    # next start, so a server bounce RESUMES verdict streams instead of
    # resetting them ("" disables)
    state_dir: str = ""

    # --- bench/test instrumentation ---
    # planted per-window scores ("0.05*8,0.95*12"): windows still ride the
    # engine (load/latency are real) but the VERDICT machines consume the
    # planted sequence, so transition tests are deterministic
    verdict_vector: str = ""

    # ------------------------------------------------------------------
    def __post_init__(self):
        super().__post_init__()
        from .streaming.verdict import VerdictThresholds
        VerdictThresholds(self.suspect_enter, self.suspect_exit,
                          self.fake_enter, self.fake_exit)  # validates
        if not 0.0 < self.verdict_ema_alpha <= 1.0:
            raise ValueError(f"--verdict-ema-alpha must be in (0, 1], got "
                             f"{self.verdict_ema_alpha}")
        if not 0.0 < self.track_ema_alpha <= 1.0:
            raise ValueError(f"--track-ema-alpha must be in (0, 1], got "
                             f"{self.track_ema_alpha}")
        if not 0.0 <= self.track_iou_min <= 1.0:
            raise ValueError(f"--track-iou-min must be in [0, 1], got "
                             f"{self.track_iou_min}")
        for name in ("window_stride", "max_inflight_windows", "max_streams",
                     "verdict_min_windows", "track_min_hits"):
            if int(getattr(self, name)) < 1:
                raise ValueError(f"--{name.replace('_', '-')} must be "
                                 f">= 1, got {getattr(self, name)}")
        if int(self.window_hop) < 0 or int(self.track_max_coast) < 0 or \
                float(self.crop_margin) < 0 or float(self.stream_ttl_s) < 0:
            raise ValueError("window-hop / track-max-coast / crop-margin / "
                             "stream-ttl-s must be >= 0")
        if self.assembly not in ("ring", "concat"):
            raise ValueError(f"--assembly must be 'ring' or 'concat', "
                             f"got {self.assembly!r}")

    @classmethod
    def argument_parser(cls) -> argparse.ArgumentParser:
        return _dataclass_parser(
            cls, "streaming-video deepfake-detection scoring server")
