"""The project manifest dfdlint runs against — the single declarative
statement of which modules/attributes carry which invariants.

Every entry here is a *promise the rest of the repo makes*:

* ``JAX_FREE_MODULES`` — modules whose import must never reach jax/flax
  transitively (PR 1's spawned-worker import discipline; spawned shm
  decode workers, data-prep hosts and reporting subprocesses import
  these with no accelerator stack).  DFD001 proves it on the static
  import graph; one subprocess canary in tests/test_lint.py proves the
  graph against reality.
* ``DONATING_FACTORIES`` — factory functions whose *returned* callable
  donates argument buffers (``donate_argnums``): reading a value after
  passing it to one is the PR 2/PR 3 use-after-free class.
* ``RNG_DIRS`` — subtrees where every random draw must derive from the
  absolute ``(seed, epoch, index)`` streams or an injected Generator
  (bit-identical resume depends on it).
* ``METRIC_REGISTRIES`` — the modules allowed to register ``dfd_*``
  Prometheus names, one prefix each; every literal reference elsewhere
  must resolve to a registered name (a typo'd metric is a silently dead
  dashboard).  ``METRIC_DYNAMIC_PREFIXES`` marks families registered
  from runtime dicts (obs collectors) that static analysis cannot
  enumerate.
* ``LOCK_GUARDED`` — (file, attribute, lock) triples where a mutation
  outside ``with <lock>`` re-opens the PR 10 split-lock gauge bug.
* ``CHAOS_MODULE`` — where the ``KNOWN_POINTS`` injection-point registry
  lives; a ``fires("typo", ...)`` probe or a ``name@step`` spec literal
  naming an unknown point is a dead injection path.
* ``CTYPES_EXEMPT`` — the one module allowed to bind ``dfd_*`` native
  symbols without its own ABI-version probe (it owns the probe).
* ``SHARD_MAP_ALLOWLIST`` — legacy manual-SPMD modules still allowed to
  call ``shard_map``/``pmap`` directly; everything else must express
  parallelism as NamedSharding under plain jit (DFD010, ISSUE 12).
"""

from __future__ import annotations

from .core import LintConfig

# Modules that must stay importable with zero jax in sys.modules.
# Note the graph includes ancestor packages: declaring a submodule
# jax-free also pins every ``__init__.py`` above it.
JAX_FREE_MODULES = (
    "deepfake_detection_tpu",               # top-level __init__ (registry+config)
    "deepfake_detection_tpu.chaos",
    "deepfake_detection_tpu.data",          # lazy __init__ (PEP 562)
    "deepfake_detection_tpu.data.packed",
    "deepfake_detection_tpu.data.native",
    "deepfake_detection_tpu.data.shm_ring",
    "deepfake_detection_tpu.obs",           # lazy __init__ (PEP 562)
    "deepfake_detection_tpu.obs.events",
    "deepfake_detection_tpu.streaming.ring",
    "deepfake_detection_tpu.streaming.tracker",
    "deepfake_detection_tpu.streaming.verdict",
    "deepfake_detection_tpu.lint",          # the linter itself
    # backfill worker-side modules: the chaos harness, make_lists
    # manifest emission and book audits run with no accelerator stack
    # (only runners/backfill.py touches jax)
    "deepfake_detection_tpu.backfill",
    "deepfake_detection_tpu.backfill.manifest",
    "deepfake_detection_tpu.backfill.lease",
    "deepfake_detection_tpu.backfill.writer",
    "deepfake_detection_tpu.backfill.source",
    # the fleet router tier (ISSUE 15): the routing process must never
    # pay — or wait on — an accelerator import; replicas are separate
    # processes that do.  utils.prometheus is the jax-free observability
    # floor these share (utils/__init__ is PEP-562 lazy for exactly this)
    # the verdict-cache core (ISSUE 17): numpy+hashlib only, shared by
    # the router edge probe and the backfill dedup pass — both run in
    # processes that never import jax
    "deepfake_detection_tpu.cache",
    "deepfake_detection_tpu.cache.content",
    "deepfake_detection_tpu.cache.store",
    # warm-start key/manifest schema (ISSUE 19): the store KEY must be
    # computable by jax-free tooling (bench reporters, fleet ops); only
    # serving.warmstart (serialize/deserialize) touches jax
    "deepfake_detection_tpu.serving.warmkey",
    "deepfake_detection_tpu.fleet",
    "deepfake_detection_tpu.fleet.registry",
    "deepfake_detection_tpu.fleet.metrics",
    "deepfake_detection_tpu.fleet.controller",
    "deepfake_detection_tpu.fleet.migrate",
    "deepfake_detection_tpu.fleet.router",
    "deepfake_detection_tpu.fleet.dataplane",
    # the ISSUE 18 control loop: SLO autoscaler + backfill tenant glue
    # run in the router process (decisions must never wait on jax)
    "deepfake_detection_tpu.fleet.autoscaler",
    "deepfake_detection_tpu.runners.router",
    "tools.pack_dataset",
    "tools.obs_report",
    "tools.make_lists",
    "tools.dfdlint",
)

DONATING_FACTORIES = {
    # train/steps.py: returned step donates the TrainState (argument 0)
    "make_train_step": (0,),
}

RNG_DIRS = (
    "deepfake_detection_tpu/data",
    "deepfake_detection_tpu/streaming",
    "deepfake_detection_tpu/serving",
    "deepfake_detection_tpu/fleet",
)

METRIC_REGISTRIES = {
    "deepfake_detection_tpu/serving/metrics.py": "dfd_serving",
    "deepfake_detection_tpu/streaming/metrics.py": "dfd_streaming",
    "deepfake_detection_tpu/obs/telemetry.py": "dfd_train",
    "deepfake_detection_tpu/fleet/metrics.py": "dfd_router",
}

# obs collectors register gauge/counter names from runtime dicts (loader
# stats, resilience counters) — those families cannot be enumerated
# statically, so literal references under these prefixes are not checked
METRIC_DYNAMIC_PREFIXES = (
    "dfd_train_",
)

LOCK_GUARDED = (
    # the PR 10 incident: inflight gauge bump/decrement must be one atom
    # with the _pending ledger mutation, under the ledger's own lock
    ("deepfake_detection_tpu/serving/engine.py", "inflight",
     "_pending_lock"),
)

CHAOS_MODULE = "deepfake_detection_tpu/chaos.py"

CTYPES_EXEMPT = (
    "deepfake_detection_tpu/data/native.py",    # owns the ABI probe
)

# Modules still allowed to call shard_map/pmap directly ("legacy manual
# SPMD").  The ISSUE 12 migration unified training on NamedSharding under
# plain jit; these two genuinely need manual per-device programs —
# collective-permute rings (ring attention) and pipeline ppermute hops —
# and each rides here only until its own migration.  DFD010 rot-checks
# the list: an entry whose file stops calling shard_map fails the gate.
SHARD_MAP_ALLOWLIST = (
    "deepfake_detection_tpu/parallel/ring_attention.py",
    "deepfake_detection_tpu/parallel/pp.py",
    # the version shim: imports + signature-probes shard_map so every
    # legacy caller shares ONE compat surface — it never builds programs
    "deepfake_detection_tpu/parallel/_compat.py",
)


def default_config() -> LintConfig:
    return LintConfig(
        jax_free_modules=JAX_FREE_MODULES,
        donating_factories=dict(DONATING_FACTORIES),
        rng_dirs=RNG_DIRS,
        metric_registries=dict(METRIC_REGISTRIES),
        metric_dynamic_prefixes=METRIC_DYNAMIC_PREFIXES,
        lock_guarded=LOCK_GUARDED,
        chaos_module=CHAOS_MODULE,
        ctypes_exempt=CTYPES_EXEMPT,
        shard_map_allowlist=SHARD_MAP_ALLOWLIST,
    )
