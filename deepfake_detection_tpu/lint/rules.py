"""dfdlint rules DFD001–DFD009.

Each rule encodes one bug class this repo has actually shipped (and
fixed) — the rule table in README.md maps every id to the CHANGES.md
incident it came from.  Rules are deliberately *pattern* checkers, not
type systems: they over-approximate, and the suppression/baseline
machinery in core.py absorbs the (few, justified) false positives.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import FileCtx, LintConfig, ProjectIndex, Violation

__all__ = ["ALL_RULES", "rule_catalog"]


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------

def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` chain as a string; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _walk_with_parents(tree: ast.AST) -> Iterator[Tuple[ast.AST,
                                                        List[ast.AST]]]:
    stack: List[Tuple[ast.AST, List[ast.AST]]] = [(tree, [])]
    while stack:
        node, parents = stack.pop()
        yield node, parents
        child_parents = parents + [node]
        for child in ast.iter_child_nodes(node):
            stack.append((child, child_parents))


def _functions(tree: ast.AST) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _own_statements(fn: ast.AST) -> Iterator[ast.stmt]:
    """Statements of ``fn``'s body, recursing into compound statements but
    NOT into nested function/class bodies (those are separate scopes)."""
    def rec(body):
        for stmt in body:
            yield stmt
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for field in ("body", "orelse", "finalbody"):
                yield from rec(getattr(stmt, field, []) or [])
            for handler in getattr(stmt, "handlers", []) or []:
                yield from rec(handler.body)
    yield from rec(fn.body)


def _scope_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Every node in ``fn``'s own scope exactly once, excluding nested
    function/class/lambda bodies (separate scopes).  Use this instead of
    ``ast.walk`` over :func:`_own_statements` — that pair visits nodes
    inside compound statements twice (once via the compound, once via the
    child statement)."""
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _module_scope_statements(tree: ast.Module) -> Iterator[ast.stmt]:
    """Statements that execute at import time (module + class bodies,
    through module-level if/try/with), excluding ``if TYPE_CHECKING:``
    guards and function bodies."""
    def is_type_checking(test: ast.AST) -> bool:
        d = _dotted(test)
        return d is not None and d.split(".")[-1] == "TYPE_CHECKING"

    def rec(body):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, ast.If) and is_type_checking(stmt.test):
                yield from rec(stmt.orelse)
                continue
            yield stmt
            if isinstance(stmt, ast.ClassDef):
                yield from rec(stmt.body)
                continue
            for field in ("body", "orelse", "finalbody"):
                yield from rec(getattr(stmt, field, []) or [])
            for handler in getattr(stmt, "handlers", []) or []:
                yield from rec(handler.body)
    yield from rec(tree.body)


class Rule:
    id = "DFD000"
    name = "base"
    bug_class = ""
    hint = ""

    def check(self, index: ProjectIndex,
              config: LintConfig) -> List[Violation]:
        raise NotImplementedError

    def v(self, ctx_or_path, line: int, message: str) -> Violation:
        path = ctx_or_path.relpath if isinstance(ctx_or_path, FileCtx) \
            else ctx_or_path
        return Violation(self.id, path, line, message, self.hint)


# ---------------------------------------------------------------------------
# DFD001 — jax purity: declared modules never reach jax transitively
# ---------------------------------------------------------------------------

class JaxPurity(Rule):
    id = "DFD001"
    name = "jax-purity"
    bug_class = ("a module declared jax-free (spawned decode workers, "
                 "data-prep hosts, reporting subprocesses) grows a "
                 "transitive jax import: seconds of startup + hundreds "
                 "of MB RSS per worker")
    hint = ("move the jax-touching import into the function that needs it "
            "(PEP 562 lazy idiom, see data/__init__.py), or drop the "
            "module from lint/manifest.py JAX_FREE_MODULES")

    def check(self, index: ProjectIndex,
              config: LintConfig) -> List[Violation]:
        banned = set(config.banned_import_roots)
        # module -> [(target_module, lineno)]
        edges: Dict[str, List[Tuple[str, int]]] = {}
        direct: Dict[str, Tuple[str, int]] = {}   # module -> (banned, line)
        for f in index.files:
            tgts = self._imports(f, index)
            edges[f.module] = tgts
            for tgt, line in tgts:
                root = tgt.split(".")[0]
                if root in banned and f.module not in direct:
                    direct[f.module] = (tgt, line)

        out: List[Violation] = []
        for declared in config.jax_free_modules:
            ctx = index.by_module.get(declared)
            if ctx is None:
                # manifest rot: a declared module that no longer exists
                # would silently stop being checked
                out.append(Violation(
                    self.id, "<manifest>", 1,
                    f"declared jax-free module {declared!r} not found in "
                    "the linted tree", self.hint))
                continue
            # importing pkg.mod executes every ancestor __init__ first
            roots = [declared]
            parts = declared.split(".")
            for i in range(1, len(parts)):
                anc = ".".join(parts[:i])
                if anc in index.by_module:
                    roots.append(anc)
            chain = self._find_banned_path(roots, edges, direct, index)
            if chain is not None:
                path_mods, (banned_tgt, line) = chain
                via = " -> ".join(path_mods)
                first = index.by_module[path_mods[0]]
                # anchor at the first import edge inside the declared
                # module's chain when it exists, else at the module head
                anchor_line = 1
                if len(path_mods) > 1:
                    for tgt, ln in edges.get(path_mods[0], []):
                        if tgt == path_mods[1] or \
                                tgt.startswith(path_mods[1] + "."):
                            anchor_line = ln
                            break
                else:
                    anchor_line = line
                out.append(self.v(
                    first, anchor_line,
                    f"module {declared!r} is declared jax-free but reaches "
                    f"{banned_tgt!r} via {via} "
                    f"({path_mods[-1]}:{line} imports it)"))
        return out

    # -- import extraction + graph walk ---------------------------------
    def _imports(self, f: FileCtx, index: ProjectIndex
                 ) -> List[Tuple[str, int]]:
        """Module-scope import targets of ``f`` as dotted names (internal
        names resolved against the index; external left as-is)."""
        out: List[Tuple[str, int]] = []
        for stmt in _module_scope_statements(f.tree):
            if isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    out.append((alias.name, stmt.lineno))
            elif isinstance(stmt, ast.ImportFrom):
                if stmt.level == 0:
                    base = stmt.module or ""
                else:
                    parts = (f.module.split(".") if
                             f.relpath.endswith("__init__.py")
                             else f.module.split(".")[:-1])
                    if stmt.level > 1:
                        parts = parts[:-(stmt.level - 1)]
                    base = ".".join(parts)
                    if stmt.module:
                        base = f"{base}.{stmt.module}" if base \
                            else stmt.module
                if base:
                    out.append((base, stmt.lineno))
                # ``from pkg import sub`` also executes pkg/sub.py when
                # sub is a module
                for alias in stmt.names:
                    cand = f"{base}.{alias.name}" if base else alias.name
                    if cand in index.by_module:
                        out.append((cand, stmt.lineno))
        return out

    def _find_banned_path(self, roots: List[str],
                          edges: Dict[str, List[Tuple[str, int]]],
                          direct: Dict[str, Tuple[str, int]],
                          index: ProjectIndex
                          ) -> Optional[Tuple[List[str], Tuple[str, int]]]:
        """BFS over internal edges from ``roots``; returns the module
        chain to the first module with a direct banned import."""
        seen: Set[str] = set()
        queue: List[List[str]] = [[r] for r in roots]
        while queue:
            path = queue.pop(0)
            mod = path[-1]
            if mod in seen:
                continue
            seen.add(mod)
            if mod in direct:
                return path, direct[mod]
            for tgt, _line in edges.get(mod, []):
                # resolve to longest internal prefix (``import a.b.c``
                # executes a, a.b and a.b.c — cover each internal level)
                parts = tgt.split(".")
                for i in range(1, len(parts) + 1):
                    pref = ".".join(parts[:i])
                    if pref in index.by_module and pref not in seen:
                        queue.append(path + [pref])
        return None


# ---------------------------------------------------------------------------
# DFD002 — donation aliasing: reads after donation, views escaping async
# ---------------------------------------------------------------------------

_VIEW_FUNCS = {"np.frombuffer", "numpy.frombuffer", "np.asarray",
               "numpy.asarray", "jax.device_get"}


class DonationAliasing(Rule):
    id = "DFD002"
    name = "donation-aliasing"
    bug_class = ("donated-buffer use-after-free: zero-copy host views of "
                 "jax buffers read after the buffer was donated (PR 2 "
                 "tp-resume SIGSEGV), or handed to a thread/async save "
                 "that serializes while the train step overwrites them "
                 "(PR 3 torn snapshots)")
    hint = ("copy before the escape/donation (`x = np.asarray(x).copy()` "
            "or `_to_host(copy=True)`), or re-bind the name from the "
            "donating call's return value")

    def check(self, index: ProjectIndex,
              config: LintConfig) -> List[Violation]:
        out: List[Violation] = []
        for f in index.files:
            module_donators = self._donating_names(
                _module_scope_statements(f.tree), config)
            for fn in _functions(f.tree):
                out.extend(self._check_fn(f, fn, dict(module_donators),
                                          config))
        return out

    # -- which local names hold donating callables -----------------------
    def _donating_names(self, stmts, config: LintConfig
                        ) -> Dict[str, Tuple[int, ...]]:
        found: Dict[str, Tuple[int, ...]] = {}
        for stmt in stmts:
            if not isinstance(stmt, ast.Assign) or \
                    not isinstance(stmt.value, ast.Call):
                continue
            call = stmt.value
            pos = self._donated_positions(call, config)
            if pos is None:
                continue
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    found[tgt.id] = pos
        return found

    def _donated_positions(self, call: ast.Call, config: LintConfig
                           ) -> Optional[Tuple]:
        """Donated argument designators: ints (positional index) and/or
        strs (``donate_argnames`` keyword name); None = not donating."""
        d = _dotted(call.func)
        if d in ("jax.jit", "jit", "pjit", "jax.pjit"):
            for kw in call.keywords:
                if kw.arg in ("donate_argnums", "donate_argnames"):
                    val = kw.value
                    if isinstance(val, (ast.Tuple, ast.List)) and \
                            not val.elts:
                        return None           # explicit empty: no donation
                    if isinstance(val, ast.Constant) and \
                            isinstance(val.value, (int, str)):
                        return (val.value,)
                    if isinstance(val, (ast.Tuple, ast.List)) and all(
                            isinstance(e, ast.Constant) and
                            isinstance(e.value, (int, str))
                            for e in val.elts):
                        return tuple(e.value for e in val.elts)
                    return (0,)               # conditional/computed: assume
            return None
        if d in config.donating_factories:
            return tuple(config.donating_factories[d])
        return None

    # -- per-function linear analysis ------------------------------------
    def _check_fn(self, f: FileCtx, fn, donators: Dict[str, Tuple[int, ...]],
                  config: LintConfig) -> List[Violation]:
        out: List[Violation] = []
        stmts = list(_own_statements(fn))
        donators.update(self._donating_names(stmts, config))

        # compound statements appear in `stmts` alongside their children;
        # per-scan seen-sets keep every Call processed exactly once while
        # preserving statement order for the views tracking below

        # (a) use-after-donate
        donations: List[Tuple[int, str, str]] = []  # (line, var, callee)
        seen_don: Set[int] = set()
        for stmt in stmts:
            for node in ast.walk(stmt):
                if not (isinstance(node, ast.Call) and
                        isinstance(node.func, ast.Name) and
                        node.func.id in donators) or id(node) in seen_don:
                    continue
                seen_don.add(id(node))
                for pos in donators[node.func.id]:
                    if isinstance(pos, str):
                        # donate_argnames: match the call's keyword args
                        # (positional resolution would need the callee's
                        # signature, which this scope may not contain)
                        for kw in node.keywords:
                            if kw.arg == pos and \
                                    isinstance(kw.value, ast.Name):
                                donations.append((node.lineno,
                                                  kw.value.id,
                                                  node.func.id))
                    elif pos < len(node.args) and \
                            isinstance(node.args[pos], ast.Name):
                        donations.append((node.lineno,
                                          node.args[pos].id,
                                          node.func.id))
        for don_line, var, callee in donations:
            event = self._first_event_after(stmts, var, don_line)
            if event is not None and event[1] == "load":
                out.append(self.v(
                    f, event[0],
                    f"`{var}` read after being donated to `{callee}` "
                    f"(line {don_line}): the buffer no longer exists"))

        # (b) zero-copy views escaping to threads/async
        views: Dict[str, int] = {}
        escapees = set(config.thread_escape_callees)
        seen_esc: Set[int] = set()
        for stmt in stmts:
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call):
                d = _dotted(stmt.value.func)
                for tgt in stmt.targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    if d in _VIEW_FUNCS:
                        views[tgt.id] = stmt.lineno
                    elif d is not None and d.endswith(".copy"):
                        views.pop(tgt.id, None)
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call) or id(node) in seen_esc:
                    continue
                seen_esc.add(id(node))
                callee = _dotted(node.func)
                leaf = callee.split(".")[-1] if callee else None
                if leaf not in escapees:
                    continue
                for arg in self._flat_args(node):
                    d = _dotted(arg.func) if isinstance(arg, ast.Call) \
                        else None
                    if d in _VIEW_FUNCS:
                        out.append(self.v(
                            f, node.lineno,
                            f"zero-copy host view ({d}) escapes to "
                            f"`{leaf}` without a copy"))
                    elif isinstance(arg, ast.Name) and arg.id in views:
                        out.append(self.v(
                            f, node.lineno,
                            f"zero-copy host view `{arg.id}` (line "
                            f"{views[arg.id]}) escapes to `{leaf}` "
                            "without a copy"))
        return out

    def _flat_args(self, call: ast.Call) -> Iterator[ast.AST]:
        pend = list(call.args) + [kw.value for kw in call.keywords]
        while pend:
            a = pend.pop()
            if isinstance(a, (ast.Tuple, ast.List)):
                pend.extend(a.elts)
            elif isinstance(a, ast.Starred):
                pend.append(a.value)
            else:
                yield a

    def _first_event_after(self, stmts, var: str, line: int
                           ) -> Optional[Tuple[int, str]]:
        events: List[Tuple[int, int, str]] = []
        for stmt in stmts:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name) and node.id == var:
                    kind = "load" if isinstance(node.ctx, ast.Load) \
                        else "store"
                    events.append((node.lineno, node.col_offset, kind))
        # stores on the donation line (the `x, m = step(x, ...)` rebind)
        # count as stores; loads on it were arguments to the call itself
        after = sorted(e for e in events if e[0] > line or
                       (e[0] == line and e[2] == "store"))
        if not after:
            return None
        ln, _col, kind = after[0]
        return ln, kind


# ---------------------------------------------------------------------------
# DFD003 — RNG discipline in data/, streaming/, serving/
# ---------------------------------------------------------------------------

_NAKED_NP = {"rand", "randn", "randint", "random", "random_sample",
             "uniform", "normal", "standard_normal", "choice", "shuffle",
             "permutation", "beta", "seed"}
_NAKED_STDLIB = {"random", "randint", "uniform", "choice", "shuffle",
                 "seed", "randrange", "gauss", "betavariate", "sample"}
_TIME_FUNCS = {"time.time", "time.time_ns", "time.perf_counter",
               "time.monotonic"}


class RngDiscipline(Rule):
    id = "DFD003"
    name = "rng-discipline"
    bug_class = ("a naked global/time-seeded RNG draw in the input or "
                 "request path breaks the absolute (seed, epoch, index) "
                 "streams that bit-identical kill/resume, packed-cache "
                 "parity and the device-augment prologue all key off")
    hint = ("derive the generator from np.random.SeedSequence([seed, "
            "epoch, index]) / fold_in, or accept an injected "
            "np.random.Generator / random.Random(seed)")

    def check(self, index: ProjectIndex,
              config: LintConfig) -> List[Violation]:
        out: List[Violation] = []
        for f in index.files:
            if not any(f.relpath.startswith(d.rstrip("/") + "/")
                       for d in config.rng_dirs):
                continue
            imports_stdlib_random = any(
                isinstance(s, ast.Import) and
                any(a.name == "random" for a in s.names)
                for s in ast.walk(f.tree) if isinstance(s, ast.Import))
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                if d is None:
                    continue
                if d in ("np.random.default_rng",
                         "numpy.random.default_rng",
                         "np.random.RandomState",
                         "numpy.random.RandomState",
                         "random.Random"):
                    if not node.args and not node.keywords:
                        out.append(self.v(
                            f, node.lineno,
                            f"unseeded `{d}()` — draws are not derivable "
                            "from (seed, epoch, index)"))
                    elif self._time_seeded(node):
                        out.append(self.v(
                            f, node.lineno,
                            f"time-seeded `{d}(...)` — run-dependent "
                            "stream breaks bit-identical resume"))
                    continue
                parts = d.split(".")
                if len(parts) == 3 and parts[0] in ("np", "numpy") and \
                        parts[1] == "random" and parts[2] in _NAKED_NP:
                    out.append(self.v(
                        f, node.lineno,
                        f"naked global-RNG draw `{d}(...)`"))
                elif len(parts) == 2 and parts[0] == "random" and \
                        imports_stdlib_random and parts[1] in _NAKED_STDLIB:
                    out.append(self.v(
                        f, node.lineno,
                        f"naked stdlib global-RNG draw `{d}(...)`"))
        return out

    def _time_seeded(self, call: ast.Call) -> bool:
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for node in ast.walk(arg):
                if isinstance(node, ast.Call) and \
                        _dotted(node.func) in _TIME_FUNCS:
                    return True
        return False


# ---------------------------------------------------------------------------
# DFD004 — recompile hygiene: jit in loops, array closures
# ---------------------------------------------------------------------------

_JIT_FUNCS = {"jax.jit", "jit", "pjit", "jax.pjit"}
_PALLAS_FUNCS = {"pl.pallas_call", "pallas_call", "jax.experimental."
                 "pallas.pallas_call"}
_ARRAY_CTORS = re.compile(
    r"^(jnp|np|numpy|jax\.numpy)\.(array|asarray|zeros|ones|full|empty|"
    r"arange|linspace|frombuffer|zeros_like|ones_like)$"
    r"|^jax\.(device_put|device_get)$|^jax\.random\.\w+$")


class RecompileHygiene(Rule):
    id = "DFD004"
    name = "recompile-hygiene"
    bug_class = ("jit/pallas_call built inside a loop body compiles (or "
                 "cache-probes) every iteration; a jit closure capturing "
                 "array values constant-folds them into the program "
                 "(~1ulp drift vs the argument form, compile-memory "
                 "bloat, and a recompile per new constant — PR 2's "
                 "closure-constant weights)")
    hint = ("hoist the jit/pallas_call construction out of the loop; "
            "pass captured arrays (weights, mean/std) as arguments of "
            "the jitted function")

    def check(self, index: ProjectIndex,
              config: LintConfig) -> List[Violation]:
        out: List[Violation] = []
        for f in index.files:
            out.extend(self._jit_in_loop(f))
            out.extend(self._array_closures(f, config))
        return out

    # -- (a) jit/pallas_call constructed in a loop body ------------------
    def _jit_in_loop(self, f: FileCtx) -> List[Violation]:
        out = []
        for node, parents in _walk_with_parents(f.tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d not in _JIT_FUNCS and d not in _PALLAS_FUNCS:
                continue
            # nearest enclosing loop, unless a function boundary sits
            # between it and the call (then the loop doesn't re-run it)
            for p in reversed(parents):
                if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    break
                if isinstance(p, (ast.For, ast.While, ast.AsyncFor)):
                    out.append(self.v(
                        f, node.lineno,
                        f"`{d}(...)` constructed inside a loop body"))
                    break
        return out

    # -- (b) jit-wrapped defs closing over array-typed values ------------
    def _array_closures(self, f: FileCtx,
                        config: LintConfig) -> List[Violation]:
        out = []
        # map def name+lineno -> def node for jit-wrap resolution
        defs: Dict[str, List[ast.AST]] = {}
        for fn in _functions(f.tree):
            defs.setdefault(fn.name, []).append(fn)

        jitted: List[ast.AST] = []
        for fn in _functions(f.tree):
            for dec in fn.decorator_list:
                d = _dotted(dec if not isinstance(dec, ast.Call)
                            else dec.func)
                if d in _JIT_FUNCS:
                    jitted.append(fn)
                elif isinstance(dec, ast.Call) and d is not None and \
                        d.split(".")[-1] == "partial" and dec.args and \
                        _dotted(dec.args[0]) in _JIT_FUNCS:
                    jitted.append(fn)
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Call) and \
                    _dotted(node.func) in _JIT_FUNCS and node.args and \
                    isinstance(node.args[0], ast.Name):
                for cand in defs.get(node.args[0].id, []):
                    jitted.append(cand)

        for fn in jitted:
            for name, why in self._suspect_frees(f, fn, config):
                out.append(self.v(
                    f, fn.lineno,
                    f"jit-wrapped `{fn.name}` closes over array-typed "
                    f"`{name}` ({why}); it will constant-fold into the "
                    "compiled program"))
        return out

    def _suspect_frees(self, f: FileCtx, fn,
                       config: LintConfig) -> List[Tuple[str, str]]:
        table = self._find_table(f.symbols(), fn.name, fn.lineno)
        if table is None:
            return []
        frees = [s.get_name() for s in table.get_symbols()
                 if s.is_free()]
        if not frees:
            return []
        suspects: List[Tuple[str, str]] = []
        enclosing = self._enclosing_fn(f.tree, fn)
        suspect_names = set(config.array_suspect_names)
        for name in frees:
            if enclosing is None:
                continue
            # bound as a parameter of the enclosing function?
            args = enclosing.args
            param_names = [a.arg for a in
                           args.posonlyargs + args.args + args.kwonlyargs]
            if name in param_names and name in suspect_names:
                suspects.append(
                    (name, f"parameter of `{enclosing.name}` with an "
                           "array-suspect name"))
                continue
            # bound by an assignment from an array constructor?
            for stmt in _own_statements(enclosing):
                if isinstance(stmt, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == name
                        for t in stmt.targets):
                    val = stmt.value
                    d = _dotted(val.func) if isinstance(val, ast.Call) \
                        else None
                    if d is not None and _ARRAY_CTORS.match(d):
                        suspects.append(
                            (name, f"assigned from `{d}(...)` at line "
                                   f"{stmt.lineno}"))
                        break
        return suspects

    def _find_table(self, table, name: str, lineno: int):
        for child in table.get_children():
            if child.get_name() == name and child.get_lineno() == lineno:
                return child
            found = self._find_table(child, name, lineno)
            if found is not None:
                return found
        return None

    def _enclosing_fn(self, tree: ast.AST, target) -> Optional[ast.AST]:
        for node, parents in _walk_with_parents(tree):
            if node is target:
                for p in reversed(parents):
                    if isinstance(p, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        return p
                return None
        return None


# ---------------------------------------------------------------------------
# DFD005 — metric hygiene: registration uniqueness, reference resolution,
#           lock-guarded mutation
# ---------------------------------------------------------------------------

_METRIC_REF_RE = re.compile(r"^dfd_[a-z0-9_]*[a-z0-9]$")
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")
_REG_METHODS = {"counter", "gauge", "header", "histogram"}


class MetricHygiene(Rule):
    id = "DFD005"
    name = "metric-hygiene"
    bug_class = ("a metric name registered twice shadows itself on the "
                 "scrape; a referenced-but-unregistered name (typo) is a "
                 "silently dead dashboard/probe; a gauge mutated outside "
                 "its owning lock re-opens the PR 10 permanently-negative "
                 "inflight gauge")
    hint = ("register dfd_* names exactly once in their registry module; "
            "fuse gauge mutation with its ledger under the declared lock "
            "(see lint/manifest.py LOCK_GUARDED)")

    def check(self, index: ProjectIndex,
              config: LintConfig) -> List[Violation]:
        out: List[Violation] = []
        registered: Dict[str, Tuple[str, int]] = {}
        reg_literal_sites: Set[Tuple[str, int, str]] = set()
        dynamic_prefixes = set(config.metric_dynamic_prefixes)

        for relpath, prefix in sorted(config.metric_registries.items()):
            ctx = index.by_relpath.get(relpath)
            if ctx is None:
                continue
            for node in ast.walk(ctx.tree):
                # registration calls appear both as `doc.counter(...)` and
                # through local aliases (`counter, gauge = doc.counter,
                # doc.gauge; counter(...)`) — accept either form inside a
                # declared registry module
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Attribute):
                    meth = node.func.attr
                elif isinstance(node.func, ast.Name):
                    meth = node.func.id
                else:
                    continue
                if meth not in _REG_METHODS:
                    continue
                if not node.args:
                    continue
                name = _str_const(node.args[0])
                if name is None:
                    # registration from a runtime value: the whole prefix
                    # family becomes uncheckable statically
                    dynamic_prefixes.add(prefix + "_")
                    continue
                full = f"{prefix}_{name}"
                names = [full]
                if meth == "histogram":
                    names += [full + s for s in _HIST_SUFFIXES]
                reg_literal_sites.add((ctx.relpath, node.lineno, full))
                for n in names:
                    prev = registered.get(n)
                    if prev is not None and \
                            prev != (ctx.relpath, node.lineno):
                        out.append(self.v(
                            ctx, node.lineno,
                            f"metric `{n}` registered more than once "
                            f"(first at {prev[0]}:{prev[1]})"))
                    registered.setdefault(n, (ctx.relpath, node.lineno))

        # --- literal references must resolve ---------------------------
        prefixes = sorted(config.metric_registries.values(),
                          key=len, reverse=True)
        for f in index.files:
            for node in ast.walk(f.tree):
                s = _str_const(node)
                if s is None or not _METRIC_REF_RE.match(s):
                    continue
                pfx = next((p for p in prefixes
                            if s.startswith(p + "_")), None)
                if pfx is None:
                    continue
                if any(s.startswith(dp) for dp in dynamic_prefixes):
                    continue
                if (f.relpath, node.lineno, s) in reg_literal_sites:
                    continue
                base = s
                for suf in _HIST_SUFFIXES:
                    if s.endswith(suf) and s[:-len(suf)] in registered:
                        base = s[:-len(suf)]
                        break
                if base not in registered:
                    out.append(self.v(
                        f, node.lineno,
                        f"references unregistered metric `{s}` (typo'd "
                        "names scrape as silent zeros)"))

        # --- lock-guarded mutation --------------------------------------
        for relpath, attr, lock_attr in config.lock_guarded:
            ctx = index.by_relpath.get(relpath)
            if ctx is None:
                continue
            for node, parents in _walk_with_parents(ctx.tree):
                if not isinstance(node, (ast.Assign, ast.AugAssign)):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                if not any(isinstance(t, ast.Attribute) and t.attr == attr
                           for t in targets):
                    continue
                guarded = any(
                    isinstance(p, ast.With) and any(
                        isinstance(item.context_expr, ast.Attribute) and
                        item.context_expr.attr == lock_attr
                        for item in p.items)
                    for p in parents)
                if not guarded:
                    out.append(self.v(
                        ctx, node.lineno,
                        f"`{attr}` mutated outside `with {lock_attr}` — "
                        "the gauge and its ledger must move as one atom"))
        return out


# ---------------------------------------------------------------------------
# DFD006 — chaos points come from the declared registry
# ---------------------------------------------------------------------------

_CHAOS_SPEC_RE = re.compile(r"^([a-z][a-z0-9_]*)@\d+")


class ChaosRegistry(Rule):
    id = "DFD006"
    name = "chaos-registry"
    bug_class = ("a typo'd DFD_CHAOS point name — at a fires() probe or "
                 "in a harness spec literal — is a dead injection path: "
                 "the chaos scenario silently tests nothing")
    hint = ("add the point to KNOWN_POINTS in chaos.py (the one "
            "registry) or fix the name to match it")

    def check(self, index: ProjectIndex,
              config: LintConfig) -> List[Violation]:
        out: List[Violation] = []
        registry = self._load_registry(index, config)
        for f in index.files:
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "fires" and node.args:
                    name = _str_const(node.args[0])
                    if name is None:
                        continue
                    if registry is None:
                        out.append(self.v(
                            f, node.lineno,
                            f"chaos probe `fires({name!r})` but no "
                            f"{config.chaos_registry_name} registry is "
                            f"declared in {config.chaos_module}"))
                    elif name not in registry:
                        out.append(self.v(
                            f, node.lineno,
                            f"chaos point {name!r} not in "
                            f"{config.chaos_registry_name} — dead "
                            "injection path"))
                s = _str_const(node)
                if s is not None and registry is not None:
                    for part in s.split(","):
                        m = _CHAOS_SPEC_RE.match(part.strip())
                        if m and m.group(1) not in registry:
                            out.append(self.v(
                                f, node.lineno,
                                f"chaos spec names unknown point "
                                f"{m.group(1)!r} — dead injection path"))
        return out

    def _load_registry(self, index: ProjectIndex,
                       config: LintConfig) -> Optional[Set[str]]:
        ctx = index.by_relpath.get(config.chaos_module)
        if ctx is None:
            return None
        for stmt in _module_scope_statements(ctx.tree):
            if isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Name) and
                    t.id == config.chaos_registry_name
                    for t in stmt.targets):
                names: Set[str] = set()
                for node in ast.walk(stmt.value):
                    s = _str_const(node)
                    if s is not None:
                        names.add(s)
                return names
        return None


# ---------------------------------------------------------------------------
# DFD007 — JSONL event-writer discipline
# ---------------------------------------------------------------------------

class EventSchema(Rule):
    id = "DFD007"
    name = "event-schema"
    bug_class = ("a JSONL event stream without a schema stamp cannot be "
                 "versioned by readers; a write without the single-line+"
                 "flush idiom tears mid-kill into unparseable multi-record "
                 "fragments the torn-tail repair cannot fix")
    hint = ("stamp a 'schema' (or 'v') key into the record, serialize to "
            "ONE line, terminate with '\\n', and flush() after every "
            "write on long-lived handles (obs/events.py is the template)")

    def check(self, index: ProjectIndex,
              config: LintConfig) -> List[Violation]:
        out: List[Violation] = []
        for f in index.files:
            for fn in _functions(f.tree):
                out.extend(self._check_fn(f, fn))
        return out

    def _check_fn(self, f: FileCtx, fn) -> List[Violation]:
        out: List[Violation] = []
        stmts = list(_own_statements(fn))

        #: names assigned `x = json.dumps(...) + "\n"` → jsonl line
        jsonl_names: Dict[str, ast.Call] = {}
        #: names assigned from a dict literal → schema-checkable payloads
        dict_literals: Dict[str, ast.Dict] = {}
        has_flush = False
        with_managed: Set[str] = set()      # file handles from `with open`
        append_mode = False
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                for item in stmt.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Call) and \
                            _dotted(ce.func) in ("open", "io.open") and \
                            item.optional_vars is not None and \
                            isinstance(item.optional_vars, ast.Name):
                        with_managed.add(item.optional_vars.id)
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                tgt = stmt.targets[0].id
                dumps = self._jsonl_dumps(stmt.value)
                if dumps is not None:
                    jsonl_names[tgt] = dumps
                if isinstance(stmt.value, ast.Dict):
                    dict_literals[tgt] = stmt.value
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    d = _dotted(node.func)
                    if d is not None and (d.endswith(".flush") or
                                          d == "os.fsync"):
                        has_flush = True
                    if isinstance(node, ast.Call) and \
                            _dotted(node.func) in ("open", "io.open"):
                        mode = node.args[1] if len(node.args) > 1 else None
                        for kw in node.keywords:
                            if kw.arg == "mode":
                                mode = kw.value
                        ms = _str_const(mode) if mode is not None else None
                        if ms is not None and "a" in ms:
                            append_mode = True

        seen_writes: Set[int] = set()
        for stmt in stmts:
            for node in ast.walk(stmt):
                if not (isinstance(node, ast.Call) and
                        isinstance(node.func, ast.Attribute) and
                        node.func.attr == "write" and
                        len(node.args) == 1) or id(node) in seen_writes:
                    continue
                seen_writes.add(id(node))
                arg = node.args[0]
                writer = node.func.value
                writer_name = writer.id if isinstance(writer, ast.Name) \
                    else None
                dumps = self._jsonl_dumps(arg)
                plain = self._plain_dumps(arg)
                if dumps is None and isinstance(arg, ast.Name) and \
                        arg.id in jsonl_names:
                    dumps = jsonl_names[arg.id]
                if dumps is None and plain is None:
                    continue
                if dumps is None and plain is not None:
                    # json.dumps written with NO newline: a bug only for
                    # append-mode streams (whole-file snapshots are fine)
                    if append_mode:
                        out.append(self.v(
                            f, node.lineno,
                            "append-mode json.dumps write is not "
                            "newline-terminated — records will fuse"))
                    continue
                # it IS a jsonl write: flush discipline on long-lived
                # handles (with-managed handles flush at close)
                long_lived = writer_name not in with_managed
                if long_lived and not has_flush:
                    out.append(self.v(
                        f, node.lineno,
                        "JSONL write on a long-lived handle without a "
                        "flush() in the same function — a kill strands "
                        "buffered records"))
                payload = dumps.args[0] if dumps.args else None
                if isinstance(payload, ast.Name) and \
                        payload.id in dict_literals:
                    payload = dict_literals[payload.id]
                if isinstance(payload, ast.Dict):
                    keys = {_str_const(k) for k in payload.keys
                            if k is not None}
                    if not keys & {"schema", "v"}:
                        out.append(self.v(
                            f, node.lineno,
                            "JSONL record lacks a 'schema'/'v' stamp — "
                            "readers cannot version it"))
        return out

    def _jsonl_dumps(self, node: ast.AST) -> Optional[ast.Call]:
        """The json.dumps call of a `json.dumps(...) + "\\n"` expression."""
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            if _str_const(node.right) == "\n":
                return self._plain_dumps(node.left) or \
                    self._jsonl_dumps(node.left)
            if _str_const(node.left) == "\n":
                return self._plain_dumps(node.right) or \
                    self._jsonl_dumps(node.right)
        return None

    def _plain_dumps(self, node: ast.AST) -> Optional[ast.Call]:
        if isinstance(node, ast.Call) and \
                _dotted(node.func) in ("json.dumps", "dumps"):
            return node
        # json.dumps(...).encode() — byte-mode writers
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "encode":
            return self._plain_dumps(node.func.value)
        return None


# ---------------------------------------------------------------------------
# DFD008 — subprocess discipline in tools/
# ---------------------------------------------------------------------------

_RUN_FUNCS = {"subprocess.run", "subprocess.call", "subprocess.check_call",
              "subprocess.check_output"}


class SubprocessDiscipline(Rule):
    id = "DFD008"
    name = "subprocess-discipline"
    bug_class = ("a subprocess.run without timeout (or a Popen whose "
                 "owner never terminate/kills) hangs the calling tool "
                 "forever when the child wedges — the bench/chaos "
                 "harnesses must always converge")
    hint = ("pass timeout= to subprocess.run, or own the Popen with a "
            "terminate()->kill() escalation (tools/chaos_serve.py "
            "_terminate is the template)")

    def check(self, index: ProjectIndex,
              config: LintConfig) -> List[Violation]:
        out: List[Violation] = []
        for f in index.files:
            kills = any(
                isinstance(n, ast.Attribute) and
                n.attr in ("kill", "terminate", "send_signal")
                for n in ast.walk(f.tree))
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                if d in _RUN_FUNCS:
                    has_timeout = any(kw.arg == "timeout" or kw.arg is None
                                      for kw in node.keywords)
                    if not has_timeout:
                        out.append(self.v(
                            f, node.lineno,
                            f"`{d}(...)` without timeout= — a wedged "
                            "child hangs the tool forever"))
                elif d is not None and d.split(".")[-1] == "Popen" and \
                        (d.startswith("subprocess") or d == "Popen"):
                    if not kills:
                        out.append(self.v(
                            f, node.lineno,
                            "Popen in a module with no terminate()/kill() "
                            "escalation anywhere — orphaned children on "
                            "every failure path"))
        return out


# ---------------------------------------------------------------------------
# DFD009 — direct ctypes native bindings must probe the ABI version
# ---------------------------------------------------------------------------

class CtypesAbi(Rule):
    id = "DFD009"
    name = "ctypes-abi"
    bug_class = ("a hand-written ctypes binding of a dfd_* native symbol "
                 "outside data/native.py goes stale when the ABI bumps — "
                 "every argument silently shifts (the PR 6 ABI-3 "
                 "bench_gil incident) instead of failing loudly")
    hint = ("call lib.dfd_abi_version() and assert it against "
            "data/native.py's _ABI_VERSION before binding symbols (or "
            "go through data/native.py's wrappers)")

    def check(self, index: ProjectIndex,
              config: LintConfig) -> List[Violation]:
        out: List[Violation] = []
        exempt = set(config.ctypes_exempt)
        pfx = config.native_symbol_prefix
        for f in index.files:
            if f.relpath in exempt:
                continue
            loads = [n for n in ast.walk(f.tree)
                     if isinstance(n, ast.Call) and
                     _dotted(n.func) in ("ctypes.PyDLL", "ctypes.CDLL",
                                         "PyDLL", "CDLL",
                                         "ctypes.cdll.LoadLibrary")]
            if not loads:
                continue
            binds = [n for n in ast.walk(f.tree)
                     if isinstance(n, ast.Attribute) and
                     n.attr.startswith(pfx) and
                     n.attr != pfx + "abi_version"]
            probed = any(isinstance(n, ast.Attribute) and
                         n.attr == pfx + "abi_version"
                         for n in ast.walk(f.tree))
            if binds and not probed:
                out.append(self.v(
                    f, loads[0].lineno,
                    f"direct ctypes load binds `{pfx}*` symbols without "
                    f"a `{pfx}abi_version()` probe — a stale binding "
                    "shifts every argument"))
        return out


# ---------------------------------------------------------------------------
# DFD010 — sharding hygiene: no bare pmap/shard_map outside the allowlist
# ---------------------------------------------------------------------------

_MANUAL_SPMD = {"shard_map", "pmap"}


class ShardingHygiene(Rule):
    id = "DFD010"
    name = "sharding-hygiene"
    bug_class = ("a bare pmap/shard_map re-forks the per-topology dispatch "
                 "the ISSUE 12 GSPMD migration removed: the program stops "
                 "scaling by mesh shape under plain jit, and every "
                 "subsystem layered on the train step (resilience, "
                 "telemetry, device-augment prologue) needs a second "
                 "proof for the manual-SPMD fork")
    hint = ("express the computation as plain jax.jit with NamedSharding/"
            "with_sharding_constraint over the unified mesh "
            "(parallel/mesh.py make_train_mesh + "
            "parallel/sharding.py train_state_shardings); genuinely "
            "manual-SPMD modules (collective-permute rings, pipeline "
            "stages) ride lint/manifest.py SHARD_MAP_ALLOWLIST until "
            "their own migration")

    def check(self, index: ProjectIndex,
              config: LintConfig) -> List[Violation]:
        out: List[Violation] = []
        allow = set(config.shard_map_allowlist)
        used_allow: Set[str] = set()

        # REFERENCE-level matching, not just calls: `@jax.pmap`
        # decorators, `functools.partial(jax.pmap, ...)` arguments and
        # stored handles are all the same manual-SPMD re-entry.  Any
        # Name/Attribute whose leaf IS pmap/shard_map counts (imports
        # produce ast.alias nodes, not Names, so `from jax import
        # shard_map` by itself does not fire — using it does).
        for f in index.files:
            seen = set()
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Attribute):
                    leaf = node.attr
                elif isinstance(node, ast.Name):
                    leaf = node.id
                else:
                    continue
                if leaf not in _MANUAL_SPMD:
                    continue
                if f.relpath in allow:
                    used_allow.add(f.relpath)
                    continue
                if (node.lineno, leaf) in seen:   # call = Name + Call
                    continue
                seen.add((node.lineno, leaf))
                out.append(self.v(
                    f, node.lineno,
                    f"bare `{leaf}` reference outside the legacy "
                    "allowlist — new code goes through the unified GSPMD "
                    "path (NamedSharding under plain jit)"))
        # allowlist rot, same contract as baseline entries: an entry whose
        # file no longer calls pmap/shard_map (the debt was paid) must be
        # deleted from the manifest or the gate fails.  Judged only for
        # files actually IN this run's index — a subset run
        # (`dfdlint deepfake_detection_tpu/data`) must not call entries
        # it never looked at rotten.
        indexed = allow & set(index.by_relpath)
        for entry in sorted(indexed - used_allow):
            out.append(self.v(
                entry, 1,
                "lint/manifest.py SHARD_MAP_ALLOWLIST entry matches no "
                "pmap/shard_map call in this file (rot) — remove it"))
        return out


# ---------------------------------------------------------------------------

ALL_RULES: Tuple[Rule, ...] = (
    JaxPurity(), DonationAliasing(), RngDiscipline(), RecompileHygiene(),
    MetricHygiene(), ChaosRegistry(), EventSchema(),
    SubprocessDiscipline(), CtypesAbi(), ShardingHygiene(),
)


def rule_catalog() -> List[Dict[str, str]]:
    """id/name/bug-class/hint table for ``--list-rules`` and the README."""
    return [{"id": r.id, "name": r.name, "bug_class": r.bug_class,
             "hint": r.hint} for r in ALL_RULES]
