"""dfdlint — in-repo static analysis enforcing the stack's hard-won invariants.

Three of the worst bugs in this repo's history were invisible to tests
until they crashed a whole pytest process or silently corrupted state:
donated-buffer use-after-free on zero-copy resume views (CHANGES.md PR
2/PR 3), closure-captured weights constant-folding into jit programs
(PR 2), and a split lock around a gauge bump/decrement that left the
in-flight gauge permanently negative (PR 10).  Every one is a statically
detectable *pattern*; this package encodes them as rules that run on
every change (``tools/dfdlint.py``, ``scripts/lint.sh``, and the
``tests/test_lint.py`` gate).

Deliberately jax-free and stdlib-only (``ast`` + ``symtable``): the
linter must be importable and fast in any subprocess — the same
discipline its own DFD001 rule enforces on the data/obs/tools modules.

Layout:

* :mod:`core`     — file indexing, suppressions, baseline, the runner
* :mod:`manifest` — the declarative project manifest the rules consume
* :mod:`rules`    — DFD001..DFD009 implementations

Per-line suppression::

    something_flagged()   # dfdlint: disable=DFD003  -- why it is safe

or on a standalone comment line directly above the flagged line.
Pre-existing debt is frozen in ``tools/dfdlint_baseline.json``; new
violations fail.  Both suppressions and baseline entries are themselves
checked: an entry that no longer matches any violation is reported as
rot (``--strict`` / the gate test fail on it), so neither can silently
outlive the code it excused.
"""

from .core import (BaselineEntry, FileCtx, LintConfig, LintResult,
                   ProjectIndex, Violation, load_baseline, run_lint,
                   save_baseline)
from .manifest import default_config
from .rules import ALL_RULES, rule_catalog

__all__ = [
    "ALL_RULES", "BaselineEntry", "FileCtx", "LintConfig", "LintResult",
    "ProjectIndex", "Violation", "default_config", "load_baseline",
    "rule_catalog", "run_lint", "save_baseline",
]
