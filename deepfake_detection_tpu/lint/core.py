"""dfdlint core: file indexing, suppressions, baseline, and the runner.

Everything here is rule-agnostic.  A lint run is::

    index  = ProjectIndex.build(paths, repo_root)
    result = run_lint(index, config)

``run_lint`` executes every rule, drops violations carrying a per-line
``# dfdlint: disable=RULE`` suppression, subtracts the frozen baseline,
and reports *rot* in both directions: suppression comments that suppress
nothing and baseline entries that match nothing.  Rot is an error under
``--strict`` (and in the tests/test_lint.py gate) so neither mechanism
can silently outlive the code it excused.

Baseline identity is ``(rule, path, stripped line text)`` rather than a
line *number*: edits elsewhere in a file must not invalidate frozen
entries, while editing the offending line itself (the moment the debt is
actually touched) surfaces the violation again.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import symtable
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Violation", "FileCtx", "ProjectIndex", "LintConfig",
           "BaselineEntry", "LintResult", "load_baseline", "save_baseline",
           "run_lint"]

_SUPPRESS_RE = re.compile(r"#\s*dfdlint:\s*disable=([A-Z0-9,\s]+)")


# ---------------------------------------------------------------------------
# data model
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Violation:
    """One rule finding, anchored to a repo-relative path and 1-based line."""
    rule: str
    path: str
    line: int
    message: str
    hint: str = ""

    def format(self, fix_hints: bool = False) -> str:
        s = f"{self.path}:{self.line}: {self.rule} {self.message}"
        if fix_hints and self.hint:
            s += f"\n    fix: {self.hint}"
        return s


@dataclasses.dataclass
class BaselineEntry:
    """Frozen pre-existing debt: matches up to ``count`` violations of
    ``rule`` in ``path`` whose stripped source line equals ``line_text``."""
    rule: str
    path: str
    line_text: str
    count: int = 1
    justification: str = ""

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.line_text)


@dataclasses.dataclass
class LintResult:
    violations: List[Violation]            # new (post-suppress, post-baseline)
    suppressed: List[Violation]            # dropped by inline comments
    baselined: List[Violation]             # dropped by baseline entries
    unused_suppressions: List[Tuple[str, int, str]]   # (path, line, rule)
    unused_baseline: List[BaselineEntry]   # entries that matched nothing

    @property
    def clean(self) -> bool:
        return not self.violations

    @property
    def strict_clean(self) -> bool:
        return (not self.violations and not self.unused_suppressions
                and not self.unused_baseline)


# ---------------------------------------------------------------------------
# file context + project index
# ---------------------------------------------------------------------------

class FileCtx:
    """One parsed source file: AST, lines, module name, suppressions."""

    def __init__(self, abspath: str, relpath: str, source: str):
        self.abspath = abspath
        self.relpath = relpath            # posix, repo-root-relative
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self.module = _module_name(relpath)
        #: line (1-based) -> set of rule ids disabled on that line.
        #: Scanned from real COMMENT tokens so a docstring *describing*
        #: the suppression syntax can't accidentally enact it.
        self.suppressions: Dict[int, set] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(source).readline):
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if m:
                    rules = {r.strip() for r in m.group(1).split(",")
                             if r.strip()}
                    self.suppressions.setdefault(
                        tok.start[0], set()).update(rules)
        except tokenize.TokenError:
            pass                          # unparseable tail: no comments
        self._symtable = None

    # symtable is built lazily — only rules that need scope analysis
    # (DFD004) pay for it, and only on files they inspect
    def symbols(self):
        if self._symtable is None:
            self._symtable = symtable.symtable(
                self.source, self.relpath, "exec")
        return self._symtable

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def suppressed_rules_at(self, line: int) -> set:
        """Rules disabled at ``line``: an inline comment on the line itself,
        or a standalone ``# dfdlint: disable=...`` comment directly above."""
        rules = set(self.suppressions.get(line, ()))
        above = line - 1
        if above in self.suppressions and \
                self.line_text(above).startswith("#"):
            rules |= self.suppressions[above]
        return rules


def _module_name(relpath: str) -> str:
    parts = relpath.replace(os.sep, "/").split("/")
    if parts[-1] == "__init__.py":
        parts = parts[:-1]
    elif parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    return ".".join(parts)


class ProjectIndex:
    """All files of one lint run + a module-name → file lookup."""

    def __init__(self, files: List[FileCtx], repo_root: str):
        self.files = files
        self.repo_root = repo_root
        self.by_module: Dict[str, FileCtx] = {f.module: f for f in files}
        self.by_relpath: Dict[str, FileCtx] = {f.relpath: f for f in files}

    @classmethod
    def build(cls, paths: Sequence[str], repo_root: str,
              skip_dirs: Iterable[str] = ("__pycache__", ".git",
                                          ".claude")) -> "ProjectIndex":
        repo_root = os.path.abspath(repo_root)
        seen: Dict[str, None] = {}
        skip = set(skip_dirs)
        for p in paths:
            p = p if os.path.isabs(p) else os.path.join(repo_root, p)
            if os.path.isfile(p) and p.endswith(".py"):
                seen.setdefault(os.path.abspath(p))
                continue
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in skip and
                                     not d.startswith("."))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        seen.setdefault(os.path.join(dirpath, fn))
        files = []
        for abspath in seen:
            rel = os.path.relpath(abspath, repo_root).replace(os.sep, "/")
            with open(abspath, encoding="utf-8") as f:
                source = f.read()
            try:
                files.append(FileCtx(abspath, rel, source))
            except SyntaxError as e:
                # a file the interpreter cannot parse is its own violation;
                # surface it instead of crashing the run
                bad = FileCtx.__new__(FileCtx)
                bad.abspath, bad.relpath, bad.source = abspath, rel, source
                bad.lines = source.splitlines()
                bad.tree = ast.Module(body=[], type_ignores=[])
                bad.module = _module_name(rel)
                bad.suppressions = {}
                bad._symtable = None
                bad.parse_error = e
                files.append(bad)
        return cls(files, repo_root)


# ---------------------------------------------------------------------------
# config (populated from manifest.py; fixtures override)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class LintConfig:
    """Declarative manifest the rules consume.  Defaults live in
    :mod:`deepfake_detection_tpu.lint.manifest`; fixture tests construct
    their own pointing at a tmp tree."""
    # DFD001
    jax_free_modules: Tuple[str, ...] = ()
    banned_import_roots: Tuple[str, ...] = (
        "jax", "jaxlib", "flax", "optax", "chex", "orbax")
    # DFD002
    donating_factories: Dict[str, Tuple[int, ...]] = \
        dataclasses.field(default_factory=dict)
    thread_escape_callees: Tuple[str, ...] = (
        "Thread", "submit", "apply_async", "start_soon")
    # DFD003
    rng_dirs: Tuple[str, ...] = ()
    # DFD004
    array_suspect_names: Tuple[str, ...] = (
        "params", "variables", "weights", "batch_stats", "opt_state",
        "ema", "mean", "std")
    # DFD005
    metric_registries: Dict[str, str] = \
        dataclasses.field(default_factory=dict)       # relpath -> prefix
    metric_dynamic_prefixes: Tuple[str, ...] = ()
    lock_guarded: Tuple[Tuple[str, str, str], ...] = ()
    # DFD006
    chaos_module: str = ""                            # relpath of registry
    chaos_registry_name: str = "KNOWN_POINTS"
    # DFD009
    ctypes_exempt: Tuple[str, ...] = ()
    native_symbol_prefix: str = "dfd_"
    # DFD010
    shard_map_allowlist: Tuple[str, ...] = ()


# ---------------------------------------------------------------------------
# baseline I/O
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> List[BaselineEntry]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("version") != 1:
        raise ValueError(f"{path}: unsupported baseline version "
                         f"{doc.get('version')!r}")
    return [BaselineEntry(**e) for e in doc.get("entries", [])]


def save_baseline(path: str, entries: Sequence[BaselineEntry]) -> None:
    doc = {
        "version": 1,
        "comment": "dfdlint frozen debt: each entry matches up to `count` "
                   "violations of `rule` in `path` on lines whose stripped "
                   "text equals `line_text`.  Entries need a written "
                   "justification; unmatched entries fail --strict (rot).",
        "entries": [dataclasses.asdict(e) for e in sorted(
            entries, key=lambda e: (e.path, e.rule, e.line_text))],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")


# ---------------------------------------------------------------------------
# the runner
# ---------------------------------------------------------------------------

def run_lint(index: ProjectIndex, config: LintConfig,
             baseline: Sequence[BaselineEntry] = (),
             rules: Optional[Sequence] = None,
             honor_suppressions: bool = True) -> LintResult:
    from .rules import ALL_RULES
    active = list(rules) if rules is not None else list(ALL_RULES)

    raw: List[Violation] = []
    for f in index.files:
        err = getattr(f, "parse_error", None)
        if err is not None:
            raw.append(Violation("DFD000", f.relpath,
                                 err.lineno or 1,
                                 f"file does not parse: {err.msg}",
                                 "fix the syntax error"))
    for rule in active:
        raw.extend(rule.check(index, config))
    raw.sort(key=lambda v: (v.path, v.line, v.rule))

    # --- inline suppressions -------------------------------------------
    kept: List[Violation] = []
    suppressed: List[Violation] = []
    used_suppressions: set = set()        # (path, line-of-comment, rule)
    if honor_suppressions:
        for v in raw:
            ctx = index.by_relpath.get(v.path)
            hit = False
            if ctx is not None:
                for cl in (v.line, v.line - 1):
                    if v.rule in ctx.suppressions.get(cl, set()) and \
                            v.rule in ctx.suppressed_rules_at(v.line):
                        used_suppressions.add((v.path, cl, v.rule))
                        hit = True
                        break
            (suppressed if hit else kept).append(v)
    else:
        kept = list(raw)

    # rot is only judged for the rules that actually ran: a filtered
    # `--rules DFD003` run must not call a DFD004 suppression/baseline
    # entry unused just because its rule never executed
    active_ids = {r.id for r in active}
    unused_suppressions: List[Tuple[str, int, str]] = []
    if honor_suppressions:
        for f in index.files:
            for line, rule_ids in sorted(f.suppressions.items()):
                for rid in sorted(rule_ids & active_ids):
                    if (f.relpath, line, rid) not in used_suppressions:
                        unused_suppressions.append((f.relpath, line, rid))

    # --- baseline ------------------------------------------------------
    budget: Dict[Tuple[str, str, str], int] = {}
    for e in baseline:
        budget[e.key()] = budget.get(e.key(), 0) + e.count
    matched: Dict[Tuple[str, str, str], int] = {}
    new: List[Violation] = []
    baselined: List[Violation] = []
    for v in kept:
        ctx = index.by_relpath.get(v.path)
        text = ctx.line_text(v.line) if ctx is not None else ""
        key = (v.rule, v.path, text)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            matched[key] = matched.get(key, 0) + 1
            baselined.append(v)
        else:
            new.append(v)
    unused = [e for e in baseline
              if e.rule in active_ids and matched.get(e.key(), 0) == 0]

    return LintResult(violations=new, suppressed=suppressed,
                      baselined=baselined,
                      unused_suppressions=unused_suppressions,
                      unused_baseline=unused)
