"""Live stream migration: drain a replica by moving its sessions.

Built on PR 10's session-durability machinery, extended from *restart*
to *migration*: the streaming server's ``POST /streams/<id>/migrate``
exports a session as the exact ``dfd.streaming.session_state.v1``
snapshot a ``--state-dir`` shutdown would have written (quiesced, with
in-flight windows booked dropped so per-stream books balance), and
``POST /streams/restore`` rebuilds the session — verdict machines,
tracker, window buffers, counters, event tail — on another replica.
Restart resume is bit-identical by PR 10's proof; migration rides the
SAME snapshot/restore code path, and tools/chaos_serve.py's
``replica_migrate`` scenario proves the migrated stream's verdict event
log bit-identical (order-normalized) to an unmigrated replay.

Failure contract (the README failure-mode table's migration-abort row):
a session is NEVER silently lost mid-move.  If the target restore
fails, the state restores back onto the source (still alive — it was
draining, not dead); if even that fails, the snapshot is dumped to a
``.state.json.bad`` file next to the router log and counted in
``dfd_router_migration_aborts_total``.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import time
from typing import Dict, List, Optional

from .controller import http_request
from .metrics import RouterMetrics
from .registry import Registry

_logger = logging.getLogger(__name__)

__all__ = ["drain_replica", "undrain_replica", "migrate_stream",
           "list_streams"]


def list_streams(netloc: str, timeout_s: float = 5.0) -> List[str]:
    status, _, body = http_request(netloc, "GET", "/streams",
                                   timeout=timeout_s)
    if status != 200:
        raise OSError(f"GET /streams on {netloc} returned {status}")
    return list(json.loads(body).get("streams", []))


def _restore(netloc: str, state: dict, timeout_s: float) -> None:
    status, _, body = http_request(
        netloc, "POST", "/streams/restore",
        json.dumps(state, sort_keys=True).encode(),
        {"Content-Type": "application/json"}, timeout=timeout_s)
    if status not in (200, 201):
        raise OSError(f"restore on {netloc} returned {status}: "
                      f"{body[:200]!r}")


def migrate_stream(registry: Registry, metrics: RouterMetrics,
                   stream_id: str, source_id: str, target_id: str,
                   timeout_s: float = 30.0) -> bool:
    """Move one live session ``source → target``; True on success.

    Export quiesces + detaches the session on the source (the replica
    side owes nothing to this stream afterwards), restore rebuilds it on
    the target, and the registry override re-pins the stream's routing.
    """
    status, _, body = http_request(
        registry.get(source_id).netloc, "POST",
        f"/streams/{stream_id}/migrate", b"", timeout=timeout_s)
    if status != 200:
        raise OSError(f"export of stream {stream_id!r} on {source_id} "
                      f"returned {status}: {body[:200]!r}")
    state = json.loads(body)
    try:
        _restore(registry.get(target_id).netloc, state, timeout_s)
    except (OSError, ValueError) as e:
        _logger.error("stream %s: restore on target %s failed (%s); "
                      "restoring back on source %s", stream_id,
                      target_id, e, source_id)
        metrics.migration_aborts_total.inc()
        try:
            _restore(registry.get(source_id).netloc, state, timeout_s)
            # routing truth: the session is back on the SOURCE.  If the
            # source is the ring home no override is needed; if the
            # source was itself a migration target (a second drain), the
            # override must keep pointing AT it — clearing would strand
            # the session behind the ring home
            if registry.ring.assign(stream_id) == source_id:
                registry.clear_override(stream_id)
            else:
                registry.set_override(stream_id, source_id)
        except (OSError, ValueError):
            # last resort: the snapshot goes to disk, loudly — a session
            # must never be silently lost mid-move
            path = os.path.join(
                tempfile.gettempdir(),
                f"dfd-migrate-{stream_id}.state.json.bad")
            with open(path, "w") as f:
                f.write(json.dumps(state, sort_keys=True))
            _logger.error("stream %s: source restore ALSO failed; "
                          "snapshot dumped to %s", stream_id, path)
        return False
    registry.set_override(stream_id, target_id)
    metrics.streams_migrated_total.inc()
    _logger.info("stream %s migrated %s -> %s (%d windows scored)",
                 stream_id, source_id, target_id,
                 int(state.get("counters", {}).get("windows_scored", 0)))
    return True


def drain_replica(registry: Registry, metrics: RouterMetrics,
                  replica_id: str, timeout_s: float = 30.0
                  ) -> Dict[str, object]:
    """Drain one replica: stop routing new traffic to it, then migrate
    each of its live streams to its ring successor.

    The replica keeps serving its in-flight work (it is draining, not
    dead); streams move one at a time so a mid-drain failure leaves
    every session either still on the source or fully restored on its
    target — never in between.  Returns a report dict (also the HTTP
    response body of ``POST /replicas/<id>/drain``).
    """
    src = registry.get(replica_id)
    if src is None:
        raise KeyError(f"unknown replica {replica_id!r}")
    src.draining = True
    metrics.drains_total.inc()
    metrics.set_fleet_gauges(registry.counts())
    t0 = time.monotonic()
    migrated: List[str] = []
    failed: List[str] = []
    skipped: List[str] = []
    try:
        streams = list_streams(src.netloc, timeout_s)
    except OSError as e:
        # a dead replica has nothing to export; its streams come back via
        # --state-dir restore when it relaunches (the replica-kill path)
        return {"replica": replica_id, "draining": True,
                "streams": 0, "migrated": [], "failed": [],
                "skipped": [], "error": f"cannot list streams: {e}"}
    for sid in streams:
        target_id = registry.ring.assign(
            sid, eligible={r.id for r in registry.eligible({replica_id})})
        if target_id is None:
            _logger.warning("stream %s: no eligible migration target; "
                            "leaving it on draining %s", sid, replica_id)
            skipped.append(sid)
            continue
        try:
            ok = migrate_stream(registry, metrics, sid, replica_id,
                                target_id, timeout_s)
        except (OSError, ValueError) as e:
            _logger.error("stream %s: migration failed before export "
                          "completed (%s)", sid, e)
            metrics.migration_aborts_total.inc()
            ok = False
        (migrated if ok else failed).append(sid)
    return {"replica": replica_id, "draining": True,
            "streams": len(streams), "migrated": migrated,
            "failed": failed, "skipped": skipped,
            "elapsed_s": round(time.monotonic() - t0, 3)}


def undrain_replica(registry: Registry, metrics: RouterMetrics,
                    replica_id: str) -> Dict[str, object]:
    """Return a drained replica to rotation (overrides written by its
    drain stay — migrated sessions live where they were restored)."""
    r = registry.get(replica_id)
    if r is None:
        raise KeyError(f"unknown replica {replica_id!r}")
    r.draining = False
    metrics.set_fleet_gauges(registry.counts())
    return {"replica": replica_id, "draining": False}
