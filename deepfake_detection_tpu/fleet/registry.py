"""Replica registry: consistent-hash stream affinity + health-derived
routing eligibility.

The fleet's routing truth lives here, deliberately jax-free and
stdlib-pure (the router tier must never pay — or wait on — an
accelerator import):

* :class:`HashRing` — classic consistent hashing with virtual nodes.
  Stream ids map to replicas through md5 points on a ring, so the
  assignment is a **pure function of the registered replica ids**:
  deterministic across router restarts (a rebooted router sends every
  live stream straight back to the replica that holds its session), and
  adding/removing one replica remaps only the key ranges adjacent to its
  virtual nodes — removal remaps EXACTLY the removed replica's keys,
  addition remaps ~1/N of everyone else's (both asserted in
  tests/test_fleet.py over 1k synthetic stream ids).

* :class:`Replica` — one backend's routing state, derived ENTIRELY from
  signals the serve/stream stack already exports: ``/readyz`` (incl. the
  per-model JSON detail), breaker state + queue depth + inflight scraped
  off ``/metrics``, and the ``Retry-After`` of its own sheds.  The
  router adds exactly one piece of its own state, ``router_inflight``
  (proxied requests outstanding), so least-depth routing self-balances
  between scrapes.

* :class:`Registry` — the table the router routes over: stateless
  requests go to the eligible replica with the least total depth,
  ``/streams/*`` requests follow the ring (or a migration override — a
  drained replica's streams re-pin to their migration target), and a
  replica that shed with ``Retry-After: n`` is skipped for the next
  ``n`` seconds before any failover hits it again.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["HashRing", "Replica", "Registry", "normalize_netloc"]


def _point(s: str) -> int:
    """Ring coordinate of a string: the top 8 bytes of its md5.  md5 is
    used as a uniform hash, not for security — and unlike ``hash()`` it
    is stable across interpreter restarts (PYTHONHASHSEED), which is
    what makes stream→replica assignment restart-deterministic."""
    return int.from_bytes(hashlib.md5(s.encode()).digest()[:8], "big")


def normalize_netloc(url: str) -> str:
    """``http://host:port/`` / ``host:port`` → ``host:port`` (the
    replica id and dial address are the same string)."""
    u = url.strip()
    for prefix in ("http://", "https://"):
        if u.startswith(prefix):
            u = u[len(prefix):]
    u = u.rstrip("/")
    if not u or ":" not in u:
        raise ValueError(f"replica url {url!r} must carry host:port")
    host, port = u.rsplit(":", 1)
    if not host or not port.isdigit():
        raise ValueError(f"replica url {url!r} must carry host:port")
    return u


class HashRing:
    """Consistent hashing over replica ids with ``vnodes`` virtual nodes
    per replica.  Not thread-safe on its own; :class:`Registry` owns the
    lock."""

    def __init__(self, replica_ids: Iterable[str] = (), vnodes: int = 64):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = int(vnodes)
        self._points: List[Tuple[int, str]] = []
        for rid in replica_ids:
            self.add(rid)

    def add(self, replica_id: str) -> None:
        for i in range(self.vnodes):
            bisect.insort(self._points,
                          (_point(f"{replica_id}#{i}"), replica_id))

    def remove(self, replica_id: str) -> None:
        self._points = [(p, r) for p, r in self._points
                        if r != replica_id]

    def ids(self) -> Set[str]:
        return {r for _, r in self._points}

    def assign(self, key: str,
               eligible: Optional[Set[str]] = None) -> Optional[str]:
        """First replica at/after ``key``'s ring point.  With
        ``eligible``, walk past ineligible replicas — keys homed on an
        eligible replica keep their assignment, only the ineligible
        replicas' ranges move (the bounded-churn property)."""
        if not self._points:
            return None
        i = bisect.bisect_left(self._points, (_point(key), ""))
        n = len(self._points)
        for step in range(n):
            _, rid = self._points[(i + step) % n]
            if eligible is None or rid in eligible:
                return rid
        return None


class Replica:
    """One backend's routing state (mutated by the health scraper and
    the router under the registry lock)."""

    __slots__ = ("id", "netloc", "healthy", "ready", "warming",
                 "draining", "breaker_state", "queue_depth", "inflight",
                 "router_inflight", "backoff_until",
                 "consecutive_failures", "exposition", "readiness",
                 "last_scrape_t", "process", "born_t", "ever_up")

    def __init__(self, url: str, process=None):
        self.netloc = normalize_netloc(url)
        self.id = self.netloc
        self.healthy = False         # scrape reaches the process
        self.ready = False           # /readyz said 200
        self.warming = False         # cold model warming (parseable 503
        # /readyz, or a just-spawned child whose port is not bound yet):
        # NOT down — the autoscaler must never retire a replica it just
        # spawned, and must count it toward capacity in flight
        self.draining = False        # operator drain: no new traffic
        self.breaker_state = 0       # scraped dfd_serving_breaker_state
        self.queue_depth = 0         # scraped dfd_serving_queue_depth
        self.inflight = 0            # scraped dfd_serving_inflight
        self.router_inflight = 0     # proxied requests outstanding HERE
        self.backoff_until = 0.0     # honoring the replica's Retry-After
        self.consecutive_failures = 0
        self.exposition: Optional[str] = None   # last /metrics text
        self.readiness: Optional[dict] = None   # last /readyz JSON detail
        self.last_scrape_t = 0.0
        self.process = process       # controller-spawned child (or None)
        self.born_t = time.monotonic()          # registration time: the
        # scraper's spawn-grace window is measured from here
        self.ever_up = False         # a scrape has succeeded at least
        # once (a replica that WAS up and stops answering is down, not
        # warming — the grace window only shields cold starts)

    def depth(self) -> int:
        """Load signal for least-depth routing: the replica's own queue
        + staged requests plus what this router has in flight to it."""
        return int(self.queue_depth) + int(self.inflight) + \
            int(self.router_inflight)

    def eligible(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        return (self.healthy and self.ready and not self.draining
                and now >= self.backoff_until)

    def summary(self) -> dict:
        return {
            "id": self.id,
            "healthy": self.healthy,
            "ready": self.ready,
            "warming": self.warming,
            "draining": self.draining,
            "eligible": self.eligible(),
            "breaker_state": self.breaker_state,
            "queue_depth": self.queue_depth,
            "inflight": self.inflight,
            "router_inflight": self.router_inflight,
            "backoff_s": max(0.0, self.backoff_until - time.monotonic()),
            "consecutive_scrape_failures": self.consecutive_failures,
            "models": (self.readiness or {}).get("models"),
        }


class Registry:
    """The routing table: replicas + ring + migration overrides."""

    def __init__(self, urls: Iterable[str] = (), vnodes: int = 64):
        self._lock = threading.Lock()
        self.replicas: Dict[str, Replica] = {}
        self.ring = HashRing(vnodes=vnodes)
        #: stream id → replica id, written by migration (a drained
        #: replica's streams re-pin here); consulted before the ring
        self.overrides: Dict[str, str] = {}
        self._rr = 0                 # least-depth tiebreak rotation
        #: immutable membership snapshot for the lock-free hot path
        #: (the event-loop data plane routes off this tuple: a plain
        #: attribute read is atomic under the GIL, so the loop thread
        #: never takes the registry lock per request)
        self._view: Tuple[Replica, ...] = ()
        #: bumped on every membership/health edge (add/remove/down);
        #: upstream connection pools key their prune passes off it, so
        #: a retired or down-marked replica's pooled sockets are closed
        #: instead of leaking for the pool owner's lifetime
        self.generation = 0
        for url in urls:
            self.add(url)

    def _rebuild_view_locked(self) -> None:
        self._view = tuple(self.replicas[k] for k in sorted(self.replicas))
        self.generation += 1

    def bump_generation(self) -> None:
        """Signal pool owners that membership/health changed (the health
        scraper calls this on a healthy→down edge)."""
        with self._lock:
            self.generation += 1

    def view(self) -> Tuple[Replica, ...]:
        """Immutable membership snapshot (lock-free read)."""
        return self._view

    # ------------------------------------------------------------------
    def add(self, url: str, process=None) -> Replica:
        r = Replica(url, process=process)
        with self._lock:
            if r.id in self.replicas:
                raise ValueError(f"replica {r.id!r} already registered")
            self.replicas[r.id] = r
            self.ring.add(r.id)
            self._rebuild_view_locked()
        return r

    def remove(self, replica_id: str) -> Optional[Replica]:
        with self._lock:
            r = self.replicas.pop(replica_id, None)
            if r is not None:
                self.ring.remove(replica_id)
                self.overrides = {sid: rid for sid, rid
                                  in self.overrides.items()
                                  if rid != replica_id}
                self._rebuild_view_locked()
        return r

    def get(self, replica_id: str) -> Optional[Replica]:
        with self._lock:
            return self.replicas.get(replica_id)

    def all(self) -> List[Replica]:
        with self._lock:
            return [self.replicas[k] for k in sorted(self.replicas)]

    def ids(self) -> List[str]:
        with self._lock:
            return sorted(self.replicas)

    # ------------------------------------------------------------------
    # routing
    # ------------------------------------------------------------------
    def eligible(self, exclude: Set[str] = frozenset()) -> List[Replica]:
        now = time.monotonic()
        with self._lock:
            return [r for k, r in sorted(self.replicas.items())
                    if k not in exclude and r.eligible(now)]

    def pick_stateless(self,
                       exclude: Set[str] = frozenset()
                       ) -> Optional[Replica]:
        """Least-depth eligible replica (stable rotation between equal
        depths so idle fleets spread instead of pinning to one id)."""
        cands = self.eligible(exclude)
        if not cands:
            return None
        lowest = min(r.depth() for r in cands)
        tied = [r for r in cands if r.depth() == lowest]
        with self._lock:
            self._rr += 1
            return tied[self._rr % len(tied)]

    def pick_stateless_fast(self,
                            exclude: Set[str] = frozenset()
                            ) -> Optional[Replica]:
        """Lock-free :meth:`pick_stateless` for the event-loop data
        plane's hot path.  Iterates the immutable membership snapshot
        (``view()``); eligibility/depth are plain attribute reads (each
        atomic under the GIL).  Membership changes land as a whole new
        tuple, so the worst concurrent-mutation outcome is routing one
        request on a one-snapshot-stale view — never a torn read."""
        now = time.monotonic()
        best: Optional[Replica] = None
        best_depth = -1
        tied: List[Replica] = []
        for r in self._view:
            if r.id in exclude or not r.eligible(now):
                continue
            d = r.depth()
            if best is None or d < best_depth:
                best, best_depth, tied = r, d, [r]
            elif d == best_depth:
                tied.append(r)
        if best is None:
            return None
        # unlocked rotation: a lost update costs one repeated tiebreak
        # pick, not correctness
        self._rr += 1
        return tied[self._rr % len(tied)]

    def pick_stream_fast(self, stream_id: str
                         ) -> Tuple[Optional[Replica], bool]:
        """Lock-free :meth:`pick_stream` (overrides dict get + ring walk
        are individually atomic; membership churn mid-read can at worst
        route one request on a stale assignment, matching what a
        one-scrape-stale threads-plane pick already allows)."""
        rid = self.overrides.get(stream_id)
        migrated = rid is not None
        if rid is None:
            rid = self.ring.assign(stream_id)
        r = self.replicas.get(rid) if rid is not None else None
        return r, migrated

    def pick_stream(self, stream_id: str
                    ) -> Tuple[Optional[Replica], bool]:
        """(replica, migrated) for one stream request.

        A migration override (the stream was moved off a draining
        replica) wins; otherwise the ring's home assignment over ALL
        registered replicas — deterministic across router restarts.  A
        home replica that is down/draining does NOT fail over: the
        session state lives there, so the honest answer is a shed until
        it returns (or until a drain migrates the stream, which is what
        writes the override)."""
        with self._lock:
            rid = self.overrides.get(stream_id)
            migrated = rid is not None
            if rid is None:
                rid = self.ring.assign(stream_id)
            r = self.replicas.get(rid) if rid is not None else None
        return r, migrated

    def set_override(self, stream_id: str, replica_id: str) -> None:
        with self._lock:
            self.overrides[stream_id] = replica_id

    def clear_override(self, stream_id: str) -> None:
        with self._lock:
            self.overrides.pop(stream_id, None)

    def mark_shed(self, replica_id: str, retry_after_s: float) -> None:
        """Honor a replica's 429/503 Retry-After: no stateless traffic
        (and no failover retries) land on it until the window passes."""
        until = time.monotonic() + max(0.0, float(retry_after_s))
        with self._lock:
            r = self.replicas.get(replica_id)
            if r is not None and until > r.backoff_until:
                r.backoff_until = until

    def note_dispatch(self, replica_id: str, n: int = 1) -> None:
        with self._lock:
            r = self.replicas.get(replica_id)
            if r is not None:
                r.router_inflight += n

    def note_done(self, replica_id: str, n: int = 1) -> None:
        with self._lock:
            r = self.replicas.get(replica_id)
            if r is not None:
                r.router_inflight = max(0, r.router_inflight - n)

    # ------------------------------------------------------------------
    def counts(self) -> Dict[str, int]:
        now = time.monotonic()
        with self._lock:
            reps = list(self.replicas.values())
        return {
            "replicas": len(reps),
            "healthy": sum(r.healthy for r in reps),
            "ready": sum(r.healthy and r.ready for r in reps),
            "warming": sum(r.warming for r in reps),
            "draining": sum(r.draining for r in reps),
            "eligible": sum(r.eligible(now) for r in reps),
        }
