"""Router observability: the ``dfd_router_*`` Prometheus catalog + the
per-replica re-export.

Same construction as ``serving/metrics.py`` (stdlib counters +
:class:`LatencyHistogram` through the shared ``utils/prometheus.py``
renderer; byte layout locked by tests/test_obs.py).  The router's
``GET /metrics`` serves this catalog followed by every replica's
last-scraped exposition re-labeled with ``replica="<id>"``
(:func:`relabel_exposition`), so ONE scrape sees the whole fleet —
router books on top, each replica's ``dfd_serving_*`` /
``dfd_streaming_*`` catalogs underneath.

Router request books — the fleet-level mirror of the serving ledger,
asserted exactly by tools/bench_serve.py and tools/chaos_serve.py::

    routed == cache_hit + forwarded + migrated + shed + failed

Every proxied request resolves exactly once: ``cache_hit`` (the edge
verdict cache answered without touching a replica, ISSUE 17),
``forwarded`` (a replica answered and its response was relayed),
``migrated`` (answered by a migration-override target — the stream was
moved off a drained replica), ``shed`` (no eligible replica, or every
failover attempt shed: router-level 503 with a jittered
``Retry-After``), or ``failed`` (transport errors exhausted the
failover budget: 502).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Set

from ..utils.prometheus import Counter as _Counter
from ..utils.prometheus import LatencyHistogram, PromText

__all__ = ["RouterMetrics", "STAGES", "BOOK_KINDS", "relabel_exposition"]

_PREFIX = "dfd_router"

#: sub-ms-resolving bounds (the serving/streaming catalogs' choice) —
#: proxy hops are host work and upstream latency tracks the replica
_BOUNDS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
           0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

STAGES = ("upstream", "total")

#: request-book resolution kinds (routed == sum of these, exactly)
BOOK_KINDS = ("cache_hit", "forwarded", "migrated", "shed", "failed")


class RouterMetrics:
    """One registry per router process."""

    def __init__(self):
        self.latency: Dict[str, LatencyHistogram] = {
            s: LatencyHistogram(_BOUNDS) for s in STAGES}
        self.requests_total: Dict[str, _Counter] = {}   # by HTTP status
        self._requests_lock = threading.Lock()
        # fleet request books: routed == cache_hit + forwarded +
        # migrated + shed + failed holds EXACTLY (chaos_serve asserts it
        # after every replica-kill scenario; bench_serve after every
        # load phase)
        self.routed_total = _Counter()
        self.cache_hit_total = _Counter()        # edge verdict-cache
        # answers (ISSUE 17): resolved at the router, no replica touched
        self.forwarded_total = _Counter()
        self.migrated_total = _Counter()
        self.shed_total = _Counter()
        self.failed_total = _Counter()
        self.retries_total = _Counter()          # failover attempts past
        # the first replica (shed/backoff/transport)
        # connection hygiene (ISSUE 16): slowloris/idle hardening + the
        # bounded-relay-buffer guard, shared by both data planes
        self.idle_closed_total = _Counter()      # connections closed on
        # a header-read or idle deadline (408/close)
        self.overflow_closed_total = _Counter()  # connections closed
        # because a stalled peer let the bounded relay buffer fill
        self.upstream_pool_closed_total = _Counter()   # pooled upstream
        # sockets closed because their replica retired or went down
        self.scrape_errors_total = _Counter()    # health-scrape failures
        self.replicas_down_total = _Counter()    # healthy -> down edges
        self.drains_total = _Counter()           # drain operations run
        self.streams_migrated_total = _Counter()
        self.migration_aborts_total = _Counter()   # restore-on-target
        # failed; the stream was restored back on its source (or, if even
        # that failed, dumped to disk — never silently lost)
        # replica lifecycle books (ISSUE 18): every spawned child resolves
        # exactly once as retired (drain-first, clean terminate) or killed
        # (the escalation fired / the child died under us)
        self.replicas_spawned_total = _Counter()
        self.replicas_retired_total = _Counter()
        self.replicas_killed_total = _Counter()
        # autoscaler decision books (ISSUE 18): acted scale decisions
        self.autoscale_up_total = _Counter()
        self.autoscale_down_total = _Counter()
        # standby pool (ISSUE 19): scale-ups served by promoting a
        # parked fully-warmed replica instead of a cold spawn
        self.standby_promotions_total = _Counter()
        # backfill tenant books (ISSUE 18): idle-capacity workers
        self.backfill_workers_spawned_total = _Counter()
        self.backfill_yields_total = _Counter()    # workers yielded at a
        # traffic spike (SIGTERM -> exit-75 lease release)
        # per-replica forward counts: (replica,) -> Counter
        self.replica_forwarded: Dict[str, _Counter] = {}
        self._replica_lock = threading.Lock()
        self.ready = False           # gauge: >= 1 eligible replica
        self.replicas = 0            # gauges, written by the scraper
        self.healthy_replicas = 0
        self.ready_replicas = 0
        self.warming_replicas = 0
        self.draining_replicas = 0
        self.autoscale_target_replicas = 0   # gauge, written by the
        # autoscaler (its current desired fleet size)
        self.standby_replicas = 0    # gauge: parked warm standbys
        # (unregistered — NOT counted in replicas/ready/warming above)
        self.backfill_workers = 0    # gauge, written by the tenant

    # ------------------------------------------------------------------
    def count_request(self, status: int) -> None:
        key = str(int(status))
        with self._requests_lock:
            c = self.requests_total.get(key)
            if c is None:
                c = self.requests_total[key] = _Counter()
        c.inc()

    def count_forward(self, replica_id: str) -> None:
        with self._replica_lock:
            c = self.replica_forwarded.get(replica_id)
            if c is None:
                c = self.replica_forwarded[replica_id] = _Counter()
        c.inc()

    def set_fleet_gauges(self, counts: Dict[str, int]) -> None:
        self.replicas = counts["replicas"]
        self.healthy_replicas = counts["healthy"]
        self.ready_replicas = counts["ready"]
        self.warming_replicas = counts.get("warming", 0)
        self.draining_replicas = counts["draining"]
        self.ready = counts["eligible"] > 0

    def books(self) -> Dict[str, int]:
        return {"routed": self.routed_total.value,
                "cache_hit": self.cache_hit_total.value,
                "forwarded": self.forwarded_total.value,
                "migrated": self.migrated_total.value,
                "shed": self.shed_total.value,
                "failed": self.failed_total.value}

    # ------------------------------------------------------------------
    def render_prometheus(self) -> str:
        doc = PromText(_PREFIX)
        counter, gauge = doc.counter, doc.gauge

        doc.header("requests_total", "Router responses by HTTP status",
                   "counter")
        with self._requests_lock:
            items = sorted((k, c.value)
                           for k, c in self.requests_total.items())
        for status, value in items:
            doc.sample("requests_total", f'{{status="{status}"}}', value)
        counter("routed_total", "Requests entering the routing path "
                "(books: routed == cache_hit + forwarded + migrated "
                "+ shed + failed)", self.routed_total.value)
        counter("cache_hit_total", "Requests resolved by the edge "
                "verdict cache (keyed on the fleet weights-epoch; no "
                "replica touched)", self.cache_hit_total.value)
        counter("forwarded_total", "Requests resolved by a replica "
                "response relayed to the client",
                self.forwarded_total.value)
        counter("migrated_total", "Requests resolved by a migration-"
                "override target (the stream was moved off a drained "
                "replica)", self.migrated_total.value)
        counter("shed_total", "Requests shed at the router (no eligible "
                "replica / every failover attempt shed): 503 + jittered "
                "Retry-After", self.shed_total.value)
        counter("failed_total", "Requests failed on transport errors "
                "after the failover budget (502)",
                self.failed_total.value)
        counter("retries_total", "Failover attempts past the first "
                "replica (upstream shed, backoff or transport error)",
                self.retries_total.value)
        counter("idle_closed_total", "Connections closed on a header-"
                "read or idle deadline (slowloris/idle hardening, both "
                "data planes)", self.idle_closed_total.value)
        counter("overflow_closed_total", "Connections closed because a "
                "stalled peer let the bounded relay buffer fill",
                self.overflow_closed_total.value)
        counter("upstream_pool_closed_total", "Pooled upstream sockets "
                "closed because their replica retired or went down",
                self.upstream_pool_closed_total.value)
        counter("scrape_errors_total", "Replica health-scrape failures",
                self.scrape_errors_total.value)
        counter("replicas_down_total", "Replica healthy->down "
                "transitions observed by the scraper",
                self.replicas_down_total.value)
        counter("drains_total", "Replica drain operations run",
                self.drains_total.value)
        counter("streams_migrated_total", "Live stream sessions moved to "
                "another replica (snapshot -> restore, books intact)",
                self.streams_migrated_total.value)
        counter("migration_aborts_total", "Stream migrations aborted "
                "(target restore failed; the session was restored back "
                "on its source or dumped to disk — never silently lost)",
                self.migration_aborts_total.value)
        counter("replicas_spawned_total", "Replica children spawned "
                "(launch + autoscaler scale-up)",
                self.replicas_spawned_total.value)
        counter("replicas_retired_total", "Replicas retired cleanly "
                "(drain-first: migrate -> settle -> terminate)",
                self.replicas_retired_total.value)
        counter("replicas_killed_total", "Replica stops that escalated "
                "to SIGKILL (or children that died under the "
                "controller)", self.replicas_killed_total.value)
        counter("autoscale_up_total", "Acted scale-up decisions "
                "(SLO breach held through the hysteresis window)",
                self.autoscale_up_total.value)
        counter("autoscale_down_total", "Acted scale-in decisions "
                "(idle held through the hysteresis window; drain-first)",
                self.autoscale_down_total.value)
        counter("standby_promotions_total", "Scale-ups served by "
                "promoting a parked warm standby into the registry "
                "(ms-scale, no spawn, no compile)",
                self.standby_promotions_total.value)
        counter("backfill_workers_spawned_total", "Backfill tenant "
                "workers launched onto idle capacity",
                self.backfill_workers_spawned_total.value)
        counter("backfill_yields_total", "Backfill tenant workers "
                "yielded at a traffic spike (SIGTERM -> exit-75 lease "
                "release)", self.backfill_yields_total.value)
        doc.header("replica_forwarded_total",
                   "Requests forwarded per replica", "counter")
        with self._replica_lock:
            rep_items = sorted((k, c.value)
                               for k, c in self.replica_forwarded.items())
        for rid, value in rep_items:
            doc.sample("replica_forwarded_total", f'{{replica="{rid}"}}',
                       value)
        gauge("ready", "1 while at least one replica is eligible "
              "(healthy + ready + not draining + not backing off)",
              int(self.ready))
        gauge("replicas", "Registered replicas", self.replicas)
        gauge("healthy_replicas", "Replicas whose scrape succeeds",
              self.healthy_replicas)
        gauge("ready_replicas", "Replicas healthy AND /readyz-ready",
              self.ready_replicas)
        gauge("warming_replicas", "Replicas warming a cold model "
              "(parseable 503 /readyz, or a spawned child inside its "
              "startup grace) — capacity in flight, NOT down",
              self.warming_replicas)
        gauge("draining_replicas", "Replicas draining (no new traffic)",
              self.draining_replicas)
        gauge("autoscale_target_replicas", "The autoscaler's current "
              "desired fleet size (0 while autoscaling is off)",
              self.autoscale_target_replicas)
        gauge("standby_replicas", "Parked fully-warmed standby replicas "
              "(unregistered: hold a capacity slot, invisible to the "
              "ring until promoted)", self.standby_replicas)
        gauge("backfill_workers", "Live backfill tenant workers on "
              "idle capacity", self.backfill_workers)
        for stage in STAGES:
            doc.histogram("latency_seconds", "Router request latency "
                          "(upstream = replica round trip, total = "
                          "socket in -> response out)",
                          self.latency[stage], labels=f'stage="{stage}"')
        return doc.render()


# ---------------------------------------------------------------------------
# per-replica re-export
# ---------------------------------------------------------------------------

def relabel_exposition(text: str, replica_id: str,
                       seen_families: Set[str]) -> List[str]:
    """One replica's exposition → lines with ``replica="<id>"`` injected
    into every sample's label set.

    ``seen_families`` dedupes ``# HELP``/``# TYPE`` headers across
    replicas (re-declaring a family's TYPE per replica would violate the
    exposition format); the caller passes one set across the whole
    aggregate render.  Unparseable lines are dropped rather than
    corrupting the document.
    """
    out: List[str] = []
    label = f'replica="{replica_id}"'
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                key = f"{parts[1]} {parts[2]}"
                if key in seen_families:
                    continue
                seen_families.add(key)
            out.append(line)
            continue
        lhs, sep, value = line.rpartition(" ")
        if not sep or not lhs:
            continue
        brace = lhs.find("{")
        if brace < 0:
            out.append(f"{lhs}{{{label}}} {value}")
        else:
            name, inner = lhs[:brace], lhs[brace + 1:].rstrip("}")
            joined = f"{label},{inner}" if inner else label
            out.append(f"{name}{{{joined}}} {value}")
    return out
