"""Fleet scale-out: a shared-nothing replica router tier.

One serve/stream process per unit of capacity, N of them behind a
stdlib-HTTP router (``runners/router.py``): consistent-hash stream
affinity, health derived from the signals the replicas already export
(``/readyz`` + ``/metrics``), shed-aware retry routing honoring each
replica's Retry-After, and live stream migration on drain via the
PR 10 session snapshot/restore machinery.

Deliberately **jax-free top to bottom** (dfdlint DFD001): the router
tier must never pay — or wait on — an accelerator import; replicas are
separate processes that do.

PEP-562 lazy exports (the ``obs/`` idiom) keep ``import
deepfake_detection_tpu.fleet`` cheap for config/tests.
"""

from __future__ import annotations

_LAZY = {
    "HashRing": "registry",
    "Replica": "registry",
    "Registry": "registry",
    "normalize_netloc": "registry",
    "RouterMetrics": "metrics",
    "relabel_exposition": "metrics",
    "HealthScraper": "controller",
    "ReplicaProcess": "controller",
    "spawn_replicas": "controller",
    "free_port": "controller",
    "http_request": "controller",
    "parse_exposition": "controller",
    "retire_replica": "controller",
    "RouterServer": "router",
    "make_router_server": "router",
    "drain_replica": "migrate",
    "undrain_replica": "migrate",
    "migrate_stream": "migrate",
    "list_streams": "migrate",
    "Autoscaler": "autoscaler",
    "BackfillTenant": "autoscaler",
    "FleetSample": "autoscaler",
    "FleetSampler": "autoscaler",
    "PolicyKnobs": "autoscaler",
    "ScalePolicy": "autoscaler",
    "replay_trace": "autoscaler",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib
    return getattr(importlib.import_module(f".{mod}", __name__), name)
