"""SLO-driven fleet autoscaler + the two-tenant elastic scheduler.

PR 15 built the mechanisms — :class:`~.controller.ReplicaProcess`
spawn/stop, health scraping, live stream migration — and left a human
deciding how many replicas run.  This module closes the loop (ISSUE 18):

* :class:`FleetSampler` folds the signals the router already has — its
  OWN ``dfd_router_latency_seconds{stage="total"}`` histogram (p99 over
  the sample window, from bucket deltas), its shed/routed books, and the
  per-replica queue depth / inflight / breaker state the
  :class:`~.controller.HealthScraper` scrapes — into one
  :class:`FleetSample` per control tick.  No new instrumentation in the
  engine; the sample is a pure read of existing counters.

* :class:`ScalePolicy` turns the sample stream into decisions
  **deterministically**: hysteresis bands (a breach band that must hold
  for ``up_samples`` consecutive ticks, an idle band that must hold for
  ``down_samples``, a dead band between them where nothing moves),
  cooldowns measured in *sample time* (``sample.t`` deltas, never a
  fresh wall-clock read), and capacity guards (never above
  ``max_replicas``, never below ``min_replicas``, never a second spawn
  while one is still warming).  ``decide()`` is a pure function of the
  sample sequence: replaying a recorded trace through a fresh policy
  yields bit-identical decisions (:func:`replay_trace`, pinned by the
  golden-trace test and asserted live by the chaos drive).

* :class:`Autoscaler` is the actuator: *up* spawns a
  :class:`~.controller.ReplicaProcess` (yielding a backfill worker
  first when the capacity slots are full), *down* retires the
  least-loaded ready replica through
  :func:`~.controller.retire_replica` — drain (PR 15 live migration) →
  settle → terminate — so scale-in is lossless by default.  Every tick
  is recorded to a schema-stamped JSONL trace (obs/events.py idiom,
  DFD007) carrying the sample AND the decision, which is what makes the
  replay check possible against a *production* run, not just a fixture.

* :class:`BackfillTenant` is the idle-capacity tenant: the fleet's
  ``max_replicas`` defines a pool of capacity slots; slots the serving
  tenant isn't using are leased through the PR 13 :class:`LeaseDir`
  test-and-set idiom (``<out>/_slots/leases/slot-NN.lease``) and each
  leased slot runs one ``runners/backfill.py`` worker against the
  shared manifest.  At a traffic spike the tenant **yields**: SIGTERM →
  the worker finishes its batch, releases its shard leases and exits 75
  (the existing preemption contract) → the slot lease is released and
  the serving tenant spawns into it.  Backfill books stay exact through
  any number of yields because shard leases + done markers already make
  the corpus resumable at shard granularity.

jax-free (dfdlint DFD001): the control loop lives in the router
process, which must never pay — or wait on — an accelerator import.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shlex
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..backfill.lease import LeaseDir
from ..obs.events import EventLog, iter_records
from .controller import ReplicaProcess, free_port, http_request, \
    retire_replica
from .metrics import RouterMetrics
from .registry import Registry

_logger = logging.getLogger(__name__)

__all__ = ["FleetSample", "FleetSampler", "PolicyKnobs", "Decision",
           "ScalePolicy", "Autoscaler", "BackfillTenant", "replay_trace",
           "EXIT_PREEMPTED"]

#: the preemption exit status (mirrors runners/backfill.py, which cannot
#: be imported here — it pulls the accelerator stack): a SIGTERMed
#: backfill worker finishes its batch, releases its leases and exits 75
EXIT_PREEMPTED = 75

#: trace schema: one ``autoscale_start`` event (policy knobs) followed
#: by one ``tick`` event per control tick (sample + decision)
TRACE_SCHEMA = "dfd.fleet.autoscale.v1"


# ---------------------------------------------------------------------------
# samples
# ---------------------------------------------------------------------------

@dataclass
class FleetSample:
    """One windowed observation of the fleet — everything the policy is
    allowed to read.  All floats are pre-rounded by the sampler so the
    JSONL trace round-trips the exact values the live decision saw."""

    t: float            # sample time (monotonic seconds); cooldowns are
    # measured as deltas of THIS field, never a fresh clock read
    ready: int          # replicas healthy + /readyz-ready + not draining
    warming: int        # capacity already in flight (cold starts)
    draining: int       # replicas on their way out
    routed: int         # requests routed during the window
    shed_rate: float    # router sheds / routed over the window (0..1)
    p99_ms: float       # router total-stage p99 over the window (ms);
    # 0.0 when the window carried no traffic
    depth: float        # mean queue+inflight per ready replica
    breakers: int       # replicas with a non-closed breaker

    def to_record(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_record(cls, d: Dict[str, Any]) -> "FleetSample":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})


def _p99_ms(bounds: Sequence[float], deltas: Sequence[int]) -> float:
    """p99 (ms) of one window's worth of histogram bucket increments.

    Resolution is the bucket upper bound (the same approximation
    ``histogram_quantile`` makes); a window whose p99 lands in the +Inf
    bucket reports twice the last finite bound — a finite, monotone
    sentinel the SLO comparison can still order."""
    total = sum(deltas)
    if total <= 0:
        return 0.0
    rank = 0.99 * total
    acc = 0
    for b, c in zip(bounds, deltas):
        acc += c
        if acc >= rank:
            return round(b * 1000.0, 6)
    return round(bounds[-1] * 2 * 1000.0, 6)


class FleetSampler:
    """Builds one :class:`FleetSample` per tick from counter deltas."""

    def __init__(self, metrics: RouterMetrics):
        self.metrics = metrics
        self._prev: Optional[Tuple[int, int, List[int]]] = None

    def sample(self, registry: Registry, now: float) -> FleetSample:
        m = self.metrics
        routed = m.routed_total.value
        shed = m.shed_total.value
        hist = m.latency["total"]
        counts, _, _ = hist.snapshot()
        if self._prev is None:
            prev_routed, prev_shed = routed, shed
            prev_counts = list(counts)
        else:
            prev_routed, prev_shed, prev_counts = self._prev
        self._prev = (routed, shed, list(counts))
        droutes = max(0, routed - prev_routed)
        dshed = max(0, shed - prev_shed)
        deltas = [max(0, c - p) for c, p in zip(counts, prev_counts)]
        reps = registry.all()
        ready = [r for r in reps
                 if r.healthy and r.ready and not r.draining]
        depth = (sum(r.depth() for r in ready) / len(ready)
                 if ready else 0.0)
        return FleetSample(
            t=round(float(now), 3),
            ready=len(ready),
            warming=sum(1 for r in reps
                        if r.warming and not r.draining),
            draining=sum(1 for r in reps if r.draining),
            routed=droutes,
            shed_rate=round(dshed / droutes, 6) if droutes else 0.0,
            p99_ms=_p99_ms(hist.bounds, deltas),
            depth=round(depth, 3),
            breakers=sum(1 for r in reps if r.breaker_state),
        )


# ---------------------------------------------------------------------------
# policy
# ---------------------------------------------------------------------------

@dataclass
class PolicyKnobs:
    """The SLO surface (RouterConfig mirrors these as ``--flags``)."""

    slo_p99_ms: float = 250.0    # the breach line
    min_replicas: int = 1
    max_replicas: int = 4
    up_samples: int = 2          # consecutive breach ticks before up
    down_samples: int = 5        # consecutive idle ticks before down
    up_cooldown_s: float = 5.0   # sample-time gap between up actions
    down_cooldown_s: float = 15.0
    shed_high: float = 0.01      # shed fraction that counts as a breach
    depth_high: float = 8.0      # per-replica depth breach line
    depth_low: float = 1.0       # per-replica depth idle line
    p99_low_frac: float = 0.5    # idle = p99 below this fraction of SLO

    def __post_init__(self):
        if int(self.min_replicas) < 1:
            raise ValueError(f"min_replicas must be >= 1, got "
                             f"{self.min_replicas}")
        if int(self.max_replicas) < int(self.min_replicas):
            raise ValueError(
                f"max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})")
        if int(self.up_samples) < 1 or int(self.down_samples) < 1:
            raise ValueError("up_samples/down_samples must be >= 1")
        if not 0.0 < float(self.p99_low_frac) < 1.0:
            raise ValueError(f"p99_low_frac must be in (0,1), got "
                             f"{self.p99_low_frac}")
        if float(self.depth_low) > float(self.depth_high):
            raise ValueError("depth_low must be <= depth_high (the "
                             "hysteresis dead band)")

    @classmethod
    def from_record(cls, d: Dict[str, Any]) -> "PolicyKnobs":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})


@dataclass(frozen=True)
class Decision:
    action: str          # "up" | "down" | "hold"
    reason: str


class ScalePolicy:
    """Deterministic sample stream → decision stream.

    Hysteresis is three mechanisms stacked: (1) distinct breach and idle
    *bands* with a dead band between them — a sample in neither band
    resets both consecutive-run counters, so noise straddling a single
    threshold can never accumulate a run; (2) consecutive-sample
    requirements (``up_samples``/``down_samples``); (3) per-direction
    cooldowns measured in sample time.  State is four integers/floats —
    replaying the same samples through a fresh instance reproduces the
    same decisions exactly."""

    def __init__(self, knobs: PolicyKnobs):
        self.knobs = knobs
        self._breach_run = 0
        self._idle_run = 0
        self._last_up_t: Optional[float] = None
        self._last_down_t: Optional[float] = None

    # ------------------------------------------------------------------
    def _classify(self, s: FleetSample) -> Tuple[str, str]:
        """(band, detail) — band is "breach" | "idle" | "neutral"."""
        k = self.knobs
        if s.p99_ms > k.slo_p99_ms:
            return "breach", (f"p99 {s.p99_ms:.3f}ms > slo "
                              f"{k.slo_p99_ms:.3f}ms")
        if s.shed_rate > k.shed_high:
            return "breach", (f"shed rate {s.shed_rate:.4f} > "
                              f"{k.shed_high:.4f}")
        if s.depth > k.depth_high:
            return "breach", (f"depth {s.depth:.3f} > "
                              f"{k.depth_high:.3f}")
        if s.breakers > 0:
            return "breach", f"{s.breakers} breaker(s) open"
        if (s.p99_ms <= k.slo_p99_ms * k.p99_low_frac
                and s.shed_rate == 0.0 and s.depth < k.depth_low):
            return "idle", (f"p99 {s.p99_ms:.3f}ms <= "
                            f"{k.slo_p99_ms * k.p99_low_frac:.3f}ms, "
                            f"no shed, depth {s.depth:.3f}")
        return "neutral", "inside the dead band"

    def decide(self, s: FleetSample) -> Decision:
        k = self.knobs
        band, detail = self._classify(s)
        if band == "breach":
            self._breach_run += 1
            self._idle_run = 0
        elif band == "idle":
            self._idle_run += 1
            self._breach_run = 0
        else:
            self._breach_run = 0
            self._idle_run = 0
        capacity = s.ready + s.warming
        # hard floor first: a fleet below min (a child died) re-spawns
        # regardless of load, still one-at-a-time and cooldown-paced
        if capacity < k.min_replicas:
            if s.warming > 0:
                return Decision("hold", f"below min ({capacity} < "
                                        f"{k.min_replicas}) but "
                                        f"{s.warming} warming")
            if (self._last_up_t is not None
                    and s.t - self._last_up_t < k.up_cooldown_s):
                return Decision("hold", "below min, in up-cooldown")
            self._last_up_t = s.t
            self._breach_run = 0
            return Decision("up", f"capacity {capacity} below min "
                                  f"{k.min_replicas}")
        if self._breach_run >= k.up_samples:
            if capacity >= k.max_replicas:
                return Decision("hold", f"breach ({detail}) but at max "
                                        f"{k.max_replicas}")
            if s.warming > 0:
                return Decision("hold", f"breach ({detail}) but "
                                        f"{s.warming} replica(s) "
                                        f"already warming")
            if (self._last_up_t is not None
                    and s.t - self._last_up_t < k.up_cooldown_s):
                return Decision("hold", f"breach ({detail}) but in "
                                        f"up-cooldown")
            self._last_up_t = s.t
            self._breach_run = 0
            return Decision("up", f"{detail} for {k.up_samples}+ "
                                  f"samples")
        if self._idle_run >= k.down_samples:
            if capacity <= k.min_replicas:
                return Decision("hold", f"idle but at min "
                                        f"{k.min_replicas}")
            if s.warming > 0:
                return Decision("hold", "idle but a replica is warming")
            if (self._last_down_t is not None
                    and s.t - self._last_down_t < k.down_cooldown_s):
                return Decision("hold", "idle but in down-cooldown")
            self._last_down_t = s.t
            self._idle_run = 0
            return Decision("down", f"{detail} for {k.down_samples}+ "
                                    f"samples")
        return Decision("hold", f"{band}: {detail} "
                                f"(runs {self._breach_run}/"
                                f"{self._idle_run})")

    # ------------------------------------------------------------------
    @classmethod
    def replay(cls, samples: Sequence[FleetSample],
               knobs: PolicyKnobs) -> List[Decision]:
        """Fresh policy over a recorded window — the determinism pin."""
        p = cls(knobs)
        return [p.decide(s) for s in samples]


def replay_trace(path: str) -> Dict[str, Any]:
    """Re-run a recorded autoscale trace through a fresh policy and
    compare: ``{"match": bool, "n": int, "recorded": [...],
    "replayed": [...], "mismatches": [...]}``.  The acceptance check for
    'scale decisions bit-reproducible from the recorded metrics trace'—
    run by the chaos drive against the live router's own trace file."""
    knobs: Optional[PolicyKnobs] = None
    samples: List[FleetSample] = []
    recorded: List[str] = []
    for rec in iter_records(path):
        if rec.get("event") == "autoscale_start":
            knobs = PolicyKnobs.from_record(rec.get("policy", {}))
        elif rec.get("event") == "tick":
            samples.append(FleetSample.from_record(rec["sample"]))
            recorded.append(rec["action"])
    if knobs is None:
        raise ValueError(f"{path}: no autoscale_start record (schema "
                         f"{TRACE_SCHEMA})")
    replayed = [d.action for d in ScalePolicy.replay(samples, knobs)]
    mismatches = [i for i, (a, b) in enumerate(zip(recorded, replayed))
                  if a != b]
    return {"match": recorded == replayed, "n": len(recorded),
            "recorded": recorded, "replayed": replayed,
            "mismatches": mismatches}


# ---------------------------------------------------------------------------
# the idle-capacity tenant
# ---------------------------------------------------------------------------

class BackfillTenant:
    """Backfill workers on the capacity slots serving isn't using.

    The slot pool is ``slot-00 .. slot-<max_replicas-1>`` under
    ``<out>/_slots`` — a :class:`LeaseDir`, so slot ownership has the
    same atomic test-and-set / TTL-steal semantics shard leases do (two
    routers pointed at one run dir cannot double-fill a slot).  Each
    held slot runs one backfill worker; ``reconcile`` is called every
    control tick with the current idle-slot count and launches/yields
    to match.  ``yield_workers`` is the spike path: SIGTERM, bounded
    wait for the exit-75 lease release, slot lease dropped."""

    def __init__(self, *, manifest: str, out: str, extra_args: str = "",
                 max_workers: int = 0, metrics: Optional[RouterMetrics]
                 = None, lease_ttl_s: float = 60.0,
                 yield_timeout_s: float = 30.0,
                 worker_cmd: Optional[List[str]] = None,
                 env: Optional[dict] = None):
        self.manifest = manifest
        self.out = out
        self.extra_args = extra_args
        self.max_workers = int(max_workers)
        self.metrics = metrics
        self.yield_timeout_s = float(yield_timeout_s)
        #: test hook: a stub command launched per slot instead of the
        #: backfill runner (must honor SIGTERM → exit 75)
        self.worker_cmd = worker_cmd
        self.env = env
        os.makedirs(out, exist_ok=True)
        self.lease = LeaseDir(os.path.join(out, "_slots"),
                              owner=f"tenant-{os.getpid()}",
                              ttl_s=lease_ttl_s)
        self.workers: Dict[str, subprocess.Popen] = {}
        self.corpus_done = False      # a worker ran the manifest dry
        self.launched = 0
        self.yields = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    @staticmethod
    def _slot_ids(n: int) -> List[str]:
        return [f"slot-{i:02d}" for i in range(max(0, int(n)))]

    def _launch_one(self, total_slots: int) -> bool:
        for slot in self._slot_ids(total_slots):
            if slot in self.workers:
                continue
            if not self.lease.acquire(slot):
                continue
            if self.worker_cmd is not None:
                cmd = list(self.worker_cmd)
            else:
                cmd = [sys.executable, "-m",
                       "deepfake_detection_tpu.runners.backfill",
                       "--manifest", self.manifest, "--out", self.out,
                       "--worker-name", f"tenant-{slot}"]
                cmd += shlex.split(self.extra_args)
            _logger.info("backfill tenant: launching worker on %s: %s",
                         slot, " ".join(cmd))
            self.workers[slot] = subprocess.Popen(cmd, env=self.env)
            self.launched += 1
            if self.metrics is not None:
                self.metrics.backfill_workers_spawned_total.inc()
            return True
        return False

    def reap(self) -> None:
        """Collect exited workers; exit 0 means the corpus ran dry."""
        for slot, proc in list(self.workers.items()):
            rc = proc.poll()
            if rc is None:
                continue
            del self.workers[slot]
            self.lease.release(slot)
            if rc == 0:
                self.corpus_done = True
                _logger.info("backfill tenant: corpus complete "
                             "(worker on %s exited 0)", slot)
            elif rc != EXIT_PREEMPTED:
                _logger.warning("backfill tenant: worker on %s exited "
                                "%d", slot, rc)

    def yield_workers(self, n: int,
                      timeout_s: Optional[float] = None) -> int:
        """SIGTERM the ``n`` highest-slot workers and wait (bounded) for
        their exit-75 lease release; returns how many exited cleanly.
        The spike contract: serving takes the slot the moment this
        returns."""
        timeout_s = self.yield_timeout_s if timeout_s is None \
            else float(timeout_s)
        victims = sorted(self.workers)[-max(0, int(n)):] if n > 0 else []
        for slot in victims:
            self.workers[slot].terminate()
        deadline = time.monotonic() + timeout_s
        clean = 0
        for slot in victims:
            proc = self.workers.pop(slot)
            try:
                rc = proc.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                _logger.warning("backfill tenant: worker on %s ignored "
                                "SIGTERM for %.1fs — killing", slot,
                                timeout_s)
                proc.kill()
                rc = proc.wait()
            self.lease.release(slot)
            self.yields += 1
            if self.metrics is not None:
                self.metrics.backfill_yields_total.inc()
            if rc in (0, EXIT_PREEMPTED):
                clean += 1
                if rc == 0:
                    self.corpus_done = True
        if self.metrics is not None:
            self.metrics.backfill_workers = len(self.workers)
        return clean

    def ensure_room(self, idle_slots: int) -> None:
        """Yield enough workers that at most ``idle_slots`` remain —
        called by the autoscaler BEFORE it spawns into a slot."""
        self.reap()
        excess = len(self.workers) - max(0, int(idle_slots))
        if excess > 0:
            self.yield_workers(excess)

    def reconcile(self, idle_slots: int, total_slots: int) -> None:
        """Match the worker count to the idle capacity (one tick)."""
        self.reap()
        if self.corpus_done:
            target = 0
        else:
            target = max(0, int(idle_slots))
            if self.max_workers > 0:
                target = min(target, self.max_workers)
        while len(self.workers) > target:
            self.yield_workers(len(self.workers) - target)
        while len(self.workers) < target:
            if not self._launch_one(int(total_slots)):
                break
        for slot in self.workers:
            self.lease.heartbeat(slot)
        if self.metrics is not None:
            self.metrics.backfill_workers = len(self.workers)

    def stop(self) -> None:
        """Yield everything (shutdown path)."""
        self.reap()
        if self.workers:
            self.yield_workers(len(self.workers))

    def status(self) -> Dict[str, Any]:
        return {"workers": sorted(self.workers),
                "launched": self.launched, "yields": self.yields,
                "corpus_done": self.corpus_done}


# ---------------------------------------------------------------------------
# the actuator
# ---------------------------------------------------------------------------

class _Standby:
    """One fully-warmed but UNREGISTERED replica parked for promotion:
    it holds a capacity slot but is invisible to the ring, the scraper
    and the fleet gauges (neither ready nor warming) until a scale-up
    promotes it into the registry — a millisecond operation against the
    51.8 s cold spawn it replaces."""

    __slots__ = ("proc", "warmed", "born_t")

    def __init__(self, proc: ReplicaProcess, born_t: float):
        self.proc = proc
        self.warmed = False
        self.born_t = born_t


class Autoscaler:
    """The control loop: sample → decide → act, one tick at a time.

    Wall clock only *schedules* ticks; every decision derives from the
    :class:`FleetSample` (whose ``t`` is recorded), so the JSONL trace
    fully determines the decision sequence (:func:`replay_trace`).
    ``tick()`` is public and takes an explicit ``now`` for tests."""

    def __init__(self, registry: Registry, metrics: RouterMetrics,
                 scraper, *, knobs: PolicyKnobs,
                 spawn_runner: str = "serve", replica_args: str = "",
                 interval_s: float = 1.0,
                 tenant: Optional[BackfillTenant] = None,
                 trace_path: str = "", migrate_timeout_s: float = 30.0,
                 settle_timeout_s: float = 20.0,
                 standby_replicas: int = 0,
                 child_env: Optional[dict] = None):
        self.registry = registry
        self.metrics = metrics
        self.scraper = scraper
        self.knobs = knobs
        self.spawn_runner = spawn_runner
        self.replica_args = replica_args
        self.interval_s = float(interval_s)
        self.tenant = tenant
        self.migrate_timeout_s = float(migrate_timeout_s)
        self.settle_timeout_s = float(settle_timeout_s)
        self.standby_replicas = int(standby_replicas)
        self.standbys: List[_Standby] = []
        self.child_env = child_env
        self.policy = ScalePolicy(knobs)
        self.sampler = FleetSampler(metrics)
        self.trace: Optional[EventLog] = \
            EventLog(trace_path) if trace_path else None
        if self.trace is not None:
            self.trace.event("autoscale_start", schema=TRACE_SCHEMA,
                             policy=dataclasses.asdict(knobs))
        self.last_decision = Decision("hold", "no ticks yet")
        self.ticks = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Decision:
        now = time.monotonic() if now is None else now
        self._reap_lost()
        self._tend_standbys()
        sample = self.sampler.sample(self.registry, now)
        d = self.policy.decide(sample)
        self.last_decision = d
        self.ticks += 1
        if self.trace is not None:
            self.trace.event("tick", sample=sample.to_record(),
                             action=d.action, reason=d.reason)
        if d.action == "up":
            self._scale_up()
        elif d.action == "down":
            self._scale_down()
        if self.tenant is not None:
            # a parked standby HOLDS its capacity slot — the backfill
            # tenant must not fill it, or promotion would have to evict
            used = len(self.registry.ids()) + len(self.standbys)
            self.tenant.reconcile(self.knobs.max_replicas - used,
                                  self.knobs.max_replicas)
        self.metrics.autoscale_target_replicas = min(
            self.knobs.max_replicas,
            max(self.knobs.min_replicas, sample.ready + sample.warming
                + (1 if d.action == "up" else
                   -1 if d.action == "down" else 0)))
        return d

    def _reap_lost(self) -> None:
        """A spawned child that died under us (SIGKILL, OOM, crash) can
        never come back on its own port — deregister it so the ring and
        pools move on, and book it killed.  The policy's min-replicas
        floor then decides whether a replacement spawns."""
        for r in self.registry.all():
            child = r.process
            if child is None or child.alive:
                continue
            _logger.warning("replica %s: child exited %s outside "
                            "retirement — deregistering", r.id,
                            child.proc.returncode)
            self.metrics.replicas_killed_total.inc()
            self.registry.remove(r.id)

    def _tend_standbys(self) -> None:
        """Keep the parked pool at ``standby_replicas``: reap dead
        children (booked killed, same as registry corpses), poll the
        unwarmed ones until their /readyz reports phase ``ready`` (fully
        warmed — a degraded standby would demote promotion back into a
        compile wait), and replenish while capacity slots remain."""
        if self.standby_replicas <= 0 and not self.standbys:
            return
        for s in list(self.standbys):
            if not s.proc.alive:
                _logger.warning("standby %s: child exited %s — reaping",
                                s.proc.netloc, s.proc.proc.returncode)
                self.metrics.replicas_killed_total.inc()
                self.standbys.remove(s)
                continue
            if not s.warmed:
                try:
                    status, _hdrs, body = http_request(
                        s.proc.netloc, "GET", "/readyz", timeout=2.0)
                    detail = json.loads(body.decode("utf-8"))
                except (OSError, ValueError):
                    continue          # still importing/compiling
                if status == 200 and detail.get("ready") and \
                        detail.get("phase", "ready") == "ready":
                    s.warmed = True
                    _logger.info("standby %s: fully warmed in %.1fs — "
                                 "parked for promotion", s.proc.netloc,
                                 time.monotonic() - s.born_t)
        while (len(self.standbys) < self.standby_replicas
               and len(self.registry.ids()) + len(self.standbys)
               < self.knobs.max_replicas):
            if self.tenant is not None:
                used = len(self.registry.ids()) + len(self.standbys)
                self.tenant.ensure_room(
                    self.knobs.max_replicas - (used + 1))
            child = ReplicaProcess(self.spawn_runner, free_port(),
                                   self.replica_args, env=self.child_env)
            self.standbys.append(_Standby(child, time.monotonic()))
            self.metrics.replicas_spawned_total.inc()
            _logger.info("autoscaler: warming standby %s (%d/%d)",
                         child.netloc, len(self.standbys),
                         self.standby_replicas)
        self.metrics.standby_replicas = len(self.standbys)

    def _promote_standby(self) -> bool:
        """Registry-promote the oldest warmed standby: the ms-scale
        scale-up path.  Booked as a scale-up but NOT a spawn (the spawn
        was booked when the standby was parked, keeping
        spawned == retired + killed + live + standby exact)."""
        for s in list(self.standbys):
            if not (s.warmed and s.proc.alive):
                continue
            self.standbys.remove(s)
            r = self.registry.add(s.proc.netloc, process=s.proc)
            r.warming = True          # first scrape flips it ready
            self.metrics.standby_replicas = len(self.standbys)
            self.metrics.standby_promotions_total.inc()
            self.metrics.autoscale_up_total.inc()
            _logger.info("autoscaler: scale-up -> promoted standby %s",
                         r.id)
            if self.trace is not None:
                self.trace.event("standby_promoted", replica=r.id)
            return True
        return False

    def _scale_up(self) -> None:
        if self._promote_standby():
            return                    # warm path: no spawn, no compile
        used = len(self.registry.ids()) + len(self.standbys)
        if used >= self.knobs.max_replicas and self.tenant is None:
            return                     # registry still holds a corpse
        if self.tenant is not None:
            # the slot we are about to take must be free of the other
            # tenant FIRST (SIGTERM → exit-75 lease release)
            self.tenant.ensure_room(
                self.knobs.max_replicas - (used + 1))
        port = free_port()
        child = ReplicaProcess(self.spawn_runner, port,
                               self.replica_args, env=self.child_env)
        r = self.registry.add(child.netloc, process=child)
        r.warming = True              # optimistic until the first scrape
        self.metrics.replicas_spawned_total.inc()
        self.metrics.autoscale_up_total.inc()
        _logger.info("autoscaler: scale-up -> spawned %s", r.id)

    def _scale_down(self) -> None:
        owned = [r for r in self.registry.all()
                 if r.process is not None and r.ready
                 and not r.draining]
        if not owned:
            return                    # nothing we own is retirable
        victim = min(owned, key=lambda r: (r.depth(), r.id))
        _logger.info("autoscaler: scale-in -> retiring %s (drain-first)",
                     victim.id)
        self.metrics.autoscale_down_total.inc()
        report = retire_replica(
            self.registry, self.metrics, victim.id,
            migrate_timeout_s=self.migrate_timeout_s,
            settle_timeout_s=self.settle_timeout_s,
            scraper=self.scraper)
        if self.trace is not None:
            self.trace.event("retired", replica=victim.id,
                             settled=report.get("settled"),
                             killed=report.get("killed"))

    # ------------------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        return {
            "enabled": True,
            "ticks": self.ticks,
            "last_action": self.last_decision.action,
            "last_reason": self.last_decision.reason,
            "target": self.metrics.autoscale_target_replicas,
            "policy": dataclasses.asdict(self.knobs),
            "books": {
                "spawned": self.metrics.replicas_spawned_total.value,
                "retired": self.metrics.replicas_retired_total.value,
                "killed": self.metrics.replicas_killed_total.value,
                "up": self.metrics.autoscale_up_total.value,
                "down": self.metrics.autoscale_down_total.value,
                "standby_promotions":
                    self.metrics.standby_promotions_total.value,
            },
            "standbys": {
                "target": self.standby_replicas,
                "parked": len(self.standbys),
                "warmed": sum(1 for s in self.standbys if s.warmed),
            },
            "tenant": (self.tenant.status()
                       if self.tenant is not None else None),
            "trace": self.trace.path if self.trace is not None else None,
        }

    # ------------------------------------------------------------------
    def start(self) -> None:
        assert self._thread is None, "autoscaler already started"
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-autoscaler",
                                        daemon=True)
        self._thread.start()

    def stop(self, stop_tenant: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        for s in self.standbys:
            s.proc.stop()
            self.metrics.replicas_killed_total.inc()
        self.standbys.clear()
        self.metrics.standby_replicas = 0
        if stop_tenant and self.tenant is not None:
            self.tenant.stop()
        if self.trace is not None:
            self.trace.event("autoscale_stop", ticks=self.ticks)
            self.trace.close()

    def _loop(self) -> None:
        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                self.tick(t0)
            except Exception:                      # noqa: BLE001
                _logger.exception("autoscaler tick failed")
            elapsed = time.monotonic() - t0
            self._stop.wait(max(0.05, self.interval_s - elapsed))
