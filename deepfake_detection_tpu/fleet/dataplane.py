"""Event-loop data plane: the ISSUE 16 router hot path.

The PR 15 threads plane spends ~0.5 ms of GIL-bound work per relayed
request (one thread per connection, header parse + byte relay), capping
the router near ~1.9k relays/s on the bench box no matter how many
replicas sit behind it.  This module rebuilds the hot path as a
single-threaded non-blocking event loop on stdlib ``selectors`` (epoll
on Linux; ``--relay-workers N`` shards accept across N loops via
SO_REUSEPORT):

* each accepted connection is a small state machine (``_Conn``) that
  parses exactly enough of the request head to resolve the route —
  method, path, Content-Length framing — then **splices bytes** between
  the client socket and a pooled non-blocking upstream socket with zero
  re-parsing and zero re-serialization: the upstream's response bytes
  are forwarded verbatim;
* deadlines (header-read, idle, upstream) live on a hashed timer wheel
  (``_TimerWheel``) — O(1) arm/advance, lazily re-filed, so slowloris
  and idle hardening cost nothing on the steady path;
* routing state is read lock-free (``Registry.view()`` /
  ``pick_stateless_fast`` / ``pick_stream_fast`` — immutable snapshot +
  GIL-atomic attribute reads), so the loop thread never blocks on the
  scraper;
* blocking control-plane verbs (``GET /streams`` fan-out, ``POST
  /replicas/<id>/drain|undrain`` migrations) run on ONE control worker
  thread and post completions back through a socketpair wake — the loop
  never blocks on them.

Behavior contract: identical to the threads plane.  Same RouterConfig,
same consistent-hash affinity, same shed-aware failover honoring
upstream Retry-After, same drain/migration overrides, same books
(``routed == cache_hit + forwarded + migrated + shed + failed``,
exactly one resolution per routed request), the same optional edge
verdict cache (``EdgeCache``, shared class), and the same
control-plane documents —
shared verbatim via ``fleet/router.py``'s module-level helpers, so the
aggregate ``/metrics`` re-export and ``/readyz`` JSON are byte-identical
across planes by construction.  tests/test_fleet.py runs parametrized
over both planes to pin this.

One deliberate divergence: a response larger than ``max_buffer_bytes``
is **streamed** (forwarded chunk-by-chunk with writability-gated
backpressure) instead of buffered.  The threads plane always buffers;
for streamed responses a mid-stream upstream tear after bytes already
reached the client cannot fail over — the connection closes and the
request books ``failed`` (exactly one resolution, still).

Must stay jax-free (dfdlint DFD001).
"""

from __future__ import annotations

import errno
import json
import logging
import queue
import random
import selectors
import socket
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ..serving.resilience import jittered_retry_after
from .controller import HealthScraper
from .metrics import RouterMetrics
from .registry import Registry, Replica
from .router import (FORWARD_HEADER_EXCLUDES, _MAX_BODY, _REPLICA_PATH,
                     _STREAM_PATH, EdgeCache, aggregate_metrics_text,
                     autoscaler_document, ensure_stream_id,
                     merged_streams, readyz_document, replica_operation)

_logger = logging.getLogger(__name__)

__all__ = ["EvLoopRouterServer"]

_RECV = 65536                 # one recv() granule (and streaming chunk)
_MAX_HEAD = 65536             # request head cap (threads: 414 on the line)

_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
            408: "Request Timeout", 414: "URI Too Long",
            501: "Not Implemented", 502: "Bad Gateway",
            503: "Service Unavailable"}

# deadline kinds (what to do when a connection's deadline fires)
_DL_IDLE = 0        # quiet between requests: close, count idle_closed
_DL_HEAD = 1        # mid-head slowloris: 408 + close, count idle_closed
_DL_BODY = 2        # stalled body sender: close, count idle_closed
_DL_UPSTREAM = 3    # upstream round trip too slow: transport error
_DL_DRAIN = 4       # full relay buffer not draining: shed (overflow)


class _TimerWheel:
    """Hashed timer wheel with lazy re-file.

    ``conn.deadline`` is the truth; wheel entries are hints.  ``arm``
    files a connection at its deadline's tick.  A deadline that moves
    LATER is handled lazily: the early entry fires, sees the deadline
    in the future, and re-files.  A deadline that moves EARLIER files
    an additional entry immediately (otherwise a short deadline — the
    10s header or 30s upstream one — would only fire at the stale 60s
    idle tick); ``conn.wheel_tick`` names the live entry so the stale
    later one is skipped when it fires.  O(1) arm, O(slot) advance —
    per-request deadline updates are two attribute writes on the
    steady path.
    """

    __slots__ = ("granularity", "nslots", "slots", "tick")

    def __init__(self, granularity: float = 0.25, nslots: int = 512):
        self.granularity = granularity
        self.nslots = nslots
        self.slots: List[list] = [[] for _ in range(nslots)]
        self.tick = 0          # next tick to process

    def _file(self, conn, deadline: float) -> None:
        t = max(int(deadline / self.granularity) + 1, self.tick)
        conn.wheel_tick = t
        self.slots[t % self.nslots].append((t, conn))

    def arm(self, conn, deadline: float, kind: int) -> None:
        conn.deadline = deadline
        conn.deadline_kind = kind
        if not conn.wheel_filed:
            conn.wheel_filed = True
            self._file(conn, deadline)
        elif int(deadline / self.granularity) + 1 < conn.wheel_tick:
            # moved earlier than the filed entry: file a fresh one (the
            # stale later entry no longer matches wheel_tick)
            self._file(conn, deadline)

    def disarm(self, conn) -> None:
        # lazy: the stale entry is dropped when its slot fires
        conn.deadline = 0.0

    def advance(self, now: float, expire) -> None:
        """Fire every slot up to ``now``; ``expire(conn, kind)`` runs
        for each connection whose deadline truly passed."""
        now_tick = int(now / self.granularity)
        while self.tick <= now_tick:
            slot = self.slots[self.tick % self.nslots]
            if slot:
                keep = []
                for t, conn in slot:
                    if t != self.tick:
                        keep.append((t, conn))   # a later wrap's entry
                        continue
                    if conn.wheel_tick != t:
                        continue     # superseded by an earlier re-file
                    conn.wheel_filed = False
                    if conn.closed or conn.deadline <= 0.0:
                        continue
                    if conn.deadline > now:
                        conn.wheel_filed = True
                        self._file(conn, conn.deadline)
                    else:
                        kind = conn.deadline_kind
                        # clear BEFORE firing so a duplicate entry at
                        # this tick can never expire the conn twice
                        conn.deadline = 0.0
                        expire(conn, kind)
                self.slots[self.tick % self.nslots] = keep
            self.tick += 1


class _Upstream:
    """One non-blocking keep-alive socket to a replica.

    Idle (pooled): registered for READ so a replica-side close is seen
    and the socket dropped.  Busy: attached to a client ``_Conn``, its
    READ events feed the response splice.
    """

    __slots__ = ("sock", "rid", "netloc", "rbuf", "reused", "conn",
                 "closed", "outbuf", "out_off", "t0", "mask",
                 "deadline", "deadline_kind", "wheel_filed",
                 "wheel_tick", "last_head", "last_parsed")

    def __init__(self, netloc: str, rid: str):
        host, port = netloc.rsplit(":", 1)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setblocking(False)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        err = self.sock.connect_ex((host, int(port)))
        if err not in (0, errno.EINPROGRESS, errno.EWOULDBLOCK):
            self.sock.close()
            raise OSError(err, "connect failed")
        self.rid = rid
        self.netloc = netloc
        self.rbuf = bytearray()       # response accumulator
        self.outbuf: List[bytes] = []  # unsent request bytes
        self.out_off = 0
        self.reused = False
        self.conn = None              # busy: owning client _Conn
        self.closed = False
        self.t0 = 0.0                 # attempt start (upstream latency)
        self.mask = 0                 # current selector interest
        # wheel bookkeeping (idle upstreams carry no deadline; the
        # owning client conn carries the in-flight one)
        self.deadline = 0.0
        self.deadline_kind = _DL_UPSTREAM
        self.wheel_filed = False
        self.wheel_tick = 0
        # steady-state response-head cache: a replica answering the
        # same request shape emits byte-identical heads (modulo a
        # once-per-second Date tick) — skip the re-parse on a hit
        self.last_head = b""
        self.last_parsed = (0, 0, False)   # (status, length, close)

    def close(self) -> None:
        self.closed = True
        try:
            self.sock.close()
        except OSError:
            pass


class _Conn:
    """One accepted client connection: the splice state machine."""

    # FSM states
    HEAD, BODY, DISPATCH, RELAY = range(4)

    __slots__ = ("sock", "inbuf", "outbuf", "out_off", "out_len",
                 "state", "closed", "closing", "keep_alive", "mask",
                 "client_gone", "processing", "drain_wait",
                 # request under assembly / in flight
                 "method", "target", "path", "head_lines", "body",
                 "body_need", "t0",
                 # routing state
                 "kind", "sid", "creating", "tried", "attempts",
                 "saw_transport", "saw_shed", "resent", "replica",
                 "via_override", "u", "cache_key",
                 # response splice state
                 "resp_status", "resp_need", "resp_head_len",
                 "resp_streaming", "resp_sent_any", "resp_close",
                 "book_resolved",
                 # steady-state head cache (identical request heads on a
                 # keep-alive connection skip the parse + rebuild)
                 "head_cache", "hc_body_need", "fwd_cache",
                 # timer wheel
                 "deadline", "deadline_kind", "wheel_filed",
                 "wheel_tick")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.inbuf = bytearray()
        self.outbuf: List[bytes] = []
        self.out_off = 0              # offset into outbuf[0]
        self.out_len = 0              # total unflushed bytes
        self.state = _Conn.HEAD
        self.closed = False
        self.closing = False          # close once outbuf drains
        self.keep_alive = True
        self.client_gone = False      # EOF seen mid-request
        self.processing = False       # _on_client_bytes reentrancy guard
        self.drain_wait = False       # paused until outbuf drains
        self.mask = 0
        self.deadline = 0.0
        self.deadline_kind = _DL_IDLE
        self.wheel_filed = False
        self.wheel_tick = 0
        # parse products survive _reset_request: on a head-cache hit the
        # previous request's method/target/path/head_lines are reused
        self.method = ""
        self.target = ""
        self.path = ""
        self.head_lines: List[bytes] = []
        self.head_cache = b""
        self.hc_body_need = 0
        self.fwd_cache: Dict[str, bytes] = {}   # rid -> head sans CL
        self._reset_request()

    def _reset_request(self) -> None:
        self.body = bytearray()
        self.body_need = 0
        self.t0 = 0.0
        self.kind = ""                # "score" | "stream" | ""
        self.sid: Optional[str] = None
        self.creating = False
        self.tried: Set[str] = set()
        self.attempts = 0
        self.saw_transport = False
        self.saw_shed = False
        self.resent = False
        self.replica: Optional[Replica] = None
        self.via_override = False
        self.u: Optional[_Upstream] = None
        self.cache_key: Optional[str] = None   # edge-cache probe digest
        # (set only on a /score miss: the 200 relay populates under it)
        self.resp_status = 0
        self.resp_need = 0            # response body bytes still owed
        self.resp_head_len = 0        # head+CRLFCRLF bytes of the resp
        self.resp_streaming = False
        self.resp_sent_any = False
        self.resp_close = False       # upstream said Connection: close
        self.book_resolved = True     # False only while a routed
        # request is unresolved — _close_conn books it failed


def _hval(low: bytes, head: bytes, name: bytes) -> Optional[bytes]:
    """Value of header ``name`` in ``head`` (``low`` = head.lower()),
    or None.  Single-pass find — no header dict is ever built."""
    i = low.find(b"\n" + name + b":")
    if i < 0:
        return None
    j = i + 1 + len(name) + 1
    k = head.find(b"\r\n", j)
    if k < 0:
        k = head.find(b"\n", j)
        if k < 0:
            k = len(head)
    return head[j:k].strip()


class _ControlJob:
    __slots__ = ("fn", "conn", "loop")

    def __init__(self, fn, conn, loop):
        self.fn = fn
        self.conn = conn
        self.loop = loop


class _Loop:
    """One event loop: selector + listener shard + timer wheel + its own
    upstream pools.  Shares registry/metrics/config via the server."""

    def __init__(self, server: "EvLoopRouterServer",
                 listener: socket.socket):
        self.server = server
        self.registry = server.registry
        self.metrics = server.metrics
        self.listener = listener
        self.sel = selectors.DefaultSelector()
        self.wheel = _TimerWheel()
        self.conns: Set[_Conn] = set()
        self.pools: Dict[str, List[_Upstream]] = {}
        self._pool_gen = -1
        # control-plane completion channel (worker thread -> loop)
        self._done: List[Tuple[_Conn, int, bytes, str]] = []
        self._done_lock = threading.Lock()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self.sel.register(self.listener, selectors.EVENT_READ,
                          ("accept", None))
        self.sel.register(self._wake_r, selectors.EVENT_READ,
                          ("wake", None))
        # Date header cache (one strftime per second, as threads plane)
        self._date_second = -1
        self._date_value = ""

    # -- selector interest bookkeeping ---------------------------------
    def _set_mask(self, obj, sock: socket.socket, mask: int,
                  tag: str) -> None:
        if mask == obj.mask:
            return
        if obj.mask == 0:
            self.sel.register(sock, mask, (tag, obj))
        elif mask == 0:
            self.sel.unregister(sock)
        else:
            self.sel.modify(sock, mask, (tag, obj))
        obj.mask = mask

    # -- response construction (router-built documents only) -----------
    def _date(self, now: float) -> str:
        sec = int(now)
        if sec != self._date_second:
            self._date_second = sec
            self._date_value = time.strftime(
                "%a, %d %b %Y %H:%M:%S GMT", time.gmtime(sec))
        return self._date_value

    def _build(self, status: int, body: bytes,
               content_type: str = "application/json",
               extra: Optional[dict] = None, close: bool = False
               ) -> bytes:
        parts = [f"HTTP/1.1 {status} {_REASONS.get(status, 'X')}\r\n"
                 f"Server: dfd-router\r\nDate: {self._date(time.time())}"
                 f"\r\nContent-Type: {content_type}\r\n"
                 f"Content-Length: {len(body)}\r\n"]
        for k, v in (extra or {}).items():
            parts.append(f"{k}: {v}\r\n")
        if close:
            parts.append("Connection: close\r\n")
        parts.append("\r\n")
        return "".join(parts).encode("latin-1") + body

    def _respond(self, c: _Conn, status: int, body: bytes,
                 content_type: str = "application/json",
                 extra: Optional[dict] = None,
                 close: bool = False) -> None:
        self.metrics.count_request(status)
        if close:
            c.keep_alive = False
        self._enqueue(c, self._build(status, body, content_type, extra,
                                     close or not c.keep_alive))

    def _json(self, c: _Conn, status: int, obj: dict,
              extra: Optional[dict] = None, close: bool = False) -> None:
        self._respond(c, status, json.dumps(obj).encode(), extra=extra,
                      close=close)

    # -- outbound splice ------------------------------------------------
    def _enqueue(self, c: _Conn, data: bytes) -> None:
        if c.closed:
            return
        c.outbuf.append(data)
        c.out_len += len(data)
        self._flush(c)

    def _flush(self, c: _Conn) -> None:
        """Optimistic writes until EAGAIN; gate WRITE interest on a
        non-empty buffer (writability-gated backpressure)."""
        before = c.out_len
        try:
            while c.outbuf:
                chunk = c.outbuf[0]
                n = c.sock.send(chunk[c.out_off:] if c.out_off
                                else chunk)
                c.out_len -= n
                c.out_off += n
                if c.out_off >= len(chunk):
                    c.outbuf.pop(0)
                    c.out_off = 0
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._close_conn(c)
            return
        if c.drain_wait and c.out_len < before:
            # the reader is draining: keep the overflow-shed deadline
            # rolling — only a reader that stops making progress with
            # the buffer past its bound is ever shed
            c.deadline = time.monotonic() + self.server.idle_timeout_s
        if c.outbuf:
            self._set_mask(c, c.sock, c.mask | selectors.EVENT_WRITE,
                           "conn")
        else:
            if c.closing:
                self._close_conn(c)
                return
            if c.mask & selectors.EVENT_WRITE:
                self._set_mask(c, c.sock,
                               c.mask & ~selectors.EVENT_WRITE, "conn")
            if c.drain_wait:
                # overflow pause over: the relay buffer drained — go
                # back to serving (possibly pipelined) requests
                c.drain_wait = False
                self.wheel.arm(c, time.monotonic() +
                               self.server.idle_timeout_s, _DL_IDLE)
                if c.inbuf:
                    self._on_client_bytes(c)
                if c.closed or c.drain_wait:
                    return
                if not (c.mask & selectors.EVENT_READ):
                    self._set_mask(c, c.sock,
                                   c.mask | selectors.EVENT_READ,
                                   "conn")
            # a paused streaming upstream resumes once we drain below
            # the low-water mark
            u = c.u
            if (u is not None and c.resp_streaming and u.mask == 0
                    and not u.closed):
                self._set_mask(u, u.sock, selectors.EVENT_READ, "up")

    def _poison(self, c: _Conn) -> None:
        """Close once the (already enqueued) response flushes — or now,
        if it already has."""
        c.closing = True
        if not c.outbuf:
            self._close_conn(c)

    def _close_conn(self, c: _Conn) -> None:
        if c.closed:
            return
        c.closed = True
        if not c.book_resolved:
            # a routed request dies with its connection (client went
            # away mid-splice): still exactly one book resolution
            c.book_resolved = True
            self.metrics.failed_total.inc()
            self.metrics.latency["total"].observe(
                time.monotonic() - c.t0)
        self.conns.discard(c)
        if c.mask:
            try:
                self.sel.unregister(c.sock)
            except (KeyError, ValueError):
                pass
            c.mask = 0
        try:
            c.sock.close()
        except OSError:
            pass
        u = c.u
        if u is not None:
            # mid-request upstream: response state unknown, not
            # poolable.  The attempt is still live here (every resolved
            # attempt clears c.u first), so settle its accounting —
            # Replica.router_inflight must not stay inflated because
            # the client died mid-relay.
            c.u = None
            u.conn = None
            self._attempt_done(c, u)
            self._kill_upstream(u)

    def _finish_response(self, c: _Conn) -> None:
        """One request fully resolved and its response enqueued: go back
        to HEAD, processing pipelined leftover immediately."""
        if not c.keep_alive or c.client_gone:
            c.closing = True
            if not c.outbuf:
                self._close_conn(c)
            return
        c.state = _Conn.HEAD
        c._reset_request()
        # bounded-buffer guard: past a full relay buffer, PAUSE — stop
        # reading the next pipelined request until the buffer drains
        # (_flush resumes us).  Closing here would discard unsent
        # response bytes an actively-draining reader is still owed
        # (silent truncation booked as success); only a reader that
        # stops making progress is shed, on the _DL_DRAIN deadline.
        if c.out_len > self.server.max_buffer_bytes:
            c.drain_wait = True
            if c.mask & selectors.EVENT_READ:
                self._set_mask(c, c.sock,
                               c.mask & ~selectors.EVENT_READ, "conn")
            self.wheel.arm(c, time.monotonic() +
                           self.server.idle_timeout_s, _DL_DRAIN)
            return
        self.wheel.arm(c, time.monotonic() + self.server.idle_timeout_s,
                       _DL_IDLE)
        if c.inbuf:
            self._on_client_bytes(c)       # pipelined request already in
        elif not (c.mask & selectors.EVENT_READ):
            self._set_mask(c, c.sock, c.mask | selectors.EVENT_READ,
                           "conn")

    # -- accept / client reads ------------------------------------------
    def _accept(self) -> None:
        while True:
            try:
                sock, _ = self.listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
            except OSError:
                pass
            c = _Conn(sock)
            self.conns.add(c)
            self._set_mask(c, sock, selectors.EVENT_READ, "conn")
            self.wheel.arm(c, time.monotonic() +
                           self.server.idle_timeout_s, _DL_IDLE)

    def _on_conn_event(self, c: _Conn, mask: int) -> None:
        if c.closed:
            return
        if mask & selectors.EVENT_WRITE:
            self._flush(c)
            if c.closed:
                return
        if mask & selectors.EVENT_READ:
            try:
                data = c.sock.recv(_RECV)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._close_conn(c)
                return
            if not data:
                if c.state in (_Conn.HEAD, _Conn.BODY):
                    # no request in flight: plain disconnect
                    self._close_conn(c)
                    return
                # EOF with a routed request in flight: let the upstream
                # resolve so the books stay exact, then close
                c.client_gone = True
                self._set_mask(c, c.sock,
                               c.mask & ~selectors.EVENT_READ, "conn")
                return
            c.inbuf += data
            if c.state in (_Conn.HEAD, _Conn.BODY):
                self._on_client_bytes(c)
            elif len(c.inbuf) > self.server.max_buffer_bytes:
                # a request is in flight: pipelined bytes accumulate in
                # inbuf; stop reading past a full buffer (resumed when
                # the in-flight response finishes)
                self._set_mask(c, c.sock,
                               c.mask & ~selectors.EVENT_READ, "conn")

    def _on_client_bytes(self, c: _Conn) -> None:
        """Drive the FSM off whatever sits in ``inbuf``.  Loops so a
        pipelined burst is consumed without extra selector turns; the
        ``processing`` guard makes nested calls (a synchronous dispatch
        finishing its response) fold into this loop instead of recursing
        once per pipelined request."""
        if c.processing:
            return
        c.processing = True
        try:
            self._client_fsm(c)
        finally:
            c.processing = False

    def _client_fsm(self, c: _Conn) -> None:
        while not c.closed and not c.drain_wait:
            if c.state == _Conn.HEAD:
                idx = c.inbuf.find(b"\r\n\r\n")
                if idx < 0:
                    if len(c.inbuf) > _MAX_HEAD:
                        self._respond(c, 414, b'{"error": "head too '
                                      b'large"}', close=True)
                        self._poison(c)
                        return
                    if c.inbuf and c.deadline_kind != _DL_HEAD:
                        # first head byte: arm the slowloris deadline
                        # ONCE — trickling more bytes must not push it
                        self.wheel.arm(
                            c, time.monotonic() +
                            self.server.header_timeout_s, _DL_HEAD)
                    return
                head = bytes(c.inbuf[:idx + 4])
                del c.inbuf[:idx + 4]
                if head == c.head_cache:
                    # steady state: byte-identical head — reuse last
                    # parse (method/target/path/head_lines persist)
                    c.body_need = c.hc_body_need
                else:
                    if not self._parse_head(c, head):
                        return
                    c.head_cache = head
                    c.hc_body_need = c.body_need
                    c.fwd_cache.clear()
                c.state = _Conn.BODY
            if c.state == _Conn.BODY:
                if c.body_need > 0 and c.inbuf:
                    take = min(c.body_need, len(c.inbuf))
                    c.body += c.inbuf[:take]
                    del c.inbuf[:take]
                    c.body_need -= take
                if c.body_need > 0:
                    # wait for more client bytes; rolling deadline —
                    # progress resets it (the threads plane's per-recv
                    # socket timeout semantics)
                    self.wheel.arm(c, time.monotonic() +
                                   self.server.idle_timeout_s, _DL_BODY)
                    return
                c.state = _Conn.DISPATCH
                # READ stays armed: pipelined bytes accumulate in inbuf
                # (bounded in _on_conn_event) with no epoll churn
                self.wheel.disarm(c)
                self._dispatch(c)
                if c.state != _Conn.HEAD:
                    return            # routed: resolves off an event
                continue              # synchronous resolve: next request
            if c.state != _Conn.HEAD:
                return

    def _parse_head(self, c: _Conn, head: bytes) -> bool:
        eol = head.find(b"\r\n")
        line = head[:eol]
        parts = line.split()
        if len(parts) != 3:
            self._respond(c, 400, b'{"error": "malformed request '
                          b'line"}', close=True)
            self._poison(c)
            return False
        method = parts[0].decode("latin-1")
        c.method = method
        c.target = parts[1].decode("latin-1")
        c.path = c.target.split("?", 1)[0]
        version = parts[2]
        low = head.lower()
        conn_tok = _hval(low, head, b"connection") or b""
        if version == b"HTTP/1.0":
            c.keep_alive = conn_tok.lower() == b"keep-alive"
        else:
            c.keep_alive = conn_tok.lower() != b"close"
        if method not in ("GET", "POST", "DELETE"):
            self._json(c, 501,
                       {"error": f"Unsupported method ({method!r})"},
                       close=True)
            self._poison(c)
            return False
        if _hval(low, head, b"transfer-encoding") is not None:
            # drain-or-poison discipline: chunked framing is never
            # spliced — reject and poison the connection
            self._json(c, 400, {"error": "unreadable/oversize body"},
                       close=True)
            self._poison(c)
            return False
        cl = _hval(low, head, b"content-length")
        try:
            length = int(cl) if cl is not None else 0
        except ValueError:
            length = -1
        if not 0 <= length <= _MAX_BODY:
            self._json(c, 400, {"error": "unreadable/oversize body"},
                       close=True)
            self._poison(c)
            return False
        c.body_need = length
        # forwardable header lines, verbatim (hop-by-hop excluded)
        c.head_lines = []
        for hl in head[eol + 2:-4].split(b"\r\n"):
            key = hl.split(b":", 1)[0].strip().lower()
            if key and key.decode("latin-1") not in \
                    FORWARD_HEADER_EXCLUDES:
                c.head_lines.append(hl)
        return True

    # -- dispatch --------------------------------------------------------
    def _dispatch(self, c: _Conn) -> None:
        method, path = c.method, c.path
        if method == "POST":
            if path.startswith("/replicas/"):
                m = _REPLICA_PATH.match(path)
                if m:
                    srv = self.server
                    rid, op = m.group(1), m.group(2) or ""
                    self._control(c, lambda: replica_operation(
                        self.registry, self.metrics, srv._drain_lock,
                        rid, op, srv.migrate_timeout_s))
                    return
            return self._proxy(c)
        if method == "GET":
            if path == "/healthz":
                self._respond(c, 200, b"ok\n", "text/plain")
                return self._finish_response(c)
            if path == "/readyz":
                status, body = readyz_document(self.registry,
                                               self.metrics)
                self._respond(c, status, body)
                return self._finish_response(c)
            if path == "/metrics":
                self._respond(c, 200, aggregate_metrics_text(
                    self.registry, self.metrics).encode(),
                    "text/plain; version=0.0.4; charset=utf-8")
                return self._finish_response(c)
            if path == "/replicas":
                self._json(c, 200, {r.id: r.summary()
                                    for r in self.registry.all()})
                return self._finish_response(c)
            if path == "/autoscaler":
                status, body = autoscaler_document(
                    getattr(self.server, "autoscaler", None))
                self._respond(c, status, body)
                return self._finish_response(c)
            if path == "/streams":
                srv = self.server
                self._control(c, lambda: (200, merged_streams(
                    self.registry, srv.upstream_timeout_s)))
                return
        self._proxy(c)

    def _control(self, c: _Conn, fn) -> None:
        """Run a blocking control verb on the worker thread; the
        completion is posted back through the wake socketpair."""
        self.server._control_q.put(_ControlJob(fn, c, self))

    def control_done(self, c: _Conn, status: int, body: bytes) -> None:
        if c.closed:
            return
        self._respond(c, status, body)
        self._finish_response(c)

    # -- proxy path: exactly one book per routed request ----------------
    def _proxy(self, c: _Conn) -> None:
        method, path = c.method, c.path
        if path == "/score":
            m = None
        else:
            m = _STREAM_PATH.match(path)
            if not ((path == "/streams" and method == "POST") or m):
                self._json(c, 404, {"error": f"no route {path!r}"})
                return self._finish_response(c)
        # client-error rejections resolve BEFORE the books (parity with
        # the threads plane: routed only counts placeable requests)
        if m and m.group(2) == "/migrate" and method == "POST":
            self._json(c, 400, {"error": "migrate via POST "
                                         "/replicas/<id>/drain"})
            return self._finish_response(c)
        if path == "/streams/restore" and method == "POST":
            self._json(c, 400, {"error": "restore via POST "
                                         "/replicas/<id>/drain (a "
                                         "restore bypassing the router "
                                         "desyncs stream affinity)"})
            return self._finish_response(c)
        body = bytes(c.body)
        if method == "POST" and path == "/streams":
            sid, body = ensure_stream_id(body)
            if sid is None:
                self._json(c, 400, {"error": "body must be empty or a "
                                             "JSON object"})
                return self._finish_response(c)
            c.sid = sid
            c.creating = True
            c.body = bytearray(body)
        c.t0 = time.monotonic()
        self.metrics.routed_total.inc()
        c.book_resolved = False
        c.state = _Conn.RELAY
        if path == "/score":
            c.kind = "score"
            cache = self.server.edge_cache
            if cache is not None:
                ct = b""
                for hl in c.head_lines:
                    if hl[:13].lower() == b"content-type:":
                        ct = hl[13:].strip()
                        break
                c.cache_key = EdgeCache.request_key(
                    method, c.target, ct.decode("latin-1"), body)
                hit = cache.get(c.cache_key)
                if hit is not None:
                    # edge verdict-cache resolution: one book, no
                    # replica touched (parity with the threads plane)
                    self.metrics.cache_hit_total.inc()
                    c.book_resolved = True
                    self._respond(c, hit[0], hit[2], hit[1])
                    if c.closed:
                        return
                    return self._resolve(c)
            self._next_attempt(c)
        else:
            c.kind = "stream"
            if not c.creating:
                c.sid = m.group(1)
            self._route_stream(c)

    def _resolve(self, c: _Conn) -> None:
        """Common tail of every book resolution: total latency."""
        self.metrics.latency["total"].observe(time.monotonic() - c.t0)
        self._finish_response(c)

    def _shed(self, c: _Conn, note: str,
              extra: Optional[dict] = None) -> None:
        self.metrics.shed_total.inc()
        c.book_resolved = True
        ra = self.server.shed_retry_after()
        self._json(c, 503, {"error": note, **(extra or {})},
                   extra={"Retry-After": max(1, round(ra))})
        self._resolve(c)

    def _fail(self, c: _Conn, note: str) -> None:
        self.metrics.failed_total.inc()
        c.book_resolved = True
        self._json(c, 502, {"error": note})
        self._resolve(c)

    def _route_stream(self, c: _Conn) -> None:
        if c.creating:
            # a NEW stream re-using a migrated-then-closed id binds to
            # its ring home, not the stale migration target
            self.registry.clear_override(c.sid)
        r, via_override = self.registry.pick_stream_fast(c.sid)
        if r is None:
            return self._shed(c, "no replicas registered")
        if not (r.healthy and r.ready) or (r.draining and c.creating):
            return self._shed(c, f"stream home replica {r.id} "
                                 f"unavailable", {"replica": r.id})
        c.via_override = via_override
        self._attach_upstream(c, r)

    def _next_attempt(self, c: _Conn) -> None:
        """Stateless shed-aware failover: the async unrolling of the
        threads plane's ``_route_stateless`` loop."""
        srv = self.server
        while c.attempts < 1 + srv.route_retries:
            r = self.registry.pick_stateless_fast(exclude=c.tried)
            if r is None:
                break
            c.tried.add(r.id)
            if c.attempts:
                self.metrics.retries_total.inc()
            c.attempts += 1
            self._attach_upstream(c, r)
            return
        if c.saw_transport and not c.saw_shed:
            return self._fail(c, "replica transport errors exhausted "
                                 "the failover budget")
        self._shed(c, "fleet overloaded or no eligible replica, retry "
                      "later", {"tried": sorted(c.tried)})

    # -- upstream pool + splice -----------------------------------------
    def _pool_acquire(self, r: Replica) -> _Upstream:
        lst = self.pools.get(r.id)
        while lst:
            u = lst.pop()
            if u.closed:
                continue
            # READ stays registered across pool/attach transitions —
            # zero epoll churn on the steady-state path
            u.reused = True
            u.rbuf.clear()
            return u
        return _Upstream(r.netloc, r.id)

    def _pool_release(self, c: _Conn, u: _Upstream) -> None:
        u.conn = None
        c.u = None
        if u.closed or c.resp_close:
            self._kill_upstream(u)
            return
        u.rbuf.clear()
        # idle pooled sockets stay readable so replica-side closes are
        # seen immediately (EOF -> drop, never handed to a request)
        self._set_mask(u, u.sock, selectors.EVENT_READ, "up")
        self.pools.setdefault(u.rid, []).append(u)

    def _kill_upstream(self, u: _Upstream) -> None:
        if u.mask:
            try:
                self.sel.unregister(u.sock)
            except (KeyError, ValueError):
                pass
            u.mask = 0
        u.close()

    def _prune_pools(self) -> None:
        gen = self.registry.generation
        if gen == self._pool_gen:
            return
        self._pool_gen = gen
        live = {r.id: r for r in self.registry.view()}
        for rid in list(self.pools):
            rep = live.get(rid)
            if rep is None or not rep.healthy:
                for u in self.pools.pop(rid):
                    if not u.closed:
                        self._kill_upstream(u)
                        self.metrics.upstream_pool_closed_total.inc()

    def _forward_head(self, c: _Conn, r: Replica) -> bytes:
        # cached per (connection head, replica): only the
        # Content-Length varies (the body may be rewritten, e.g. stream
        # id injection), so the prefix is reusable verbatim
        prefix = c.fwd_cache.get(r.id)
        if prefix is None:
            parts = [f"{c.method} {c.target} HTTP/1.1\r\n"
                     f"Host: {r.netloc}\r\n".encode("latin-1")]
            for hl in c.head_lines:
                parts.append(hl + b"\r\n")
            prefix = b"".join(parts)
            c.fwd_cache[r.id] = prefix
        return prefix + b"Content-Length: %d\r\n\r\n" % len(c.body)

    def _attach_upstream(self, c: _Conn, r: Replica) -> None:
        try:
            u = self._pool_acquire(r)
        except OSError:
            return self._attempt_failed(c, r, timeout=False,
                                        connect=True)
        u.conn = c
        c.u = u
        c.replica = r
        c.resp_status = 0
        c.resp_need = 0
        c.resp_streaming = False
        c.resp_sent_any = False
        c.resp_close = False
        u.rbuf.clear()
        # head + body as ONE buffer: one send() on the fast path
        u.outbuf = [self._forward_head(c, r) + bytes(c.body)]
        u.out_off = 0
        u.t0 = time.monotonic()
        # lock-free inflight accounting (single loop thread per shard;
        # a lost update across shards skews depth by one, not books)
        r.router_inflight += 1
        self.wheel.arm(c, u.t0 + self.server.upstream_timeout_s,
                       _DL_UPSTREAM)
        self._pump_upstream_out(u)
        if not u.closed and not (u.mask & selectors.EVENT_READ):
            # fresh socket (connect in flight): register now; pooled
            # sockets kept READ across the attach
            self._set_mask(
                u, u.sock, selectors.EVENT_READ |
                (selectors.EVENT_WRITE if u.outbuf else 0), "up")

    def _attempt_done(self, c: _Conn, u: _Upstream) -> None:
        """Per-attempt accounting shared by success and error paths."""
        r = c.replica
        if r is not None:
            r.router_inflight = max(0, r.router_inflight - 1)
        self.metrics.latency["upstream"].observe(
            time.monotonic() - u.t0)
        self.wheel.disarm(c)

    def _pump_upstream_out(self, u: _Upstream) -> None:
        try:
            while u.outbuf:
                chunk = u.outbuf[0]
                n = u.sock.send(chunk[u.out_off:] if u.out_off
                                else chunk)
                u.out_off += n
                if u.out_off >= len(chunk):
                    u.outbuf.pop(0)
                    u.out_off = 0
        except (BlockingIOError, InterruptedError):
            if u.mask and not (u.mask & selectors.EVENT_WRITE):
                self._set_mask(u, u.sock, u.mask |
                               selectors.EVENT_WRITE, "up")
            return
        except OSError:
            c = u.conn
            if c is not None:
                self._upstream_error(c, timeout=False)
            else:
                self._kill_upstream(u)
            return
        if not u.outbuf and u.mask & selectors.EVENT_WRITE:
            self._set_mask(u, u.sock, selectors.EVENT_READ, "up")

    def _on_upstream_event(self, u: _Upstream, mask: int) -> None:
        if u.closed:
            return
        c = u.conn
        if c is None:
            # idle pooled socket: the only legitimate event is a
            # replica-side close — anything arriving means the socket
            # is no longer trustworthy for splicing, so drop it
            try:
                u.sock.recv(_RECV)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                pass
            self._kill_upstream(u)
            return
        if mask & selectors.EVENT_WRITE:
            self._pump_upstream_out(u)
            if u.closed:
                return
        if mask & selectors.EVENT_READ:
            try:
                data = u.sock.recv(_RECV)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._upstream_error(c, timeout=False)
                return
            if not data:
                self._upstream_error(c, timeout=False)
                return
            self._on_upstream_bytes(c, u, data)

    def _upstream_error(self, c: _Conn, timeout: bool) -> None:
        """Transport failure (EOF / reset / deadline) on the attempt's
        upstream.  Mirrors the threads plane's retry-once-on-reused
        rule: an idled-out keep-alive socket (EOF class, nothing
        relayed yet) retries the SAME replica once on a fresh socket; a
        timeout never retries (the replica may have the request — a
        resend would double-deliver)."""
        u = c.u
        r = c.replica
        self._attempt_done(c, u)
        c.u = None
        u.conn = None
        self._kill_upstream(u)
        if (u.reused and not timeout and not c.resp_sent_any
                and not c.resent and r is not None):
            c.resent = True
            self._attach_upstream(c, r)
            return
        self._attempt_failed(c, r, timeout=timeout, connect=False)

    def _attempt_failed(self, c: _Conn, r: Optional[Replica],
                        timeout: bool, connect: bool) -> None:
        rid = r.id if r is not None else "?"
        if c.resp_sent_any:
            # torn splice: bytes already reached the client — no
            # failover possible; exactly one book (failed) and close
            _logger.warning("replica %s: upstream tore mid-stream on "
                            "%s", rid, c.target)
            self.metrics.failed_total.inc()
            c.book_resolved = True
            self.metrics.latency["total"].observe(
                time.monotonic() - c.t0)
            self._close_conn(c)
            return
        if c.kind == "score":
            c.saw_transport = True
            c.resent = False
            _logger.warning("replica %s: transport error on %s "
                            "(failing over)", rid, c.target)
            self._next_attempt(c)
            return
        self._fail(c, f"stream home replica {rid} transport error")

    def _on_upstream_bytes(self, c: _Conn, u: _Upstream,
                           data: bytes) -> None:
        # refresh the round-trip deadline on progress (the threads
        # plane's per-recv socket timeout semantics)
        c.deadline = time.monotonic() + self.server.upstream_timeout_s
        if c.resp_streaming:
            if len(data) > c.resp_need:
                # overrun: bytes past Content-Length (e.g. a pipelined
                # next response on the keep-alive socket) must never be
                # spliced into the client's stream — and the socket's
                # framing is no longer trustworthy, so don't pool it
                data = data[:c.resp_need]
                c.resp_close = True
            c.resp_need -= len(data)
            self._enqueue(c, data)
            if c.closed:
                return
            if c.resp_need <= 0:
                self._relay_complete(c, u)
            elif c.out_len > self.server.max_buffer_bytes:
                # backpressure: stop reading the upstream until the
                # client drains below the mark (resumed in _flush)
                self._set_mask(u, u.sock, 0, "up")
            return
        u.rbuf += data
        if c.resp_status == 0:
            idx = u.rbuf.find(b"\r\n\r\n")
            if idx < 0:
                if len(u.rbuf) > _MAX_HEAD:
                    self._upstream_error(c, timeout=False)
                return
            head = bytes(u.rbuf[:idx + 4])
            if head == u.last_head:
                # steady state: byte-identical response head (modulo
                # the once-per-second Date tick) — skip the re-parse
                status, length, rclose = u.last_parsed
            else:
                low = head.lower()
                try:
                    status = int(head[9:12])
                except ValueError:
                    self._upstream_error(c, timeout=False)
                    return
                cl = _hval(low, head, b"content-length")
                try:
                    length = int(cl) if cl is not None else 0
                except ValueError:
                    self._upstream_error(c, timeout=False)
                    return
                rclose = (_hval(low, head, b"connection") or
                          b"").lower() == b"close"
                u.last_head = head
                u.last_parsed = (status, length, rclose)
            # shed responses (429/503 on /score) stay buffered however
            # large: the failover path needs the whole document
            c.resp_status = status
            c.resp_need = length
            c.resp_head_len = idx + 4
            c.resp_close = rclose
            if (idx + 4 + length > self.server.max_buffer_bytes
                    and status not in (429, 503)):
                # streaming splice: forward verbatim, book at the end
                c.resp_streaming = True
                c.resp_sent_any = True
                full = idx + 4 + length
                if len(u.rbuf) > full:
                    # overrun past Content-Length: clamp, don't pool
                    chunk = bytes(u.rbuf[:full])
                    c.resp_close = True
                else:
                    chunk = bytes(u.rbuf)
                c.resp_need = full - len(chunk)
                self._enqueue(c, chunk)
                u.rbuf.clear()
                if c.closed:
                    return
                if c.resp_need <= 0:
                    self._relay_complete(c, u)
                return
        total = c.resp_head_len + c.resp_need
        if len(u.rbuf) >= total:
            if len(u.rbuf) > total:
                # trailing bytes past the framed response: the socket
                # can't be trusted for reuse (clamped by the slice)
                c.resp_close = True
            self._buffered_response(c, u, total)

    def _buffered_response(self, c: _Conn, u: _Upstream,
                           total: int) -> None:
        status = c.resp_status
        raw = bytes(u.rbuf[:total])
        self._attempt_done(c, u)
        r = c.replica
        if c.kind == "score" and status in (429, 503):
            low = raw[:raw.find(b"\r\n\r\n") + 4].lower()
            ra = _hval(low, raw, b"retry-after")
            try:
                ra_s = float(ra) if ra is not None else 1.0
            except (TypeError, ValueError):
                ra_s = 1.0
            self.registry.mark_shed(u.rid, ra_s)
            c.saw_shed = True
            c.resent = False
            self._pool_release(c, u)
            self._next_attempt(c)
            return
        # success: relay the response bytes VERBATIM (zero
        # re-serialization), then the books — exactly one resolution
        if c.kind == "stream":
            if c.method == "DELETE" and 200 <= status < 300:
                self.registry.clear_override(c.sid)
            book = (self.metrics.migrated_total if c.via_override
                    else self.metrics.forwarded_total)
        else:
            book = self.metrics.forwarded_total
        book.inc()
        c.book_resolved = True
        self.metrics.count_forward(u.rid)
        self.metrics.count_request(status)
        if c.kind == "score" and c.cache_key is not None and status == 200:
            # populate the edge cache with the buffered body (streamed
            # responses never reach here — _relay_complete skips)
            head = raw[:c.resp_head_len]
            ct = _hval(head.lower(), head, b"content-type")
            self.server.edge_cache.put(
                c.cache_key, status,
                (ct or b"application/json").decode("latin-1"),
                raw[c.resp_head_len:])
        self._pool_release(c, u)
        self._enqueue(c, raw)
        if c.closed:
            return
        self.metrics.latency["total"].observe(time.monotonic() - c.t0)
        self._finish_response(c)

    def _relay_complete(self, c: _Conn, u: _Upstream) -> None:
        """Streamed response fully forwarded: book it now."""
        status = c.resp_status
        self._attempt_done(c, u)
        if c.kind == "stream":
            if c.method == "DELETE" and 200 <= status < 300:
                self.registry.clear_override(c.sid)
            book = (self.metrics.migrated_total if c.via_override
                    else self.metrics.forwarded_total)
        else:
            book = self.metrics.forwarded_total
        book.inc()
        c.book_resolved = True
        self.metrics.count_forward(u.rid)
        self.metrics.count_request(status)
        self._pool_release(c, u)
        self.metrics.latency["total"].observe(time.monotonic() - c.t0)
        self._finish_response(c)

    # -- deadlines -------------------------------------------------------
    def _expire(self, c, kind: int) -> None:
        if isinstance(c, _Upstream):
            return
        if kind == _DL_UPSTREAM:
            if c.u is not None:
                self._upstream_error(c, timeout=True)
            return
        if kind == _DL_DRAIN:
            # overflow shed: a full relay buffer made zero progress for
            # an entire idle window — the reader is genuinely stalled
            self.metrics.overflow_closed_total.inc()
            self._close_conn(c)
            return
        self.metrics.idle_closed_total.inc()
        if kind == _DL_HEAD:
            # slowloris: 408, then close once the response flushes
            self.metrics.count_request(408)
            self._enqueue(c, b"HTTP/1.1 408 Request Timeout\r\n"
                             b"Content-Length: 0\r\n"
                             b"Connection: close\r\n\r\n")
            c.closing = True
            if not c.outbuf:
                self._close_conn(c)
            return
        self._close_conn(c)

    # -- the loop --------------------------------------------------------
    def run(self, stop: threading.Event) -> None:
        sel = self.sel
        wheel = self.wheel
        granularity = wheel.granularity
        wheel.tick = int(time.monotonic() / granularity)
        while not stop.is_set():
            events = sel.select(granularity)
            for key, mask in events:
                tag, obj = key.data
                if tag == "conn":
                    self._on_conn_event(obj, mask)
                elif tag == "up":
                    self._on_upstream_event(obj, mask)
                elif tag == "accept":
                    self._accept()
                else:                      # wake
                    try:
                        self._wake_r.recv(4096)
                    except (BlockingIOError, InterruptedError, OSError):
                        pass
            with self._done_lock:
                done, self._done = self._done, []
            for conn, status, body, _ in done:
                self.control_done(conn, status, body)
            wheel.advance(time.monotonic(), self._expire)
            self._prune_pools()
        self.close()

    def post_completion(self, conn: _Conn, status: int,
                        body: bytes) -> None:
        """Called from the control worker thread."""
        with self._done_lock:
            self._done.append((conn, status, body, ""))
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    def close(self) -> None:
        for c in list(self.conns):
            self._close_conn(c)
        for lst in self.pools.values():
            for u in lst:
                if not u.closed:
                    self._kill_upstream(u)
        self.pools.clear()
        try:
            self.sel.close()
        except OSError:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass


class EvLoopRouterServer:
    """Event-loop router server: the RouterServer surface (serve_forever
    / shutdown / server_close / server_address + fleet wiring), hot path
    on :class:`_Loop` threads instead of a thread per connection."""

    def __init__(self, addr: Tuple[str, int], registry: Registry,
                 metrics: RouterMetrics, scraper: HealthScraper, *,
                 relay_workers: int = 1,
                 route_retries: int = 2, upstream_timeout_s: float = 30.0,
                 shed_retry_after_s: float = 1.0,
                 retry_jitter_s: float = 2.0,
                 migrate_timeout_s: float = 30.0,
                 idle_timeout_s: float = 60.0,
                 header_timeout_s: float = 10.0,
                 max_buffer_bytes: int = 1 << 20,
                 edge_cache_entries: int = 0,
                 edge_cache_ttl_s: float = 2.0):
        self.registry = registry
        self.metrics = metrics
        self.scraper = scraper
        self.route_retries = max(0, int(route_retries))
        self.upstream_timeout_s = float(upstream_timeout_s)
        self.shed_retry_after_s = float(shed_retry_after_s)
        self.retry_jitter_s = float(retry_jitter_s)
        self.migrate_timeout_s = float(migrate_timeout_s)
        self.idle_timeout_s = float(idle_timeout_s)
        self.header_timeout_s = float(header_timeout_s)
        self.max_buffer_bytes = int(max_buffer_bytes)
        # optional edge verdict cache (ISSUE 17), shared across loops
        # (VerdictCache is internally locked): 0 entries = off
        self.edge_cache = (
            EdgeCache(registry, edge_cache_entries, edge_cache_ttl_s,
                      max_value_bytes=self.max_buffer_bytes)
            if int(edge_cache_entries) > 0 else None)
        self.relay_workers = max(1, int(relay_workers))
        # same seeded-rng shed jitter as the threads plane (DFD003;
        # pinned by the seeded-spread test run against both planes)
        self._shed_rng = random.Random(0x0F1EE7)
        self._shed_rng_lock = threading.Lock()
        self._drain_lock = threading.Lock()
        #: the control loop (ISSUE 18), attached by the runner when
        #: --autoscale is set; serves GET /autoscaler on both planes
        self.autoscaler = None
        self._stop = threading.Event()
        self._started = threading.Event()
        self._threads: List[threading.Thread] = []
        self._control_q: "queue.Queue[Optional[_ControlJob]]" = \
            queue.Queue()
        # listeners: one per worker, SO_REUSEPORT-sharded accept
        self._listeners: List[socket.socket] = []
        host, port = addr
        for i in range(self.relay_workers):
            ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if self.relay_workers > 1:
                ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            ls.bind((host, port))
            if port == 0:
                port = ls.getsockname()[1]
            ls.listen(256)
            ls.setblocking(False)
            self._listeners.append(ls)
        self.server_address = self._listeners[0].getsockname()
        self._loops = [_Loop(self, ls) for ls in self._listeners]

    # -- RouterServer surface -------------------------------------------
    def shed_retry_after(self) -> float:
        with self._shed_rng_lock:
            return jittered_retry_after(self.shed_retry_after_s,
                                        self.retry_jitter_s,
                                        self._shed_rng)

    def serve_forever(self, poll_interval: Optional[float] = None
                      ) -> None:
        del poll_interval            # signature parity with socketserver
        ts = [threading.Thread(target=lo.run, args=(self._stop,),
                               name=f"dfd-evloop-{i}", daemon=True)
              for i, lo in enumerate(self._loops)]
        ts.append(threading.Thread(target=self._control_worker,
                                   name="dfd-evloop-control",
                                   daemon=True))
        self._threads = ts
        for t in ts:
            t.start()
        self._started.set()
        self._stop.wait()

    def shutdown(self) -> None:
        self._stop.set()
        self._control_q.put(None)
        for lo in self._loops:
            try:
                lo._wake_w.send(b"x")     # pop the select() immediately
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=10.0)
        self._threads = []

    def server_close(self) -> None:
        self._stop.set()
        for ls in self._listeners:
            try:
                ls.close()
            except OSError:
                pass

    # -- control worker --------------------------------------------------
    def _control_worker(self) -> None:
        while True:
            job = self._control_q.get()
            if job is None or self._stop.is_set():
                return
            try:
                status, doc = job.fn()
                body = json.dumps(doc).encode()
            except Exception as e:                 # noqa: BLE001
                _logger.exception("control operation failed")
                status, body = 500, json.dumps(
                    {"error": f"control operation failed: {e!r}"}
                ).encode()
            job.loop.post_completion(job.conn, status, body)
