"""Fleet controller: replica health scraping + optional replica process
management.

**No new instrumentation in the engine**: replica health is derived
entirely from signals the serve/stream stack already exports —

* ``GET /readyz`` — the per-model JSON readiness detail (status 503 with
  a parseable body means *cold model warming*; a connection error means
  *engine down* — the distinction the router needs to route around a
  re-warm without declaring the replica dead);
* ``GET /metrics`` — breaker state (``dfd_serving_breaker_state``),
  queue depth, inflight and the full exposition text (kept verbatim for
  the router's ``replica=``-labeled re-export).

A replica whose scrape fails ``fail_after`` consecutive times is marked
down (an open breaker or a watchdog re-warm drains traffic away much
earlier, via ready=False / breaker_state on the same scrape).

:class:`ReplicaProcess` spawns one ``runners/serve.py`` /
``runners/stream.py`` child per replica for the self-hosted topology
(``runners/router.py --spawn N``); the harnesses spawn their own
children and attach by URL instead.  The controller itself must stay
jax-free (dfdlint DFD001) — children import the accelerator stack, the
router tier never does.
"""

from __future__ import annotations

import http.client
import json
import logging
import shlex
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from .metrics import RouterMetrics
from .registry import Registry, Replica

_logger = logging.getLogger(__name__)

__all__ = ["HealthScraper", "ReplicaProcess", "free_port",
           "http_request", "parse_exposition"]


def free_port(host: str = "127.0.0.1") -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def http_request(netloc: str, method: str, path: str, body: bytes = b"",
                 headers: Optional[dict] = None, timeout: float = 5.0
                 ) -> tuple:
    """One short-lived HTTP round trip → (status, headers dict, body).
    Raises OSError on transport failure (the caller's down-detection)."""
    host, port = netloc.rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request(method, path, body or None, headers or {})
        resp = conn.getresponse()
        data = resp.read()
        hdrs = {k.lower(): v for k, v in resp.getheaders()}
        return resp.status, hdrs, data
    except http.client.HTTPException as e:
        raise OSError(f"bad HTTP response from {netloc}: {e!r}") from e
    finally:
        conn.close()


def parse_exposition(text: str) -> Dict[str, float]:
    """Unlabeled samples of one exposition document → {name: value}."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) == 2 and "{" not in parts[0]:
            try:
                out[parts[0]] = float(parts[1])
            except ValueError:
                pass
    return out


class HealthScraper:
    """One thread polling every replica's /readyz + /metrics on a fixed
    cadence, folding the results into the registry's routing state."""

    def __init__(self, registry: Registry, metrics: RouterMetrics,
                 interval_s: float = 0.5, fail_after: int = 3,
                 timeout_s: float = 2.0):
        self.registry = registry
        self.metrics = metrics
        self.interval_s = float(interval_s)
        self.fail_after = max(1, int(fail_after))
        self.timeout_s = float(timeout_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def scrape_once(self, r: Replica) -> None:
        """Scrape one replica; mutates its routing state in place."""
        try:
            status, _, body = http_request(
                r.netloc, "GET", "/readyz", timeout=self.timeout_s)
            try:
                readiness = json.loads(body)
            except ValueError:
                readiness = None      # pre-JSON replicas: status rules
            _, _, mtext = http_request(
                r.netloc, "GET", "/metrics", timeout=self.timeout_s)
        except OSError:
            self.metrics.scrape_errors_total.inc()
            r.consecutive_failures += 1
            if r.consecutive_failures >= self.fail_after and r.healthy:
                _logger.warning("replica %s: %d consecutive scrape "
                                "failures — marking DOWN", r.id,
                                r.consecutive_failures)
                self.metrics.replicas_down_total.inc()
                r.healthy = False
                r.ready = False
                r.exposition = None
                # pool owners prune on generation change: a down
                # replica's pooled upstream sockets close instead of
                # leaking until the pool owner's own lifetime ends
                self.registry.bump_generation()
            return
        text = mtext.decode("utf-8", "replace")
        samples = parse_exposition(text)
        was_healthy = r.healthy
        r.consecutive_failures = 0
        r.healthy = True
        r.ready = status == 200
        r.readiness = readiness if isinstance(readiness, dict) else None
        r.breaker_state = int(samples.get("dfd_serving_breaker_state", 0))
        r.queue_depth = int(samples.get("dfd_serving_queue_depth", 0))
        r.inflight = int(samples.get("dfd_serving_inflight", 0))
        r.exposition = text
        r.last_scrape_t = time.monotonic()
        if not was_healthy:
            _logger.info("replica %s: back up (ready=%s)", r.id, r.ready)

    def scrape_all(self) -> None:
        for r in self.registry.all():
            if self._stop.is_set():
                return
            self.scrape_once(r)
        self.metrics.set_fleet_gauges(self.registry.counts())

    # ------------------------------------------------------------------
    def start(self) -> None:
        assert self._thread is None, "scraper already started"
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-health-scraper",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                self.scrape_all()
            except Exception:                      # noqa: BLE001
                _logger.exception("health scrape pass failed")
            elapsed = time.monotonic() - t0
            self._stop.wait(max(0.05, self.interval_s - elapsed))


class ReplicaProcess:
    """One spawned replica child (serve or stream runner) on a local
    free port, with the terminate→kill shutdown escalation."""

    RUNNERS = ("serve", "stream")

    def __init__(self, runner: str, port: int, extra_args: str = "",
                 env: Optional[dict] = None):
        if runner not in self.RUNNERS:
            raise ValueError(f"runner must be one of {self.RUNNERS}, "
                             f"got {runner!r}")
        self.runner = runner
        self.port = int(port)
        self.cmd = [sys.executable, "-m",
                    f"deepfake_detection_tpu.runners.{runner}",
                    "--port", str(self.port)] + shlex.split(extra_args)
        _logger.info("spawning replica: %s", " ".join(self.cmd))
        self.proc = subprocess.Popen(self.cmd, env=env)

    @property
    def netloc(self) -> str:
        return f"127.0.0.1:{self.port}"

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def stop(self, timeout_s: float = 15.0) -> Optional[int]:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=timeout_s)
        return self.proc.returncode


def spawn_replicas(n: int, runner: str, extra_args: str = "",
                   env: Optional[dict] = None) -> List[ReplicaProcess]:
    """``n`` replica children on free local ports (the --spawn path)."""
    return [ReplicaProcess(runner, free_port(), extra_args, env=env)
            for _ in range(n)]
