"""Fleet controller: replica health scraping + optional replica process
management.

**No new instrumentation in the engine**: replica health is derived
entirely from signals the serve/stream stack already exports —

* ``GET /readyz`` — the per-model JSON readiness detail (status 503 with
  a parseable body means *cold model warming*; a connection error means
  *engine down* — the distinction the router needs to route around a
  re-warm without declaring the replica dead);
* ``GET /metrics`` — breaker state (``dfd_serving_breaker_state``),
  queue depth, inflight and the full exposition text (kept verbatim for
  the router's ``replica=``-labeled re-export).

A replica whose scrape fails ``fail_after`` consecutive times is marked
down (an open breaker or a watchdog re-warm drains traffic away much
earlier, via ready=False / breaker_state on the same scrape).

:class:`ReplicaProcess` spawns one ``runners/serve.py`` /
``runners/stream.py`` child per replica for the self-hosted topology
(``runners/router.py --spawn N``); the harnesses spawn their own
children and attach by URL instead.  The controller itself must stay
jax-free (dfdlint DFD001) — children import the accelerator stack, the
router tier never does.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import random
import shlex
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from .metrics import RouterMetrics
from .registry import Registry, Replica

_logger = logging.getLogger(__name__)

__all__ = ["HealthScraper", "ReplicaProcess", "free_port",
           "http_request", "parse_exposition", "retire_replica"]


def free_port(host: str = "127.0.0.1") -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def http_request(netloc: str, method: str, path: str, body: bytes = b"",
                 headers: Optional[dict] = None, timeout: float = 5.0
                 ) -> tuple:
    """One short-lived HTTP round trip → (status, headers dict, body).
    Raises OSError on transport failure (the caller's down-detection)."""
    host, port = netloc.rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request(method, path, body or None, headers or {})
        resp = conn.getresponse()
        data = resp.read()
        hdrs = {k.lower(): v for k, v in resp.getheaders()}
        return resp.status, hdrs, data
    except http.client.HTTPException as e:
        raise OSError(f"bad HTTP response from {netloc}: {e!r}") from e
    finally:
        conn.close()


def parse_exposition(text: str) -> Dict[str, float]:
    """Unlabeled samples of one exposition document → {name: value}."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) == 2 and "{" not in parts[0]:
            try:
                out[parts[0]] = float(parts[1])
            except ValueError:
                pass
    return out


class HealthScraper:
    """One thread polling every replica's /readyz + /metrics on a fixed
    cadence (jittered — the PR 10 anti-thundering-herd idiom, so N
    routers scraping one fleet never align their bursts), folding the
    results into the registry's routing state.

    Replica state is three-valued, not two (ISSUE 18): *warming* — a
    parseable 503 ``/readyz`` (cold model warming) OR a spawned child
    whose port is not bound yet, still inside ``spawn_grace_s`` and
    never yet scraped up — is distinct from *down*.  The autoscaler
    must never retire a replica it just spawned, and must count warming
    replicas toward capacity already in flight (or every control tick
    during a cold start would spawn another child)."""

    def __init__(self, registry: Registry, metrics: RouterMetrics,
                 interval_s: float = 0.5, fail_after: int = 3,
                 timeout_s: float = 2.0, spawn_grace_s: float = 900.0):
        self.registry = registry
        self.metrics = metrics
        self.interval_s = float(interval_s)
        self.fail_after = max(1, int(fail_after))
        self.timeout_s = float(timeout_s)
        self.spawn_grace_s = float(spawn_grace_s)
        # seeded: deterministic under test, decorrelated in a fleet of
        # routers (each process seeds with its own pid)
        self._rng = random.Random(0x5C8A9E ^ (id(self) & 0xFFFF))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def scrape_once(self, r: Replica, now: Optional[float] = None) -> None:
        """Scrape one replica; mutates its routing state in place."""
        now = time.monotonic() if now is None else now
        try:
            status, _, body = http_request(
                r.netloc, "GET", "/readyz", timeout=self.timeout_s)
            try:
                readiness = json.loads(body)
            except ValueError:
                readiness = None      # pre-JSON replicas: status rules
            _, _, mtext = http_request(
                r.netloc, "GET", "/metrics", timeout=self.timeout_s)
        except OSError:
            self.metrics.scrape_errors_total.inc()
            r.consecutive_failures += 1
            child = r.process
            child_dead = child is not None and not child.alive
            if not child_dead and not r.ever_up and child is not None \
                    and (now - r.born_t) < self.spawn_grace_s:
                # a just-spawned child that has not bound its port yet:
                # warming, NOT down — fail_after must not retire a cold
                # start (satellite 2: a dead socket and a parseable 503
                # are the same thing during startup)
                r.warming = True
                return
            if (r.consecutive_failures >= self.fail_after or child_dead) \
                    and (r.healthy or r.warming):
                _logger.warning(
                    "replica %s: %s — marking DOWN", r.id,
                    "child process exited" if child_dead else
                    f"{r.consecutive_failures} consecutive scrape "
                    f"failures")
                self.metrics.replicas_down_total.inc()
                r.healthy = False
                r.ready = False
                r.warming = False
                r.exposition = None
                # pool owners prune on generation change: a down
                # replica's pooled upstream sockets close instead of
                # leaking until the pool owner's own lifetime ends
                self.registry.bump_generation()
            return
        text = mtext.decode("utf-8", "replace")
        samples = parse_exposition(text)
        was_healthy = r.healthy
        r.consecutive_failures = 0
        r.healthy = True
        r.ready = status == 200
        r.ever_up = True
        r.readiness = readiness if isinstance(readiness, dict) else None
        # a parseable 503 /readyz is a live engine warming a cold model
        r.warming = (not r.ready) and isinstance(readiness, dict)
        r.breaker_state = int(samples.get("dfd_serving_breaker_state", 0))
        r.queue_depth = int(samples.get("dfd_serving_queue_depth", 0))
        r.inflight = int(samples.get("dfd_serving_inflight", 0))
        r.exposition = text
        r.last_scrape_t = now
        if not was_healthy:
            _logger.info("replica %s: back up (ready=%s)", r.id, r.ready)

    def scrape_all(self) -> None:
        for r in self.registry.all():
            if self._stop.is_set():
                return
            self.scrape_once(r)
        self.metrics.set_fleet_gauges(self.registry.counts())

    # ------------------------------------------------------------------
    def start(self) -> None:
        assert self._thread is None, "scraper already started"
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-health-scraper",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                self.scrape_all()
            except Exception:                      # noqa: BLE001
                _logger.exception("health scrape pass failed")
            elapsed = time.monotonic() - t0
            # jittered cadence: base interval + uniform [0, interval/5)
            # so N scrapers against one fleet decorrelate (the PR 10
            # jittered_retry_after idiom, seeded rng)
            jitter = self._rng.uniform(0.0, self.interval_s * 0.2)
            self._stop.wait(max(0.05, self.interval_s - elapsed) + jitter)


class ReplicaProcess:
    """One spawned replica child (serve or stream runner) on a local
    free port, with the terminate→kill shutdown escalation.

    ``stop()`` is the LAST step of retirement, not the whole of it —
    scale-in goes through :func:`retire_replica` (drain → bounded wait
    for migrations/inflight → terminate) so the lossless path is the
    default and the kill escalation is the exception it was meant to be.
    ``kill_escalated`` records whether the escalation fired (the
    ``dfd_router_replicas_killed_total`` book)."""

    RUNNERS = ("serve", "stream")

    def __init__(self, runner: str, port: int, extra_args: str = "",
                 env: Optional[dict] = None):
        if runner not in self.RUNNERS:
            raise ValueError(f"runner must be one of {self.RUNNERS}, "
                             f"got {runner!r}")
        self.runner = runner
        self.port = int(port)
        self.extra_args = extra_args
        self.kill_escalated = False
        self.cmd = [sys.executable, "-m",
                    f"deepfake_detection_tpu.runners.{runner}",
                    "--port", str(self.port)] + shlex.split(extra_args)
        _logger.info("spawning replica: %s", " ".join(self.cmd))
        # spawn timestamp for the child's cold-start stage breakdown
        # (dfd_serving_warmup_seconds{stage="spawn"}): wall-clock, since
        # monotonic clocks don't compare across processes
        child_env = dict(os.environ if env is None else env)
        child_env.setdefault("DFD_SPAWN_T", repr(time.time()))
        self.proc = subprocess.Popen(self.cmd, env=child_env)

    @property
    def netloc(self) -> str:
        return f"127.0.0.1:{self.port}"

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def stop(self, timeout_s: float = 15.0) -> Optional[int]:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.kill_escalated = True
                self.proc.kill()
                self.proc.wait(timeout=timeout_s)
        return self.proc.returncode


def spawn_replicas(n: int, runner: str, extra_args: str = "",
                   env: Optional[dict] = None) -> List[ReplicaProcess]:
    """``n`` replica children on free local ports (the --spawn path)."""
    return [ReplicaProcess(runner, free_port(), extra_args, env=env)
            for _ in range(n)]


def retire_replica(registry: Registry, metrics: RouterMetrics,
                   replica_id: str, *, migrate_timeout_s: float = 30.0,
                   settle_timeout_s: float = 20.0,
                   scraper: Optional[HealthScraper] = None,
                   stop_timeout_s: float = 15.0) -> dict:
    """Drain-first replica retirement — the lossless scale-in path.

    Order of operations (each step bounded):

    1. drain: mark the replica draining (no new traffic) and live-migrate
       its stream sessions to their ring successors (fleet/migrate.py —
       the PR 15 machinery, so affine streams move with their state);
    2. settle: wait up to ``settle_timeout_s`` for the replica's own
       inflight/queue and this router's outstanding proxied requests to
       reach zero (re-scraping if a scraper is given);
    3. terminate: graceful stop of the spawned child (if the controller
       owns one), with the kill escalation counted separately
       (``dfd_router_replicas_killed_total``) from the clean retirements
       (``dfd_router_replicas_retired_total``);
    4. deregister: remove from the registry (pools prune on generation).

    Returns the retirement report (drain report nested verbatim).
    """
    r = registry.get(replica_id)
    if r is None:
        return {"error": f"unknown replica {replica_id!r}",
                "replicas": registry.ids()}
    from .migrate import drain_replica    # function-level: migrate.py
    # imports this module (http_request) — module-level would be a cycle
    try:
        drain = drain_replica(registry, metrics, replica_id,
                              timeout_s=migrate_timeout_s)
    except Exception as e:                             # noqa: BLE001
        r.draining = True             # still stop new traffic
        drain = {"error": f"drain failed: {e!r}"}
    deadline = time.monotonic() + max(0.0, float(settle_timeout_s))
    settled = False
    while True:
        if scraper is not None and r.healthy:
            scraper.scrape_once(r)
        if r.router_inflight <= 0 and \
                (not r.healthy or (r.inflight <= 0 and
                                   r.queue_depth <= 0)):
            settled = True
            break
        if time.monotonic() >= deadline:
            break
        time.sleep(0.05)
    rc: Optional[int] = None
    killed = False
    child = r.process
    if child is not None:
        rc = child.stop(timeout_s=stop_timeout_s)
        killed = bool(getattr(child, "kill_escalated", False))
    if killed:
        metrics.replicas_killed_total.inc()
    else:
        metrics.replicas_retired_total.inc()
    registry.remove(replica_id)
    _logger.info("replica %s retired (settled=%s rc=%s killed=%s)",
                 replica_id, settled, rc, killed)
    return {"replica": replica_id, "drain": drain, "settled": settled,
            "rc": rc, "killed": killed}
