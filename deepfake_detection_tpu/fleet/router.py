"""The fleet router: a stdlib-HTTP tier in front of N shared-nothing
serve/stream replicas.

One Python process tops out at ~200–250 req/s of HTTP+dispatch host work
on this class of box no matter how fast the engine gets (SERVE_BENCH.md)
— the GIL ceiling binds before the device does.  The fleet answer is the
reference paper's: one worker per device behind a shared rendezvous,
here N independent ``runners/serve.py`` processes behind this router.
The router does strictly less per request than a replica (no JPEG
decode, no canvas resize, no JSON build — header parse + byte relay on
keep-alive sockets), so each replica added is a full unit of host *and*
device capacity.

Routing:

* ``POST /score`` (stateless) — least-depth eligible replica (scraped
  queue depth + inflight + this router's own outstanding proxies).  An
  upstream 429/503 marks the replica backed-off for its **Retry-After**
  (shed-aware: the hint is honored before any failover lands there
  again) and the request fails over to the next eligible replica;
  transport errors likewise.  When no replica remains the router sheds
  503 with a **jittered** Retry-After (the PR 10 idiom — a constant
  would synchronize every client into one resend wave).
* ``/streams/*`` (session-affine) — consistent-hash affinity
  (``registry.HashRing``): deterministic across router restarts, so a
  rebooted router keeps sending each stream to the replica holding its
  session.  A migration override (written when a drain moves a session)
  beats the ring.  Affine traffic never fails over — the session state
  has exactly one home — a down home replica is an honest 503 +
  Retry-After until it returns (``--state-dir`` restores its sessions on
  relaunch).
* Router-owned: ``/healthz``, ``/readyz`` (ready while ≥1 replica is
  eligible; JSON per-replica detail), ``/metrics`` (``dfd_router_*``
  catalog + every replica's exposition re-labeled ``replica="<id>"``),
  ``/replicas`` (+ ``POST /replicas/<id>/drain|undrain`` — drain
  live-migrates the replica's streams via fleet/migrate.py).

Books (asserted exactly by bench_serve + chaos_serve)::

    routed == cache_hit + forwarded + migrated + shed + failed

``cache_hit`` is the optional **edge verdict cache** (ISSUE 17): whole
``POST /score`` responses keyed on the exact request bytes under the
fleet *weights-epoch* (:class:`EdgeCache`), resolved at the router
without touching a replica.
"""

from __future__ import annotations

import hashlib
import json
import logging
import random
import re
import socket
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Set, Tuple

from ..cache import VerdictCache
from ..serving.resilience import jittered_retry_after
from .controller import HealthScraper, http_request
from .metrics import RouterMetrics, relabel_exposition
from .migrate import drain_replica, undrain_replica
from .registry import Registry, Replica

_logger = logging.getLogger(__name__)

__all__ = ["RouterServer", "make_router_server", "EdgeCache",
           "FORWARD_HEADER_EXCLUDES", "readyz_document",
           "aggregate_metrics_text", "merged_streams",
           "replica_operation", "ensure_stream_id",
           "autoscaler_document"]

_MAX_BODY = 64 * 1024 * 1024          # one frame chunk, not one image
_STREAM_PATH = re.compile(
    r"^/streams/([A-Za-z0-9_.-]{1,64})(/frames|/migrate)?$")
_REPLICA_PATH = re.compile(r"^/replicas/([^/]+)(/drain|/undrain)?$")

#: hop-by-hop / recomputed headers never forwarded upstream
FORWARD_HEADER_EXCLUDES = frozenset(
    {"host", "connection", "content-length", "transfer-encoding",
     "keep-alive"})

# ---------------------------------------------------------------------------
# control-plane documents, shared verbatim by BOTH data planes (threads
# here, the ISSUE 16 event loop in fleet/dataplane.py) — extracting them
# is what makes the aggregate /metrics re-export and the /readyz JSON
# byte-identical across planes by construction
# ---------------------------------------------------------------------------

def readyz_document(registry: Registry,
                    metrics: RouterMetrics) -> Tuple[int, bytes]:
    """(status, body) of ``GET /readyz``: ready while ≥1 replica is
    eligible, with the per-replica JSON detail."""
    counts = registry.counts()
    metrics.set_fleet_gauges(counts)
    body = (json.dumps({
        "ready": counts["eligible"] > 0,
        "counts": counts,
        "replicas": {r.id: r.summary() for r in registry.all()},
    }, sort_keys=True) + "\n").encode()
    return (200 if counts["eligible"] > 0 else 503), body


def aggregate_metrics_text(registry: Registry,
                           metrics: RouterMetrics) -> str:
    """Router catalog + every replica's last exposition re-labeled
    ``replica="<id>"`` (one scrape sees the whole fleet)."""
    metrics.set_fleet_gauges(registry.counts())
    lines = [metrics.render_prometheus().rstrip("\n")]
    seen: Set[str] = set()
    for r in registry.all():
        if r.exposition:
            lines.extend(relabel_exposition(r.exposition, r.id, seen))
    return "\n".join(lines) + "\n"


def merged_streams(registry: Registry, timeout_s: float) -> dict:
    """Fleet-wide stream listing (one blocking round trip per healthy
    replica — control plane, never the hot path)."""
    streams: Dict[str, str] = {}
    for r in registry.all():
        if not r.healthy:
            continue
        try:
            _, _, body = http_request(r.netloc, "GET", "/streams",
                                      timeout=timeout_s)
            for sid in json.loads(body).get("streams", []):
                streams[sid] = r.id
        except (OSError, ValueError):
            continue
    return {"streams": sorted(streams),
            "active": len(streams),
            "by_replica": streams}


def replica_operation(registry: Registry, metrics: RouterMetrics,
                      drain_lock: threading.Lock, replica_id: str,
                      op: str, migrate_timeout_s: float
                      ) -> Tuple[int, dict]:
    """(status, JSON body) of ``POST /replicas/<id>[/drain|/undrain]``.
    Blocking (migrations run inside) — control plane only."""
    if registry.get(replica_id) is None:
        return 404, {"error": f"unknown replica {replica_id!r}",
                     "replicas": registry.ids()}
    if op == "/drain":
        with drain_lock:
            return 200, drain_replica(registry, metrics, replica_id,
                                      timeout_s=migrate_timeout_s)
    if op == "/undrain":
        with drain_lock:
            return 200, undrain_replica(registry, metrics, replica_id)
    return 404, {"error": "POST /replicas/<id>/drain or /undrain"}


def autoscaler_document(autoscaler) -> Tuple[int, bytes]:
    """(status, body) of ``GET /autoscaler`` — shared by both data
    planes.  404 while autoscaling is off (the runner attaches the
    autoscaler to the server object when ``--autoscale`` is set)."""
    if autoscaler is None:
        return 404, (json.dumps({"enabled": False,
                                 "error": "autoscaler disabled "
                                          "(--autoscale)"},
                                sort_keys=True) + "\n").encode()
    return 200, (json.dumps(autoscaler.status(), sort_keys=True)
                 + "\n").encode()


def ensure_stream_id(body: bytes) -> Tuple[Optional[str], bytes]:
    """(stream id, possibly-rewritten body) for POST /streams; id is
    None when the body is unparseable (400 path).  Creation must pass
    through the router so it can hash the id — a client that did not
    name one gets a router-assigned id injected into the body."""
    payload: dict = {}
    if body:
        try:
            payload = json.loads(body)
        except ValueError:
            return None, body
        if not isinstance(payload, dict):
            return None, body
    sid = payload.get("stream_id")
    if not sid:
        sid = uuid.uuid4().hex[:12]
        payload["stream_id"] = sid
        body = json.dumps(payload).encode()
    return str(sid), body


class EdgeCache:
    """Router-edge verdict cache (ISSUE 17): whole ``POST /score``
    responses keyed on the exact request bytes, shared by BOTH data
    planes.

    The store is the jax-free :class:`~..cache.VerdictCache` under a
    synthetic model id ``"edge"`` whose *fingerprint* is the **fleet
    weights-epoch**: a digest of every ready replica's
    ``{model: checkpoint-fingerprint}`` map from its scraped ``/readyz``
    detail.  Any hot reload or quantized swap anywhere in the fleet
    moves a replica fingerprint, therefore the epoch, therefore the
    addressable key space — old entries are orphaned (and eagerly
    cleared) rather than invalidated one by one, the same story as the
    in-replica cache.

    Two honesty rules:

    * while ANY ready replica's readiness detail lacks model
      fingerprints (scrape not landed yet, mixed versions mid-rollout)
      the epoch is ``None`` and the cache **bypasses** — correctness
      never leans on scrape freshness;
    * the epoch only moves when a scrape lands, so an edge hit can be
      stale by at most ``min(ttl, scrape interval)`` after a reload —
      which is why the edge TTL defaults to seconds where the
      in-replica cache (exact by construction) defaults to minutes.
    """

    __slots__ = ("store", "registry", "max_value_bytes", "_epoch",
                 "_epoch_sig")

    def __init__(self, registry: Registry, entries: int, ttl_s: float,
                 *, max_value_bytes: int = 1 << 20):
        self.store = VerdictCache(int(entries), float(ttl_s))
        self.registry = registry
        # streamed / oversize responses are relayed, never buffered for
        # the cache: the router's memory bound stays the relay bound
        self.max_value_bytes = int(max_value_bytes)
        # epoch memo keyed on the identity of every replica's last
        # readiness doc (the scraper replaces the dict wholesale, so
        # ``id()`` moves iff a new scrape landed).  Unsynchronized by
        # design: the worst data race costs one redundant recompute.
        self._epoch: Optional[str] = None
        self._epoch_sig: Optional[tuple] = None

    @staticmethod
    def request_key(method: str, target: str, content_type: str,
                    body: bytes) -> str:
        """Exact byte identity of one request: method + target
        (query included) + content type + raw body."""
        h = hashlib.sha256()
        h.update(method.encode("latin-1", "replace"))
        h.update(b"\0")
        h.update(target.encode("latin-1", "replace"))
        h.update(b"\0")
        h.update((content_type or "").encode("latin-1", "replace"))
        h.update(b"\0")
        h.update(body)
        return h.hexdigest()

    def epoch(self) -> Optional[str]:
        view = self.registry.view()
        sig = tuple((r.id, id(r.readiness)) for r in view)
        if sig == self._epoch_sig:
            return self._epoch
        pairs: Optional[Set[str]] = set()
        for r in view:
            if not (r.healthy and r.ready):
                continue
            models = (r.readiness or {}).get("models")
            if not isinstance(models, dict) or not models:
                pairs = None
                break
            for mid, det in models.items():
                fp = det.get("fingerprint") \
                    if isinstance(det, dict) else None
                if not fp:
                    pairs = None
                    break
                pairs.add(f"{mid}={fp}")
            if pairs is None:
                break
        epoch = (hashlib.sha256("\n".join(sorted(pairs)).encode())
                 .hexdigest() if pairs else None)
        if self._epoch is not None and epoch != self._epoch:
            # the epoch moved (reload / membership change): every held
            # entry is unaddressable — reclaim eagerly
            self.store.clear()
        self._epoch_sig, self._epoch = sig, epoch
        return epoch

    def get(self, key: str):
        """(status, content_type, body) | None."""
        ep = self.epoch()
        if ep is None:
            return None
        return self.store.get(key, "edge", ep)

    def put(self, key: str, status: int, content_type: str,
            body: bytes) -> None:
        ep = self.epoch()
        if ep is None or len(body) > self.max_value_bytes:
            return
        self.store.put(key, "edge", ep,
                       (int(status), content_type, body))


#: per-thread upstream connection pool ({replica_id: _UpstreamConn}).
#: ThreadingHTTPServer runs one thread per client connection and clients
#: keep-alive, so the pool amortizes the upstream TCP handshake to zero
#: on the steady path — the router must do LESS host work per request
#: than a replica, or the fleet could never clear the host ceiling.
_tls = threading.local()


class _UpstreamConn:
    """One keep-alive raw socket to a replica with a minimal HTTP/1.1
    response reader (status line + headers + Content-Length body).

    ``http.client`` costs ~as much per round trip as the replica's own
    GIL-bound request handling — mostly ``email.parser`` on the response
    headers — which would cap the fleet near 1× no matter how many
    replicas sit behind the router (measured: ~1.3k relays/s object-churn
    path vs ~2.6k raw on this box).  The replicas always answer with
    Content-Length (the serving/streaming handlers never chunk), so the
    minimal reader is exact, and an upstream ``Connection: close`` marks
    the socket stale instead of being reused."""

    __slots__ = ("sock", "rfile", "stale")

    def __init__(self, netloc: str, timeout_s: float):
        host, port = netloc.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)),
                                             timeout=timeout_s)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.rfile = self.sock.makefile("rb")
        self.stale = False

    def close(self) -> None:
        try:
            self.rfile.close()
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass

    def round_trip(self, head: bytes,
                   body: bytes) -> Tuple[int, dict, bytes]:
        """Send one pre-serialized request, read one response.  Raises
        OSError on any transport/parse failure (caller drops the conn)."""
        try:
            self.sock.sendall(head + body if body else head)
            line = self.rfile.readline(65537)
            if not line:
                raise OSError("upstream closed the connection")
            status = int(line.split(b" ", 2)[1])
            hdrs: Dict[str, str] = {}
            while True:
                h = self.rfile.readline(65537)
                if h in (b"\r\n", b"\n", b""):
                    break
                k, _, v = h.partition(b":")
                hdrs[k.strip().lower().decode("latin-1")] = \
                    v.strip().decode("latin-1")
            length = int(hdrs.get("content-length", 0))
            data = self.rfile.read(length) if length > 0 else b""
            if len(data) != length:
                raise OSError("short upstream body")
        except (ValueError, IndexError) as e:
            raise OSError(f"unparseable upstream response: {e}") from e
        if hdrs.get("connection", "").lower() == "close":
            self.stale = True
        return status, hdrs, data


class RouterServer(ThreadingHTTPServer):
    """ThreadingHTTPServer carrying the fleet wiring."""

    daemon_threads = True
    protocol_version = "HTTP/1.1"
    # a fleet's worth of clients connects in one burst; the stdlib
    # default backlog of 5 turns that into SYN drops + 1s retransmit
    # stalls that read as mysterious tail latency
    request_queue_size = 256

    def __init__(self, addr: Tuple[str, int], registry: Registry,
                 metrics: RouterMetrics, scraper: HealthScraper, *,
                 route_retries: int = 2, upstream_timeout_s: float = 30.0,
                 shed_retry_after_s: float = 1.0,
                 retry_jitter_s: float = 2.0,
                 migrate_timeout_s: float = 30.0,
                 idle_timeout_s: float = 60.0,
                 header_timeout_s: float = 10.0,
                 max_buffer_bytes: int = 1 << 20,
                 edge_cache_entries: int = 0,
                 edge_cache_ttl_s: float = 2.0):
        super().__init__(addr, _RouterHandler)
        self.registry = registry
        self.metrics = metrics
        self.scraper = scraper
        self.route_retries = max(0, int(route_retries))
        self.upstream_timeout_s = float(upstream_timeout_s)
        self.shed_retry_after_s = float(shed_retry_after_s)
        self.retry_jitter_s = float(retry_jitter_s)
        self.migrate_timeout_s = float(migrate_timeout_s)
        # slowloris/idle hardening (ISSUE 16), matched by the evloop
        # plane: idle keep-alive connections and stalled header reads
        # are closed on deadline instead of pinning a thread forever
        self.idle_timeout_s = float(idle_timeout_s)
        self.header_timeout_s = float(header_timeout_s)
        # per-connection relay buffer bound — only the evloop plane
        # buffers, but both planes accept the knob so RouterConfig can
        # drive either through one kwargs dict
        self.max_buffer_bytes = int(max_buffer_bytes)
        # optional edge verdict cache (ISSUE 17): 0 entries = off
        self.edge_cache = (
            EdgeCache(registry, edge_cache_entries, edge_cache_ttl_s,
                      max_value_bytes=self.max_buffer_bytes)
            if int(edge_cache_entries) > 0 else None)
        # seeded: deterministic under test, de-correlated in production
        # (per-process stream; DFD003 discipline)
        self._shed_rng = random.Random(0x0F1EE7)
        self._shed_rng_lock = threading.Lock()
        #: serializes drain/undrain (a drain mid-drain would double-move)
        self._drain_lock = threading.Lock()
        #: the control loop (ISSUE 18), attached by the runner when
        #: --autoscale is set; serves GET /autoscaler on both planes
        self.autoscaler = None

    def shed_retry_after(self) -> float:
        """Router-level shed Retry-After: base + bounded uniform jitter
        (serving/resilience.py's ``jittered_retry_after`` — the PR 10
        idiom, pinned by a seeded-rng spread test)."""
        with self._shed_rng_lock:
            return jittered_retry_after(self.shed_retry_after_s,
                                        self.retry_jitter_s,
                                        self._shed_rng)


class _Headers(dict):
    """Minimal case-insensitive header map (keys stored lower-case) —
    just the surface the proxy path reads (``get``/``items``)."""

    def get(self, key, default=None):          # noqa: A003 (stdlib API)
        return dict.get(self, key.lower(), default)


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # headers and body go out as two writes; with Nagle on, the second
    # waits on the client's delayed ACK of the first (~40 ms a hop) —
    # the classic small-response stall, measured on this box
    disable_nagle_algorithm = True
    server: RouterServer      # typing aid

    def log_message(self, fmt, *args):
        _logger.debug("%s " + fmt, self.address_string(), *args)

    def setup(self) -> None:
        # arm the idle deadline as the socket timeout: a keep-alive
        # connection that goes quiet stops costing a thread at
        # idle_timeout_s instead of forever
        self.timeout = self.server.idle_timeout_s
        # bytes read past the head terminator (the head read pulls one
        # raw recv at a time, which can over-read into the body or the
        # next pipelined request); consumed before rfile everywhere
        self._head_excess = b""
        super().setup()

    # Date-header cache: BaseHTTP's send_response runs strftime per
    # response; at fleet rates that is real GIL time.  Worst case of the
    # benign class-attr race is one redundant strftime.
    _date_second = -1
    _date_value = ""

    def send_response(self, code, message=None):
        self.log_request(code)
        self.send_response_only(code, message)
        self.send_header("Server", "dfd-router")
        now = int(time.time())
        cls = _RouterHandler
        if cls._date_second != now:
            cls._date_value = self.date_time_string()
            cls._date_second = now
        self.send_header("Date", cls._date_value)

    def handle_one_request(self) -> None:
        """Minimal HTTP/1.1 request read for the proxy hot path.

        BaseHTTPRequestHandler parses headers through ``email.parser`` —
        roughly the same GIL-bound cost as a whole raw relay — so the
        stock loop would spend more on parsing than on routing and cap
        the fleet's aggregate near 1×.  This override keeps the stdlib
        server's connection/dispatch semantics (keep-alive, 501 on
        unknown verbs, timeouts poison the connection) with a plain
        split parse over the raw head.  No Expect: 100-continue
        handling — the serving stack's clients never send it.

        The whole head (request line + headers) is read via
        ``rfile.read1`` — at most ONE raw recv per call — under a
        socket timeout that shrinks toward a hard deadline: the first
        byte may wait out the idle timeout (quiet keep-alive), but once
        any byte has arrived the complete head is owed within
        header_timeout_s.  A per-recv timeout alone (readline) would
        let a client trickling bytes — even within a single header
        line — reset it forever while pinning this thread."""
        self.command = self.requestline = ""
        self.request_version = self.protocol_version
        srv = self.server
        try:
            buf, self._head_excess = self._head_excess, b""
            deadline = 0.0            # armed at the first head byte
            try:
                while True:
                    i = buf.find(b"\r\n\r\n")
                    sep = 4
                    if i < 0:
                        i = buf.find(b"\n\n")
                        sep = 2
                    if i >= 0:
                        break
                    if len(buf) > 65536:
                        self.send_error(414)
                        return
                    now = time.monotonic()
                    if buf and deadline == 0.0:
                        deadline = now + srv.header_timeout_s
                    if deadline:
                        remaining = deadline - now
                        if remaining <= 0:
                            raise TimeoutError("header deadline")
                        self.connection.settimeout(remaining)
                    else:
                        self.connection.settimeout(srv.idle_timeout_s)
                    try:
                        chunk = self.rfile.read1(65536)
                    except TimeoutError:
                        if deadline == 0.0:
                            # idle deadline between requests: quiet
                            # keep-alive connection, close without a
                            # response (same as evloop)
                            srv.metrics.idle_closed_total.inc()
                            self.close_connection = True
                            return
                        raise
                    if not chunk:
                        self.close_connection = True
                        return
                    buf += chunk
            except TimeoutError:
                srv.metrics.idle_closed_total.inc()
                self.close_connection = True
                self.wfile.write(b"HTTP/1.1 408 Request Timeout\r\n"
                                 b"Content-Length: 0\r\n"
                                 b"Connection: close\r\n\r\n")
                srv.metrics.count_request(408)
                return
            self._head_excess = buf[i + sep:]
            lines = buf[:i].split(b"\n")
            line = lines[0].decode("latin-1").rstrip("\r")
            parts = line.split()
            if len(parts) != 3:
                self.close_connection = True
                if line:
                    self.send_error(400, "malformed request line")
                return
            self.command, self.path, self.request_version = parts
            self.requestline = line
            headers = _Headers()
            for hl in lines[1:]:
                k, hsep, v = hl.decode("latin-1").partition(":")
                if hsep:
                    headers[k.strip().lower()] = v.strip()
            self.connection.settimeout(srv.idle_timeout_s)
            self.headers = headers
            conn_tok = headers.get("connection", "").lower()
            if self.request_version == "HTTP/1.0":
                self.close_connection = conn_tok != "keep-alive"
            else:
                self.close_connection = conn_tok == "close"
            method = getattr(self, "do_" + self.command, None)
            if method is None:
                self.send_error(
                    501, f"Unsupported method ({self.command!r})")
                return
            method()
            self.wfile.flush()
        except TimeoutError:
            # body-read (or response-write) stall past the idle
            # deadline: poison the connection, count the close
            srv.metrics.idle_closed_total.inc()
            self.close_connection = True
        except OSError:
            # client vanished mid-request (reset/EPIPE on a write):
            # every route path settles its book before writing to the
            # client, so just poison the connection quietly
            self.close_connection = True

    # -- plumbing (the serving handler's keep-alive discipline) --------
    def _respond(self, status: int, body: bytes,
                 content_type: str = "application/json",
                 extra_headers: Optional[dict] = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)
        self.server.metrics.count_request(status)

    def _json(self, status: int, obj: dict,
              extra_headers: Optional[dict] = None) -> None:
        self._respond(status, json.dumps(obj).encode(),
                      extra_headers=extra_headers)

    def _read_body(self) -> Optional[bytes]:
        """Drain the body before ANY response (keep-alive: an unread
        body would be parsed as the next request line)."""
        if self.headers.get("Transfer-Encoding"):
            self.close_connection = True
            return None
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = -1
        if not 0 <= length <= _MAX_BODY:
            self.close_connection = True
            return None
        excess = self._head_excess
        if excess:
            # the head read over-ran into the body: consume that first
            head, self._head_excess = excess[:length], excess[length:]
            need = length - len(head)
            if need:
                return head + self.rfile.read(need)
            return head
        return self.rfile.read(length)

    # ------------------------------------------------------------------
    # router-owned endpoints
    # ------------------------------------------------------------------
    def do_GET(self) -> None:                     # noqa: N802 (stdlib API)
        path = self.path.split("?", 1)[0]
        srv = self.server
        if path == "/healthz":
            self._respond(200, b"ok\n", "text/plain")
        elif path == "/readyz":
            status, body = readyz_document(srv.registry, srv.metrics)
            self._respond(status, body)
        elif path == "/metrics":
            self._respond(200, aggregate_metrics_text(
                srv.registry, srv.metrics).encode(),
                "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/replicas":
            self._json(200, {r.id: r.summary()
                             for r in srv.registry.all()})
        elif path == "/autoscaler":
            status, body = autoscaler_document(
                getattr(srv, "autoscaler", None))
            self._respond(status, body)
        elif path == "/streams":
            self._json(200, merged_streams(srv.registry,
                                           srv.upstream_timeout_s))
        else:
            self._proxy("GET", None)

    def do_POST(self) -> None:                    # noqa: N802 (stdlib API)
        path = self.path.split("?", 1)[0]
        m = _REPLICA_PATH.match(path)
        if m:
            self._read_body()                     # drain (keep-alive)
            self._replica_op(m.group(1), m.group(2) or "")
            return
        self._proxy("POST", None)

    def do_DELETE(self) -> None:                  # noqa: N802 (stdlib API)
        self._proxy("DELETE", None)

    # ------------------------------------------------------------------
    def _replica_op(self, replica_id: str, op: str) -> None:
        srv = self.server
        status, doc = replica_operation(srv.registry, srv.metrics,
                                        srv._drain_lock, replica_id, op,
                                        srv.migrate_timeout_s)
        self._json(status, doc)

    # ------------------------------------------------------------------
    # proxy path — every resolution increments EXACTLY one book
    # ------------------------------------------------------------------
    def _proxy(self, method: str, _unused) -> None:
        t0 = time.monotonic()
        srv = self.server
        body = self._read_body()
        if body is None:
            self._json(400, {"error": "unreadable/oversize body"})
            return
        path, _, query = self.path.partition("?")
        target = path + ("?" + query if query else "")
        m = _STREAM_PATH.match(path)
        if not (path == "/score" or
                (path == "/streams" and method == "POST") or m):
            self._json(404, {"error": f"no route {path!r}"})
            return
        # client-error rejections resolve BEFORE the books: routed only
        # counts requests the router actually tried to place
        if m and m.group(2) == "/migrate" and method == "POST":
            # migration/restore are the ROUTER's verbs (POST /replicas/
            # <id>/drain): moving a session behind the router's back
            # would leave its affinity pointing at a replica that no
            # longer holds it
            self._json(400, {"error": "migrate via POST "
                                      "/replicas/<id>/drain"})
            return
        if path == "/streams/restore" and method == "POST":
            self._json(400, {"error": "restore via POST "
                                      "/replicas/<id>/drain (a restore "
                                      "bypassing the router desyncs "
                                      "stream affinity)"})
            return
        sid = None
        if method == "POST" and path == "/streams":
            # creation: the router must know the id to hash it — inject
            # one when the client didn't name it
            sid, body = ensure_stream_id(body)
            if sid is None:
                self._json(400, {"error": "body must be empty or a JSON "
                                          "object"})
                return
        srv.metrics.routed_total.inc()
        try:
            if path == "/score":
                cache, cache_key = srv.edge_cache, None
                if cache is not None:
                    cache_key = EdgeCache.request_key(
                        method, target,
                        self.headers.get("content-type", ""), body)
                    hit = cache.get(cache_key)
                    if hit is not None:
                        # edge verdict-cache resolution: one book, no
                        # replica touched
                        srv.metrics.cache_hit_total.inc()
                        self._relay(hit[0], {"content-type": hit[1]},
                                    hit[2])
                        return
                self._route_stateless(method, target, body,
                                      cache_key=cache_key)
            else:
                self._route_stream(method, path, target, body,
                                   create_sid=sid)
        finally:
            srv.metrics.latency["total"].observe(time.monotonic() - t0)

    def _shed(self, note: str, extra: Optional[dict] = None) -> None:
        srv = self.server
        srv.metrics.shed_total.inc()
        ra = srv.shed_retry_after()
        self._json(503, {"error": note, **(extra or {})},
                   extra_headers={"Retry-After": max(1, round(ra))})

    def _fail(self, note: str) -> None:
        self.server.metrics.failed_total.inc()
        self._json(502, {"error": note})

    def _pooled_conn(self, r: Replica) -> Tuple["_UpstreamConn", bool]:
        """(connection, was_reused) from this thread's upstream pool.

        The pool is pruned whenever the registry generation moved
        (replica removed or down-marked): sockets to retired replicas
        close instead of leaking one FD per pool owner until the thread
        dies."""
        srv = self.server
        pool = getattr(_tls, "pool", None)
        if pool is None:
            pool = _tls.pool = {}
            _tls.generation = -1
        gen = srv.registry.generation
        if _tls.generation != gen:
            _tls.generation = gen
            for rid in list(pool):
                rep = srv.registry.get(rid)
                if rep is None or not rep.healthy:
                    pool.pop(rid).close()
                    srv.metrics.upstream_pool_closed_total.inc()
        conn = pool.get(r.id)
        if conn is not None:
            return conn, True
        conn = _UpstreamConn(r.netloc, srv.upstream_timeout_s)
        pool[r.id] = conn
        return conn, False

    def _drop_conn(self, r: Replica) -> None:
        pool = getattr(_tls, "pool", None)
        conn = pool.pop(r.id, None) if pool else None
        if conn is not None:
            conn.close()

    def _send_upstream(self, r: Replica, method: str, target: str,
                       body: bytes) -> Tuple[int, dict, bytes]:
        """One upstream round trip on this thread's keep-alive pool,
        with inflight + latency accounting.  A failure on a REUSED
        connection retries once on a fresh socket — but ONLY the
        idled-out-keep-alive class (EOF/reset): a TIMEOUT means the
        replica may have fully received (and be processing) the request,
        and resending a non-idempotent POST there would double-deliver —
        e.g. a frame chunk ingested twice, breaking the bit-identical
        replay contract.  Real transport failures raise OSError."""
        srv = self.server
        head = self._upstream_head(r, method, target, len(body))
        srv.registry.note_dispatch(r.id)
        t0 = time.monotonic()
        try:
            for _ in range(2):
                conn, reused = self._pooled_conn(r)
                try:
                    out = conn.round_trip(head, body)
                    if conn.stale:
                        self._drop_conn(r)
                    return out
                except OSError as e:
                    self._drop_conn(r)
                    if not reused or isinstance(e, TimeoutError):
                        raise OSError(
                            f"upstream {r.id} failed: {e!r}") from e
            raise OSError(f"upstream {r.id} failed twice")
        finally:
            srv.registry.note_done(r.id)
            srv.metrics.latency["upstream"].observe(
                time.monotonic() - t0)

    def _upstream_head(self, r: Replica, method: str, target: str,
                       body_len: int) -> bytes:
        """Pre-serialized upstream request head (raw-socket data plane:
        the router must do LESS HTTP work per request than a replica, so
        the relay skips http.client's object churn both ways)."""
        parts = [f"{method} {target} HTTP/1.1\r\nHost: {r.netloc}\r\n"]
        for k, v in self.headers.items():
            if k.lower() not in FORWARD_HEADER_EXCLUDES:
                parts.append(f"{k}: {v}\r\n")
        parts.append(f"Content-Length: {body_len}\r\n\r\n")
        return "".join(parts).encode("latin-1")

    def _relay(self, status: int, hdrs: dict, rbody: bytes) -> None:
        extra = {}
        if "retry-after" in hdrs:
            extra["Retry-After"] = hdrs["retry-after"]
        self._respond(status, rbody,
                      hdrs.get("content-type", "application/json"),
                      extra_headers=extra)

    @staticmethod
    def _retry_after_of(hdrs: dict, default: float = 1.0) -> float:
        try:
            return float(hdrs.get("retry-after", default))
        except (TypeError, ValueError):
            return default

    def _route_stateless(self, method: str, target: str, body: bytes,
                         cache_key: Optional[str] = None) -> None:
        """Least-depth routing with shed-aware failover: an upstream
        429/503 backs the replica off for its Retry-After and the
        request moves on; transport errors likewise.  Exactly one book
        resolution on every path out.  A 200 relay populates the edge
        cache when the probe missed (``cache_key`` carries the probe's
        request digest)."""
        srv = self.server
        tried: Set[str] = set()
        saw_transport_error = False
        saw_shed = False
        for attempt in range(1 + srv.route_retries):
            r = srv.registry.pick_stateless(exclude=tried)
            if r is None:
                break
            tried.add(r.id)
            if attempt:
                srv.metrics.retries_total.inc()
            try:
                status, hdrs, rbody = self._send_upstream(
                    r, method, target, body)
            except OSError:
                saw_transport_error = True
                _logger.warning("replica %s: transport error on %s "
                                "(failing over)", r.id, target)
                continue
            if status in (429, 503):
                saw_shed = True
                srv.registry.mark_shed(r.id,
                                       self._retry_after_of(hdrs))
                continue
            srv.metrics.forwarded_total.inc()
            srv.metrics.count_forward(r.id)
            if cache_key is not None and status == 200:
                srv.edge_cache.put(
                    cache_key, status,
                    hdrs.get("content-type", "application/json"), rbody)
            self._relay(status, hdrs, rbody)
            return
        if saw_transport_error and not saw_shed:
            # nothing shed us — the fleet is unreachable, not overloaded
            self._fail("replica transport errors exhausted the "
                       "failover budget")
            return
        self._shed("fleet overloaded or no eligible replica, retry "
                   "later", {"tried": sorted(tried)})

    def _route_stream(self, method: str, path: str, target: str,
                      body: bytes,
                      create_sid: Optional[str] = None) -> None:
        """Session-affine routing: overrides (migration) beat the ring;
        no failover — a session has exactly one home."""
        srv = self.server
        creating = create_sid is not None
        if creating:
            sid = create_sid
            # a NEW stream re-using a migrated-then-closed id must bind
            # to its ring home, not the stale migration target
            srv.registry.clear_override(sid)
        else:
            sid = _STREAM_PATH.match(path).group(1)
        r, via_override = srv.registry.pick_stream(sid)
        if r is None:
            self._shed("no replicas registered")
            return
        if not (r.healthy and r.ready) or (r.draining and creating):
            # down home: honest shed until it returns (its sessions
            # restore from --state-dir on relaunch) or a drain migrates
            # the stream; draining replicas take no NEW streams
            self._shed(f"stream home replica {r.id} unavailable",
                       {"replica": r.id})
            return
        try:
            status, hdrs, rbody = self._send_upstream(r, method, target,
                                                      body)
        except OSError:
            self._fail(f"stream home replica {r.id} transport error")
            return
        if method == "DELETE" and 200 <= status < 300:
            # the session is gone: drop its migration override so the
            # overrides map cannot grow one stale entry per migrated
            # stream for the router's lifetime (replica-side TTL
            # eviction still leaks its entry until the id is reused or
            # re-created — bounded by drains, not by traffic)
            srv.registry.clear_override(sid)
        (srv.metrics.migrated_total if via_override
         else srv.metrics.forwarded_total).inc()
        srv.metrics.count_forward(r.id)
        self._relay(status, hdrs, rbody)


def make_router_server(host: str, port: int, registry: Registry,
                       metrics: Optional[RouterMetrics] = None,
                       scraper: Optional[HealthScraper] = None, *,
                       data_plane: str = "threads",
                       relay_workers: int = 1, **kw):
    """Build a router server on the chosen data plane.

    ``threads`` (default): :class:`RouterServer`, one thread per client
    connection.  ``evloop``: the ISSUE 16 non-blocking event loop
    (``fleet/dataplane.py``), same control plane and books, one loop
    thread (``relay_workers`` shards accept across N loops via
    SO_REUSEPORT).  Both return objects with the same serve_forever /
    shutdown / server_close / server_address surface.
    """
    metrics = metrics if metrics is not None else RouterMetrics()
    scraper = scraper if scraper is not None else HealthScraper(
        registry, metrics)
    if data_plane == "evloop":
        # lazy import: dataplane imports this module's shared helpers
        from .dataplane import EvLoopRouterServer
        return EvLoopRouterServer((host, port), registry, metrics,
                                  scraper, relay_workers=relay_workers,
                                  **kw)
    if data_plane != "threads":
        raise ValueError(f"data_plane must be 'threads' or 'evloop', "
                         f"got {data_plane!r}")
    return RouterServer((host, port), registry, metrics, scraper, **kw)
