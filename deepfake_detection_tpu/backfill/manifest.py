"""The backfill work manifest: the corpus chopped into leaseable shards.

One JSON file (schema ``dfd.backfill.manifest.v1``) fixes, up front and
immutably, WHAT a backfill run scores: every clip of the corpus in
deterministic order (root-major, fakes before reals — the pack
convention), grouped into fixed-size shards that are the unit of
leasing, resume and accounting.  Exact books are only meaningful
against a frozen denominator, so the manifest carries a **source
fingerprint** the runner re-derives from its live sources at startup —
list files that changed since the manifest was built, or a pack with a
different fingerprint, are a loud :class:`BackfillManifestStale`
(the ``PackedCacheStale`` contract of data/packed.py), never a run
that silently scores a skewed corpus.

Two builders share the shard arithmetic:

* :func:`build_manifest_from_lists` — from the v3
  ``real_list.txt``/``fake_list.txt`` roots (the raw-tree decode path);
* :func:`build_manifest_from_pack` — from a packed cache's own index
  (``tools/pack_dataset.py``), inheriting the pack's fingerprint so the
  manifest is stale exactly when the pack is.

jax-free on purpose: ``tools/make_lists.py`` (a declared JAX_FREE
module) emits manifests, and lease/book tooling reads them from
processes with no accelerator stack.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from ..data.packed import load_index, read_source_lists

__all__ = ["MANIFEST_SCHEMA", "BackfillManifestStale",
           "build_manifest_from_lists", "build_manifest_from_pack",
           "load_manifest", "manifest_entries", "save_manifest",
           "verify_manifest_source"]

MANIFEST_SCHEMA = "dfd.backfill.manifest.v1"

#: one manifest entry: (kind, root_index, clip_name, num_frames)
Entry = Tuple[str, int, str, int]

_REQUIRED_KEYS = ("schema", "shard_clips", "source", "fingerprint",
                  "num_clips", "shards")


class BackfillManifestStale(RuntimeError):
    """The manifest disagrees with the live sources (list files changed,
    pack rebuilt, shard table damaged).  Rebuild the manifest with
    ``tools/make_lists.py --manifest`` rather than backfilling a corpus
    that is not the one the books will claim."""


def _lists_fingerprint(lists: List[Dict[str, list]]) -> str:
    payload = json.dumps(lists, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()


def _entries_from_lists(lists: List[Dict[str, list]]) -> List[Entry]:
    """Deterministic corpus order: root-major, fakes before reals — the
    exact order ``data/packed.py::write_pack`` packs, so a manifest over
    a pack and one over the pack's source lists enumerate identically."""
    entries: List[Entry] = []
    for ri in range(len(lists)):
        for kind in ("fake", "real"):
            entries += [(kind, ri, name, int(num))
                        for name, num in lists[ri][kind]]
    return entries


def _shard_table(entries: List[Entry], shard_clips: int) -> List[Dict]:
    if shard_clips < 1:
        raise ValueError(f"shard_clips must be >= 1, got {shard_clips}")
    shards = []
    for si in range(0, len(entries), shard_clips):
        chunk = entries[si:si + shard_clips]
        shards.append({
            "id": f"shard-{si // shard_clips:05d}",
            "clips": [[k, ri, name, num] for k, ri, name, num in chunk],
        })
    return shards


def _finish(source: Dict[str, Any], source_fp: str, entries: List[Entry],
            shard_clips: int) -> Dict[str, Any]:
    if not entries:
        raise ValueError(f"no clips to manifest from source {source}")
    shards = _shard_table(entries, int(shard_clips))
    # the manifest's own fingerprint covers source identity AND the shard
    # layout, so two manifests over one corpus with different --shard-clips
    # are distinguishable in telemetry/books
    fp = hashlib.sha256(json.dumps(
        {"source_fp": source_fp, "shard_clips": int(shard_clips),
         "num_clips": len(entries)},
        sort_keys=True, separators=(",", ":")).encode()).hexdigest()
    return {"schema": MANIFEST_SCHEMA, "shard_clips": int(shard_clips),
            "source": dict(source, fingerprint=source_fp),
            "fingerprint": fp, "num_clips": len(entries), "shards": shards}


def build_manifest_from_lists(roots, shard_clips: int = 256
                              ) -> Dict[str, Any]:
    """Manifest from v3 list-file roots (``':'``-separated or a list)."""
    if isinstance(roots, str):
        roots = [r for r in roots.split(":") if r]
    roots = [os.fspath(r) for r in roots]
    lists = read_source_lists(roots)
    source = {"type": "lists", "roots": roots}
    return _finish(source, _lists_fingerprint(lists),
                   _entries_from_lists(lists), shard_clips)


def build_manifest_from_pack(pack_dir: str, shard_clips: int = 256
                             ) -> Dict[str, Any]:
    """Manifest from a packed cache's index; stale exactly when the pack
    is (the pack fingerprint IS the source fingerprint)."""
    index = load_index(pack_dir)
    entries: List[Entry] = [(kind, int(ri), name, int(num))
                            for kind, ri, name, num, _label
                            in index["clips"]]
    source = {"type": "pack", "pack_dir": os.fspath(pack_dir),
              "frames_per_clip": int(index["frames_per_clip"]),
              "sample_hw": [int(v) for v in index["sample_hw"]]}
    return _finish(source, index["fingerprint"], entries, shard_clips)


def save_manifest(path: str, manifest: Dict[str, Any]) -> None:
    """write → fsync → atomic rename (the pack_dataset idiom): a reader
    never sees a half-written manifest."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_manifest(path: str) -> Dict[str, Any]:
    """Read + structurally validate a manifest; loud on anything off."""
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise BackfillManifestStale(f"{path}: unreadable manifest ({e})")
    except FileNotFoundError:
        raise FileNotFoundError(
            f"{path}: no backfill manifest (build one with "
            f"tools/make_lists.py --manifest)")
    missing = [k for k in _REQUIRED_KEYS if k not in manifest]
    if missing or manifest.get("schema") != MANIFEST_SCHEMA:
        raise BackfillManifestStale(
            f"{path}: manifest schema mismatch (schema "
            f"{manifest.get('schema')!r}, missing keys {missing}) — "
            f"rebuild with this build's tools/make_lists.py")
    n = sum(len(s["clips"]) for s in manifest["shards"])
    if n != int(manifest["num_clips"]):
        raise BackfillManifestStale(
            f"{path}: shard table holds {n} clips but num_clips says "
            f"{manifest['num_clips']} — damaged manifest")
    seen = set()
    for s in manifest["shards"]:
        for kind, ri, name, _num in s["clips"]:
            key = (kind, int(ri), name)
            if key in seen:
                raise BackfillManifestStale(
                    f"{path}: clip {kind}/{name} (root {ri}) appears "
                    f"twice — books could never balance")
            seen.add(key)
    return manifest


def manifest_entries(manifest: Dict[str, Any],
                     shard_id: Optional[str] = None) -> Iterator[Entry]:
    """Entries of one shard (or the whole corpus) as typed tuples."""
    for s in manifest["shards"]:
        if shard_id is not None and s["id"] != shard_id:
            continue
        for kind, ri, name, num in s["clips"]:
            yield (kind, int(ri), name, int(num))


def verify_manifest_source(manifest: Dict[str, Any],
                           roots: Optional[Sequence[str]] = None,
                           pack_dir: Optional[str] = None) -> None:
    """Prove the live sources still are what the manifest was built from.

    Exactly one of ``roots``/``pack_dir`` must be given (what the runner
    was launched against); a fingerprint mismatch is a loud
    :class:`BackfillManifestStale` naming both sides.
    """
    src = manifest["source"]
    if pack_dir is not None:
        index = load_index(pack_dir)
        if index["fingerprint"] != src["fingerprint"]:
            raise BackfillManifestStale(
                f"{pack_dir}: pack fingerprint "
                f"{index['fingerprint'][:12]}… does not match the "
                f"manifest's source fingerprint "
                f"{src['fingerprint'][:12]}… — the pack was rebuilt "
                f"since the manifest; re-run tools/make_lists.py "
                f"--manifest")
        return
    if roots is not None:
        if isinstance(roots, str):
            roots = [r for r in roots.split(":") if r]
        fp = _lists_fingerprint(read_source_lists(list(roots)))
        if fp != src["fingerprint"]:
            raise BackfillManifestStale(
                f"{roots}: source list files changed since the manifest "
                f"was built (fingerprint {fp[:12]}… vs manifest "
                f"{src['fingerprint'][:12]}…) — re-run "
                f"tools/make_lists.py --manifest")
        return
    raise ValueError("verify_manifest_source needs roots or pack_dir")
