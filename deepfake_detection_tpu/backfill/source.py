"""Clip sources for the backfill runner: packed mmaps or decoded trees.

The manifest names WHAT to score; a source answers HOW a clip's pixels
are obtained.  Two implementations share one contract —
``load(entry) -> (H, W, 3·frames) uint8`` with a fixed
``(frames_per_clip, sample_hw)`` geometry the runner compiles its one
batch bucket against:

* :class:`PackSource` — the steady-state path: zero-decode ``np.memmap``
  views over a ``tools/pack_dataset.py`` cache (the data/packed.py
  layout; its size audit runs at open so a truncated pack fails before
  the first batch, not as garbage pixels mid-corpus).  Host cost per
  clip is one slab memcpy.
* :class:`TreeSource` — the raw-tree path: frames decode through the
  same native C++ pool the trainer uses (``data/dataset.py::
  _load_images``) and resample to a canonical resolution
  (``canonical_clip_array``), for corpora that were never packed.
  Mixed source resolutions without an explicit ``image_size`` are a
  loud error naming the clip, never a shape-mismatched batch.

jax-free (DFD001): sources run on worker hosts with no accelerator
stack; the runner moves their uint8 output to device unmodified (the
uint8 wire — normalize runs inside the compiled call).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..data.dataset import _load_images, clip_frame_paths
from ..data.packed import (PackedShardCorrupt, canonical_clip_array,
                           clip_records, load_index, open_shard_array,
                           verify_pack)
from .manifest import Entry

__all__ = ["PackSource", "TreeSource"]


class PackSource:
    """Zero-decode clip lookup over a packed cache's mmapped shards."""

    #: a load is one mmap slice view — consumers may skip thread fan-out
    #: for small clips (scheduling costs more than the memcpy)
    zero_decode = True

    def __init__(self, pack_dir: str):
        self.pack_dir = os.fspath(pack_dir)
        self.index = load_index(self.pack_dir)
        problems = verify_pack(self.pack_dir, checksums=False)
        if problems:
            raise PackedShardCorrupt("; ".join(problems))
        self.frames_per_clip = int(self.index["frames_per_clip"])
        hw = [int(v) for v in self.index["sample_hw"]]
        self.sample_hw: Tuple[int, int] = (hw[0], hw[1])
        # the shared pack-reader machinery (data/packed.py): sample
        # lookup table + size-audited lazy mmaps — one implementation
        # for PackedDataset and this source
        self._records = clip_records(self.index)
        self._mmaps: Dict[int, np.ndarray] = {}
        self._open_lock = threading.Lock()

    def _shard_array(self, si: int) -> np.ndarray:
        arr = self._mmaps.get(si)
        if arr is None:
            with self._open_lock:
                arr = self._mmaps.get(si)
                if arr is None:
                    arr = open_shard_array(self.pack_dir, self.index, si)
                    self._mmaps[si] = arr
        return arr

    def load(self, entry: Entry) -> np.ndarray:
        kind, ri, name, _num = entry
        rec = self._records.get((kind, int(ri), name))
        if rec is None:
            from .manifest import BackfillManifestStale
            raise BackfillManifestStale(
                f"{self.pack_dir}: manifest clip {kind}/{name} (root "
                f"{ri}) is not in the pack index — stale manifest")
        si, slot = rec
        return self._shard_array(si)[slot]


class TreeSource:
    """Decode-path clip lookup over v3 list-file roots."""

    def __init__(self, roots, frames_per_clip: int = 4,
                 image_size: int = 0):
        if isinstance(roots, str):
            roots = [r for r in roots.split(":") if r]
        self.roots = [os.fspath(r) for r in roots]
        self.frames_per_clip = int(frames_per_clip)
        self.image_size = int(image_size or 0)
        #: fixed once the first clip decodes (or immediately for an
        #: explicit image_size); every later clip must match it
        self.sample_hw: Optional[Tuple[int, int]] = (
            (self.image_size, self.image_size) if self.image_size else None)

    def load(self, entry: Entry) -> np.ndarray:
        kind, ri, name, num = entry
        imgs = _load_images(clip_frame_paths(
            self.roots, kind, (name, int(num), int(ri)),
            self.frames_per_clip))
        arr = canonical_clip_array(imgs, self.image_size or None)
        hw = (int(arr.shape[0]), int(arr.shape[1]))
        if self.sample_hw is None:
            self.sample_hw = hw
        elif hw != self.sample_hw:
            raise ValueError(
                f"clip {kind}/{name}: decoded {hw[1]}x{hw[0]}, the run's "
                f"batch bucket is {self.sample_hw[1]}x{self.sample_hw[0]} "
                f"— sources are mixed-resolution; set --image-size")
        return arr
