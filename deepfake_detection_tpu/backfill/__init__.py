"""Corpus-scale offline backfill: leased work shards, exact books.

Streaming (PR 7) optimizes latency and serving (PR 2/10) optimizes
request fan-in; this package is the third workload shape — pure
throughput over an *archived* corpus.  A sharded work **manifest**
(clips grouped into fixed-size shards, built by ``tools/make_lists.py
--manifest`` from the v3 lists or a packed cache) is mapped over by N
independent worker processes that **lease** shards through atomic
filesystem operations in a shared run directory, score each shard
through a deadline-free double-buffered pipeline
(``runners/backfill.py``), and append schema-versioned
``dfd.backfill.verdict.v1`` JSONL per shard with a per-shard done
marker — so a SIGTERM (or a dead host) at any point resumes at shard
granularity with exact books: ``manifest clips == scored + failed``,
no clip scored twice, none missing.

Import discipline: this package (manifest/lease/writer/source) is
jax-free — the chaos harness, ``tools/make_lists.py`` and reporting
subprocesses import it with no accelerator stack (dfdlint DFD001 pins
it).  Only ``runners/backfill.py`` touches jax.
"""

from .lease import LeaseDir
from .manifest import (BackfillManifestStale, MANIFEST_SCHEMA,
                       build_manifest_from_lists, build_manifest_from_pack,
                       load_manifest, manifest_entries, verify_manifest_source)
from .writer import (VERDICT_SCHEMA, ShardVerdictWriter, collect_books,
                     read_verdicts)

__all__ = [
    "BackfillManifestStale", "LeaseDir", "MANIFEST_SCHEMA",
    "ShardVerdictWriter", "VERDICT_SCHEMA", "build_manifest_from_lists",
    "build_manifest_from_pack", "collect_books", "load_manifest",
    "manifest_entries", "read_verdicts", "verify_manifest_source",
]
