"""Shard leases: atomic filesystem claims with mtime-based expiry.

N worker processes (possibly on N hosts over a shared filesystem)
coordinate through two directories in the backfill run dir, with no
coordinator process and no network protocol:

* ``leases/<shard>.lease`` — the claim.  Acquisition is an atomic
  test-and-set built from the pack_dataset write→fsync→atomic-link
  idiom: the owner record is written to a private tmp file, fsynced,
  and ``os.link``ed to the lease path — link fails with ``EEXIST`` iff
  another worker already holds the shard, and never leaves a partial
  lease behind.  A live owner **heartbeats** the lease (``os.utime``)
  between batches; a lease whose mtime is older than ``ttl_s`` belonged
  to a dead host and may be broken — the break itself is an atomic
  ``os.rename`` of the stale lease to a per-contender name, so exactly
  ONE contender wins the right to re-lease even when several notice the
  expiry simultaneously.
* ``done/<shard>.json`` — the commit marker, written atomically
  (write→fsync→rename) AFTER the shard's verdict JSONL is durable.  A
  done shard is never re-leased (acquire refuses), so completion is
  idempotent: relaunches skip finished work at shard granularity.

The TTL contract (documented, not enforced): ``ttl_s`` must exceed the
worst heartbeat gap — one device batch plus slack — or a merely *slow*
owner can be mistaken for a dead one and its shard double-scored.  The
runner heartbeats every batch, checks :meth:`LeaseDir.still_owner`
at the same cadence, and abandons a shard it no longer owns instead of
committing it.

jax-free (DFD001): the chaos harness and book tooling drive leases from
processes with no accelerator stack.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["LeaseDir"]

_LEASES = "leases"
_DONE = "done"


class LeaseDir:
    """One worker's handle on the shared lease/done state of a run dir."""

    def __init__(self, run_dir: str, owner: str, ttl_s: float = 600.0):
        if ttl_s <= 0:
            raise ValueError(f"lease ttl_s must be > 0, got {ttl_s}")
        self.run_dir = os.fspath(run_dir)
        self.owner = str(owner)
        #: lease IDENTITY — the owner name plus a per-process random
        #: token, so two workers accidentally launched with the same
        #: --worker-name can never pass each other's ``still_owner``
        #: check after a steal (owner strings are display/telemetry)
        self.token = f"{self.owner}:{os.getpid()}:{os.urandom(4).hex()}"
        self.ttl_s = float(ttl_s)
        self.lease_dir = os.path.join(self.run_dir, _LEASES)
        self.done_dir = os.path.join(self.run_dir, _DONE)
        os.makedirs(self.lease_dir, exist_ok=True)
        os.makedirs(self.done_dir, exist_ok=True)
        self._steal_seq = 0
        #: owner record of the last stale lease this worker broke (None
        #: until a steal happens) — surfaced into telemetry by the runner
        self.last_steal: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------------
    def _lease_path(self, shard_id: str) -> str:
        return os.path.join(self.lease_dir, f"{shard_id}.lease")

    def _done_path(self, shard_id: str) -> str:
        return os.path.join(self.done_dir, f"{shard_id}.json")

    _tmp_seq = itertools.count()      # class-level: unique across ALL
    # instances in a process (pid alone collides when threads of one
    # process race a claim — tests drive leases that way)

    def _try_claim(self, shard_id: str) -> bool:
        """The atomic test-and-set: tmp write → fsync → link."""
        path = self._lease_path(shard_id)
        tmp = (f"{path}.tmp.{os.getpid()}.{threading.get_ident()}."
               f"{next(self._tmp_seq)}")
        with open(tmp, "w") as f:
            json.dump({"owner": self.owner, "token": self.token,
                       "pid": os.getpid(), "shard": shard_id}, f)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, path)        # EEXIST iff someone else holds it
            return True
        except FileExistsError:
            return False
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass

    def _claim_checked(self, shard_id: str) -> bool:
        """Claim + re-check done (a commit can land between the caller's
        done check and the link) — never hold a lease on a done shard."""
        if not self._try_claim(shard_id):
            return False
        if self.is_done(shard_id):
            self.release(shard_id)
            return False
        return True

    def acquire(self, shard_id: str) -> bool:
        """Claim ``shard_id``; False = done already, someone else holds a
        live lease, or we lost the break-stale race — the caller moves on
        to the next shard (the loser's contract)."""
        if self.is_done(shard_id):
            return False
        if self._claim_checked(shard_id):
            return True
        # claim lost: live owner, or a dead host's stale leftover?
        path = self._lease_path(shard_id)
        try:
            age = time.time() - os.stat(path).st_mtime
        except OSError:
            # the holder released/committed between our link and stat;
            # one clean retry, then defer to the next sweep
            return self._claim_checked(shard_id)
        if age <= self.ttl_s:
            return False              # live owner — respect the lease
        # stale: break it atomically.  rename succeeds for exactly one
        # contender; everyone else gets ENOENT and loses cleanly.
        self._steal_seq += 1
        grave = f"{path}.stale.{os.getpid()}.{self._steal_seq}"
        try:
            os.rename(path, grave)
        except OSError:
            return False              # another contender broke it first
        try:
            with open(grave) as f:
                prev = json.load(f)
        except (OSError, json.JSONDecodeError):
            prev = {}
        try:
            os.remove(grave)
        except OSError:
            pass
        claimed = self._claim_checked(shard_id)
        if claimed:
            # visible in logs/telemetry: a re-lease means a dead (or
            # TTL-starved) worker — silence here would hide flapping
            self.last_steal = prev
        return claimed

    def heartbeat(self, shard_id: str) -> None:
        """Refresh the lease mtime (the liveness signal expiry reads)."""
        try:
            os.utime(self._lease_path(shard_id))
        except OSError:
            pass                      # lost the lease; still_owner says so

    def still_owner(self, shard_id: str) -> bool:
        """True while OUR lease record is the one on disk (compared by
        the per-process token, not the display name).  A worker that
        lost its lease (TTL expiry while stalled) must NOT commit the
        shard — the stealer owns its books now."""
        try:
            with open(self._lease_path(shard_id)) as f:
                return json.load(f).get("token") == self.token
        except (OSError, json.JSONDecodeError):
            return False

    def release(self, shard_id: str) -> None:
        """Drop our lease — atomically, so a steal landing between an
        ownership check and a bare unlink can never delete the STEALER's
        live lease.  The file is renamed to a private grave first; if it
        turns out not to be ours it is restored (``os.link`` back — and
        if a third worker claimed the briefly-empty slot, its claim
        stands and the displaced owner notices via ``still_owner``)."""
        if not self.still_owner(shard_id):
            # clearly not ours (already released, or stolen): touching
            # the file at all would make the rename below briefly hide
            # the rightful owner's lease from its own liveness checks
            return
        path = self._lease_path(shard_id)
        self._steal_seq += 1
        grave = f"{path}.release.{os.getpid()}.{self._steal_seq}"
        try:
            os.rename(path, grave)
        except OSError:
            return                    # no lease (already released/stolen)
        try:
            with open(grave) as f:
                mine = json.load(f).get("token") == self.token
        except (OSError, json.JSONDecodeError):
            mine = True               # unreadable = not worth restoring
        if not mine:
            try:
                os.link(grave, path)  # put the rightful owner's back
            except OSError:
                pass                  # someone claimed meanwhile — theirs
        try:
            os.remove(grave)
        except OSError:
            pass

    # ------------------------------------------------------------------
    def mark_done(self, shard_id: str, record: Dict[str, Any]) -> bool:
        """Commit the shard: done marker lands atomically, then the lease
        is released.  Refuses (False) when the lease was lost — the
        shard's verdicts will be re-derived by the current owner.
        Idempotent: marking an already-done shard is a no-op (True)."""
        if self.is_done(shard_id):
            self.release(shard_id)
            return True
        if not self.still_owner(shard_id):
            return False
        path = self._done_path(shard_id)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(dict(record, shard=shard_id, owner=self.owner), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self.release(shard_id)
        return True

    def is_done(self, shard_id: str) -> bool:
        return os.path.isfile(self._done_path(shard_id))

    def done_record(self, shard_id: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._done_path(shard_id)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def pending_shards(self, manifest: Dict[str, Any]) -> List[str]:
        """Manifest shards with no done marker, in manifest order."""
        return [s["id"] for s in manifest["shards"]
                if not self.is_done(s["id"])]
