"""Per-shard verdict JSONL + the exact-books auditor.

Each leased shard appends one ``dfd.backfill.verdict.v1`` record per
clip to ``verdicts/<shard>.jsonl`` with the obs/events write discipline
(one ``json.dumps`` line + flush per record, so a kill can tear at most
the final line) and is committed by the lease layer's done marker only
after the file is fsynced.  Records are **deterministic** — no
timestamps, no worker names — because the chaos acceptance criterion
compares a killed+resumed run's concatenated verdicts against an
unkilled run's, order-normalized: any nondeterministic field would make
that identity unfalsifiable.

Resume contract (how "no clip scored twice" survives a mid-shard
death): a worker that re-leases a partially written shard opens the
writer, which first repairs the torn tail
(:func:`~deepfake_detection_tpu.obs.events.repair_torn_tail` — the one
truncation routine the whole repo shares) and reads the clip keys
already recorded; the runner then scores only the remainder.  The
re-leased *shard* is the unit of recovery; the surviving records within
it are kept, not re-scored.

:func:`collect_books` is the auditor both the runner's exit path and
the chaos harness call: ``manifest clips == scored + failed +
skipped_dup``, with duplicates and missing clips named, never
summarized away.  ``skipped_dup`` records (the ``--dedup`` pass:
clips whose canonical pixel content already occurs earlier in the
manifest) carry ``dup_of`` naming the canonical clip — a skip is a
booked decision, never a silently absent row.

jax-free (DFD001): the chaos harness audits books with no accelerator
stack.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Set, Tuple

from ..obs.events import repair_torn_tail

__all__ = ["VERDICT_SCHEMA", "ShardVerdictWriter", "clip_key",
           "collect_books", "read_verdicts", "verdict_path"]

VERDICT_SCHEMA = "dfd.backfill.verdict.v1"
_VERDICTS = "verdicts"

#: a clip's identity in the books: (kind, root_index, clip_name)
Key = Tuple[str, int, str]


def clip_key(rec: Dict[str, Any]) -> Key:
    return (rec["kind"], int(rec["root"]), rec["clip"])


def verdict_path(run_dir: str, shard_id: str) -> str:
    return os.path.join(run_dir, _VERDICTS, f"{shard_id}.jsonl")


class ShardVerdictWriter:
    """Append-only verdict stream for one leased shard.

    Opening repairs a torn tail left by a killed predecessor and indexes
    the surviving records, so :attr:`scored_keys` is exactly the set of
    clips the resuming runner must skip.
    """

    def __init__(self, run_dir: str, shard_id: str):
        self.shard_id = shard_id
        self.path = verdict_path(run_dir, shard_id)
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self.torn_bytes_dropped = repair_torn_tail(self.path)
        self.scored_keys: Set[Key] = set()
        self.records = 0
        self.failed = 0
        self.skipped = 0          # skipped_dup records (--dedup pass)
        # ONE pass over the surviving bytes indexes the records AND
        # seeds the incremental content hash, so finalize() never
        # re-reads the stream — shard opens are a measurable cost under
        # slow syscall layers
        self._sha = hashlib.sha256()
        try:
            with open(self.path, "rb") as f:
                for raw in f:
                    self._sha.update(raw)
                    line = raw.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("schema") != VERDICT_SCHEMA:
                        continue
                    self.scored_keys.add(clip_key(rec))
                    self.records += 1
                    if rec.get("skipped_dup"):
                        self.skipped += 1
                    elif not rec.get("ok"):
                        self.failed += 1
        except OSError:
            pass
        self._f = open(self.path, "a", encoding="utf-8")

    def _record(self, kind: str, root: int, clip: str, label: int,
                score: Optional[float], err: str) -> Dict[str, Any]:
        ok = score is not None
        rec = {"schema": VERDICT_SCHEMA, "shard": self.shard_id,
               "kind": kind, "root": int(root), "clip": clip,
               "label": int(label), "ok": ok,
               "score": float(score) if ok else None}
        if err:
            rec["err"] = err
        return rec

    def _book(self, rec: Dict[str, Any]) -> None:
        self.scored_keys.add(clip_key(rec))
        self.records += 1
        if rec.get("skipped_dup"):
            self.skipped += 1
        elif not rec["ok"]:
            self.failed += 1

    def append(self, kind: str, root: int, clip: str, label: int,
               score: Optional[float], err: str = "") -> None:
        """One clip's verdict: ``score`` is P(fake) (None for a failed
        clip, which records ``ok=false`` + the error instead)."""
        rec = self._record(kind, root, clip, label, score, err)
        line = json.dumps(rec, separators=(",", ":"),
                          allow_nan=False) + "\n"
        self._f.write(line)
        self._f.flush()
        self._sha.update(line.encode())
        self._book(rec)

    def append_many(self, rows) -> None:
        """One device batch's verdicts in one write + one flush (the hot
        loop's path — per-record flush syscalls are measurable at
        saturation).  ``rows``: ``(kind, root, clip, label, score, err)``
        tuples; each row is still serialized to its own schema-stamped
        single line, so kill-tearing semantics are unchanged."""
        recs = [self._record(*row) for row in rows]
        if not recs:
            return
        text = "".join(
            json.dumps(r, separators=(",", ":"), allow_nan=False) + "\n"
            for r in recs)
        self._f.write(text)
        self._f.flush()
        self._sha.update(text.encode())
        for rec in recs:
            self._book(rec)

    def append_dups(self, rows) -> None:
        """Book a batch of duplicate clips without scoring them.
        ``rows``: ``(kind, root, clip, label, dup_of)`` tuples, where
        ``dup_of`` names the canonical clip (``kind/root/clip``) whose
        identical pixel content occurs earlier in the manifest.  The
        record carries ``skipped_dup: true`` + ``dup_of`` so the books
        auditor can bucket it apart from scored AND from failed —
        a dedup skip is a decision, not damage."""
        recs = []
        for kind, root, clip, label, dup_of in rows:
            rec = self._record(kind, root, clip, label, None, "")
            rec["skipped_dup"] = True
            rec["dup_of"] = dup_of
            recs.append(rec)
        if not recs:
            return
        text = "".join(
            json.dumps(r, separators=(",", ":"), allow_nan=False) + "\n"
            for r in recs)
        self._f.write(text)
        self._f.flush()
        self._sha.update(text.encode())
        for rec in recs:
            self._book(rec)

    def finalize(self) -> Dict[str, Any]:
        """fsync the stream and return the shard's book entry (what the
        done marker records): counts + content hash of the JSONL."""
        self._f.flush()
        os.fsync(self._f.fileno())
        return {"clips": self.records,
                "scored": self.records - self.failed - self.skipped,
                "failed": self.failed, "skipped_dup": self.skipped,
                "sha256": self._sha.hexdigest()}

    def tear(self) -> None:
        """Chaos seam (``backfill_torn_shard``): leave exactly the damage
        a mid-``write`` kill leaves — half a record, no terminating
        newline — flushed to disk so the relaunch's
        :func:`repair_torn_tail` has something real to repair."""
        self._f.write('{"schema":"' + VERDICT_SCHEMA + '","shard":"'
                      + self.shard_id + '","clip":"torn-mid-wri')
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self) -> "ShardVerdictWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_verdicts(path: str) -> List[Dict[str, Any]]:
    """Parsed verdict records (empty for a missing file).  A torn tail is
    tolerated read-side (skipped) but writers repair it instead."""
    out: List[Dict[str, Any]] = []
    try:
        f = open(path, encoding="utf-8")
    except OSError:
        return out
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue                        # torn tail (read-side)
            if rec.get("schema") == VERDICT_SCHEMA:
                out.append(rec)
    return out


def collect_books(run_dir: str, manifest: Dict[str, Any]
                  ) -> Dict[str, Any]:
    """The exact-books audit over a run dir's verdict files.

    Walks every manifest shard's JSONL and checks the one identity the
    whole subsystem exists to uphold::

        manifest clips == scored + failed + skipped_dup,
        each clip exactly once

    (``skipped_dup`` is zero unless the run used ``--dedup``: a clip
    whose canonical pixel content duplicates an earlier manifest clip
    books a skip record instead of a score — still exactly one row.)

    Returns counts plus the *named* discrepancies (missing /
    duplicated / alien clips) and ``balanced`` — True iff every shard
    is done and the identity holds exactly.
    """
    from .lease import _DONE             # cycle-free: lease imports no one
    expected: Set[Key] = set()
    for s in manifest["shards"]:
        for kind, ri, name, _num in s["clips"]:
            expected.add((kind, int(ri), name))
    seen: Dict[Key, int] = {}
    scored = failed = skipped = 0
    shards_done = 0
    for s in manifest["shards"]:
        if os.path.isfile(os.path.join(run_dir, _DONE,
                                       f"{s['id']}.json")):
            shards_done += 1
        for rec in read_verdicts(verdict_path(run_dir, s["id"])):
            key = clip_key(rec)
            seen[key] = seen.get(key, 0) + 1
            if rec.get("skipped_dup"):
                skipped += 1
            elif rec.get("ok"):
                scored += 1
            else:
                failed += 1
    missing = sorted("/".join(map(str, k)) for k in expected - set(seen))
    alien = sorted("/".join(map(str, k)) for k in set(seen) - expected)
    dup = sorted("/".join(map(str, k)) for k, n in seen.items() if n > 1)
    complete = shards_done == len(manifest["shards"])
    balanced = (complete and not missing and not alien and not dup
                and scored + failed + skipped ==
                int(manifest["num_clips"]))
    return {"manifest_clips": int(manifest["num_clips"]),
            "scored": scored, "failed": failed,
            "skipped_dup": skipped,
            "shards_done": shards_done,
            "shards_total": len(manifest["shards"]),
            "missing": missing, "duplicated": dup, "alien": alien,
            "complete": complete, "balanced": balanced}
