"""Inference constants + preprocessing helpers.

Parity with ``/root/reference/dfd/params.py``: ImageNet mean/std ×255
(:24-27), 600×600 canvas + ``img_num=4`` (:28-31), the softmax score wrapper
``DeepFakeModel`` (:34-42), aspect-preserving :func:`resize` (:45) and center
:func:`padding_image` (:58).  All NHWC numpy/PIL — no cv2/torch dependency.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image

__all__ = ["img_mean", "img_std", "image_max_height", "image_max_width",
           "img_num", "resize", "padding_image", "prepare_canvas",
           "normalize_replicate", "normalize_concat", "make_score_fn"]

img_mean = np.asarray([0.485, 0.456, 0.406], np.float32) * 255.0
img_std = np.asarray([0.229, 0.224, 0.225], np.float32) * 255.0
image_max_height = 600
image_max_width = 600
image_max_w_h = (image_max_width, image_max_height)
img_num = 4


def resize(image: np.ndarray,
           max_w_h: Tuple[int, int] = image_max_w_h) -> np.ndarray:
    """Aspect-preserving downfit to ≤600×600 (reference :45-55)."""
    height_o, width_o = image.shape[:2]
    if float(height_o) / width_o > float(max_w_h[1]) / max_w_h[0]:
        height_target = max_w_h[1]
        width_target = int(width_o * float(height_target) / height_o)
    else:
        width_target = max_w_h[0]
        height_target = int(height_o * float(width_target) / width_o)
    pil = Image.fromarray(image)
    return np.asarray(pil.resize((width_target, height_target),
                                 Image.BILINEAR))


def padding_image(image: np.ndarray, target_h: int = image_max_height,
                  target_w: int = image_max_width) -> np.ndarray:
    """Center zero-pad to the fixed canvas (reference :58-67)."""
    height_o, width_o = image.shape[:2]
    if height_o == target_h and width_o == target_w:
        return image
    top = (target_h - height_o) // 2
    bottom = target_h - height_o - top
    left = (target_w - width_o) // 2
    right = target_w - width_o - left
    return np.pad(image, ((top, bottom), (left, right), (0, 0)),
                  "constant", constant_values=0)


def prepare_canvas(image: np.ndarray, size: int = image_max_height
                   ) -> np.ndarray:
    """Geometric half of the inference preprocess: aspect-preserving downfit
    + center pad to the ``size×size`` canvas, still uint8 HWC.

    Split out of ``runners/test.py::preprocess`` so the serving engine can
    ship this uint8 canvas over the wire and run the photometric half
    (:func:`normalize_replicate`) inside the batched device call — same
    uint8-wire idiom as ``data/loader.py``'s device prologue.
    """
    return padding_image(resize(image, (size, size)), size, size)


def normalize_replicate(image: np.ndarray, num: int = img_num) -> np.ndarray:
    """Photometric half: uint8 HWC → normalized float32, replicated ×num to
    the model's ``3*num``-channel input (reference test.py:56-57).

    Elementwise float32 ops only, so the jitted device-side version in
    ``serving/engine.py`` is bit-identical to this host version.
    """
    image = (image.astype(np.float32) - img_mean) / img_std
    if num > 1:
        image = np.concatenate([image] * num, axis=-1)
    return image


def normalize_concat(frames, num: Optional[int] = None) -> np.ndarray:
    """Photometric half for ``num`` *distinct* frames: normalize each uint8
    HWC canvas and channel-concatenate → ``(H, W, 3·num)`` float32 — the
    temporal clip layout the multi-frame models train on (``MultiConcate``).

    Identical frames reproduce :func:`normalize_replicate` byte-for-byte
    (same per-frame arithmetic, same concat), which is the parity contract
    of the serving/streaming multi-frame wire: a clip of ``num`` copies of
    one frame scores bit-identically to the single-frame replicate path.
    """
    frames = list(frames)
    if num is not None and len(frames) != num:
        raise ValueError(f"expected {num} frames, got {len(frames)}")
    if not frames:
        raise ValueError("normalize_concat needs at least one frame")
    return np.concatenate(
        [(f.astype(np.float32) - img_mean) / img_std for f in frames],
        axis=-1)


def make_score_fn(model, variables):
    """Jitted ``image → softmax scores`` (the reference's ``DeepFakeModel``
    nn wrapper, params.py:34-42); ``scores[:, 0]`` = P(fake).

    ``variables`` ride the jitted call as an *argument*, not a closure
    constant: closed-over weights would be embedded into the program as
    constants (bloating compile memory and enabling constant-folding whose
    rounding drifts ~1 ulp from the argument-passing form), and the
    serving engine (serving/engine.py) compiles this exact
    variables-as-argument program — so CLI and server scores agree
    bit-for-bit.

    ``variables`` may also be a ``serving/quant.py`` post-training-
    quantized tree (bf16 cast or int8 containers): the in-trace
    ``realize_tree`` dequantizes it inside the compiled call, and is a
    structural no-op on plain f32 trees — the bit-parity contract above
    is untouched at f32 (tests/test_serving_quant.py pins both)."""
    from .serving.quant import realize_tree

    @jax.jit
    def score(variables, x: jnp.ndarray) -> jnp.ndarray:
        logits = model.apply(realize_tree(variables), x, training=False)
        return jax.nn.softmax(logits, axis=-1)

    return lambda x: score(variables, x)
