"""Inference engine: bucketed AOT compile cache, double-buffered staging,
hot weight reload.

Design (mirrors what ``data/loader.py`` does for training input):

* **Bucketed compile cache** — the scoring function is AOT-compiled once
  per batch bucket (default 1/4/16/64) at startup, *before* the server
  reports ready.  Every device call thereafter hits a pre-compiled
  executable: a partial batch pads up to the nearest bucket and the pad
  rows are sliced off the result.  Because batch rows are independent in
  eval mode (running-stat BN, per-row softmax), the real rows of a padded
  bucket are bit-identical to an unpadded call (tests/test_serving.py).
  Novel shapes cannot recompile silently — an unknown bucket is a hard
  error, and ``compiles_total`` growing after ready=1 is the alarm.

* **uint8 wire** — HTTP threads ship the geometric canvas
  (``params.prepare_canvas``, uint8 HWC); normalize + ×img_num replication
  run inside the compiled call (``params.normalize_replicate`` semantics,
  elementwise float32, bit-identical to the CLI's host version).  Same
  idiom as the training loader's device prologue: 4× less host→device
  traffic and the photometrics get batched for free.

* **Double-buffered staging** — while batch k executes, the engine drains
  already-queued requests into batch k+1 and dispatches it (JAX async
  dispatch) before blocking on k's result: transfer/stage of k+1 overlaps
  device compute of k, exactly like ``DeviceLoader.__iter__``.

* **Hot weight reload** — params ride the compiled call as an *argument*
  (not a closure constant), so swapping them is aval-compatible and free
  of recompiles.  A watcher thread polls a checkpoint dir; a new file is
  loaded host-side through ``models/helpers.py`` and swapped in atomically
  between batches.  Shape-incompatible checkpoints are rejected, counted,
  and the old weights keep serving.

* **Crash recovery** — an exception anywhere in the serve loop fails the
  affected requests (HTTP 500) and restarts the loop; the worker thread
  never dies with requests stranded.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..params import image_max_height, img_mean, img_num as _default_img_num, \
    img_std
from .batcher import MicroBatcher, Request, pick_bucket
from .metrics import ServingMetrics

_logger = logging.getLogger(__name__)

__all__ = ["InferenceEngine", "DEFAULT_BUCKETS"]

DEFAULT_BUCKETS = (1, 4, 16, 64)

#: checkpoint filenames the reload watcher considers (others — .tmp
#: renames in flight, logs — are ignored)
_CKPT_SUFFIXES = (".msgpack", ".ckpt", ".flax", ".pkt")


class _Staged:
    __slots__ = ("requests", "out", "bucket", "dispatch_t")

    def __init__(self, requests: List[Request], out: Any, bucket: int,
                 dispatch_t: float):
        self.requests = requests
        self.out = out
        self.bucket = bucket
        self.dispatch_t = dispatch_t


class InferenceEngine:
    def __init__(self, model, variables, *,
                 image_size: int = image_max_height,
                 img_num: int = _default_img_num,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 metrics: Optional[ServingMetrics] = None,
                 wire: str = "float32",
                 multi_frame: bool = True,
                 warmup: bool = True):
        self.model = model
        self.image_size = int(image_size)
        self.img_num = int(img_num)
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"invalid buckets {buckets}")
        if wire not in ("float32", "uint8"):
            raise ValueError(f"wire must be float32|uint8, got {wire!r}")
        self.wire = wire
        self.metrics = metrics if metrics is not None else ServingMetrics()
        # real-compile observer: a silent recompile anywhere in the process
        # shows up in /metrics as backend_compiles_total growth (the
        # engine's own counter below only counts its AOT bucket builds)
        from .metrics import install_backend_compile_listener
        install_backend_compile_listener()
        # host-side template for non-strict reload merging; the device copy
        # is what executes
        self._host_template = jax.tree.map(np.asarray, variables)
        self._variables = jax.device_put(variables)
        self._var_shapes = jax.tree.map(
            lambda a: (tuple(np.shape(a)), np.asarray(a).dtype),
            self._host_template)
        self._compiled: Dict[int, Any] = {}
        self._compiled_multi: Dict[int, Any] = {}
        self._pending: List[_Staged] = []
        self._reload_box: List[Tuple[Any, str]] = []   # [(host_tree, path)]
        self._reload_lock = threading.Lock()
        self._last_reload_key: Optional[Tuple[str, float, int]] = None
        self.reload_count = 0
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None
        self._watcher: Optional[threading.Thread] = None

        # Wire formats:
        #
        # * ``float32`` (default) — HTTP threads run the FULL CLI
        #   preprocess (``params.normalize_replicate`` incl. ×img_num
        #   replication) and ship normalized float32; the compiled program
        #   is exactly the CLI's score fn, so server scores reproduce
        #   ``runners/test.py`` bit-for-bit (tested).
        # * ``uint8`` — HTTP threads ship the uint8 canvas and normalize +
        #   replicate run inside the batched device call (the training
        #   loader's device-prologue idiom): 4·img_num× less host→device
        #   traffic — the deployment mode for real accelerators.  Mean/std
        #   ride the call as ARGUMENTS (a constant divisor would be
        #   strength-reduced to multiply-by-reciprocal, ~1 ulp off host
        #   division), but cross-program fusion still allows ulp-level
        #   drift vs the CLI, so this mode is "allclose", not bit-equal.
        self._mean = jax.device_put(jnp.asarray(img_mean))
        self._std = jax.device_put(jnp.asarray(img_std))
        # multi-frame wire: mean/std tiled to the 3·img_num clip channels
        # so the SAME per-element arithmetic runs whether the channels came
        # from replication or from img_num distinct frames
        self._mean_multi = jax.device_put(jnp.asarray(
            np.tile(img_mean, self.img_num)))
        self._std_multi = jax.device_put(jnp.asarray(
            np.tile(img_std, self.img_num)))
        n_rep = self.img_num
        # uint8 wire with img_num == 1 needs no second program: a 1-frame
        # "clip" IS the single-frame sample.  float32 wire never needs one
        # (replicate and concat payloads share the (·, ·, 3·img_num)
        # float32 shape, so the CLI-parity program serves both).
        self.multi_frame = bool(multi_frame) and self.wire == "uint8" \
            and self.img_num > 1

        if self.wire == "uint8":
            def _score(variables, x_u8, mean, std):
                x = (x_u8.astype(jnp.float32) - mean) / std
                if n_rep > 1:
                    x = jnp.tile(x, (1, 1, 1, n_rep))
                logits = self.model.apply(variables, x, training=False)
                return jax.nn.softmax(logits, axis=-1)

            def _score_multi(variables, x_u8, mean, std):
                # x_u8 already carries img_num distinct frames channel-
                # concatenated; normalize elementwise (tiled mean/std), no
                # replication
                x = (x_u8.astype(jnp.float32) - mean) / std
                logits = self.model.apply(variables, x, training=False)
                return jax.nn.softmax(logits, axis=-1)
        else:
            def _score(variables, x):
                logits = self.model.apply(variables, x, training=False)
                return jax.nn.softmax(logits, axis=-1)

            _score_multi = None

        self._score = _score
        self._score_multi = _score_multi
        if warmup:
            self.warmup()

    @property
    def _wire_spec(self) -> Tuple[int, Any]:
        """(channels, dtype) of one SINGLE-frame wire sample."""
        if self.wire == "uint8":
            return 3, np.uint8
        return 3 * self.img_num, np.float32

    def allowed_chans(self) -> Tuple[int, ...]:
        """Channel counts a request array may carry on this wire."""
        base, _ = self._wire_spec
        if self.multi_frame:
            return (base, 3 * self.img_num)
        return (base,)

    def _run(self, bucket: int, variables, x, multi: bool = False):
        if self.wire == "uint8":
            if multi:
                return self._compiled_multi[bucket](
                    variables, x, self._mean_multi, self._std_multi)
            return self._compiled[bucket](variables, x, self._mean,
                                          self._std)
        return self._compiled[bucket](variables, x)

    # ------------------------------------------------------------------
    # compile cache
    # ------------------------------------------------------------------
    @property
    def compile_count(self) -> int:
        return self.metrics.compiles_total.value

    @property
    def ready(self) -> bool:
        return self.metrics.ready

    def warmup(self) -> None:
        """AOT-compile every bucket (plus, on a multi-frame uint8 wire,
        every bucket's multi-frame executable) and execute each once
        (primes any first-run allocation paths), then flip ready."""
        s = self.image_size
        chans, dtype = self._wire_spec
        for b in self.buckets:
            if b in self._compiled:
                continue
            t0 = time.monotonic()
            x_spec = jax.ShapeDtypeStruct((b, s, s, chans),
                                          jnp.dtype(dtype))
            if self.wire == "uint8":
                lowered = jax.jit(self._score).lower(
                    self._variables, x_spec, self._mean, self._std)
            else:
                lowered = jax.jit(self._score).lower(self._variables,
                                                     x_spec)
            self._compiled[b] = lowered.compile()
            self.metrics.compiles_total.inc()
            out = self._run(b, self._variables,
                            jnp.zeros((b, s, s, chans), dtype))
            jax.block_until_ready(out)
            _logger.info("bucket %d compiled + warmed in %.1fs", b,
                         time.monotonic() - t0)
        if self.multi_frame:
            mchans = 3 * self.img_num
            for b in self.buckets:
                if b in self._compiled_multi:
                    continue
                t0 = time.monotonic()
                x_spec = jax.ShapeDtypeStruct((b, s, s, mchans),
                                              jnp.dtype(np.uint8))
                lowered = jax.jit(self._score_multi).lower(
                    self._variables, x_spec, self._mean_multi,
                    self._std_multi)
                self._compiled_multi[b] = lowered.compile()
                self.metrics.compiles_total.inc()
                out = self._run(b, self._variables,
                                jnp.zeros((b, s, s, mchans), np.uint8),
                                multi=True)
                jax.block_until_ready(out)
                _logger.info("bucket %d (multi-frame) compiled + warmed "
                             "in %.1fs", b, time.monotonic() - t0)
        self.metrics.ready = True

    # ------------------------------------------------------------------
    # scoring
    # ------------------------------------------------------------------
    def _chans_of(self, array) -> int:
        """Wire channel count of one request array, validated against the
        engine's compiled programs (unknown widths must fail loudly here,
        never reach an uncompiled shape)."""
        chans = int(np.shape(array)[-1]) if np.ndim(array) else 0
        if chans not in self.allowed_chans():
            raise ValueError(
                f"request carries {chans} channels; this engine accepts "
                f"{self.allowed_chans()} (wire={self.wire}, "
                f"img_num={self.img_num}, multi_frame={self.multi_frame})")
        return chans

    def _pad_batch(self, arrays: List[np.ndarray],
                   chans: int) -> Tuple[np.ndarray, int]:
        n = len(arrays)
        bucket = pick_bucket(n, self.buckets)
        s = self.image_size
        _, dtype = self._wire_spec
        # fresh buffer every batch: jax CPU device_put zero-copies aligned
        # host memory, so reusing one buffer would race the still-executing
        # previous batch (same hazard data/loader.py guards with
        # block_until_ready)
        buf = np.zeros((bucket, s, s, chans), dtype)
        for i, a in enumerate(arrays):
            buf[i] = a
        return buf, bucket

    def _is_multi(self, chans: int) -> bool:
        return self.multi_frame and chans == 3 * self.img_num

    def score_batch(self, arrays: List[np.ndarray]) -> np.ndarray:
        """Synchronous scoring of up to max-bucket wire-format samples
        (tests, warm checks); one uniform channel width per call — the
        serving path goes through stage/complete instead and may mix."""
        chans = self._chans_of(arrays[0])
        for a in arrays[1:]:
            if self._chans_of(a) != chans:
                raise ValueError("score_batch arrays must share one "
                                 "channel width; the async path handles "
                                 "mixed single/multi-frame traffic")
        buf, bucket = self._pad_batch(arrays, chans)
        out = self._run(bucket, self._variables, jax.device_put(buf),
                        multi=self._is_multi(chans))
        return np.asarray(out)[:len(arrays)]

    def _stage(self, requests: List[Request]) -> List[_Staged]:
        """Dispatch requests as one device batch per channel width.

        Single-frame and multi-frame requests ride different compiled
        programs, so a coalesced batch that mixes them splits into (at
        most two) staged sub-batches — each still a pre-compiled bucket,
        dispatched back-to-back so both overlap the previous batch's
        completion."""
        groups: Dict[int, List[Request]] = {}
        for r in requests:
            groups.setdefault(self._chans_of(r.array), []).append(r)
        staged: List[_Staged] = []
        try:
            for chans, grp in groups.items():
                buf, bucket = self._pad_batch([r.array for r in grp],
                                              chans)
                out = self._run(bucket, self._variables,
                                jax.device_put(buf),
                                multi=self._is_multi(chans))
                self.metrics.inflight += len(grp)
                now = time.monotonic()
                for r in grp:
                    r.timings["queue"] = now - r.enqueue_t
                staged.append(_Staged(grp, out, bucket, now))
        except Exception:
            # a later group poisoned the stage: the caller fails EVERY
            # request of the coalesced batch, so unwind the sub-batches
            # already dispatched (their device work is wasted, not leaked)
            for st in staged:
                self.metrics.inflight -= len(st.requests)
            raise
        return staged

    def _complete(self, staged: _Staged) -> None:
        scores = np.asarray(staged.out)          # blocks on the device
        now = time.monotonic()
        device_dt = now - staged.dispatch_t
        n = len(staged.requests)
        m = self.metrics
        m.inflight -= n
        m.batches_total.inc()
        m.batch_rows_total.inc(n)
        m.padded_rows_total.inc(staged.bucket - n)
        m.latency["device"].observe(device_dt)
        m.count_completion(n, now)
        for i, r in enumerate(staged.requests):
            r.timings["device"] = device_dt
            m.latency["queue"].observe(r.timings.get("queue", 0.0))
            r.set_result(scores[i])

    @staticmethod
    def _fail(requests: List[Request], err: BaseException) -> None:
        for r in requests:
            if not r._event.is_set():
                r.set_exception(err)

    # ------------------------------------------------------------------
    # serve loop
    # ------------------------------------------------------------------
    @staticmethod
    def _out_ready(out) -> bool:
        try:
            return bool(out.is_ready())
        except AttributeError:        # pragma: no cover — very old jax
            return True

    def _loop_once(self, batcher: MicroBatcher) -> None:
        self._maybe_apply_reload()
        if not self._pending:
            # device idle: block for the first request, then coalesce
            # within the deadline window
            requests = batcher.next_batch(timeout=0.05)
            if requests:
                try:
                    self._pending = self._stage(requests)
                except Exception as e:             # noqa: BLE001
                    self._fail(requests, e)        # poisoned batch: 500s
                    raise                          # now, not at timeout
            return
        # Device busy on batch k: its execution time is FREE coalescing
        # time — gather batch k+1 until k's result lands AND the deadline
        # window has run, or the bucket fills (short-poll takes so
        # is_ready is re-checked ~1ms), then a last non-blocking drain for
        # stragglers already queued.  Honoring the deadline window here
        # too matters under closed-loop load: responses fan out staggered,
        # so the resends of batch k's clients arrive over several ms — a
        # gather that stops the instant the device idles locks into a
        # small-batch equilibrium (tiny batch → short exec → short gather
        # → tiny batch again).
        requests: List[Request] = []
        out = self._pending[-1].out        # last sub-batch lands last
        flush_at = time.monotonic() + batcher.deadline_s
        while len(requests) < batcher.max_batch:
            if self._out_ready(out) and time.monotonic() >= flush_at:
                break
            r = batcher.take(timeout=0.001)
            if r is not None:
                requests.append(r)
        while len(requests) < batcher.max_batch:
            r = batcher.take(timeout=0.0)
            if r is None:
                break
            requests.append(r)
        # dispatch k+1 (async) BEFORE blocking on k: transfer + compute of
        # k+1 overlap k's completion — the DeviceLoader double buffer
        staged: List[_Staged] = []
        if requests:
            try:
                staged = self._stage(requests)
            except Exception as e:                 # noqa: BLE001
                self._fail(requests, e)
                raise
        pending, self._pending = self._pending, []
        err: Optional[Exception] = None
        for st in pending:
            try:
                self._complete(st)
            except Exception as e:                 # noqa: BLE001
                self.metrics.inflight -= len(st.requests)
                self._fail(st.requests, e)
                err = e
        self._pending = staged
        if err is not None:
            raise err

    def serve_loop(self, batcher: MicroBatcher) -> None:
        """Run until stop(); never lets an exception strand requests or
        kill the worker."""
        while not self._stop.is_set():
            try:
                self._loop_once(batcher)
            except Exception:                      # noqa: BLE001
                # _loop_once already failed the requests of whichever batch
                # crashed; self._pending (if any) is a healthy dispatched
                # batch the next iteration will complete — don't touch it
                _logger.exception("engine worker crashed; recovering")
                self.metrics.worker_restarts_total.inc()
                time.sleep(0.01)     # a persistent fault must not spin-log

    def start(self, batcher: MicroBatcher) -> None:
        assert self._worker is None, "engine already started"
        self._worker = threading.Thread(
            target=self.serve_loop, args=(batcher,),
            name="serving-engine", daemon=True)
        self._worker.start()

    def stop(self) -> None:
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout=5.0)
            self._worker = None
        for st in self._pending:
            self._fail(st.requests, RuntimeError("server shutting down"))
        self._pending = []

    # ------------------------------------------------------------------
    # hot weight reload
    # ------------------------------------------------------------------
    def submit_reload(self, host_tree: Any, source: str = "<api>") -> None:
        """Queue a host-side variable tree for an atomic between-batch swap
        (called by the watcher thread, or directly in tests)."""
        with self._reload_lock:
            self._reload_box = [(host_tree, source)]

    def _maybe_apply_reload(self) -> None:
        with self._reload_lock:
            if not self._reload_box:
                return
            host_tree, source = self._reload_box.pop()
        try:
            shapes = jax.tree.map(
                lambda a: (tuple(np.shape(a)), np.asarray(a).dtype),
                host_tree)
            if shapes != self._var_shapes:
                raise ValueError("checkpoint tree/shape mismatch vs the "
                                 "serving model")
            new_vars = jax.device_put(host_tree)
            # one throwaway execution proves aval compatibility with the
            # compiled executables BEFORE the swap (a dtype drift would
            # otherwise 500 every request after)
            chans, dtype = self._wire_spec
            probe = self._run(
                self.buckets[0], new_vars,
                jnp.zeros((self.buckets[0], self.image_size,
                           self.image_size, chans), dtype))
            jax.block_until_ready(probe)
        except Exception:                          # noqa: BLE001
            _logger.exception("hot reload from %s rejected", source)
            self.metrics.reload_errors_total.inc()
            return
        self._variables = new_vars
        self.reload_count += 1
        self.metrics.reloads_total.inc()
        _logger.info("hot-reloaded weights from %s (reload #%d)", source,
                     self.reload_count)

    # ------------------------------------------------------------------
    def _newest_checkpoint(self, ckpt_dir: str
                           ) -> Optional[Tuple[str, float, int]]:
        try:
            names = os.listdir(ckpt_dir)
        except OSError:
            return None
        best = None
        for name in names:
            if not name.endswith(_CKPT_SUFFIXES):
                continue
            path = os.path.join(ckpt_dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            key = (path, st.st_mtime, st.st_size)
            if best is None or key[1] > best[1]:
                best = key
        return best

    def _watch_loop(self, ckpt_dir: str, interval_s: float,
                    use_ema: bool) -> None:
        from ..models.helpers import load_checkpoint
        while not self._stop.wait(interval_s):
            newest = self._newest_checkpoint(ckpt_dir)
            if newest is None or newest == self._last_reload_key:
                continue
            path = newest[0]
            try:
                loaded = load_checkpoint(self._host_template, path,
                                         use_ema=use_ema, strict=False)
            except Exception:                      # noqa: BLE001
                _logger.exception("reload watcher: cannot load %s", path)
                self.metrics.reload_errors_total.inc()
                self._last_reload_key = newest     # don't re-log every tick
                continue
            self._last_reload_key = newest
            self.submit_reload(loaded, source=path)

    def start_reload_watcher(self, ckpt_dir: str, interval_s: float = 5.0,
                             use_ema: bool = False) -> None:
        """Poll ``ckpt_dir`` for new ``models/helpers.py`` checkpoints and
        hot-swap them in.  Writers must rename atomically into place (the
        repo's ``save_model_checkpoint`` does)."""
        assert self._watcher is None, "watcher already started"
        # remember the current newest so only files appearing AFTER start
        # trigger a reload (the serving checkpoint itself usually lives in
        # the watched dir)
        self._last_reload_key = self._newest_checkpoint(ckpt_dir)
        self._watcher = threading.Thread(
            target=self._watch_loop, args=(ckpt_dir, interval_s, use_ema),
            name="serving-reload-watcher", daemon=True)
        self._watcher.start()
