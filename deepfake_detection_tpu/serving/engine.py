"""Inference engine: multi-model table over a bucketed AOT compile cache,
double-buffered staging, hot weight reload, weight-only PTQ.

Design (mirrors what ``data/loader.py`` does for training input):

* **Model table** — the engine serves N models from ONE worker loop and
  ONE micro-batch queue: each :class:`_ModelEntry` owns its params, its
  canvas geometry, its compiled executables and its reload/canary state.
  The compile cache is keyed ``(model_id, bucket, chans, wire)``; every
  executable is AOT-warmed before the server reports ready, and a model
  added to a warmed engine DROPS readiness until its own warmup ran —
  ``/readyz`` never lies about a cold model.  Requests carry a
  ``model_id`` (HTTP: the ``model`` field / query param, defaulting to
  the primary model) and the request books are mirrored per model.

* **Bucketed compile cache** — the scoring function is AOT-compiled once
  per (model, batch bucket) at startup, *before* the server reports
  ready.  Every device call thereafter hits a pre-compiled executable: a
  partial batch pads up to the nearest bucket and the pad rows are
  sliced off the result.  Because batch rows are independent in eval
  mode (running-stat BN, per-row softmax), the real rows of a padded
  bucket are bit-identical to an unpadded call (tests/test_serving.py).
  Novel shapes cannot recompile silently — an unknown bucket or channel
  width is a hard error, and ``compiles_total`` growing after ready=1 is
  the alarm.

* **uint8 wire** — HTTP threads ship the geometric canvas
  (``params.prepare_canvas``, uint8 HWC); normalize + ×img_num replication
  run inside the compiled call (``params.normalize_replicate`` semantics,
  elementwise float32, bit-identical to the CLI's host version).  Same
  idiom as the training loader's device prologue: 4× less host→device
  traffic and the photometrics get batched for free.

* **Post-training quantization** (serving/quant.py) — ``dtype`` bf16
  casts the params, ``int8`` quantizes conv/dense kernels with
  per-output-channel symmetric scales; the in-trace ``realize_tree``
  dequant fuses into the compiled program next to the normalize
  epilogue.  The transform applies at warmup AND to every hot reload
  from its f32 checkpoint (the canary then gates the *quantized* swap),
  while the shape gate keeps comparing against the f32 template.

* **Double-buffered staging** — while batch k executes, the engine drains
  already-queued requests into batch k+1 and dispatches it (JAX async
  dispatch) before blocking on k's result: transfer/stage of k+1 overlaps
  device compute of k, exactly like ``DeviceLoader.__iter__``.

* **Hot weight reload** — params ride the compiled call as an *argument*
  (not a closure constant), so swapping them is aval-compatible and free
  of recompiles.  A watcher thread per watched model polls a checkpoint
  dir; a new file is loaded host-side through ``models/helpers.py`` and
  swapped in atomically between batches (the A/B path).  Shape-
  incompatible checkpoints — including a checkpoint of a DIFFERENT
  model's tree — are rejected loudly, counted, and the old weights keep
  serving.

* **Crash recovery** — an exception anywhere in the serve loop fails the
  affected requests (HTTP 500) and restarts the loop; the worker thread
  never dies with requests stranded.

* **Self-healing** (serving/resilience.py) — the failure modes crash
  recovery can't absorb have their own recovery contracts, each loudly
  counted in /metrics and each reachable through an env-gated
  ``DFD_CHAOS`` injection point (``serve_exc`` / ``serve_nan`` /
  ``serve_hang`` / ``serve_kill`` / ``torn_reload``, stepped by device
  batch or reload attempt — ``chaos.py``'s fire-once grammar):

  - a batch that returns **NaN/Inf scores** fails every rider with 503
    (``nonfinite_batches_total``) — a non-finite score is never served;
  - a batch that **never completes** (or a worker that died outright)
    trips the stuck-batch watchdog: in-flight requests fail 503,
    readiness DROPS, a new worker generation starts, and every AOT
    bucket of every model is re-executed (no recompiles — the
    executables survive) before ``/readyz`` goes true again;
  - **consecutive batch failures** open a circuit breaker (immediate
    503 + Retry-After at the HTTP edge, half-open probe after the
    cooldown, close on probe success);
  - a **hot reload** must pass a golden-batch canary (finite,
    shape-correct, optionally drift-bounded scores — run on the
    QUANTIZED candidate under the target's serving dtype) before the
    swap; torn/garbage/mismatched checkpoints are rejected loudly and
    the old weights keep serving bit-identically.
"""

from __future__ import annotations

import hashlib
import logging
import os
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..cache.content import tree_fingerprint
from ..chaos import chaos_from_env
from ..params import image_max_height, img_mean, img_num as _default_img_num, \
    img_std
from .batcher import MicroBatcher, Request, pick_bucket
from .metrics import ServingMetrics
from .quant import canonical_mode, quant_summary, quantize_tree, realize_tree
from .resilience import (CircuitBreaker, EngineStalled, NonFiniteScores,
                         ServeWatchdog, torn_copy)

_logger = logging.getLogger(__name__)

__all__ = ["InferenceEngine", "DEFAULT_BUCKETS"]

DEFAULT_BUCKETS = (1, 4, 16, 64)

#: checkpoint filenames the reload watcher considers (others — .tmp
#: renames in flight, logs — are ignored)
_CKPT_SUFFIXES = (".msgpack", ".ckpt", ".flax", ".pkt")


def _params_fingerprint(host_tree: Any, dtype: str) -> str:
    """Stable hex digest of a host-side params tree: the weight identity
    the verdict cache keys on (ISSUE 17) and ``/readyz`` exposes.

    Digests every leaf's key-path, shape, dtype and bytes, plus the
    serving dtype — an f32→bf16/int8 swap of the SAME checkpoint scores
    differently and must never share cached verdicts."""
    leaves = jax.tree_util.tree_flatten_with_path(host_tree)[0]
    return tree_fingerprint(
        ((jax.tree_util.keystr(path), np.asarray(leaf))
         for path, leaf in leaves),
        extra=(canonical_mode(dtype),))


class _ModelEntry:
    """One served model: params, geometry, compiled programs, reload and
    canary state.  The engine's model table maps ``model_id`` → entry."""

    __slots__ = ("model_id", "model", "image_size", "img_num", "dtype",
                 "multi_frame", "host_template", "var_shapes", "variables",
                 "mean", "std", "mean_multi", "std_multi", "compiled",
                 "golden", "golden_ref", "fingerprint", "reload_count",
                 "last_reload_key", "reload_attempts", "watcher", "warmed")

    def __init__(self, model_id: str, model, variables, *,
                 image_size: int, img_num: int, dtype: str,
                 wire: str, multi_frame: bool):
        self.model_id = model_id
        self.model = model
        self.image_size = int(image_size)
        self.img_num = int(img_num)
        self.dtype = canonical_mode(dtype)
        # multi-frame needs a second program per bucket only on the uint8
        # wire (float32 payloads share the (·, ·, 3·img_num) shape)
        self.multi_frame = bool(multi_frame) and wire == "uint8" \
            and self.img_num > 1
        # host-side f32 template: the reload merge target AND the shape
        # gate — reloads stay f32 on disk regardless of serving dtype
        self.host_template = jax.tree.map(np.asarray, variables)
        self.var_shapes = jax.tree.map(
            lambda a: (tuple(np.shape(a)), np.asarray(a).dtype),
            self.host_template)
        # the device copy is what executes: PTQ applies here (and to
        # every reload), never to the template
        self.variables = jax.device_put(quantize_tree(variables,
                                                      self.dtype))
        # device_put of host arrays is a pure transfer: the warm path
        # must not pay (or count) a single backend compile for constants
        self.mean = jax.device_put(np.asarray(img_mean, np.float32))
        self.std = jax.device_put(np.asarray(img_std, np.float32))
        # multi-frame wire: mean/std tiled to the 3·img_num clip channels
        # so the SAME per-element arithmetic runs whether the channels
        # came from replication or img_num distinct frames
        self.mean_multi = jax.device_put(
            np.tile(np.asarray(img_mean, np.float32), self.img_num))
        self.std_multi = jax.device_put(
            np.tile(np.asarray(img_std, np.float32), self.img_num))
        self.compiled: Dict[Tuple[int, int], Any] = {}  # (bucket, chans)
        self.golden: Optional[np.ndarray] = None
        self.golden_ref: Optional[np.ndarray] = None
        # weight identity: part of every verdict-cache key, so a reload
        # (which re-assigns this atomically under the commit lock) orphans
        # all cached verdicts of the old weights by construction
        self.fingerprint = _params_fingerprint(self.host_template,
                                               self.dtype)
        self.reload_count = 0
        self.last_reload_key: Optional[Tuple[str, float, int]] = None
        self.reload_attempts = 0           # torn_reload chaos step counter
        self.watcher: Optional[threading.Thread] = None
        self.warmed = False


class _Staged:
    __slots__ = ("requests", "out", "bucket", "dispatch_t", "seq",
                 "model_id")

    def __init__(self, requests: List[Request], out: Any, bucket: int,
                 dispatch_t: float, seq: int, model_id: str):
        self.requests = requests
        self.out = out
        self.bucket = bucket
        self.dispatch_t = dispatch_t
        self.seq = seq          # device-batch sequence (the chaos step)
        self.model_id = model_id


class InferenceEngine:
    def __init__(self, model, variables, *,
                 image_size: int = image_max_height,
                 img_num: int = _default_img_num,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 metrics: Optional[ServingMetrics] = None,
                 wire: str = "float32",
                 multi_frame: bool = True,
                 warmup: bool = True,
                 dtype: str = "f32",
                 model_id: str = "default",
                 watchdog_timeout_s: float = 30.0,
                 breaker_threshold: int = 5,
                 breaker_open_s: float = 5.0,
                 reload_drift_tol: float = -1.0,
                 retry_jitter_s: float = 2.0,
                 warmstart=None,
                 warm_priority: Optional[Sequence[int]] = None,
                 warm_parallel: int = 0,
                 chaos=None):
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"invalid buckets {buckets}")
        #: warm-start executable store (serving/warmstart.py) or None —
        #: warmup consults it before paying lower().compile()
        self.warmstart = warmstart
        self._warm_priority = tuple(int(b) for b in (warm_priority or ()))
        bad = [b for b in self._warm_priority if b not in self.buckets]
        if bad:
            raise ValueError(
                f"warm_priority {bad} not in buckets {self.buckets}")
        self._warm_parallel = int(warm_parallel)
        #: readiness phase: cold -> degraded (staged warmup: priority
        #: bucket serving, rest warming in background) -> ready
        self._phase = "cold"
        self._warm_thread: Optional[threading.Thread] = None
        #: per-unit compile walls + last warmup wall (the staged-warmup
        #: overlap test reads these; keys are (bucket, chans))
        self.warm_compile_walls: Dict[Tuple[int, int], float] = {}
        self.last_warmup_wall = 0.0
        if wire not in ("float32", "uint8"):
            raise ValueError(f"wire must be float32|uint8, got {wire!r}")
        self.wire = wire
        self._multi_frame_opt = bool(multi_frame)
        self.metrics = metrics if metrics is not None else ServingMetrics()
        # real-compile observer: a silent recompile anywhere in the process
        # shows up in /metrics as backend_compiles_total growth (the
        # engine's own counter below only counts its AOT bucket builds)
        from .metrics import install_backend_compile_listener
        install_backend_compile_listener()
        # the model table; insertion order is stable, the FIRST entry is
        # the primary (default-routed) model
        self._models: Dict[str, _ModelEntry] = {}
        self.default_model_id = str(model_id)
        #: authoritative in-flight ledger — staged sub-batches live here
        #: from dispatch until completion, so the stuck-batch watchdog
        #: can read the oldest dispatch time even while the worker is
        #: blocked inside a completion
        self._pending: List[_Staged] = []
        self._pending_lock = threading.Lock()
        # reload box: latest submitted host tree per model id
        self._reload_box: Dict[str, Tuple[Any, str]] = {}
        self._reload_lock = threading.Lock()
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None
        self._watcher: Optional[threading.Thread] = None   # primary's
        self._batcher: Optional[MicroBatcher] = None
        # resilience: chaos injector, worker generations, breaker, watchdog
        self.chaos = chaos if chaos is not None else chaos_from_env()
        self._gen = 0                      # bumped by every recovery; a
        # stale worker checks it before touching shared state
        self._batch_seq = 0                # device-batch counter (chaos step)
        self._recover_lock = threading.Lock()
        self.reload_drift_tol = float(reload_drift_tol)
        self.breaker = CircuitBreaker(breaker_threshold, breaker_open_s,
                                      metrics=self.metrics,
                                      retry_jitter_s=retry_jitter_s)
        self.watchdog = ServeWatchdog(
            watchdog_timeout_s, self._oldest_dispatch, self._worker_alive,
            self._recover)
        # a recovery re-warm against a TRULY hung device would block the
        # watchdog thread forever in block_until_ready — run it bounded
        self._rewarm_timeout_s = max(30.0, 4.0 * float(watchdog_timeout_s))
        self._rewarm_thread: Optional[threading.Thread] = None
        self._canary_hook = None           # test seam: runs mid-canary
        #: verdict cache (cache/store.py VerdictCache), attached by the
        #: runner; start() hands it (plus the fingerprint resolver) to
        #: the batcher, and a reload commit purges the orphaned entries
        self.verdict_cache = None

        self.add_model(self.default_model_id, model, variables,
                       image_size=image_size, img_num=img_num, dtype=dtype)
        if warmup:
            self.warmup()

    # ------------------------------------------------------------------
    # model table
    # ------------------------------------------------------------------
    def add_model(self, model_id: str, model, variables, *,
                  image_size: Optional[int] = None,
                  img_num: Optional[int] = None,
                  dtype: str = "f32") -> None:
        """Register one more model in the table.  Readiness DROPS until
        :meth:`warmup` has AOT-compiled + warmed the new entry's buckets
        — a cold model must never be routable behind a ready /readyz."""
        model_id = str(model_id)
        # table mutation rides the recovery lock: the watchdog's
        # recovery (and its re-warm probe) iterates this dict from
        # another thread
        with self._recover_lock:
            if model_id in self._models:
                raise ValueError(
                    f"model id {model_id!r} already registered")
            primary = next(iter(self._models.values()), None)
            entry = _ModelEntry(
                model_id, model, variables,
                image_size=(image_size if image_size is not None
                            else (primary.image_size if primary
                                  else image_max_height)),
                img_num=(img_num if img_num is not None
                         else (primary.img_num if primary
                               else _default_img_num)),
                dtype=dtype, wire=self.wire,
                multi_frame=self._multi_frame_opt)
            self._models[model_id] = entry
        if entry.dtype != "f32":
            _logger.info("model %r quantized to %s: %s", model_id,
                         entry.dtype, quant_summary(entry.variables))
        self.metrics.ready = False         # one cold model => not ready

    def entry(self, model_id: Optional[str] = None) -> _ModelEntry:
        """The table entry for ``model_id`` (None = primary); unknown ids
        are a loud error, never a fallback to some other model."""
        if model_id is None:
            model_id = self.default_model_id
        try:
            return self._models[model_id]
        except KeyError:
            raise ValueError(
                f"unknown model {model_id!r}; this engine serves "
                f"{self.model_ids()}") from None

    def has_model(self, model_id: str) -> bool:
        return model_id in self._models

    def model_fingerprint(self, model_id: Optional[str] = None) -> str:
        """The checkpoint fingerprint of one model (None = primary): a
        stable hex digest of its host params tree + serving dtype.  This
        is the weight identity the verdict cache keys on and ``/readyz``
        publishes per model — a hot reload or quantized swap changes it
        atomically with the weights."""
        return self.entry(model_id).fingerprint

    def model_ids(self) -> Tuple[str, ...]:
        return tuple(self._models)

    # --- single-model back-compat surface (primary entry) -------------
    @property
    def model(self):
        return self.entry().model

    @property
    def image_size(self) -> int:
        return self.entry().image_size

    @property
    def img_num(self) -> int:
        return self.entry().img_num

    @property
    def multi_frame(self) -> bool:
        return self.entry().multi_frame

    @property
    def _variables(self):
        return self.entry().variables

    @property
    def _host_template(self):
        return self.entry().host_template

    @property
    def reload_count(self) -> int:
        return sum(e.reload_count for e in self._models.values())

    # ------------------------------------------------------------------
    # wire / program shapes
    # ------------------------------------------------------------------
    def _entry_wire_spec(self, entry: _ModelEntry) -> Tuple[int, Any]:
        """(channels, dtype) of one SINGLE-frame wire sample."""
        if self.wire == "uint8":
            return 3, np.uint8
        return 3 * entry.img_num, np.float32

    @property
    def _wire_spec(self) -> Tuple[int, Any]:
        return self._entry_wire_spec(self.entry())

    def _entry_chans(self, entry: _ModelEntry) -> Tuple[int, ...]:
        """Channel widths this entry compiles (one program per width per
        bucket): the single-frame wire width plus, on a multi-frame uint8
        wire, the channel-concatenated clip width."""
        base, _ = self._entry_wire_spec(entry)
        if entry.multi_frame:
            return (base, 3 * entry.img_num)
        return (base,)

    def allowed_chans(self, model_id: Optional[str] = None
                      ) -> Tuple[int, ...]:
        """Channel counts a request array may carry on this wire."""
        return self._entry_chans(self.entry(model_id))

    def _make_program(self, entry: _ModelEntry, chans: int):
        """The traced score function for one (model, channel-width): the
        uint8 wire fuses normalize (+ replicate) with the model, and
        quantized params dequantize in-trace (realize_tree — a no-op at
        f32, preserving the CLI bit-parity contract)."""
        model, n_rep = entry.model, entry.img_num
        if self.wire == "uint8":
            replicate = (chans == 3 and n_rep > 1)

            def _score(variables, x_u8, mean, std):
                x = (x_u8.astype(jnp.float32) - mean) / std
                if replicate:
                    x = jnp.tile(x, (1, 1, 1, n_rep))
                logits = model.apply(realize_tree(variables), x,
                                     training=False)
                return jax.nn.softmax(logits, axis=-1)
        else:
            def _score(variables, x):
                logits = model.apply(realize_tree(variables), x,
                                     training=False)
                return jax.nn.softmax(logits, axis=-1)
        return _score

    def _run(self, entry: _ModelEntry, bucket: int, chans: int,
             variables, x):
        ex = entry.compiled[(bucket, chans)]
        if self.wire == "uint8":
            if chans == 3:
                return ex(variables, x, entry.mean, entry.std)
            return ex(variables, x, entry.mean_multi, entry.std_multi)
        return ex(variables, x)

    # ------------------------------------------------------------------
    # compile cache
    # ------------------------------------------------------------------
    @property
    def compile_count(self) -> int:
        return self.metrics.compiles_total.value

    @property
    def ready(self) -> bool:
        return self.metrics.ready

    def readiness_detail(self) -> Dict[str, Any]:
        """The ``/readyz`` JSON body: per-model readiness + the health
        signals a fleet router scrapes.  A 503 with this body means
        "process up, serving set not ready" (cold model warming,
        watchdog re-warm, reload canary) — distinguishable from "engine
        down" (no response at all) without parsing metrics text."""
        return {
            "ready": bool(self.metrics.ready),
            # degraded = ready on a SUBSET of buckets while the rest warm
            # in background (staged warmup); the router's scraper routes
            # any 200, so degraded capacity is routable by construction
            "phase": self._phase,
            # snapshot: a live add_model grows the table from another
            # thread (the PR 14 warmup/_rewarm discipline)
            "models": {
                mid: {"warmed": e.warmed,
                      "image_size": e.image_size,
                      "img_num": e.img_num,
                      "dtype": e.dtype,
                      "fingerprint": e.fingerprint,
                      "reloads": e.reload_count,
                      "warm_buckets": sorted(
                          {b for (b, _c) in list(e.compiled)})}
                for mid, e in list(self._models.items())},
            "breaker": self.breaker.state,
            "queue_depth": int(self.metrics.queue_depth),
            "inflight": int(self.metrics.inflight),
        }

    def _warm_order(self) -> Tuple[int, ...]:
        """Bucket warm order: the configured priority first, remaining
        buckets smallest-first (small buckets compile fastest and already
        serve single requests — the best capacity-per-second spent)."""
        rest = [b for b in self.buckets if b not in self._warm_priority]
        return self._warm_priority + tuple(rest)

    def warmup(self, staged: bool = False) -> None:
        """Obtain every (model, bucket, chans) executable — from the
        warm-start store when attached, else a fresh AOT compile — and
        execute each once (primes any first-run allocation paths), then
        flip ready.  Idempotent per entry: adding a model to a warmed
        engine only builds the new entry's programs.

        ``staged=True`` warms only the FIRST priority bucket before
        declaring readiness (phase ``degraded``: /readyz goes 200, the
        dispatch path pads into the already-warm buckets only) and warms
        the remaining buckets on a background thread, flipping the phase
        to ``ready`` when the full set is live.  A recovery firing
        mid-stage aborts the background warm — the recovery generation
        owns readiness and the warmed subset keeps serving."""
        gen = self._gen
        t0 = time.monotonic()
        compile0 = self.metrics.warmup_seconds["compile"]
        order = self._warm_order()
        first, rest = order[:1], order[1:]
        # snapshot: a concurrent add_model may grow the table mid-loop
        for entry in list(self._models.values()):
            self._warm_entry(entry, buckets=(first if staged and rest
                                             else order))
        # the live add_model path runs this on the caller's thread while
        # the watchdog (or a reload canary) may be mid-recovery: only the
        # generation that was current for the WHOLE warmup may declare
        # readiness — a recovery in between owns the flag (its own
        # re-warm proves the device before it restores ready)
        with self._recover_lock:
            if gen == self._gen:
                self._phase = "degraded" if staged and rest else "ready"
                self.metrics.ready = True
        self.last_warmup_wall = time.monotonic() - t0
        # warm = everything warmup did beyond obtaining executables
        # (execute-once priming, canaries, store serialization)
        self.metrics.warmup_seconds["warm"] += max(
            0.0, self.last_warmup_wall
            - (self.metrics.warmup_seconds["compile"] - compile0))
        if staged and rest:
            t = threading.Thread(target=self._warm_rest,
                                 args=(gen, rest), daemon=True,
                                 name="serving-warm-bg")
            self._warm_thread = t
            t.start()

    def _warm_rest(self, gen: int, buckets: Tuple[int, ...]) -> None:
        """Background half of a staged warmup: one bucket at a time, so
        dispatch sees capacity grow between buckets, not after all."""
        try:
            for b in buckets:
                if gen != self._gen or self._stop.is_set():
                    return             # a recovery owns readiness now
                for entry in list(self._models.values()):
                    self._warm_entry(entry, buckets=(b,))
            with self._recover_lock:
                if gen == self._gen:
                    self._phase = "ready"
        except Exception:                              # noqa: BLE001
            _logger.exception("staged warmup: background bucket warm "
                              "failed; engine stays degraded on the "
                              "already-warm buckets")

    # -- warm-start store plumbing -------------------------------------
    def _store_fields(self, entry: _ModelEntry, bucket: int,
                      chans: int) -> Dict[str, Any]:
        """The complete warmstart key fields of one executable (see
        serving/warmkey.py).  The program hash digests the model config
        (flax dataclass repr), the *signature* of the quantized params
        tree (paths/shapes/dtypes — weights are call arguments, so
        checkpoints of one architecture share executables) and the
        normalization constants; quant/wire/geometry ride as their own
        loud fields."""
        from . import warmkey
        h = hashlib.sha256()
        h.update(repr(entry.model).encode())
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                entry.variables)[0]:
            h.update(jax.tree_util.keystr(path).encode())
            h.update(str(jnp.shape(leaf)).encode())
            h.update(str(jnp.result_type(leaf)).encode())
        for a in (entry.mean, entry.std, entry.mean_multi,
                  entry.std_multi):
            h.update(np.asarray(a).tobytes())
        dev = jax.devices()[0]
        return warmkey.key_fields(
            backend=jax.default_backend(),
            device_kind=dev.device_kind,
            program=h.hexdigest(),
            geometry={"image_size": entry.image_size,
                      "img_num": entry.img_num,
                      "multi_frame": entry.multi_frame,
                      "model_class": type(entry.model).__name__},
            bucket=bucket, chans=chans, wire=self.wire,
            quant=entry.dtype, sharding="")

    def _warm_golden_input(self, entry: _ModelEntry, bucket: int,
                           chans: int) -> np.ndarray:
        """Deterministic canary input for one (bucket, chans): identical
        across processes (fixed seed), so manifest golden scores from the
        serializing process can demand bit-exactness in the loading one."""
        s = entry.image_size
        _, dtype = self._entry_wire_spec(entry)
        rng = np.random.default_rng(0xCA9A87)
        if np.dtype(dtype) == np.uint8:
            return rng.integers(0, 256, (bucket, s, s, chans),
                                dtype=np.uint8)
        return rng.random((bucket, s, s, chans), dtype=np.float32)

    def _store_load(self, entry: _ModelEntry, bucket: int, chans: int):
        """Try the store for one executable.  Returns ``(compiled,
        (fields, manifest))`` or None (counted miss/fallback)."""
        if self.warmstart is None:
            return None
        from .warmstart import WarmstartMiss
        fields = self._store_fields(entry, bucket, chans)
        try:
            compiled, manifest = self.warmstart.load(fields)
        except WarmstartMiss as e:
            if e.reason == "absent":
                self.metrics.warmstart_misses_total.inc()
            else:
                # present but unusable — corrupt blob, foreign manifest,
                # version skew baked into the key fields: fall back to a
                # fresh compile, loudly, and re-serialize over it
                self.metrics.warmstart_fallbacks_total.inc()
                _logger.warning(
                    "warmstart: %s bucket %d (%dch): %s — compiling "
                    "fresh", entry.model_id, bucket, chans, e)
            return None
        self.metrics.warmstart_hits_total.inc()
        return compiled, (fields, manifest)

    def _warm_canary(self, entry: _ModelEntry, bucket: int, chans: int,
                     fields: Dict[str, Any],
                     manifest: Dict[str, Any]) -> bool:
        """Golden-batch gate for ONE deserialized executable: scores must
        be finite and shape-correct, and — when the manifest was written
        under the currently-served checkpoint (fingerprint match, the
        scale-up common path) — bit-exact against the recorded scores.
        A fingerprint-skewed entry that passes gets its manifest
        re-stamped so the next same-checkpoint spawn regains the
        bit-exact gate."""
        from . import warmkey
        gx = self._warm_golden_input(entry, bucket, chans)
        why = ""
        scores: Optional[np.ndarray] = None
        try:
            scores = np.asarray(self._run(entry, bucket, chans,
                                          entry.variables, gx))
        except Exception as e:                         # noqa: BLE001
            why = f"execution failed: {e}"
        if why == "" and (scores.ndim != 2 or scores.shape[0] != bucket):
            why = f"scores shape {scores.shape} for bucket {bucket}"
        if why == "" and not np.isfinite(scores).all():
            why = "non-finite scores"
        same_ckpt = (manifest.get("params_fingerprint")
                     == entry.fingerprint)
        if why == "" and same_ckpt:
            try:
                ref = warmkey.decode_array(manifest["golden_scores"])
            except Exception as e:                     # noqa: BLE001
                why = f"manifest golden scores unreadable: {e}"
            else:
                if ref.shape != scores.shape or \
                        not np.array_equal(ref, scores):
                    why = ("scores not bit-identical to the manifest's "
                           "(same checkpoint fingerprint)")
        if why:
            self.metrics.warmstart_canary_rejects_total.inc()
            _logger.error("warmstart: canary REJECTED deserialized "
                          "executable %s bucket %d (%dch): %s — "
                          "recompiling fresh", entry.model_id, bucket,
                          chans, why)
            return False
        if not same_ckpt and self.warmstart is not None:
            self.warmstart.refresh_manifest(
                fields, golden_scores=scores,
                params_fingerprint=entry.fingerprint)
        return True

    def _store_save(self, entry: _ModelEntry, bucket: int,
                    chans: int) -> None:
        if self.warmstart is None:
            return
        fields = self._store_fields(entry, bucket, chans)
        gx = self._warm_golden_input(entry, bucket, chans)
        scores = np.asarray(self._run(entry, bucket, chans,
                                      entry.variables, gx))
        if self.warmstart.save(fields, entry.compiled[(bucket, chans)],
                               golden_scores=scores,
                               params_fingerprint=entry.fingerprint):
            self.metrics.warmstart_serialized_total.inc()

    def _compile_units(self, entry: _ModelEntry,
                       units: List[Tuple[int, int]]) -> None:
        """Fresh-compile the given (bucket, chans) units, dispatching
        independent compiles concurrently: ``lower()`` traces under the
        GIL but ``compile()`` releases it inside XLA, so a thread pool
        overlaps the bucket compiles (the wall win materializes with
        spare cores; the per-unit walls in ``warm_compile_walls`` always
        prove the overlap).  Metrics/store writes stay on the caller's
        thread."""
        if not units:
            return
        s = entry.image_size
        _, dtype = self._entry_wire_spec(entry)

        def _build(unit: Tuple[int, int]):
            b, chans = unit
            t0 = time.monotonic()
            x_spec = jax.ShapeDtypeStruct((b, s, s, chans),
                                          jnp.dtype(dtype))
            fn = self._make_program(entry, chans)
            # per-bucket AOT lowering is the POINT of this loop: one
            # deliberate compile per declared (model, bucket, chans)
            # at warmup, counted in compiles_total, zero recompiles
            # after ready
            if self.wire == "uint8":
                mean, std = (entry.mean, entry.std) if chans == 3 \
                    else (entry.mean_multi, entry.std_multi)
                lowered = jax.jit(fn).lower(entry.variables, x_spec,
                                            mean, std)
            else:
                lowered = jax.jit(fn).lower(entry.variables, x_spec)
            return unit, lowered.compile(), time.monotonic() - t0

        workers = self._warm_parallel if self._warm_parallel > 0 \
            else min(4, len(units))
        if workers <= 1 or len(units) == 1:
            results = [_build(u) for u in units]
        else:
            with ThreadPoolExecutor(
                    max_workers=min(workers, len(units)),
                    thread_name_prefix="serving-warm-compile") as pool:
                results = list(pool.map(_build, units))
        for unit, compiled, wall in results:
            entry.compiled[unit] = compiled
            self.warm_compile_walls[unit] = wall
            self.metrics.compiles_total.inc()
            _logger.info("model %r bucket %d (%dch) compiled in %.1fs",
                         entry.model_id, unit[0], unit[1], wall)

    def _warm_entry(self, entry: _ModelEntry,
                    buckets: Optional[Sequence[int]] = None) -> None:
        """Bring one entry's executables live for ``buckets`` (None =
        the full warm order): store-deserialize what the warm-start tier
        has (canary-gated), fresh-compile the rest (concurrently), warm-
        execute every new unit once, then (re)serialize fresh compiles."""
        warm_buckets = tuple(buckets) if buckets is not None \
            else self._warm_order()
        s = entry.image_size
        _, dtype = self._entry_wire_spec(entry)
        units = [(b, chans) for chans in self._entry_chans(entry)
                 for b in warm_buckets if (b, chans) not in entry.compiled]
        t_compile0 = time.monotonic()
        loaded: Dict[Tuple[int, int], Tuple[Dict, Dict]] = {}
        misses: List[Tuple[int, int]] = []
        for unit in units:
            got = self._store_load(entry, *unit)
            if got is not None:
                entry.compiled[unit] = got[0]
                self.warm_compile_walls[unit] = 0.0
                loaded[unit] = got[1]
            else:
                misses.append(unit)
        self._compile_units(entry, misses)
        self.metrics.warmup_seconds["compile"] += \
            time.monotonic() - t_compile0
        # canary-gate every deserialized executable BEFORE it can serve;
        # a reject is evicted, recompiled fresh and re-serialized over
        for unit, (fields, manifest) in loaded.items():
            if not self._warm_canary(entry, unit[0], unit[1], fields,
                                     manifest):
                entry.compiled.pop(unit, None)
                self._compile_units(entry, [unit])
                misses.append(unit)
        # one warm execution per new unit primes first-run allocations
        # (host zeros + device_put: a jnp.zeros fill would compile a tiny
        # broadcast program and break the warm path's zero-compile bar)
        for b, chans in units:
            jax.block_until_ready(self._run(
                entry, b, chans, entry.variables,
                jax.device_put(np.zeros((b, s, s, chans), dtype))))
        for unit in misses:
            self._store_save(entry, *unit)
        # golden canary batch: a fixed seeded input whose scores under the
        # CURRENT weights baseline both the reload canary and (optionally)
        # its drift tolerance — tied to the canonical smallest bucket, so
        # a staged/priority warm that hasn't built it yet defers to the
        # _warm_entry call that does
        base_chans, dtype = self._entry_wire_spec(entry)
        if (self.buckets[0], base_chans) in entry.compiled:
            if entry.golden is None:
                entry.golden = self._warm_golden_input(
                    entry, self.buckets[0], base_chans)
            entry.golden_ref = np.asarray(
                self._run(entry, self.buckets[0], base_chans,
                          entry.variables, entry.golden))
        entry.warmed = True

    def _rewarm(self) -> None:
        """Execute every AOT (model, bucket, chans) executable once
        against the serving weights (the recovery path's proof that the
        device answers again).  Runs the EXISTING compiled executables —
        a recovery never recompiles, which is what lets chaos_serve
        assert zero post-recovery backend compiles.  Snapshot the table:
        a timed-out recovery releases _recover_lock while this probe is
        still running, so a live add_model may grow the dict mid-loop
        (the new entry's own warmup proves it; this probe owes it
        nothing)."""
        for entry in list(self._models.values()):
            if not entry.warmed:
                continue       # cold add_model entry: no executables yet
            s = entry.image_size
            _, dtype = self._entry_wire_spec(entry)
            # the executables that exist, not the full bucket grid: a
            # staged warmup may still be building the tail buckets
            for b, chans in sorted(list(entry.compiled)):
                jax.block_until_ready(self._run(
                    entry, b, chans, entry.variables,
                    jax.device_put(np.zeros((b, s, s, chans), dtype))))
        self.metrics.rewarms_total.inc()

    # ------------------------------------------------------------------
    # scoring
    # ------------------------------------------------------------------
    def _chans_of(self, entry: _ModelEntry, array) -> int:
        """Wire channel count of one request array, validated against the
        entry's compiled programs (unknown widths must fail loudly here,
        never reach an uncompiled shape)."""
        chans = int(np.shape(array)[-1]) if np.ndim(array) else 0
        if chans not in self._entry_chans(entry):
            raise ValueError(
                f"request carries {chans} channels; model "
                f"{entry.model_id!r} accepts {self._entry_chans(entry)} "
                f"(wire={self.wire}, img_num={entry.img_num}, "
                f"multi_frame={entry.multi_frame})")
        return chans

    def _warm_buckets(self, entry: _ModelEntry,
                      chans: int) -> Tuple[int, ...]:
        """Buckets with a LIVE executable for this channel width — the
        only shapes dispatch may pad into.  During a staged warmup this
        is a growing prefix of the bucket grid; fully warmed it equals
        ``self.buckets``.  ``list()`` snapshots against the background
        warm thread growing the dict mid-iteration."""
        avail = sorted(b for (b, c) in list(entry.compiled) if c == chans)
        return tuple(avail) if avail else self.buckets

    def _pad_batch(self, entry: _ModelEntry, arrays: List[np.ndarray],
                   chans: int) -> Tuple[np.ndarray, int]:
        n = len(arrays)
        bucket = pick_bucket(n, self._warm_buckets(entry, chans))
        s = entry.image_size
        _, dtype = self._entry_wire_spec(entry)
        # fresh buffer every batch: jax CPU device_put zero-copies aligned
        # host memory, so reusing one buffer would race the still-executing
        # previous batch (same hazard data/loader.py guards with
        # block_until_ready)
        buf = np.zeros((bucket, s, s, chans), dtype)
        for i, a in enumerate(arrays):
            write_into = getattr(a, "write_into", None)
            if write_into is not None:
                # streaming FrameStack payload (streaming/ring.py): the
                # window's frames gather straight from the crop ring into
                # this slab row — the ONE copy of the window's life —
                # and the ring pins release
                write_into(buf[i])
            else:
                buf[i] = a
        return buf, bucket

    def score_batch(self, arrays: List[np.ndarray],
                    model_id: Optional[str] = None) -> np.ndarray:
        """Synchronous scoring of up to max-bucket wire-format samples
        (tests, warm checks) against one model; one uniform channel width
        per call — the serving path goes through stage/complete instead
        and may mix widths and models."""
        entry = self.entry(model_id)
        chans = self._chans_of(entry, arrays[0])
        for a in arrays[1:]:
            if self._chans_of(entry, a) != chans:
                raise ValueError("score_batch arrays must share one "
                                 "channel width; the async path handles "
                                 "mixed single/multi-frame traffic")
        buf, bucket = self._pad_batch(entry, arrays, chans)
        out = self._run(entry, bucket, chans, entry.variables,
                        jax.device_put(buf))
        return np.asarray(out)[:len(arrays)]

    def _stage(self, requests: List[Request]) -> List[_Staged]:
        """Dispatch requests as one device batch per (model, channel
        width).

        Requests for different models (or different frame layouts) ride
        different compiled programs, so a coalesced batch that mixes them
        splits into staged sub-batches — each still a pre-compiled
        bucket, dispatched back-to-back so all overlap the previous
        batch's completion.  Every sub-batch enters the ``_pending``
        ledger at dispatch so the watchdog sees its age."""
        groups: Dict[Tuple[str, int], List[Request]] = {}
        for r in requests:
            # per-request validation: an unknown model id or channel
            # width (possible on direct library submits — the HTTP edge
            # pre-validates) must fail THAT request, never the whole
            # coalesced batch (which would 500 innocent riders and feed
            # the circuit breaker a non-device failure)
            try:
                entry = self.entry(r.model_id)
                key = (entry.model_id, self._chans_of(entry, r.array))
            except ValueError as e:
                if r.claim():
                    self.metrics.failed_total.inc()
                    self.metrics.count_model("failed", r.model_id)
                    r.set_exception(e)
                continue
            groups.setdefault(key, []).append(r)
        staged: List[_Staged] = []
        try:
            for (model_id, chans), grp in groups.items():
                entry = self._models[model_id]
                # during a staged warmup the coalesced group may exceed
                # the largest LIVE bucket: split it — each chunk is still
                # a pre-compiled bucket, dispatched back-to-back (fully
                # warmed, cap == max_batch and this is one chunk)
                cap = self._warm_buckets(entry, chans)[-1]
                for i0 in range(0, len(grp), cap):
                    sub = grp[i0:i0 + cap]
                    seq = self._batch_seq
                    self._batch_seq += 1
                    if self.chaos.active and \
                            self.chaos.fires("serve_exc", seq):
                        self.metrics.count_chaos("serve_exc")
                        raise RuntimeError(
                            f"chaos: injected score-fn exception "
                            f"(batch {seq})")
                    buf, bucket = self._pad_batch(
                        entry, [r.array for r in sub], chans)
                    out = self._run(entry, bucket, chans,
                                    entry.variables, jax.device_put(buf))
                    now = time.monotonic()
                    for r in sub:
                        r.timings["queue"] = now - r.enqueue_t
                    st = _Staged(sub, out, bucket, now, seq, model_id)
                    # gauge bump + ledger entry are ONE atom vs the
                    # recovery path (which zeroes the gauge and clears
                    # the ledger under the same lock) — split, a recovery
                    # landing between them would leave the inflight gauge
                    # permanently negative
                    with self._pending_lock:
                        self.metrics.inflight += len(sub)
                        self._pending.append(st)
                    staged.append(st)
        except Exception:
            # a later group poisoned the stage: the caller fails EVERY
            # request of the coalesced batch, so unwind the sub-batches
            # already dispatched (their device work is wasted, not leaked)
            for st in staged:
                self._unpend(st)
            raise
        return staged

    def _unpend(self, staged: _Staged) -> bool:
        """Claim a staged batch out of the in-flight ledger; the claim
        carries its inflight-gauge decrement (one atom, same lock as the
        recovery path's clear-and-zero).  False = a recovery already
        claimed it — the caller owns neither the gauge nor the
        requests."""
        with self._pending_lock:
            try:
                self._pending.remove(staged)
            except ValueError:
                return False
            self.metrics.inflight -= len(staged.requests)
            return True

    def _complete(self, staged: _Staged, gen: int) -> None:
        if gen != self._gen:
            return                 # recovery owns these requests now
        if self.chaos.active and self.chaos.fires("serve_hang", staged.seq):
            hang_s = self.chaos.arg("serve_hang", 30.0)
            self.metrics.count_chaos("serve_hang")
            _logger.error("chaos: hanging completion of batch %d for "
                          "%.1fs", staged.seq, hang_s)
            time.sleep(hang_s)
        scores = np.asarray(staged.out)          # blocks on the device
        now = time.monotonic()
        if gen != self._gen or not self._unpend(staged):
            # the watchdog recovered while we were blocked: it already
            # failed these requests and zeroed the gauges — touch nothing
            # (the ledger claim is the tiebreaker for the last-instant
            # race between the gen check and the recovery's clear)
            return
        if self.chaos.active and self.chaos.fires("serve_nan", staged.seq):
            self.metrics.count_chaos("serve_nan")
            scores = np.full_like(scores, np.nan)
        device_dt = now - staged.dispatch_t
        n = len(staged.requests)
        m = self.metrics
        if not np.isfinite(scores[:n]).all():
            # a non-finite score is NEVER served: fail every rider with a
            # 503-mapped error and let the breaker see the batch failure
            m.nonfinite_batches_total.inc()
            self.breaker.record_failure()
            _logger.error("device batch %d produced non-finite scores; "
                          "failing %d request(s)", staged.seq, n)
            self._fail(staged.requests, NonFiniteScores(
                f"device batch {staged.seq} produced non-finite scores "
                f"(bucket {staged.bucket}); retry against healthy weights"))
            return
        m.batches_total.inc()
        m.batch_rows_total.inc(n)
        m.padded_rows_total.inc(staged.bucket - n)
        m.count_bucket_rows(staged.model_id, staged.bucket, n,
                            staged.bucket - n)
        m.latency["device"].observe(device_dt)
        m.count_completion(n, now)
        for i, r in enumerate(staged.requests):
            r.timings["device"] = device_dt
            m.latency["queue"].observe(r.timings.get("queue", 0.0))
            if r.claim():
                m.scored_total.inc()
                m.count_model("scored", r.model_id)
                r.set_result(scores[i])
        self.breaker.record_success()

    def _fail(self, requests: List[Request], err: BaseException) -> None:
        for r in requests:
            if r.claim():
                self.metrics.failed_total.inc()
                self.metrics.count_model("failed", r.model_id)
                r.set_exception(err)

    # ------------------------------------------------------------------
    # serve loop
    # ------------------------------------------------------------------
    @staticmethod
    def _out_ready(out) -> bool:
        try:
            return bool(out.is_ready())
        except AttributeError:        # pragma: no cover — very old jax
            return True

    def _loop_once(self, batcher: MicroBatcher, gen: int) -> None:
        if self.chaos.active and \
                self.chaos.fires("serve_kill", self._batch_seq):
            self.metrics.count_chaos("serve_kill")
            _logger.error("chaos: killing engine worker (gen %d)", gen)
            # SystemExit ends the worker thread outright (serve_loop's
            # crash recovery deliberately does not absorb it) — the
            # watchdog's worker-liveness probe is what must bring
            # serving back
            raise SystemExit("chaos: serve_kill")
        self._maybe_apply_reload()
        with self._pending_lock:
            pending = list(self._pending)
        if not pending:
            # device idle: block for the first request, then coalesce
            # within the deadline window
            requests = batcher.next_batch(timeout=0.05)
            if requests:
                try:
                    self._stage(requests)
                except Exception as e:             # noqa: BLE001
                    self._fail(requests, e)        # poisoned batch: 500s
                    self.breaker.record_failure()
                    raise                          # now, not at timeout
            return
        # Device busy on batch k: its execution time is FREE coalescing
        # time — gather batch k+1 until k's result lands AND the deadline
        # window has run, or the bucket fills (short-poll takes so
        # is_ready is re-checked ~1ms), then a last non-blocking drain for
        # stragglers already queued.  Honoring the deadline window here
        # too matters under closed-loop load: responses fan out staggered,
        # so the resends of batch k's clients arrive over several ms — a
        # gather that stops the instant the device idles locks into a
        # small-batch equilibrium (tiny batch → short exec → short gather
        # → tiny batch again).
        requests: List[Request] = []
        out = pending[-1].out              # last sub-batch lands last
        flush_at = time.monotonic() + batcher.deadline_s
        while len(requests) < batcher.max_batch and gen == self._gen:
            if self._out_ready(out) and time.monotonic() >= flush_at:
                break
            r = batcher.take(timeout=0.001)
            if r is not None:
                requests.append(r)
        while len(requests) < batcher.max_batch and gen == self._gen:
            r = batcher.take(timeout=0.0)
            if r is None:
                break
            requests.append(r)
        if gen != self._gen:
            # a recovery fired while we gathered (a REAL device hang parks
            # the worker right here, endlessly re-polling is_ready): the
            # dequeued requests would otherwise be stranded — fail them
            self._fail(requests, EngineStalled(
                "engine restarted while this request was being batched"))
            return
        # dispatch k+1 (async) BEFORE blocking on k: transfer + compute of
        # k+1 overlap k's completion — the DeviceLoader double buffer
        if requests:
            try:
                self._stage(requests)
            except Exception as e:                 # noqa: BLE001
                self._fail(requests, e)
                self.breaker.record_failure()
                raise
        err: Optional[Exception] = None
        for st in pending:
            try:
                self._complete(st, gen)
            except Exception as e:                 # noqa: BLE001
                if gen != self._gen:
                    return             # recovery already owns the ledger
                self._unpend(st)       # claim carries the gauge decrement
                self._fail(st.requests, e)
                self.breaker.record_failure()
                err = e
        if err is not None:
            raise err

    def serve_loop(self, batcher: MicroBatcher, gen: int = 0) -> None:
        """Run until stop() or a newer worker generation supersedes this
        one; never lets an exception strand requests or kill the worker
        (an injected SystemExit — the worker-kill chaos — does end the
        thread, and the watchdog's liveness probe recovers from it)."""
        while not self._stop.is_set() and gen == self._gen:
            try:
                self._loop_once(batcher, gen)
            except SystemExit:
                # the worker-kill chaos: die like a crashed thread (the
                # watchdog must notice and respawn) but without tripping
                # pytest's thread-exception hook — matching Python's own
                # silent-SystemExit thread semantics
                return
            except Exception:                      # noqa: BLE001
                # _loop_once already failed the requests of whichever batch
                # crashed; self._pending (if any) is a healthy dispatched
                # batch the next iteration will complete — don't touch it
                if gen != self._gen:
                    return
                _logger.exception("engine worker crashed; recovering")
                self.metrics.worker_restarts_total.inc()
                time.sleep(0.01)     # a persistent fault must not spin-log

    def _spawn_worker(self) -> None:
        gen = self._gen
        self._worker = threading.Thread(
            target=self.serve_loop, args=(self._batcher, gen),
            name=f"serving-engine-g{gen}", daemon=True)
        self._worker.start()

    def start(self, batcher: MicroBatcher) -> None:
        assert self._batcher is None, "engine already started"
        self._batcher = batcher
        # unrouted submits land on the primary model's books
        batcher.default_model_id = self.default_model_id
        # verdict cache: the batcher's probe keys on the engine's weight
        # identity — a submit races a reload only in the safe direction
        # (new scores stored under the orphaned old fingerprint, never
        # old scores under the new one)
        batcher.fingerprint_of = self.model_fingerprint
        if self.verdict_cache is not None and batcher.cache is None:
            batcher.cache = self.verdict_cache
        self._spawn_worker()
        self.watchdog.start()

    def stop(self) -> None:
        self._stop.set()
        self.watchdog.stop()       # before the join: a recovery must not
        if self._worker is not None:    # race the shutdown
            self._worker.join(timeout=5.0)
            self._worker = None
        with self._pending_lock:
            pending, self._pending = self._pending, []
        for st in pending:
            self._fail(st.requests, RuntimeError("server shutting down"))

    # ------------------------------------------------------------------
    # watchdog recovery (serving/resilience.py runs the monitor thread)
    # ------------------------------------------------------------------
    def _oldest_dispatch(self) -> Optional[float]:
        with self._pending_lock:
            if not self._pending:
                return None
            return min(st.dispatch_t for st in self._pending)

    def _worker_alive(self) -> bool:
        return self._worker is None or self._worker.is_alive()

    def _recover(self, reason: str) -> None:
        """Watchdog-thread recovery: fail everything in flight, retire the
        current worker generation, prove the device answers by re-warming
        every AOT bucket of every model (readiness stays FALSE until it
        does), then start a fresh worker.  Zero recompiles by
        construction — the bucket executables survive the restart."""
        with self._recover_lock:
            if self._stop.is_set():
                return
            if self._rewarm_thread is not None and \
                    self._rewarm_thread.is_alive():
                # an earlier recovery's re-warm is still wedged on the
                # device: spawning another would just stack threads —
                # stay not-ready until the device answers or ops act
                return
            _logger.error("engine recovery (%s): failing in-flight "
                          "requests, restarting worker, re-warming %d "
                          "bucket(s) x %d model(s)", reason,
                          len(self.buckets), len(self._models))
            self.metrics.ready = False
            self.metrics.watchdog_recoveries_total.inc()
            self.breaker.record_failure()
            self._gen += 1         # neuters the old worker's late writes
            with self._pending_lock:
                # clear + zero under the ledger lock: pairs with _stage's
                # atomic {gauge bump, ledger append} and _unpend's atomic
                # {claim, gauge decrement}
                pending, self._pending = self._pending, []
                self.metrics.inflight = 0
            for st in pending:
                self._fail(st.requests, EngineStalled(
                    f"engine recovery ({reason}) abandoned this batch"))
            # bounded re-warm on a helper thread: against a genuinely
            # hung device, block_until_ready never returns — the watchdog
            # thread must stay free to keep polling (and to let stop()
            # shut down), so a re-warm that overruns its budget leaves
            # the engine not-ready and the next watchdog tick re-enters
            # here (the still-alive guard above keeps it single-flight)
            done = threading.Event()

            def _rewarm_probe():
                try:
                    self._rewarm()
                    done.set()
                except Exception:                  # noqa: BLE001
                    _logger.exception("post-recovery re-warm failed; "
                                      "engine stays not-ready")

            t = threading.Thread(target=_rewarm_probe, daemon=True,
                                 name="serving-rewarm")
            self._rewarm_thread = t
            t.start()
            deadline = time.monotonic() + self._rewarm_timeout_s
            while not done.wait(0.2):
                if self._stop.is_set():
                    return
                if time.monotonic() > deadline:
                    _logger.error(
                        "post-recovery re-warm still blocked after %.0fs "
                        "(device wedged?); engine stays not-ready",
                        self._rewarm_timeout_s)
                    return
                if not t.is_alive() and not done.is_set():
                    return             # probe raised; already logged
            self._rewarm_thread = None
            if self._batcher is not None:
                self._spawn_worker()
            self.metrics.ready = True
            _logger.info("engine recovered (%s): worker gen %d serving, "
                         "buckets re-warmed", reason, self._gen)

    # ------------------------------------------------------------------
    # hot weight reload
    # ------------------------------------------------------------------
    def submit_reload(self, host_tree: Any, source: str = "<api>",
                      model_id: Optional[str] = None) -> None:
        """Queue a host-side f32 variable tree for an atomic between-batch
        swap of one model's weights (called by the watcher threads, or
        directly in tests)."""
        if model_id is None:
            model_id = self.default_model_id
        with self._reload_lock:
            self._reload_box[model_id] = (host_tree, source)

    def _maybe_apply_reload(self) -> None:
        with self._reload_lock:
            if not self._reload_box:
                return
            model_id, (host_tree, source) = self._reload_box.popitem()
        try:
            entry = self.entry(model_id)
        except ValueError:
            _logger.error("reload for unknown model %r dropped", model_id)
            self.metrics.reload_errors_total.inc()
            return
        # Readiness must not lie while the canary runs: the worker thread
        # is busy proving the candidate weights, not dispatching batches,
        # so /readyz drops for the canary window (/healthz stays up) and
        # load balancers can route around the pause.  `gen` is captured
        # so a watchdog recovery firing mid-canary wins every race: the
        # stale worker neither commits the swap nor touches the ready
        # flag the recovery now owns — the reload attempt is requeued
        # for the fresh worker instead.
        gen = self._gen
        was_ready = self.metrics.ready
        self.metrics.ready = False
        try:
            if self._canary_hook is not None:      # test seam
                self._canary_hook()
            try:
                shapes = jax.tree.map(
                    lambda a: (tuple(np.shape(a)), np.asarray(a).dtype),
                    host_tree)
                if shapes != entry.var_shapes:
                    # a checkpoint of some OTHER model's tree lands here
                    # too: cross-model swaps are rejected loudly, never
                    # silently served
                    raise ValueError(
                        f"checkpoint tree/shape mismatch vs serving "
                        f"model {entry.model_id!r}")
                # the serving copy is quantized; the canary then gates
                # the QUANTIZED candidate — a quantization-broken swap
                # (NaN after dequant, drifted scores) rolls back here
                new_vars = jax.device_put(
                    quantize_tree(host_tree, entry.dtype))
                canary = self._canary_scores(entry, new_vars)
                # weight identity of the candidate, hashed OUTSIDE the
                # commit lock (bytes-proportional work) and assigned
                # inside it — one atom with the variables swap
                new_fp = _params_fingerprint(host_tree, entry.dtype)
            except Exception:                      # noqa: BLE001
                _logger.exception("hot reload of model %r from %s "
                                  "rejected; previous weights keep "
                                  "serving", entry.model_id, source)
                self.metrics.reload_errors_total.inc()
                return
            with self._recover_lock:   # serialize the commit vs recovery
                if gen != self._gen:
                    self.submit_reload(host_tree, source,
                                       model_id=model_id)   # retry fresh
                    return
                entry.variables = new_vars
                if canary is not None:
                    entry.golden_ref = canary      # new drift baseline
                # the fingerprint bump orphans every cached verdict of
                # the old weights: a stale hit is impossible from this
                # point on, no sweep required
                entry.fingerprint = new_fp
                entry.reload_count += 1
            if self.verdict_cache is not None:
                purged = self.verdict_cache.purge_model(
                    entry.model_id, keep_fingerprint=new_fp)
                if purged:
                    self.metrics.cache_invalidated_total.inc(purged)
                    self.metrics.cache_entries = self.verdict_cache.size()
            self.metrics.reloads_total.inc()
            self.metrics.count_model("reloads", entry.model_id)
            _logger.info("hot-reloaded model %r weights from %s "
                         "(reload #%d)", entry.model_id, source,
                         entry.reload_count)
        finally:
            with self._recover_lock:
                if gen == self._gen:
                    self.metrics.ready = was_ready

    def _canary_scores(self, entry: _ModelEntry,
                       new_vars) -> Optional[np.ndarray]:
        """Golden-batch canary: the candidate weights must produce finite,
        shape-correct scores — and, when ``reload_drift_tol`` >= 0, scores
        within that tolerance of the serving weights' on the SAME input —
        before they may serve.  Raises on any violation (the caller
        rejects and rolls back to the serving set).  Doubles as the aval-
        compatibility probe: it executes a compiled bucket with the new
        (quantized) params, so a dtype drift fails here, not on live
        traffic."""
        chans, dtype = self._entry_wire_spec(entry)
        if entry.golden is None:                   # warmup=False engines
            s = entry.image_size
            probe = self._run(
                entry, self.buckets[0], chans, new_vars,
                jax.device_put(
                    np.zeros((self.buckets[0], s, s, chans), dtype)))
            jax.block_until_ready(probe)
            return None
        canary = np.asarray(self._run(entry, self.buckets[0], chans,
                                      new_vars, entry.golden))
        if entry.golden_ref is not None and \
                canary.shape != entry.golden_ref.shape:
            self.metrics.reload_canary_failures_total.inc()
            raise ValueError(
                f"canary: golden-batch scores have shape {canary.shape}, "
                f"serving weights produce {entry.golden_ref.shape}")
        if not np.isfinite(canary).all():
            self.metrics.reload_canary_failures_total.inc()
            raise ValueError("canary: candidate weights produce "
                             "non-finite scores on the golden batch")
        if self.reload_drift_tol >= 0 and entry.golden_ref is not None:
            drift = float(np.max(np.abs(canary - entry.golden_ref)))
            if drift > self.reload_drift_tol:
                self.metrics.reload_canary_failures_total.inc()
                raise ValueError(
                    f"canary: golden-batch score drift {drift:.6g} "
                    f"exceeds --reload-drift-tol {self.reload_drift_tol}")
        return canary

    # ------------------------------------------------------------------
    def _newest_checkpoint(self, ckpt_dir: str
                           ) -> Optional[Tuple[str, float, int]]:
        try:
            names = os.listdir(ckpt_dir)
        except OSError:
            return None
        best = None
        for name in names:
            # dotfiles are never candidates (editor temps, the chaos
            # harness's torn copies)
            if name.startswith(".") or not name.endswith(_CKPT_SUFFIXES):
                continue
            path = os.path.join(ckpt_dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            key = (path, st.st_mtime, st.st_size)
            if best is None or key[1] > best[1]:
                best = key
        return best

    def _watch_loop(self, ckpt_dir: str, interval_s: float,
                    use_ema: bool, model_id: str) -> None:
        from ..models.helpers import load_checkpoint
        entry = self.entry(model_id)
        while not self._stop.wait(interval_s):
            newest = self._newest_checkpoint(ckpt_dir)
            if newest is None or newest == entry.last_reload_key:
                continue
            path = load_path = newest[0]
            seq = entry.reload_attempts
            entry.reload_attempts += 1
            if self.chaos.active and self.chaos.fires("torn_reload", seq):
                # route the load through a half-truncated copy so the
                # REAL torn-msgpack rejection (CheckpointCorrupt naming
                # the file) is what recovers, not a synthetic stand-in
                self.metrics.count_chaos("torn_reload")
                load_path = torn_copy(path, tempfile.gettempdir())
                _logger.error("chaos: reloading torn checkpoint copy %s",
                              load_path)
            try:
                loaded = load_checkpoint(entry.host_template, load_path,
                                         use_ema=use_ema, strict=False)
            except Exception:                      # noqa: BLE001
                _logger.exception("reload watcher (%s): cannot load %s; "
                                  "previous weights keep serving",
                                  entry.model_id, load_path)
                self.metrics.reload_errors_total.inc()
                if load_path == path:
                    # don't re-log a genuinely corrupt file every tick —
                    # but a chaos-torn COPY leaves the real file untried,
                    # so the next tick retries it clean (fire-once)
                    entry.last_reload_key = newest
                continue
            finally:
                if load_path != path:
                    try:
                        os.unlink(load_path)
                    except OSError:
                        pass
            entry.last_reload_key = newest
            self.submit_reload(loaded, source=path,
                               model_id=entry.model_id)

    def start_reload_watcher(self, ckpt_dir: str, interval_s: float = 5.0,
                             use_ema: bool = False,
                             model_id: Optional[str] = None) -> None:
        """Poll ``ckpt_dir`` for new ``models/helpers.py`` checkpoints and
        hot-swap them into ``model_id``'s slot (None = the primary
        model).  Writers must rename atomically into place (the repo's
        ``save_model_checkpoint`` does)."""
        entry = self.entry(model_id)
        assert entry.watcher is None, \
            f"watcher already started for model {entry.model_id!r}"
        # remember the current newest so only files appearing AFTER start
        # trigger a reload (the serving checkpoint itself usually lives in
        # the watched dir)
        entry.last_reload_key = self._newest_checkpoint(ckpt_dir)
        entry.watcher = threading.Thread(
            target=self._watch_loop,
            args=(ckpt_dir, interval_s, use_ema, entry.model_id),
            name=f"serving-reload-watcher-{entry.model_id}", daemon=True)
        if entry.model_id == self.default_model_id:
            self._watcher = entry.watcher      # single-model back-compat
        entry.watcher.start()
