"""Two-tier scoring cascade: a small student triages every clip, only
suspects pay for the flagship (ISSUE 14).

The req/s-per-chip lever on real traffic mixes: most clips are obviously
clean (or obviously fake) and a model a fraction of the flagship's size
clears them with the same verdict.  The router scores EVERY clip on the
student first; a fake-probability inside the configurable **suspect
band** ``[low, high]`` escalates the clip to the flagship, anything
outside the band returns the student verdict directly.  Both tiers ride
the SAME engine/batcher/buckets, so the PR 2/PR 10 invariants (AOT-only
executables, exact request books, breaker/watchdog recovery) apply to
cascade traffic unchanged.

Books — both identities hold EXACTLY through every fault, audited from
/metrics by tools/bench_serve.py and the cascade tests::

    cascade_triaged   == cascade_cleared + cascade_escalated
    cascade_escalated == cascade_flagship_scored + cascade_escalation_failed

Failure semantics: a *student*-phase failure (shed, deadline, engine
fault) propagates to the client exactly like a single-model request —
the clip was never triaged.  A *flagship*-phase failure serves the
student verdict instead, counted in ``cascade_escalation_failed_total``
— an escalation failure is NEVER a silent drop, and never an error for
a clip the student already scored.

Per-tier latency rides ``dfd_serving_cascade_latency_seconds{tier=}``.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional

from .metrics import ServingMetrics

__all__ = ["CascadeResult", "CascadeRouter", "DeadlineExhausted"]


class DeadlineExhausted(RuntimeError):
    """The shared cascade budget was spent before the flagship leg could
    start — handled as an escalation failure (student verdict served)."""


class CascadeResult:
    """Outcome of one cascade scoring: the served scores plus the triage
    trail (which tier answered, the student's fake score, whether an
    escalation happened/failed)."""

    __slots__ = ("scores", "tier", "student_score", "escalated",
                 "escalation_error", "timings")

    def __init__(self, scores: Any, tier: str, student_score: float,
                 escalated: bool,
                 escalation_error: Optional[str] = None,
                 timings: Optional[dict] = None):
        self.scores = scores
        self.tier = tier                   # "student" | "flagship"
        self.student_score = student_score
        self.escalated = escalated
        self.escalation_error = escalation_error
        # queue/device timings of the request whose verdict was SERVED
        # (the student's when tier == "student") — the HTTP layer reports
        # these instead of zeros for cascade traffic
        self.timings = timings if timings is not None else {}


class CascadeRouter:
    """Student-first routing over one micro-batcher.

    ``batcher`` only needs ``submit(array, timeout_s=..., model_id=...)``
    returning an object with ``result(timeout=...)`` — the real
    :class:`~.batcher.MicroBatcher` in production, a stub in the
    fault-sequencing unit tests.
    """

    def __init__(self, batcher, metrics: ServingMetrics, *,
                 student_id: str, flagship_id: str,
                 low: float, high: float, timeout_s: float = 2.0):
        if not 0.0 <= float(low) <= float(high) <= 1.0:
            raise ValueError(f"suspect band must satisfy 0 <= low <= "
                             f"high <= 1, got [{low}, {high}]")
        if student_id == flagship_id:
            raise ValueError("cascade student and flagship must be "
                             "different models")
        self.batcher = batcher
        self.metrics = metrics
        self.student_id = student_id
        self.flagship_id = flagship_id
        self.low = float(low)
        self.high = float(high)
        self.timeout_s = float(timeout_s)

    # ------------------------------------------------------------------
    def suspect(self, p_fake: float) -> bool:
        """True iff the student's fake score falls in the suspect band."""
        return self.low <= p_fake <= self.high

    def score(self, student_payload: Any,
              flagship_payload: Callable[[], Any],
              content_key: Optional[Any] = None) -> CascadeResult:
        """Triage one clip.

        ``flagship_payload`` is a thunk so the (possibly larger) flagship
        canvas is only prepared for the escalated fraction.  Student-
        phase exceptions propagate; flagship-phase exceptions degrade to
        the student verdict (counted).

        ``content_key`` is the clip's verdict-cache identity (ISSUE 17),
        forwarded to BOTH tier submits — the cache key carries the model
        id, so student and flagship verdicts never mix, and the tiers
        compose multiplicatively: cache → student → flagship.

        The two tiers share ONE ``timeout_s`` budget: the flagship leg
        gets whatever the student left (an exhausted budget at escalation
        time is a flagship-phase failure → student verdict + counter),
        so an escalated request can never take ~2× the configured
        deadline behind a 200."""
        m = self.metrics
        t0 = time.monotonic()
        kw = {} if content_key is None else {"content_key": content_key}
        req = self.batcher.submit(student_payload,
                                  timeout_s=self.timeout_s,
                                  model_id=self.student_id, **kw)
        # raises on shed/deadline/fault: the clip was never triaged, and
        # the per-model books already account the failed student request
        s_scores = req.result(timeout=self.timeout_s + 5.0)
        # timings are optional on the batcher contract (stubs omit them)
        s_timings = dict(getattr(req, "timings", {}))
        m.cascade_latency["student"].observe(time.monotonic() - t0)
        m.cascade_triaged_total.inc()
        p_fake = float(s_scores[0])
        if not self.suspect(p_fake):
            m.cascade_cleared_total.inc()
            return CascadeResult(s_scores, "student", p_fake,
                                 escalated=False, timings=s_timings)
        m.cascade_escalated_total.inc()
        t1 = time.monotonic()
        remaining = self.timeout_s - (t1 - t0)
        try:
            if remaining <= 0:
                raise DeadlineExhausted(
                    f"cascade budget {self.timeout_s:.3f}s spent in the "
                    f"student phase")
            freq = self.batcher.submit(flagship_payload(),
                                       timeout_s=remaining,
                                       model_id=self.flagship_id, **kw)
            f_scores = freq.result(timeout=remaining + 5.0)
        except Exception as e:                     # noqa: BLE001
            # the student verdict is still a verdict: serve it, count the
            # failed escalation — never a silent drop, never a client
            # error for a clip the student already scored
            m.cascade_escalation_failed_total.inc()
            return CascadeResult(s_scores, "student", p_fake,
                                 escalated=True,
                                 escalation_error=repr(e),
                                 timings=s_timings)
        m.cascade_latency["flagship"].observe(time.monotonic() - t1)
        m.cascade_flagship_scored_total.inc()
        return CascadeResult(f_scores, "flagship", p_fake, escalated=True,
                             timings=dict(getattr(freq, "timings", {})))
